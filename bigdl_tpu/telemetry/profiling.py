"""Compile flight recorder: ``tracked_jit`` — a ``jax.jit`` wrapper that
ATTRIBUTES cost instead of just spending it.

PR 5's telemetry records durations; nothing said which jitted programs
compiled, how long each compilation took, what FLOPs/HBM bytes a program
accounts for, or what memory it holds. This module closes that gap with
one primitive every jitted site adopts (``optim/optimizer.py``,
``parallel/distri_optimizer.py``, ``models/serving.py``,
``models/generation.py``, ``optim/evaluator.py``, ``bench.py``):

    step = tracked_jit(step_fn, site="train.step", donate_argnums=(0, 1, 2))

Mechanics: the wrapper keys calls by the ABSTRACT argument signature
(pytree structure + per-leaf shape/dtype/sharding — exactly what XLA
specializes on) and compiles new signatures through the AOT path
(``jitted.lower(*args).compile()``), so each compilation happens exactly
once, is timed on the wall clock, and yields the compiled executable's
``cost_analysis()`` (FLOPs, bytes accessed) and ``memory_analysis()``
(temp/output bytes) BEFORE the first execution. Repeat calls dispatch the
cached executable directly. One flight-recorder event per compilation
lands in:

- ``bigdl_compiles_total{site}`` / ``bigdl_compile_seconds{site}``;
- per-site last-program cost gauges ``bigdl_program_flops{site}``,
  ``bigdl_program_bytes_accessed{site}``, ``bigdl_program_temp_bytes``
  ``/_output_bytes{site}``;
- a ``profiling.compile`` span (site + signature + seconds) when the
  tracer is on, so compile storms are visible inside a Chrome trace.

Cost fields are present-or-None: backends that cannot answer (some CPU
builds, PJRT plugins without analysis support) degrade to counting and
timing only — never to an exception on the serving path. Any AOT failure
falls back to the plain jitted call for that signature, still counted.

The per-signature executable cache is bounded (``cache_size``) with
OLDEST-FIRST SINGLE-ENTRY eviction — evicting one program on overflow
instead of wiping the cache, so live signatures under mixed traffic do
not all recompile at once (the clear-at-cap eviction storm this PR fixes
in the serving prefill and generate() caches). Evictions count in
``bigdl_compile_cache_evictions_total{site}``.

jax-free at import (the telemetry package contract): jax loads on first
``tracked_jit`` construction.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from bigdl_tpu.telemetry.registry import MetricsRegistry, get_registry
from bigdl_tpu.telemetry.tracing import span

__all__ = ["tracked_jit", "TrackedJit", "CompileEvent", "peak_flops",
           "sample_device_memory", "DEFAULT_CACHE_SIZE"]

#: Default retained-executable bound per tracked site. Generous for
#: steady-state sites (a training loop has ONE signature) and for the
#: O(1)/O(log) program families the chunked/bucketed serving prefill
#: dispatches through a single wrapper.
DEFAULT_CACHE_SIZE = 64


class CompileEvent:
    """One recorded compilation: what compiled, how long, what it costs."""

    __slots__ = ("site", "signature", "seconds", "flops", "bytes_accessed",
                 "temp_bytes", "output_bytes", "argument_bytes")

    def __init__(self, site: str, signature: str, seconds: float,
                 flops: Optional[float] = None,
                 bytes_accessed: Optional[float] = None,
                 temp_bytes: Optional[int] = None,
                 output_bytes: Optional[int] = None,
                 argument_bytes: Optional[int] = None):
        self.site = site
        self.signature = signature
        self.seconds = seconds
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.temp_bytes = temp_bytes
        self.output_bytes = output_bytes
        self.argument_bytes = argument_bytes

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


def _leaf_key(x) -> Tuple:
    """Hashable abstract descriptor of one argument leaf. jax arrays key
    on (shape, dtype, weak_type, sharding) — sharding included because a
    compiled executable is specialized to its input layout (a mesh-
    committed and an uncommitted array of the same shape need different
    programs). Non-array leaves key on their type: a Python scalar traces
    as a weak-typed 0-d input, so its VALUE does not split programs.

    TRACER leaves raise TypeError: a tracked fn called inside another
    trace (the eval scorer calls the tracked forward) must inline through
    the plain jit wrapper — a compiled executable cannot consume
    tracers. ``__call__`` catches and dispatches accordingly."""
    import jax
    if isinstance(x, jax.core.Tracer):
        raise TypeError("tracer argument: dispatch through jax.jit")
    aval = getattr(x, "aval", None)
    if aval is not None:                       # jax.Array fast path
        return (aval.shape, str(aval.dtype), bool(aval.weak_type),
                getattr(x, "sharding", None))
    shape = getattr(x, "shape", None)
    if shape is not None and hasattr(x, "dtype"):   # numpy array
        return (tuple(shape), str(x.dtype), False, None)
    return (type(x),)


def _cost_number(analysis, key: str) -> Optional[float]:
    """Pull one scalar out of ``Compiled.cost_analysis()`` across the API
    shapes jax has shipped: a dict, or a list with one dict per
    computation (sum them — a multi-computation program spends all of
    them per call)."""
    if analysis is None:
        return None
    if isinstance(analysis, dict):
        analysis = [analysis]
    total, seen = 0.0, False
    try:
        for entry in analysis:
            v = entry.get(key)
            if v is not None and v >= 0:
                total += float(v)
                seen = True
    except (AttributeError, TypeError):
        return None
    return total if seen else None


class TrackedJit:
    """``jax.jit`` with a compile flight recorder (see module docstring).

    NOT a drop-in for every jit feature: static_argnums/argnames are
    passed through to the underlying jit, but the signature key treats
    Python scalars by TYPE, so static-arg call families should keep using
    plain ``jax.jit`` (graftlint JG013 already polices those). All
    adopted sites in this repo take array pytrees only.
    """

    def __init__(self, fn: Callable, *, site: str,
                 registry: Optional[MetricsRegistry] = None,
                 cache_size: int = DEFAULT_CACHE_SIZE,
                 **jit_kwargs):
        import jax

        from bigdl_tpu.telemetry.catalogue import instruments
        self.site = site
        self.cache_size = max(1, int(cache_size))
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._registry = registry if registry is not None else get_registry()
        self._tm = instruments(self._registry)
        # signature -> compiled executable (None = AOT unsupported for
        # that signature; dispatch through the plain jitted wrapper)
        self._programs: "OrderedDict[Tuple, Any]" = OrderedDict()
        self.events: list = []            # CompileEvent, oldest first
        self.last_event: Optional[CompileEvent] = None
        self.compiles = 0

    # ------------------------------------------------------------- recording
    @property
    def last_flops(self) -> Optional[float]:
        ev = self.last_event
        return ev.flops if ev is not None else None

    def _signature(self, args) -> Tuple:
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(args)
        return (treedef, tuple(_leaf_key(x) for x in leaves))

    def _describe(self, args) -> str:
        """Human-readable shape signature for the event/span (kept terse:
        leaf count + first few leaf shapes)."""
        import jax
        leaves = jax.tree_util.tree_leaves(args)
        shapes = []
        for x in leaves[:4]:
            shapes.append("x".join(str(d) for d in getattr(x, "shape", ()))
                          or "scalar")
        extra = f"+{len(leaves) - 4}" if len(leaves) > 4 else ""
        return f"{len(leaves)} leaves ({','.join(shapes)}{extra})"

    def _record(self, seconds: float, compiled, signature: str) -> None:
        flops = bytes_accessed = temp = outb = argb = None
        if compiled is not None:
            try:
                analysis = compiled.cost_analysis()
                flops = _cost_number(analysis, "flops")
                bytes_accessed = _cost_number(analysis, "bytes accessed")
            except Exception:       # noqa: BLE001 — analysis is best-effort
                pass
            try:
                mem = compiled.memory_analysis()
                temp = int(getattr(mem, "temp_size_in_bytes", None))
                outb = int(getattr(mem, "output_size_in_bytes", None))
                argb = int(getattr(mem, "argument_size_in_bytes", None))
            except Exception:       # noqa: BLE001
                pass
        ev = CompileEvent(self.site, signature, seconds, flops,
                          bytes_accessed, temp, outb, argb)
        self.events.append(ev)
        self.last_event = ev
        self.compiles += 1
        site = self.site
        self._tm.compiles_total.labels(site=site).inc()
        self._tm.compile_seconds.labels(site=site).observe(seconds)
        if flops is not None:
            self._tm.program_flops.labels(site=site).set(flops)
        if bytes_accessed is not None:
            self._tm.program_bytes_accessed.labels(site=site).set(
                bytes_accessed)
        if temp is not None:
            self._tm.program_temp_bytes.labels(site=site).set(temp)
        if outb is not None:
            self._tm.program_output_bytes.labels(site=site).set(outb)

    # ------------------------------------------------------------- dispatch
    def __call__(self, *args):
        programs = self._programs
        try:
            key = self._signature(args)
        except TypeError:         # unhashable leaf metadata: bypass tracking
            return self._jitted(*args)
        compiled = programs.get(key, _MISS)
        if compiled is _MISS:
            compiled = self._compile(key, args)
        elif compiled is None:    # known-unsupported signature
            return self._jitted(*args)
        else:
            programs.move_to_end(key)
        return compiled(*args)

    def _compile(self, key, args):
        """AOT-compile a new signature, record the event, bound the cache.
        Returns the executable, or falls back to (and returns the result
        semantics of) the plain jitted path by caching ``None``."""
        desc = self._describe(args)
        t0 = time.perf_counter()
        try:
            with span("profiling.compile", site=self.site, signature=desc):
                compiled = self._jitted.lower(*args).compile()
        except Exception:       # noqa: BLE001 — AOT unsupported here: the
            # plain jit call must still work (and still counts: its first
            # dispatch IS the compile, timed around the call)
            self._programs[key] = None
            result = self._jitted(*args)
            self._record(time.perf_counter() - t0, None, desc)
            self._evict()
            # hand the caller the already-computed result through the
            # normal `compiled(*args)` return path
            return _Precomputed(result)
        self._record(time.perf_counter() - t0, compiled, desc)
        self._programs[key] = compiled
        self._evict()
        return compiled

    def _evict(self) -> None:
        while len(self._programs) > self.cache_size:
            # oldest-first SINGLE-entry eviction — never clear-at-cap
            # (evicting everything forces every live signature to
            # recompile immediately; see module docstring)
            self._programs.popitem(last=False)
            self._tm.compile_cache_evictions_total.labels(
                site=self.site).inc()

    # -------------------------------------------------------------- AOT API
    def lower(self, *args, **kwargs):
        """Delegate to the underlying ``jax.jit`` wrapper (HLO-contract
        tests lower and inspect programs without executing them)."""
        return self._jitted.lower(*args, **kwargs)

    def __repr__(self) -> str:
        return (f"TrackedJit(site={self.site!r}, compiles={self.compiles}, "
                f"cached={len(self._programs)})")


class _Precomputed:
    """Adapter so ``_compile``'s fallback path can return 'an executable'
    whose one pending call result is already known."""

    __slots__ = ("_result",)

    def __init__(self, result):
        self._result = result

    def __call__(self, *args):
        return self._result


_MISS = object()


def tracked_jit(fn: Callable, *, site: str,
                registry: Optional[MetricsRegistry] = None,
                cache_size: int = DEFAULT_CACHE_SIZE,
                **jit_kwargs) -> TrackedJit:
    """Wrap ``fn`` as a compile-tracked jit (see :class:`TrackedJit`)."""
    return TrackedJit(fn, site=site, registry=registry,
                      cache_size=cache_size, **jit_kwargs)


# ---------------------------------------------------------------------------
# Peak-FLOPs model + MFU
# ---------------------------------------------------------------------------

# bf16 peak FLOP/s by device kind substring (the roofline numerators the
# PERF.md analyses already use; first match wins)
_PEAK_BY_KIND = (
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("v6", 918e12),
)

_peak_cache: Dict[str, Optional[float]] = {}


def peak_flops() -> Optional[float]:
    """Per-chip peak FLOP/s for MFU computation, or None when unknown.

    ``BIGDL_TPU_PEAK_FLOPS`` overrides (any backend — the only way to get
    MFU on CPU or an unrecognized accelerator); otherwise the TPU device
    kind maps through the table above. Unknown = None: an MFU computed
    against a made-up roof is worse than no MFU."""
    env = os.environ.get("BIGDL_TPU_PEAK_FLOPS", "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if "kind" not in _peak_cache:
        kind = ""
        try:
            import jax
            dev = jax.local_devices()[0]
            if dev.platform == "tpu":
                kind = getattr(dev, "device_kind", "").lower()
        except Exception:       # noqa: BLE001 — no backend, no roof
            kind = ""
        _peak_cache["kind"] = next(
            (f for sub, f in _PEAK_BY_KIND if sub in kind), None)
    return _peak_cache["kind"]


def mfu(flops_per_step: Optional[float],
        step_seconds: float) -> Optional[float]:
    """Model-FLOPs utilization: cost-analysis FLOPs / wall seconds /
    peak. None whenever either input is unknown."""
    peak = peak_flops()
    if not flops_per_step or not step_seconds or not peak:
        return None
    return flops_per_step / step_seconds / peak


# ---------------------------------------------------------------------------
# Device-memory watermark
# ---------------------------------------------------------------------------

_mem_unsupported = False


def sample_device_memory(registry: Optional[MetricsRegistry] = None) -> \
        Optional[int]:
    """Sample device 0's memory stats into the
    ``bigdl_device_memory_bytes`` / ``_peak_bytes`` gauges; returns the
    peak, or None where the runtime has no allocator stats (CPU). Called
    at step boundaries and slot admission — cheap (one PJRT call), and a
    no-op forever after the first unsupported answer."""
    global _mem_unsupported
    if _mem_unsupported:
        return None
    stats = None
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
    except Exception:       # noqa: BLE001 — absent backend == unsupported
        stats = None
    if not stats:
        _mem_unsupported = True
        return None
    from bigdl_tpu.telemetry.catalogue import instruments
    tm = instruments(registry if registry is not None else get_registry())
    in_use = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use")
    if in_use is not None:
        tm.device_memory_bytes.set(in_use)
    if peak is not None:
        tm.device_memory_peak_bytes.set(peak)
    return peak
