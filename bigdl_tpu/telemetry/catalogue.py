"""Well-known metric and span inventory — the single source of truth.

Every instrumented subsystem (serving, training, eval, bench) creates its
families FROM these specs, and ``scripts/gen_api_doc.py`` renders this
table into ``docs/API.md`` — so the docs can never drift from what a
scrape actually returns. Narrative guide: ``docs/OBSERVABILITY.md``.

Bucket choices: serving latencies use the sub-ms-to-seconds default;
training step phases reuse it (a CPU-fallback step is seconds, a TPU
step sub-ms — the shared ladder covers both); batch sizes use power-of-
two buckets matching the bucketed batcher's padding.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from bigdl_tpu.telemetry.registry import (DEFAULT_LATENCY_BUCKETS,
                                          MetricSpec, MetricsRegistry)

__all__ = ["METRIC_SPECS", "SPAN_SPECS", "instruments"]

BATCH_SIZE_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128)

METRIC_SPECS: List[MetricSpec] = [
    # ---- continuous-batching serving engine (models/serving.py)
    MetricSpec("bigdl_serving_ttft_seconds", "histogram",
               "Time to first token: request submit to first sampled token "
               "(prefill + queue wait).", (), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_serving_token_latency_seconds", "histogram",
               "Per-token decode latency, observed once per decode block "
               "as block wall-clock / tokens.", (), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_serving_request_latency_seconds", "histogram",
               "Whole-request latency: submit to completion (one "
               "observation per completed request).",
               (), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_serving_queue_depth", "gauge",
               "Requests waiting for a slot (admission queue)."),
    MetricSpec("bigdl_serving_slots_occupied", "gauge",
               "Slots currently decoding a live request."),
    MetricSpec("bigdl_serving_slots_total", "gauge",
               "Configured slot count of the continuous server."),
    MetricSpec("bigdl_serving_admissions_total", "counter",
               "Requests admitted into a slot (prefill + insert done)."),
    MetricSpec("bigdl_serving_requests_completed_total", "counter",
               "Requests finished (eos or token budget)."),
    MetricSpec("bigdl_serving_request_errors_total", "counter",
               "Requests failed (admission or decode error)."),
    MetricSpec("bigdl_serving_recompiles_total", "counter",
               "New XLA program builds: the O(1) chunked-prefill pair "
               "(or a first-seen pow2 length bucket in bucketed mode), "
               "the step program, the insert program."),
    MetricSpec("bigdl_serving_decode_blocks_total", "counter",
               "Jitted decode blocks dispatched over all slots."),
    MetricSpec("bigdl_serving_tokens_total", "counter",
               "Tokens emitted to live requests (dead-slot lanes "
               "excluded)."),
    MetricSpec("bigdl_serving_ttft_hit_seconds", "histogram",
               "TTFT of admissions whose prefix-cache lookup hit "
               "(>= one chunk of prefill skipped). Only populated while "
               "the prefix cache is enabled.",
               (), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_serving_ttft_miss_seconds", "histogram",
               "TTFT of admissions that prefilled cold (prefix-cache "
               "miss). Only populated while the prefix cache is enabled.",
               (), DEFAULT_LATENCY_BUCKETS),
    # ---- serving fleet: drain / handoff / router (models/router.py)
    MetricSpec("bigdl_serving_drains_total", "counter",
               "Graceful drains entered by a continuous server (SIGTERM "
               "or drain()): admission stops, in-flight slots leave as "
               "handoff cursors."),
    MetricSpec("bigdl_router_requests_total", "counter",
               "Requests accepted by the fleet router (counted once per "
               "request, before any dispatch attempts)."),
    MetricSpec("bigdl_router_retries_total", "counter",
               "Dispatch attempts re-tried against another replica after "
               "a failed or rejected attempt (bounded, with backoff)."),
    MetricSpec("bigdl_router_requeues_total", "counter",
               "Requests re-dispatched WITH a handoff cursor after their "
               "replica died or drained mid-flight (a subset of "
               "retries: the request had been accepted)."),
    MetricSpec("bigdl_handoff_seconds", "histogram",
               "Wall-clock of producing one serialized prefill handoff "
               "partition on a prefill replica (disaggregation's ship "
               "cost, observed by the router).",
               (), DEFAULT_LATENCY_BUCKETS),
    # ---- cross-request KV prefix cache (models/prefix_cache.py)
    MetricSpec("bigdl_prefix_cache_hits", "counter",
               "Admissions whose chunk-aligned token prefix matched a "
               "cached prefill-state snapshot (tail-only prefill)."),
    MetricSpec("bigdl_prefix_cache_misses", "counter",
               "Admissions that found no cached chunk-aligned prefix and "
               "prefilled from token 0."),
    MetricSpec("bigdl_prefix_cache_evictions", "counter",
               "Prefix-state snapshots dropped LRU-first from the "
               "size-bounded trie, counted one entry at a time (never "
               "clear-at-cap)."),
    MetricSpec("bigdl_prefix_cache_bytes", "gauge",
               "Bytes of prefill-state snapshots currently held by the "
               "serving prefix trie(s) (target + draft in speculative "
               "mode)."),
    # ---- speculative serving (models/serving.py draft=...)
    MetricSpec("bigdl_spec_proposed_tokens_total", "counter",
               "Draft tokens proposed by speculative serving rounds "
               "(spec_len per live slot per round)."),
    MetricSpec("bigdl_spec_accepted_tokens_total", "counter",
               "Draft proposals accepted by target verification (the "
               "per-round bonus token is not counted, so accept rate = "
               "accepted / proposed)."),
    # ---- bucketed batch server (models/lm_server.py)
    MetricSpec("bigdl_lmserver_batch_size", "histogram",
               "Requests per dispatched batch (pre-padding).",
               (), BATCH_SIZE_BUCKETS),
    MetricSpec("bigdl_lmserver_batch_wait_seconds", "histogram",
               "Anchor request's wait from submit to batch dispatch.",
               (), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_lmserver_batches_total", "counter",
               "Batches decoded by the bucketed server."),
    MetricSpec("bigdl_lmserver_requests_total", "counter",
               "Requests served by the bucketed server."),
    MetricSpec("bigdl_lmserver_queue_depth", "gauge",
               "Requests queued or held awaiting same-length company."),
    # ---- training loops (optim/optimizer.py, parallel/distri_optimizer.py)
    MetricSpec("bigdl_train_step_seconds", "histogram",
               "Per-iteration device step time (window wall-clock / "
               "iterations in the dispatch window).",
               ("mode",), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_train_data_wait_seconds", "histogram",
               "Host wait on the data pipeline per dispatch window.",
               ("mode",), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_train_dispatch_seconds", "histogram",
               "Host time handing a window to the device (H2D + enqueue; "
               "async — excludes device compute).",
               ("mode",), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_train_sync_seconds", "histogram",
               "Host block fetching the pipelined losses (device->host "
               "sync point).", ("mode",), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_train_steps_total", "counter",
               "Optimizer iterations completed.", ("mode",)),
    MetricSpec("bigdl_train_records_total", "counter",
               "Training records consumed.", ("mode",)),
    MetricSpec("bigdl_train_records_per_second", "gauge",
               "Most recent per-iteration throughput (records or tokens "
               "per second).", ("mode",)),
    MetricSpec("bigdl_train_compiles_total", "counter",
               "Trace+compile events charged to the loop (first dispatch "
               "of a step program).", ("mode",)),
    MetricSpec("bigdl_train_validation_seconds", "histogram",
               "Wall-clock of in-training validation passes.",
               ("mode",), DEFAULT_LATENCY_BUCKETS),
    # ---- staged ingest engine (dataset/ingest/)
    MetricSpec("bigdl_ingest_queue_depth", "gauge",
               "Items waiting between ingest stages (stage = shards "
               "done-but-unordered, chunks awaiting decode, batches "
               "done-but-unordered, out = device-ready hand-off queue).",
               ("stage",)),
    MetricSpec("bigdl_ingest_stage_seconds", "histogram",
               "Wall-clock of one ingest work unit (stage = read one "
               "shard / decode one chunk / device_put one batch).",
               ("stage",), DEFAULT_LATENCY_BUCKETS),
    MetricSpec("bigdl_ingest_records_total", "counter",
               "Records handed to the consumer by the ingest engine."),
    MetricSpec("bigdl_ingest_bytes_total", "counter",
               "Raw shard payload bytes read by the reader pool."),
    MetricSpec("bigdl_ingest_batches_total", "counter",
               "Batches handed to the consumer by the ingest engine."),
    MetricSpec("bigdl_ingest_stall_seconds_total", "counter",
               "Starvation attribution: time a stage waited for INPUT "
               "while the pipeline had admission room (waits under "
               "downstream backpressure are charged to nobody). "
               "stage=step is the consumer starving (ingest-bound "
               "training); stage=materialize is DeviceCachedDataSet's "
               "blocking first-fill.", ("stage",)),
    # ---- batch evaluation (optim/evaluator.py)
    MetricSpec("bigdl_eval_batches_total", "counter",
               "Evaluation batches scored."),
    MetricSpec("bigdl_eval_records_total", "counter",
               "Evaluation records scored."),
    MetricSpec("bigdl_eval_batch_seconds", "histogram",
               "Host wall-clock per evaluation batch (async dispatch in "
               "the device-accumulation steady state).",
               (), DEFAULT_LATENCY_BUCKETS),
    # ---- resilience (bigdl_tpu/resilience/, docs/RESILIENCE.md)
    MetricSpec("bigdl_resilience_preemptions_total", "counter",
               "Preemption notices received (SIGTERM/SIGINT or a "
               "cooperative chaos/test trigger)."),
    MetricSpec("bigdl_resilience_snapshot_seconds", "histogram",
               "Wall-clock of the end-of-step preemption snapshot "
               "(model + state + RESUME marker).",
               (), DEFAULT_LATENCY_BUCKETS + (30.0, 120.0)),
    MetricSpec("bigdl_resilience_resumes_total", "counter",
               "Training restarts from a discovered snapshot; "
               "elastic=true when the process/device count changed "
               "(unknown = markerless legacy snapshot).", ("elastic",)),
    # ---- kernel dispatch (ops/int8_matmul.py, parallel/expert.py)
    MetricSpec("bigdl_moe_dispatch_total", "counter",
               "MoE forwards by dispatch formulation (path label: "
               "sort / scatter / einsum). Counted once per eager call / "
               "once per TRACE under jit — the branch runs at trace "
               "time, so this records which formulation each compiled "
               "MoE program uses, not per-step traffic. 'sort' (the "
               "round-10 default) replaces the k-fold one-hot+cumsum+"
               "scatter-add chains with one stable argsort plus "
               "gathers.", ("path",)),
    MetricSpec("bigdl_int8_fallbacks_total", "counter",
               "int8_matmul decode-shaped calls that LOST the fused "
               "kernel because K is off the 128-lane quantum (XLA "
               "dequant fallback at ~2x the int8 byte floor). Any output "
               "dim takes the kernel since the round-10 full-coverage "
               "tiling (the ceil grid masks the partial final tile), so "
               "this stays 0 on real model shapes — V=32000 and "
               "V=151936 included. Counted once per eager call / once "
               "per TRACE under jit (the decision runs at trace time), "
               "and warned once per shape."),
    # ---- compile flight recorder (telemetry/profiling.py tracked_jit)
    MetricSpec("bigdl_compiles_total", "counter",
               "XLA program compilations recorded by tracked_jit — one "
               "per new (site, abstract arg signature).", ("site",)),
    MetricSpec("bigdl_compile_seconds", "histogram",
               "Wall-clock of one tracked_jit trace+lower+compile.",
               ("site",), DEFAULT_LATENCY_BUCKETS + (60.0, 120.0)),
    MetricSpec("bigdl_program_flops", "gauge",
               "cost_analysis FLOPs of the site's most recently compiled "
               "program (per execution of that program).", ("site",)),
    MetricSpec("bigdl_program_bytes_accessed", "gauge",
               "cost_analysis HBM bytes accessed per execution of the "
               "site's most recently compiled program.", ("site",)),
    MetricSpec("bigdl_program_temp_bytes", "gauge",
               "memory_analysis temp (scratch) allocation of the site's "
               "most recently compiled program.", ("site",)),
    MetricSpec("bigdl_program_output_bytes", "gauge",
               "memory_analysis output allocation of the site's most "
               "recently compiled program.", ("site",)),
    MetricSpec("bigdl_compile_cache_evictions_total", "counter",
               "Compiled programs dropped oldest-first from a bounded "
               "program cache (tracked_jit executables, the serving "
               "prefill family, generate() signature family).", ("site",)),
    MetricSpec("bigdl_train_mfu", "gauge",
               "Live model-FLOPs utilization of the training loop: "
               "cost-analysis FLOPs per dispatch / dispatch wall seconds "
               "/ peak chip FLOP/s (absent when the backend reports no "
               "cost analysis or the peak is unknown — override with "
               "BIGDL_TPU_PEAK_FLOPS).", ("mode",)),
    MetricSpec("bigdl_device_memory_bytes", "gauge",
               "Device 0 bytes currently allocated (sampled at step "
               "boundaries and slot admission; absent on runtimes "
               "without allocator stats, e.g. CPU)."),
    MetricSpec("bigdl_device_memory_peak_bytes", "gauge",
               "Device 0 peak-bytes-in-use watermark (same sampling "
               "points as bigdl_device_memory_bytes)."),
    # ---- legacy bridge (optim/metrics.py)
    MetricSpec("bigdl_legacy_metric", "gauge",
               "Legacy optim.Metrics counters bridged onto the registry "
               "(scope = one Metrics instance, name = reference counter "
               "name).", ("scope", "name")),
    # ---- bench harness (bench.py)
    MetricSpec("bigdl_bench_step_seconds", "histogram",
               "Benchmark timed-loop per-step wall-clock (chunk time / "
               "steps; embedded in BENCH_*.json).",
               (), DEFAULT_LATENCY_BUCKETS + (60.0, 120.0)),
]

#: Span inventory (tracing.span names) with where they fire.
SPAN_SPECS: List[Tuple[str, str]] = [
    ("serving.request", "Async lifecycle of ONE continuous-serving "
     "request (Chrome async events sharing the request id): begins at "
     "submit, instants at admission, ends at completion/failure — with "
     "serving.queue_wait/prefill/insert carrying the same rid arg, a "
     "single dump reconstructs the whole journey."),
    ("serving.queue_wait", "Retrodicted span from a request's submit to "
     "the start of its admission (queue-wait attribution; rid arg links "
     "it to its serving.request lifecycle)."),
    ("serving.prefill", "Out-of-band b=1 prompt prefill + admission "
     "sampling (models/serving.py _admit)."),
    ("serving.insert", "Jitted cache scatter of a prefilled request into "
     "a free slot row."),
    ("serving.decode_block", "One jitted decode_block-token step over all "
     "slots."),
    ("lmserver.request", "Async lifecycle of one bucketed-server request "
     "(submit -> batch dispatch -> completion) under the request id."),
    ("lmserver.gather", "Batcher wait assembling one same-length batch."),
    ("lmserver.decode_batch", "One batched prefill+decode program "
     "(models/lm_server.py)."),
    ("ingest.read_shard", "Reader-pool thread reading + CRC-verifying one "
     "shard (and applying its seeded record shuffle) "
     "(dataset/ingest/engine.py)."),
    ("ingest.decode", "Decode-pool thread running one record chunk "
     "through its cloned decode/collate chain."),
    ("ingest.device_put", "Device-feed thread issuing the async H2D "
     "transfer of one batch (overlaps the step consuming the previous "
     "one)."),
    ("ingest.step", "Consumer-side work between batch pops in "
     "apps/ingest_bench.py's pipelined measurement (the lane the "
     "read/decode/device_put spans overlap with)."),
    ("ingest.materialize", "DeviceCachedDataSet building its whole-epoch "
     "device cache on first use; the same wall time lands in "
     "bigdl_ingest_stall_seconds_total{stage=materialize} "
     "(dataset/device_cache.py)."),
    ("train.dispatch", "Handing one training window to the device (H2D + "
     "enqueue)."),
    ("train.sync", "Blocking fetch of the pipelined window losses."),
    ("train.validate", "In-training validation pass."),
    ("resilience.snapshot", "End-of-step preemption snapshot: model + "
     "state + RESUME marker (optim/optimizer.py)."),
    ("eval.batches", "One evaluate_batches call (all batches + the final "
     "device->host merge)."),
    ("profiling.compile", "One tracked_jit compilation of a new "
     "(site, signature) — trace+lower+compile wall time "
     "(telemetry/profiling.py)."),
]


class _Instruments:
    """Attribute-addressed families for one registry: ``ins.<name>`` with
    the ``bigdl_`` prefix stripped. Built once per (registry) and cached
    on the registry object — instrument sites pay one dict lookup."""

    def __init__(self, registry: MetricsRegistry):
        for spec in METRIC_SPECS:
            fam = registry.from_spec(spec)
            if not spec.labels:
                fam.labels()  # expose at 0 before first use (scrape-friendly)
            setattr(self, spec.name[len("bigdl_"):], fam)


def instruments(registry: MetricsRegistry) -> _Instruments:
    """Get-or-build the catalogue's families on ``registry``."""
    ins = getattr(registry, "_bigdl_instruments", None)
    if ins is None:
        ins = _Instruments(registry)
        registry._bigdl_instruments = ins
    return ins
