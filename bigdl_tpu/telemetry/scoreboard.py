"""Automated serving scoreboard (ROADMAP #1's measurement half).

Drives a SEEDED Zipf mixed-length prompt workload against a live
``ContinuousLMServer`` per slot count (default slots ∈ {8, 16, 32}),
aggregates the serving SLO surface out of the telemetry registry —
tok/s, p50/p95 TTFT, per-token latency, compile counts from the PR-14
flight recorder, peak device memory — into a JSON artifact plus the
PERF.md markdown table, and diffs two artifacts with configurable
regression thresholds (nonzero exit = regression), so the scoreboard is
a CI gate and not just a report.

Three modes behind ``python -m bigdl_tpu.telemetry scoreboard`` /
``scripts/bigdl-tpu.sh scoreboard``:

- **run** (default): build a small LM (or the configured shape), run the
  workload per slot count against an in-process server, write the
  artifact (+ markdown with ``--markdown``);
- **scrape <url>**: snapshot an EXISTING server's ``/metrics`` into a
  one-row artifact (no jax, no model — operator-side);
- **diff <old> <new>**: compare artifacts row-by-row (matched on slots
  plus fleet shape — replicas and prefill:decode split) and exit 1 past
  the thresholds.

Workload determinism: prompt lengths are drawn from a Zipf-weighted
rank distribution over [lmin, lmax] and token ids uniformly from the
vocab, all under one ``random.Random(seed)`` — two runs of the same
config submit byte-identical prompts in the same order. Round 9 adds
``workload="shared-prefix"`` (Zipf draws over a small pool of long
shared templates + unique tails — the prefix-cache stress profile) and
the serving-mode levers ``prefix_cache``/``draft``/``spec_len``, with
``prefix_hit_rate``, ``spec_accept_rate`` and the hit/miss TTFT split
as new row columns.

jax-free at import (scrape/diff must run on a bare host); the run mode
lazy-imports the model/server stack.
"""

from __future__ import annotations

import json
import random
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["ScoreboardConfig", "zipf_lengths", "make_prompts",
           "shared_prefix_prompts", "run", "scrape", "render_markdown",
           "diff", "DEFAULT_THRESHOLDS", "quantile_from_snapshot"]

SCHEMA = 1
DEFAULT_SLOTS = (8, 16, 32)

#: Regression gates for ``diff`` (fractions of the OLD value; compiles
#: is an absolute count allowance). Loose enough for run-to-run noise on
#: a shared host, tight enough that an eviction storm or a lost kernel
#: cannot hide.
DEFAULT_THRESHOLDS: Dict[str, float] = {
    "tok_s_drop": 0.15,          # throughput may drop <= 15%
    "ttft_p50_rise": 0.30,
    "ttft_p95_rise": 0.30,
    "token_latency_rise": 0.30,
    "compiles_rise": 0,          # absolute extra programs allowed
    "peak_memory_rise": 0.10,
}


class ScoreboardConfig:
    """Workload + model shape for the run mode (defaults are sized to
    produce a meaningful mixed-length compile profile on one chip — or
    CPU — in minutes)."""

    def __init__(self, slots: Sequence[int] = DEFAULT_SLOTS,
                 requests: int = 48, clients: int = 8, seed: int = 0,
                 lmin: int = 4, lmax: int = 24, alpha: float = 1.1,
                 max_new: int = 16, decode_block: int = 4,
                 vocab: int = 256, embed: int = 32, heads: int = 2,
                 ffn: int = 64, layers: int = 2,
                 timeout: float = 600.0, prefill_mode: str = "chunked",
                 prefill_chunk: int = 16, workload: str = "zipf",
                 templates: int = 4, template_len: int = 48,
                 prefix_cache: bool = True, draft: bool = False,
                 spec_len: int = 4, replicas: int = 1,
                 disaggregate: Optional[str] = None):
        self.slots = [int(s) for s in slots]
        self.requests = int(requests)
        self.clients = max(1, int(clients))
        self.seed = int(seed)
        self.lmin, self.lmax = int(lmin), int(lmax)
        self.alpha = float(alpha)
        self.max_new = int(max_new)
        self.decode_block = int(decode_block)
        self.vocab = int(vocab)
        self.embed, self.heads = int(embed), int(heads)
        self.ffn, self.layers = int(ffn), int(layers)
        self.timeout = float(timeout)
        # chunked (default) vs bucketed prefill — the PR-15 O(1)-compile
        # modes; the chunk default is sized to the Zipf lmax so a toy
        # workload still exercises a multi-chunk prompt now and then
        self.prefill_mode = str(prefill_mode)
        self.prefill_chunk = int(prefill_chunk)
        # workload "zipf" (the legacy mixed-length draw) or
        # "shared-prefix": Zipf draws over a small pool of LONG shared
        # templates plus unique random tails — the prefix-cache stress
        # profile (real traffic: few system prompts, many continuations)
        if workload not in ("zipf", "shared-prefix"):
            raise ValueError(f"workload must be 'zipf' or 'shared-prefix',"
                             f" got {workload!r}")
        self.workload = workload
        self.templates = int(templates)
        self.template_len = int(template_len)
        # serving-mode levers under measurement: cross-request prefix
        # cache (chunked mode) and speculative decode. Draft modes:
        # "identical" = a same-seed copy of the target (the acceptance-
        # rate CEILING, 1.0 by construction), "int8" = a quantized twin
        # (self-speculation — the acceptance an actual deployment
        # pattern measures; ~0.95+ on the seeded workload). bool stays
        # accepted for compatibility (True == "identical").
        self.prefix_cache = bool(prefix_cache)
        if draft in (False, None, ""):
            self.draft = None
        elif draft in (True, "identical"):
            self.draft = "identical"
        elif draft == "int8":
            self.draft = "int8"
        else:
            raise ValueError(f"draft must be False, 'identical' or "
                             f"'int8', got {draft!r}")
        self.spec_len = int(spec_len)
        # round-12 fleet levers: replicas > 1 routes the workload over N
        # in-process servers via models.router.LMRouter; disaggregate
        # "P:D" splits admission prefill onto dedicated prefill replicas
        # shipping serialized state partitions to D decode replicas
        # (overrides replicas). Rows then carry replicas/split columns
        # and the diff gate keys on (slots, replicas, split).
        if disaggregate:
            from bigdl_tpu.resilience.serving_drill import parse_split
            p, d = parse_split(str(disaggregate))
            self.disaggregate = f"{p}:{d}"
            self.replicas = d
            self.prefill_replicas = p
        else:
            self.disaggregate = None
            self.replicas = max(1, int(replicas))
            self.prefill_replicas = 0
        if self.draft and (self.replicas > 1 or self.prefill_replicas):
            raise ValueError("draft does not compose with a fleet (state "
                             "handoff is incompatible with speculative "
                             "serving)")
        tpl = self.template_len if workload == "shared-prefix" else 0
        self.max_len = tpl + self.lmax + self.max_new + 8

    def workload_dict(self) -> dict:
        d = {"requests": self.requests, "clients": self.clients,
             "seed": self.seed, "workload": self.workload,
             "zipf": {"lmin": self.lmin, "lmax": self.lmax,
                      "alpha": self.alpha},
             "max_new": self.max_new,
             "prefill": {"mode": self.prefill_mode,
                         "chunk": self.prefill_chunk},
             "prefix_cache": self.prefix_cache,
             "model": {"vocab": self.vocab, "embed": self.embed,
                       "heads": self.heads, "ffn": self.ffn,
                       "layers": self.layers}}
        if self.workload == "shared-prefix":
            d["shared_prefix"] = {"templates": self.templates,
                                  "template_len": self.template_len}
        if self.draft:
            d["speculative"] = {"spec_len": self.spec_len,
                                "draft": ("identical-weights"
                                          if self.draft == "identical"
                                          else "int8-self")}
        if self.replicas > 1 or self.prefill_replicas:
            d["fleet"] = {"replicas": self.replicas,
                          "disaggregate": self.disaggregate}
        return d


def zipf_lengths(n: int, *, seed: int, lmin: int, lmax: int,
                 alpha: float = 1.1) -> List[int]:
    """``n`` prompt lengths: rank r of the shuffled [lmin, lmax] length
    set is drawn with probability ∝ r^-alpha — a few lengths dominate
    (real traffic), but the tail keeps minting NEW lengths (the compile-
    storm trigger the scoreboard exists to measure). Deterministic under
    ``seed``."""
    if lmax < lmin:
        raise ValueError(f"lmax {lmax} < lmin {lmin}")
    rng = random.Random(seed)
    lengths = list(range(lmin, lmax + 1))
    rng.shuffle(lengths)                 # rank -> length is seed-dependent
    weights = [1.0 / (r + 1) ** alpha for r in range(len(lengths))]
    return rng.choices(lengths, weights=weights, k=n)


def shared_prefix_prompts(cfg: ScoreboardConfig) -> List[List[int]]:
    """The prefix-cache stress workload: a Zipf-weighted draw over a
    SMALL pool of long shared templates, each request appending a unique
    random tail — the few-system-prompts/many-continuations shape real
    serving traffic has. Deterministic under the config seed; tail
    lengths reuse the Zipf length machinery over [lmin, lmax]."""
    rng = random.Random(cfg.seed + 2)
    pool = [[rng.randint(1, cfg.vocab) for _ in range(cfg.template_len)]
            for _ in range(max(1, cfg.templates))]
    ranks = list(range(len(pool)))
    weights = [1.0 / (r + 1) ** cfg.alpha for r in ranks]
    tails = zipf_lengths(cfg.requests, seed=cfg.seed + 3, lmin=cfg.lmin,
                         lmax=cfg.lmax, alpha=cfg.alpha)
    out = []
    for ln in tails:
        tpl = pool[rng.choices(ranks, weights=weights)[0]]
        out.append(tpl + [rng.randint(1, cfg.vocab) for _ in range(ln)])
    return out


def make_prompts(cfg: ScoreboardConfig) -> List[List[int]]:
    """The seeded workload: one 1-based id list per request."""
    if cfg.workload == "shared-prefix":
        return shared_prefix_prompts(cfg)
    rng = random.Random(cfg.seed + 1)
    out = []
    for ln in zipf_lengths(cfg.requests, seed=cfg.seed, lmin=cfg.lmin,
                           lmax=cfg.lmax, alpha=cfg.alpha):
        out.append([rng.randint(1, cfg.vocab) for _ in range(ln)])
    return out


def quantile_from_snapshot(snap: dict, q: float) -> Optional[float]:
    """Bucket-estimated quantile (upper bound of the bucket holding it)
    from a registry ``Histogram.snapshot()``; None on empty."""
    count = snap["count"]
    if not count:
        return None
    target = q * count
    for bound, cum in snap["buckets"]:
        if cum >= target:
            return float(bound)
    return float(snap["buckets"][-1][0]) if snap["buckets"] else None


def _build_model(cfg: ScoreboardConfig):
    from bigdl_tpu.models import transformer
    from bigdl_tpu.utils.rng import manual_seed
    manual_seed(cfg.seed + 17)
    return transformer.build_lm(cfg.vocab, cfg.embed, cfg.heads, cfg.ffn,
                                num_layers=cfg.layers, max_len=cfg.max_len,
                                rope=True, norm="rms")


def _drive_one(cfg: ScoreboardConfig, slots: int) -> dict:
    """One scoreboard row: a fresh model + server + PRIVATE registry (so
    compile counts and latency histograms belong to THIS run), the full
    seeded workload, aggregation from the registry."""
    from bigdl_tpu.models.serving import ContinuousLMServer
    from bigdl_tpu.telemetry import MetricsRegistry, instruments
    from bigdl_tpu.telemetry.profiling import sample_device_memory
    registry = MetricsRegistry()
    tm = instruments(registry)
    # the PJRT peak-bytes watermark is PROCESS-lifetime monotonic: a row
    # may only claim a peak it raised itself, else the slots=8 run's
    # high-water mark would be reported for every later row too
    peak_before = sample_device_memory(registry)
    model = _build_model(cfg)
    # "identical" draft (same seeded build) is the acceptance-rate
    # CEILING for the speculative machinery; "int8" is self-speculation
    # against a quantized twin — the acceptance a real deployment
    # pattern measures. Either way the row's headline is the verify-
    # dispatch economics at the measured acceptance, not a wall-clock
    # win: at toy scale no draft is cheaper than the target.
    draft = None
    if cfg.draft == "identical":
        draft = _build_model(cfg)
    elif cfg.draft == "int8":
        from bigdl_tpu.nn.quantized import quantize_model
        draft = quantize_model(_build_model(cfg))

    def mk_server(mdl, n_slots):
        return ContinuousLMServer(mdl, slots=n_slots, max_len=cfg.max_len,
                                  decode_block=cfg.decode_block, greedy=True,
                                  max_new_tokens=cfg.max_new,
                                  seed=cfg.seed, registry=registry,
                                  prefill_mode=cfg.prefill_mode,
                                  prefill_chunk=cfg.prefill_chunk,
                                  prefix_cache=cfg.prefix_cache,
                                  draft=draft, spec_len=cfg.spec_len)

    if cfg.replicas > 1 or cfg.prefill_replicas:
        # fleet row: each replica needs its own module instance (one
        # module cannot hold two decode states); same-seed rebuilds keep
        # the weights bit-identical, the handoff contract
        from bigdl_tpu.models.router import LMRouter
        decode = [mk_server(model if i == 0 else _build_model(cfg), slots)
                  for i in range(cfg.replicas)]
        prefill = [mk_server(_build_model(cfg), 1)
                   for _ in range(cfg.prefill_replicas)]
        server = LMRouter(decode, prefill_replicas=prefill,
                          registry=registry)
        prefix_enabled = decode[0].prefix_cache_enabled
    else:
        server = mk_server(model, slots)
        prefix_enabled = server.prefix_cache_enabled
    prompts = make_prompts(cfg)
    errors: List[str] = []
    lock = threading.Lock()
    cursor = {"i": 0}

    def client():
        while True:
            with lock:
                i = cursor["i"]
                if i >= len(prompts):
                    return
                cursor["i"] = i + 1
            try:
                server.submit(prompts[i], max_new_tokens=cfg.max_new,
                              timeout=cfg.timeout)
            except Exception as e:      # noqa: BLE001 — a failed request
                # is a row-level fact, not a scoreboard crash
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

    t0 = time.perf_counter()
    try:
        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(min(cfg.clients, len(prompts)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
    finally:
        server.close()

    ttft = tm.serving_ttft_seconds.labels().snapshot()
    tok = tm.serving_token_latency_seconds.labels().snapshot()
    compiles = sum(child.value
                   for _, child in tm.compiles_total.children())
    evictions = sum(child.value
                    for _, child in
                    tm.compile_cache_evictions_total.children())
    compile_seconds = sum(
        child.sum for _, child in tm.compile_seconds.children())
    peak_mem = tm.device_memory_peak_bytes.value or None
    if peak_mem is not None and peak_before is not None \
            and peak_mem <= peak_before:
        peak_mem = None     # watermark set by an EARLIER row: unknown here
    tokens = tm.serving_tokens_total.value
    # round-9 serving modes: hit rate counts ADMISSIONS (one verdict per
    # prefill), accept rate counts DRAFT tokens (the target's bonus token
    # is excluded on both sides of the ratio)
    p_hits = tm.prefix_cache_hits.value
    p_miss = tm.prefix_cache_misses.value
    hit_rate = (round(p_hits / (p_hits + p_miss), 3)
                if prefix_enabled and (p_hits + p_miss)
                else None)
    proposed = tm.spec_proposed_tokens_total.value
    accepted = tm.spec_accepted_tokens_total.value
    accept_rate = (round(accepted / proposed, 3)
                   if cfg.draft and proposed else None)
    ttft_hit = tm.serving_ttft_hit_seconds.labels().snapshot()
    ttft_miss = tm.serving_ttft_miss_seconds.labels().snapshot()
    return {
        "slots": slots,
        "replicas": cfg.replicas,
        "split": cfg.disaggregate,
        "prefill_mode": cfg.prefill_mode,
        "requests": len(prompts),
        "failed": len(errors),
        "wall_s": round(wall, 3),
        "tok_s": round(tokens / wall, 2) if wall > 0 else 0.0,
        "ttft_p50_s": quantile_from_snapshot(ttft, 0.5),
        "ttft_p95_s": quantile_from_snapshot(ttft, 0.95),
        "ttft_hit_p50_s": quantile_from_snapshot(ttft_hit, 0.5),
        "ttft_miss_p50_s": quantile_from_snapshot(ttft_miss, 0.5),
        "token_latency_s": (round(tok["sum"] / tok["count"], 6)
                            if tok["count"] else None),
        "prefix_hit_rate": hit_rate,
        "spec_accept_rate": accept_rate,
        "compiles": int(compiles),
        "compile_seconds": round(compile_seconds, 3),
        "cache_evictions": int(evictions),
        "peak_memory_bytes": (int(peak_mem)
                              if peak_mem is not None else None),
        "errors": errors[:5],
    }


def run(cfg: ScoreboardConfig) -> dict:
    """The full artifact: one row per configured slot count."""
    import jax
    backend = jax.default_backend()
    rows = [_drive_one(cfg, s) for s in cfg.slots]
    return {"schema": SCHEMA, "kind": "bigdl_tpu_serving_scoreboard",
            "backend": backend, "workload": cfg.workload_dict(),
            "rows": rows}


# ---------------------------------------------------------------------------
# Scrape mode: one row out of a live /metrics endpoint
# ---------------------------------------------------------------------------

def _parse_prometheus(text: str) -> Tuple[Dict[str, float],
                                          Dict[str, dict]]:
    """Minimal parser for OUR exposition: plain and labeled samples sum
    into ``values[name]``; ``_bucket``/``_sum``/``_count`` triples build
    ``hists[name]`` snapshots shaped like ``Histogram.snapshot()``.

    A LABELED family exposes one series per label set
    (``bigdl_compile_seconds_sum{site="serving.prefill"}`` next to
    ``{site="serving.step"}``); everything ACCUMULATES across label
    sets — sums, counts, and per-bound bucket counts (all children of
    one family share the same bounds, and a sum of cumulative counts is
    the cumulative count of the merged distribution)."""
    values: Dict[str, float] = {}
    buckets: Dict[str, Dict[float, float]] = {}
    hists: Dict[str, dict] = {}
    sample = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+([0-9.eE+-]+|NaN)$")
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = sample.match(line.strip())
        if not m:
            continue
        name, labels, val = m.group(1), m.group(2) or "", float(m.group(3))
        if name.endswith("_bucket"):
            base = name[:-len("_bucket")]
            le = re.search(r'le="([^"]+)"', labels)
            if le:
                bound = (float("inf") if le.group(1) == "+Inf"
                         else float(le.group(1)))
                by_bound = buckets.setdefault(base, {})
                by_bound[bound] = by_bound.get(bound, 0.0) + val
            continue
        if name.endswith("_sum"):
            h = hists.setdefault(name[:-4], {})
            h["sum"] = h.get("sum", 0.0) + val
            continue
        if name.endswith("_count"):
            h = hists.setdefault(name[:-6], {})
            h["count"] = h.get("count", 0) + int(val)
            continue
        values[name] = values.get(name, 0.0) + val
    for base, by_bound in buckets.items():
        h = hists.setdefault(base, {})
        h["buckets"] = sorted((b, c) for b, c in by_bound.items()
                              if b != float("inf"))
        h["inf"] = by_bound.get(float("inf"), h.get("count", 0))
        h.setdefault("count", int(h["inf"]))
        h.setdefault("sum", 0.0)
    return values, hists


def scrape(url: str, timeout: float = 5.0) -> dict:
    """One-row artifact from a LIVE server's /metrics (operator mode: no
    jax, no model — whatever the server accumulated since boot)."""
    import urllib.request
    if "://" not in url:
        url = "http://" + url
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        text = resp.read().decode("utf-8", errors="replace")
    values, hists = _parse_prometheus(text)
    empty = {"buckets": [], "count": 0, "sum": 0.0, "inf": 0}
    ttft = hists.get("bigdl_serving_ttft_seconds", empty)
    tok = hists.get("bigdl_serving_token_latency_seconds", empty)
    ttft_hit = hists.get("bigdl_serving_ttft_hit_seconds", empty)
    ttft_miss = hists.get("bigdl_serving_ttft_miss_seconds", empty)
    peak = values.get("bigdl_device_memory_peak_bytes")
    p_hits = values.get("bigdl_prefix_cache_hits", 0.0)
    p_miss = values.get("bigdl_prefix_cache_misses", 0.0)
    proposed = values.get("bigdl_spec_proposed_tokens_total", 0.0)
    accepted = values.get("bigdl_spec_accepted_tokens_total", 0.0)
    row = {
        "slots": int(values.get("bigdl_serving_slots_total", 0)),
        "prefill_mode": None,       # not exposed by /metrics; unknown
        "requests": int(values.get(
            "bigdl_serving_requests_completed_total", 0)),
        "failed": int(values.get("bigdl_serving_request_errors_total", 0)),
        "wall_s": None,              # a scrape has no workload wall-clock
        "tok_s": None,
        "tokens": int(values.get("bigdl_serving_tokens_total", 0)),
        "ttft_p50_s": quantile_from_snapshot(ttft, 0.5),
        "ttft_p95_s": quantile_from_snapshot(ttft, 0.95),
        "ttft_hit_p50_s": quantile_from_snapshot(ttft_hit, 0.5),
        "ttft_miss_p50_s": quantile_from_snapshot(ttft_miss, 0.5),
        "token_latency_s": (round(tok["sum"] / tok["count"], 6)
                            if tok.get("count") else None),
        "prefix_hit_rate": (round(p_hits / (p_hits + p_miss), 3)
                            if (p_hits + p_miss) else None),
        "spec_accept_rate": (round(accepted / proposed, 3)
                             if proposed else None),
        "compiles": int(values.get("bigdl_compiles_total", 0)),
        "compile_seconds": round(
            hists.get("bigdl_compile_seconds", {}).get("sum", 0.0), 3),
        "cache_evictions": int(values.get(
            "bigdl_compile_cache_evictions_total", 0)),
        "peak_memory_bytes": int(peak) if peak else None,
        "errors": [],
    }
    return {"schema": SCHEMA, "kind": "bigdl_tpu_serving_scoreboard",
            "backend": "scrape", "workload": {"source": url},
            "rows": [row]}


# ---------------------------------------------------------------------------
# Rendering + diff
# ---------------------------------------------------------------------------

def _fmt_ms(v: Optional[float]) -> str:
    return "—" if v is None else f"{v * 1e3:.1f}"


def _fmt_mem(v: Optional[float]) -> str:
    return "—" if not v else f"{v / (1 << 20):.1f}"


def _fmt_rate(v: Optional[float]) -> str:
    return "—" if v is None else f"{v:.2f}"


def render_markdown(artifact: dict) -> str:
    """The PERF.md serving-scoreboard table. The round-9 serving-mode
    columns (prefix hit rate + hit/miss TTFT split, speculative accept
    rate) render only when some row carries them, so pre-round-9
    artifacts keep their exact historical table shape."""
    rows = artifact.get("rows", [])
    with_prefix = any(r.get("prefix_hit_rate") is not None or
                      r.get("ttft_hit_p50_s") is not None for r in rows)
    with_spec = any(r.get("spec_accept_rate") is not None for r in rows)
    with_fleet = any((r.get("replicas") or 1) != 1 or r.get("split")
                     for r in rows)
    w = artifact.get("workload", {})
    z = w.get("zipf", {})
    head = "| slots |"
    rule = "|------:|"
    if with_fleet:
        head += " replicas | split |"
        rule += "---------:|:------|"
    head += (" prefill | tok/s | TTFT p50 (ms) | TTFT p95 (ms) |"
             " per-token (ms) |")
    rule += (":--------|------:|--------------:|--------------:|"
             "---------------:|")
    if with_prefix:
        head += " hit rate | TTFT hit p50 (ms) | TTFT miss p50 (ms) |"
        rule += "---------:|------------------:|-------------------:|"
    if with_spec:
        head += " accept |"
        rule += "-------:|"
    head += (" compiles | compile s | evictions | peak mem (MiB) |")
    rule += ("---------:|----------:|----------:|---------------:|")
    lines = [head, rule]
    for r in rows:
        tok_s = r.get("tok_s")
        cells = [f"{r.get('slots', '?')}"]
        if with_fleet:
            cells += [f"{r.get('replicas') or 1}",
                      f"{r.get('split') or '—'}"]
        cells += [
            f"{r.get('prefill_mode') or '—'}",
            f"{tok_s if tok_s is not None else '—'}",
            _fmt_ms(r.get("ttft_p50_s")),
            _fmt_ms(r.get("ttft_p95_s")),
            _fmt_ms(r.get("token_latency_s")),
        ]
        if with_prefix:
            cells += [_fmt_rate(r.get("prefix_hit_rate")),
                      _fmt_ms(r.get("ttft_hit_p50_s")),
                      _fmt_ms(r.get("ttft_miss_p50_s"))]
        if with_spec:
            cells.append(_fmt_rate(r.get("spec_accept_rate")))
        cells += [f"{r.get('compiles', '—')}",
                  f"{r.get('compile_seconds', '—')}",
                  f"{r.get('cache_evictions', '—')}",
                  _fmt_mem(r.get("peak_memory_bytes"))]
        lines.append("| " + " | ".join(cells) + " |")
    meta = (f"backend={artifact.get('backend', '?')}, "
            f"requests={w.get('requests', '?')}/slot-count, "
            f"Zipf({z.get('alpha', '?')}) prompt lengths "
            f"[{z.get('lmin', '?')}, {z.get('lmax', '?')}], "
            f"seed={w.get('seed', '?')}")
    if w.get("workload") == "shared-prefix":
        sp = w.get("shared_prefix", {})
        meta += (f", shared-prefix {sp.get('templates', '?')} templates × "
                 f"{sp.get('template_len', '?')} tokens")
    if w.get("speculative"):
        meta += (f", speculative k={w['speculative'].get('spec_len', '?')}"
                 f" ({w['speculative'].get('draft', '?')} draft)")
    fl = w.get("fleet") or {}
    if fl.get("replicas"):
        meta += f", fleet replicas={fl['replicas']}"
        if fl.get("disaggregate"):
            meta += f" disaggregated {fl['disaggregate']} prefill:decode"
    lines.append("")
    lines.append(f"<small>{meta}</small>")
    return "\n".join(lines)


def _row_key(r: dict) -> tuple:
    """Diff identity of a row: fleet shape included, with pre-round-12
    artifacts (no replicas/split keys) reading as single-replica rows."""
    return (r.get("slots"), r.get("replicas") or 1, r.get("split") or None)


def _row_tag(r: dict) -> str:
    tag = f"slots={r.get('slots')}"
    if (r.get("replicas") or 1) != 1:
        tag += f",replicas={r.get('replicas')}"
    if r.get("split"):
        tag += f",split={r.get('split')}"
    return tag


def _rise(old: Optional[float], new: Optional[float]) -> Optional[float]:
    if old is None or new is None or old <= 0:
        return None
    return (new - old) / old


def diff(old: dict, new: dict,
         thresholds: Optional[Dict[str, float]] = None) -> List[str]:
    """Row-by-row (matched on slots) regression check. Returns human-
    readable regression messages — empty means the gate passes. Metrics
    absent on either side (CPU peak memory, scrape tok/s) are skipped:
    the gate never fails on missing data, only on measured regressions."""
    th = dict(DEFAULT_THRESHOLDS)
    th.update(thresholds or {})
    by_key = {_row_key(r): r for r in old.get("rows", [])}
    out: List[str] = []
    for nr in new.get("rows", []):
        orow = by_key.get(_row_key(nr))
        if orow is None:
            continue           # new slot count / fleet shape: no gate yet
        tag = _row_tag(nr)
        o_tok, n_tok = orow.get("tok_s"), nr.get("tok_s")
        if o_tok and n_tok is not None and \
                n_tok < o_tok * (1 - th["tok_s_drop"]):
            out.append(f"{tag}: tok/s {o_tok} -> {n_tok} "
                       f"(drop > {th['tok_s_drop']:.0%})")
        for key, thr in (("ttft_p50_s", "ttft_p50_rise"),
                         ("ttft_p95_s", "ttft_p95_rise"),
                         ("token_latency_s", "token_latency_rise")):
            r = _rise(orow.get(key), nr.get(key))
            if r is not None and r > th[thr]:
                out.append(f"{tag}: {key} {orow[key]} -> {nr[key]} "
                           f"(rise > {th[thr]:.0%})")
        o_c, n_c = orow.get("compiles"), nr.get("compiles")
        if o_c is not None and n_c is not None and \
                n_c > o_c + th["compiles_rise"]:
            out.append(f"{tag}: compiles {o_c} -> {n_c} "
                       f"(allowed +{int(th['compiles_rise'])})")
        r = _rise(orow.get("peak_memory_bytes"), nr.get("peak_memory_bytes"))
        if r is not None and r > th["peak_memory_rise"]:
            out.append(f"{tag}: peak_memory_bytes "
                       f"{orow['peak_memory_bytes']} -> "
                       f"{nr['peak_memory_bytes']} "
                       f"(rise > {th['peak_memory_rise']:.0%})")
    new_keys = {_row_key(r) for r in new.get("rows", [])}
    for key, orow in by_key.items():
        if key not in new_keys:
            out.append(f"{_row_tag(orow)}: row present in old artifact "
                       "but missing from new")
    return out


def load_artifact(path: str) -> dict:
    with open(path) as f:
        obj = json.load(f)
    if obj.get("kind") != "bigdl_tpu_serving_scoreboard":
        raise ValueError(f"{path} is not a scoreboard artifact")
    return obj
