"""Metric exposition: Prometheus text format 0.0.4 and JSON.

Both render from ``MetricsRegistry.collect()`` — one snapshot, two
serializations — so a scrape never observes two formats disagreeing.
``GET /metrics`` on the serving HTTP rim (``models/lm_server.py
make_http_server``) serves the Prometheus form; the JSON form embeds in
BENCH snapshots and drives ``python -m bigdl_tpu.telemetry metrics
--format json``.
"""

from __future__ import annotations

import json
from typing import Optional

from bigdl_tpu.telemetry.registry import MetricsRegistry, get_registry

__all__ = ["render_prometheus", "render_json", "PROMETHEUS_CONTENT_TYPE"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt(v: float) -> str:
    # integers print bare (Prometheus idiom: counters are usually whole);
    # floats print via repr for round-trip fidelity
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_str(labels: dict, extra: Optional[dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in items.items())
    return "{" + inner + "}"


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Text exposition format 0.0.4 (scrapeable by Prometheus, readable
    over curl). Histograms expose cumulative ``_bucket{le=...}`` series
    plus ``_sum``/``_count`` (the +Inf bucket equals count by
    construction — taken from one locked snapshot)."""
    reg = registry if registry is not None else get_registry()
    lines = []
    for fam in reg.collect():
        lines.append(f"# HELP {fam['name']} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {fam['name']} {fam['kind']}")
        for sample in fam["samples"]:
            labels = sample["labels"]
            if fam["kind"] == "histogram":
                h = sample["histogram"]
                for bound, cum in h["buckets"]:
                    lines.append(
                        f"{fam['name']}_bucket"
                        f"{_labels_str(labels, {'le': _fmt(bound)})} {cum}")
                lines.append(f"{fam['name']}_bucket"
                             f"{_labels_str(labels, {'le': '+Inf'})} "
                             f"{h['inf']}")
                lines.append(f"{fam['name']}_sum{_labels_str(labels)} "
                             f"{_fmt(h['sum'])}")
                lines.append(f"{fam['name']}_count{_labels_str(labels)} "
                             f"{h['count']}")
            else:
                lines.append(f"{fam['name']}{_labels_str(labels)} "
                             f"{_fmt(sample['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_json(registry: Optional[MetricsRegistry] = None, *,
                indent: Optional[int] = None) -> str:
    """JSON exposition: ``{"metrics": [collect() entries]}``."""
    reg = registry if registry is not None else get_registry()
    return json.dumps({"metrics": reg.collect()}, indent=indent)
