"""Thread-safe metrics registry: labeled counters, gauges, fixed-bucket
histograms (reference ``optim/Metrics.scala:31`` driver-aggregated
accumulators, generalized into the Prometheus data model).

The reference's Metrics class is a bag of named driver-side accumulators
that exists only for the training loop's debug summary; a serving system
needs the operator trio — counters (monotonic totals), gauges (current
level) and histograms (latency distributions) — scrapeable while the
process runs. One registry instance per process is the norm
(``get_registry()``); private instances exist for tests and for callers
that need isolation (``MetricsRegistry()``).

Concurrency contract: every child mutation takes that child's lock, so
counters observed by a scraper thread are monotonic and histogram
(bucket, sum, count) triples are never torn. Family/child creation takes
the registry lock; creation is idempotent (same name + same shape returns
the existing family) and shape conflicts raise at the second
registration site, not at scrape time.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

__all__ = ["MetricSpec", "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "CounterFamily", "GaugeFamily", "HistogramFamily",
           "get_registry", "set_registry", "DEFAULT_LATENCY_BUCKETS"]

# Latency-shaped default buckets (seconds): sub-ms serving steps through
# multi-second compiles. Fixed at family creation — fixed buckets keep
# ``observe`` O(#buckets) with no rebalancing and make cross-scrape deltas
# meaningful.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0)


class MetricSpec(NamedTuple):
    """Declarative description of one family (see ``catalogue.py`` for the
    well-known inventory; ``MetricsRegistry.from_spec`` instantiates)."""
    name: str
    kind: str                              # "counter" | "gauge" | "histogram"
    help: str
    labels: Tuple[str, ...] = ()
    buckets: Optional[Tuple[float, ...]] = None   # histograms only


def _check_name(name: str) -> None:
    if not name or not all(c.isalnum() or c in "_:" for c in name):
        raise ValueError(f"invalid metric name {name!r} (use "
                         "[a-zA-Z0-9_:] only)")


class _Child:
    """One labeled time series. Subclasses define the mutation surface;
    all of them guard state with ``self._lock`` so concurrent writers and
    a scraper thread never tear a read."""

    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class Counter(_Child):
    """Monotonic total. ``inc`` rejects negative amounts — a counter that
    can go down is a gauge, and monotonicity is what lets a scraper
    compute rates across restarts-free windows."""

    __slots__ = ("_value",)

    def __init__(self):
        super().__init__()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Child):
    """Current level; settable both ways."""

    __slots__ = ("_value",)

    def __init__(self):
        super().__init__()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Child):
    """Fixed-bucket distribution: per-bucket counts + sum + count.

    ``snapshot()`` returns CUMULATIVE bucket counts keyed by upper bound
    (Prometheus ``le`` semantics, +Inf last == count), taken under the
    lock so (buckets, sum, count) always agree with each other.
    """

    __slots__ = ("_bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds: Sequence[float]):
        super().__init__()
        bounds = tuple(float(b) for b in bounds)
        if not bounds or list(bounds) != sorted(bounds):
            raise ValueError(f"histogram buckets must be a sorted non-empty "
                             f"sequence, got {bounds}")
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)   # final slot = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        # C bisect, not an interpreted scan: observe sits on the serving
        # decode loop, and the scan costs ~1µs/call at 15 buckets
        i = bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict:
        with self._lock:
            raw = list(self._counts)
            total, s = self._count, self._sum
        cum, acc = [], 0
        for c in raw:
            acc += c
            cum.append(acc)
        return {"buckets": list(zip(self._bounds, cum[:-1])),
                "inf": cum[-1], "sum": s, "count": total}

    def summary(self) -> dict:
        """Bucket-estimated quantiles for humans/JSON embedding (BENCH
        snapshots): count, sum, mean, p50/p90/p99 (upper bound of the
        bucket holding the quantile; +Inf reported as the last bound)."""
        snap = self.snapshot()
        count = snap["count"]
        out = {"count": count, "sum": round(snap["sum"], 6),
               "mean": round(snap["sum"] / count, 6) if count else 0.0}
        for q in (0.5, 0.9, 0.99):
            target, est = q * count, None
            for bound, cum in snap["buckets"]:
                if cum >= target and count:
                    est = bound
                    break
            if est is None:
                est = self._bounds[-1] if count else 0.0
            out[f"p{int(q * 100)}"] = est
        return out


class _Family:
    """A named metric with a fixed label schema; children per label-value
    tuple. With an empty schema the family proxies its single child, so
    ``registry.counter("x", "...").inc()`` works without ``.labels()``."""

    kind = ""
    _child_cls = _Child

    def __init__(self, name: str, help: str, labels: Tuple[str, ...]):
        self.name = name
        self.help = help
        self.label_names = labels
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()
        self._solo_child: Optional[_Child] = None  # label-less fast path

    def _new_child(self):
        return self._child_cls()

    def labels(self, **labelvalues) -> _Child:
        if tuple(sorted(labelvalues)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got "
                f"{tuple(sorted(labelvalues))}")
        key = tuple(str(labelvalues[k]) for k in self.label_names)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def _solo(self) -> _Child:
        # hot-path shortcut: family-level ops on a label-less family skip
        # the labels() schema check (it costs ~2µs of dict/sort work per
        # call — the difference between "free" and "shows up in a decode
        # block" on the serving loop)
        child = self._solo_child
        if child is not None:
            return child
        if self.label_names:
            raise ValueError(f"{self.name} has labels {self.label_names}; "
                             "address a child via .labels(...)")
        child = self.labels()
        self._solo_child = child
        return child

    def remove(self, **labelvalues) -> None:
        """Drop one labeled child (no-op if absent) — the lifecycle hook
        for per-instance scopes (``optim.Metrics``) so a long-lived
        process's scrape does not accumulate dead series forever."""
        key = tuple(str(labelvalues.get(k, "")) for k in self.label_names)
        with self._lock:
            self._children.pop(key, None)
            if not self.label_names:
                self._solo_child = None

    def children(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())


class CounterFamily(_Family):
    kind = "counter"
    _child_cls = Counter

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    @property
    def value(self) -> float:
        return self._solo().value


class GaugeFamily(_Family):
    kind = "gauge"
    _child_cls = Gauge

    def set(self, value: float) -> None:
        self._solo().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    @property
    def value(self) -> float:
        return self._solo().value


class HistogramFamily(_Family):
    kind = "histogram"
    _child_cls = Histogram

    def __init__(self, name, help, labels, buckets):
        super().__init__(name, help, labels)
        self.buckets = tuple(buckets)

    def _new_child(self):
        return Histogram(self.buckets)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    def summary(self) -> dict:
        return self._solo().summary()


class MetricsRegistry:
    """Name -> family map; creation idempotent, shape conflicts raise."""

    def __init__(self):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labels, **extra):
        _check_name(name)
        labels = tuple(labels)
        for ln in labels:
            _check_name(ln)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or fam.label_names != labels:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names}, cannot re-register "
                        f"as {cls.kind}{labels}")
                if (isinstance(fam, HistogramFamily) and "buckets" in extra
                        and tuple(extra["buckets"]) != fam.buckets):
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam.buckets}")
                return fam
            fam = cls(name, help, labels, **extra)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> CounterFamily:
        return self._get_or_create(CounterFamily, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> GaugeFamily:
        return self._get_or_create(GaugeFamily, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> HistogramFamily:
        return self._get_or_create(HistogramFamily, name, help, labels,
                                   buckets=tuple(buckets))

    def from_spec(self, spec: MetricSpec) -> _Family:
        if spec.kind == "counter":
            return self.counter(spec.name, spec.help, spec.labels)
        if spec.kind == "gauge":
            return self.gauge(spec.name, spec.help, spec.labels)
        if spec.kind == "histogram":
            return self.histogram(spec.name, spec.help, spec.labels,
                                  spec.buckets or DEFAULT_LATENCY_BUCKETS)
        raise ValueError(f"unknown metric kind {spec.kind!r}")

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def collect(self) -> List[dict]:
        """Plain-data snapshot: the one structure both exposition formats
        render from (``exposition.py``)."""
        out = []
        for fam in self.families():
            samples = []
            for labelvalues, child in fam.children():
                labels = dict(zip(fam.label_names, labelvalues))
                if isinstance(child, Histogram):
                    samples.append({"labels": labels,
                                    "histogram": child.snapshot()})
                else:
                    samples.append({"labels": labels, "value": child.value})
            out.append({"name": fam.name, "kind": fam.kind,
                        "help": fam.help,
                        "label_names": list(fam.label_names),
                        "samples": samples})
        return out


_global_registry = MetricsRegistry()
_global_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-global registry every default instrument writes to —
    one scrape covers serving + training + eval in one place."""
    return _global_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests); returns the previous."""
    global _global_registry
    with _global_lock:
        prev, _global_registry = _global_registry, registry
    return prev
