"""Low-overhead span tracer: ``span("name")`` -> bounded ring buffer ->
Chrome ``trace_event`` JSON (loads in ``chrome://tracing`` / Perfetto).

The host-side counterpart of ``jax.profiler`` device traces
(``Optimizer.set_profiling``): the profiler answers "what did the chip
do inside one program", this answers "where did the HOST spend a request
or a training step" — batcher waits, prefill vs decode blocks, data wait
vs dispatch vs sync — across threads, cheap enough to leave compiled in.

Disabled is the default and the whole cost: ``span()`` checks one
module-global flag and returns a shared no-op context manager — no
allocation, no clock read, nothing appended. Enable for a window with
``enable()`` (or process-wide via ``BIGDL_TPU_TRACE=/path.json``, dumped
at exit), then ``dump()``/``to_chrome_trace()``. The buffer is a
``deque(maxlen=capacity)``: a forgotten-enabled tracer costs bounded
memory and keeps the newest events, matching how operators actually use
a flight recorder.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

__all__ = ["span", "enable", "disable", "is_enabled", "clear", "events",
           "to_chrome_trace", "dump", "set_capacity", "capacity",
           "async_begin", "async_instant", "async_end", "complete_event",
           "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 65536

_enabled = False
_lock = threading.Lock()
_buffer: deque = deque(maxlen=DEFAULT_CAPACITY)
# perf_counter origin for µs timestamps: monotonic, shared by every
# thread, zeroed at import so traces start near t=0
_T0 = time.perf_counter()


def is_enabled() -> bool:
    return _enabled


def enable(capacity: Optional[int] = None) -> None:
    """Turn the tracer on (optionally resizing the ring buffer; existing
    events carry over, newest-first retention)."""
    global _enabled
    if capacity is not None:
        set_capacity(capacity)
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def set_capacity(capacity: int) -> None:
    global _buffer
    if int(capacity) < 1:
        raise ValueError(f"trace capacity must be >= 1, got {capacity}")
    with _lock:
        _buffer = deque(_buffer, maxlen=int(capacity))


def capacity() -> int:
    return _buffer.maxlen or DEFAULT_CAPACITY


def clear() -> None:
    with _lock:
        _buffer.clear()


def events() -> List[dict]:
    """Snapshot of buffered events (oldest first)."""
    with _lock:
        return list(_buffer)


class _NoopSpan:
    """The disabled path: one shared, stateless, reentrant instance."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **kwargs) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_name", "_cat", "_args", "_t0")

    def __init__(self, name: str, cat: str, args: dict):
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def annotate(self, **kwargs) -> None:
        """Attach key/values mid-span (they land in the event's args)."""
        self._args.update(kwargs)

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        ev = {"name": self._name, "cat": self._cat, "ph": "X",
              "ts": (self._t0 - _T0) * 1e6, "dur": (t1 - self._t0) * 1e6,
              "pid": os.getpid(), "tid": threading.get_ident()}
        if exc_type is not None:
            self._args["error"] = exc_type.__name__
        if self._args:
            ev["args"] = self._args
        with _lock:
            _buffer.append(ev)
        return False


def span(name: str, cat: str = "bigdl", **args):
    """Context manager timing one named region.

    Disabled (the default): a single branch returning the shared no-op —
    safe on the hottest host paths. Enabled: records a Chrome
    ``trace_event`` complete event ("ph": "X") with µs timestamps, the
    thread id, and any keyword args."""
    if not _enabled:
        return _NOOP
    return _Span(name, cat, dict(args))


def _async_event(ph: str, name: str, id: int, cat: str, args: dict) -> None:
    ev = {"name": name, "cat": cat, "ph": ph, "id": int(id),
          "ts": (time.perf_counter() - _T0) * 1e6,
          "pid": os.getpid(), "tid": threading.get_ident()}
    if args:
        ev["args"] = args
    with _lock:
        _buffer.append(ev)


def async_begin(name: str, id: int, cat: str = "bigdl", **args) -> None:
    """Open a Chrome async phase (``ph: "b"``) under ``id``. Async events
    sharing (cat, id, name) render as one lifecycle lane in Perfetto —
    the per-request linkage the serving engines use: every phase of one
    request carries the same id, so a single trace dump reconstructs its
    submit -> queue -> admit -> decode -> complete journey."""
    if _enabled:
        _async_event("b", name, id, cat, args)


def async_instant(name: str, id: int, cat: str = "bigdl", **args) -> None:
    """Mark a point inside an open async phase (``ph: "n"``)."""
    if _enabled:
        _async_event("n", name, id, cat, args)


def async_end(name: str, id: int, cat: str = "bigdl", **args) -> None:
    """Close the async phase opened by ``async_begin`` with the same
    (cat, id, name)."""
    if _enabled:
        _async_event("e", name, id, cat, args)


def complete_event(name: str, t0: float, t1: float, cat: str = "bigdl",
                   **args) -> None:
    """Record an X event for an ALREADY-elapsed [t0, t1] window
    (``time.perf_counter()`` values) — e.g. a request's queue wait, whose
    start happened on another thread before anyone knew how long it would
    be. ``span()`` covers the with-block case; this covers retrodiction."""
    if not _enabled:
        return
    ev = {"name": name, "cat": cat, "ph": "X", "ts": (t0 - _T0) * 1e6,
          "dur": max(0.0, (t1 - t0) * 1e6),
          "pid": os.getpid(), "tid": threading.get_ident()}
    if args:
        ev["args"] = args
    with _lock:
        _buffer.append(ev)


def to_chrome_trace() -> dict:
    """The buffered events as a Chrome trace_event JSON object — load the
    dumped file in chrome://tracing or https://ui.perfetto.dev."""
    return {"traceEvents": events(), "displayTimeUnit": "ms"}


def dump(path: str) -> str:
    """Write the Chrome trace JSON to ``path``; returns the path."""
    with open(path, "w") as f:
        json.dump(to_chrome_trace(), f)
    return path


# BIGDL_TPU_TRACE=/path.json: process-wide flight recorder — enable at
# import, dump on interpreter exit (operator lever documented in
# docs/OBSERVABILITY.md; the launcher forwards the variable untouched).
_env_path = os.environ.get("BIGDL_TPU_TRACE", "")
if _env_path:
    enable()
    atexit.register(dump, _env_path)
