"""bigdl_tpu.telemetry — unified runtime observability.

One subsystem answers the operator's first three questions (what is my
TTFT, where does a training step spend its wall-clock, is the queue
backing up) instead of per-module ad-hoc counters:

- ``registry``: thread-safe labeled counters / gauges / fixed-bucket
  histograms (``get_registry()`` is the process-global instance);
- ``exposition``: Prometheus text 0.0.4 + JSON, served as ``GET
  /metrics`` by the serving HTTP rim (``models/lm_server.py``);
- ``tracing``: ``span("name")`` -> bounded ring buffer -> Chrome
  ``trace_event`` JSON, disabled-by-default at one-branch cost;
- ``catalogue``: the well-known metric/span inventory every instrumented
  subsystem builds from (rendered into ``docs/API.md``);
- ``profiling``: the compile flight recorder — ``tracked_jit(site=...)``
  records one event (wall seconds + cost/memory analysis) per program
  compilation at every adopted jit site;
- ``scoreboard``: the automated serving scoreboard (seeded Zipf workload
  driver, /metrics scrape, markdown table, regression diff).

jax-free by design: importable from the bench orchestrator, the CLI
(``python -m bigdl_tpu.telemetry``) and the launcher subcommands
(``scripts/bigdl-tpu.sh metrics|trace|scoreboard``) without touching a
backend (``profiling``/``scoreboard`` lazy-import jax only when a
program is actually wrapped / a workload actually driven).
Guide: ``docs/OBSERVABILITY.md``.
"""

from bigdl_tpu.telemetry.registry import (Counter, CounterFamily, Gauge,
                                          GaugeFamily, Histogram,
                                          HistogramFamily, MetricSpec,
                                          MetricsRegistry,
                                          DEFAULT_LATENCY_BUCKETS,
                                          get_registry, set_registry)
from bigdl_tpu.telemetry.exposition import (PROMETHEUS_CONTENT_TYPE,
                                            render_json, render_prometheus)
from bigdl_tpu.telemetry import profiling, scoreboard, tracing
from bigdl_tpu.telemetry.tracing import span
from bigdl_tpu.telemetry.catalogue import (METRIC_SPECS, SPAN_SPECS,
                                           instruments)
from bigdl_tpu.telemetry.profiling import (CompileEvent, TrackedJit,
                                           peak_flops,
                                           sample_device_memory,
                                           tracked_jit)

__all__ = [
    "MetricsRegistry", "MetricSpec", "Counter", "Gauge", "Histogram",
    "CounterFamily", "GaugeFamily", "HistogramFamily",
    "DEFAULT_LATENCY_BUCKETS", "get_registry", "set_registry",
    "render_prometheus", "render_json", "PROMETHEUS_CONTENT_TYPE",
    "tracing", "span", "METRIC_SPECS", "SPAN_SPECS", "instruments",
    "profiling", "scoreboard", "tracked_jit", "TrackedJit",
    "CompileEvent", "peak_flops", "sample_device_memory",
]
