"""Telemetry CLI: ``python -m bigdl_tpu.telemetry
{metrics|trace|scoreboard} ...`` (wrapped by ``scripts/bigdl-tpu.sh``).

``metrics``     scrape a running server's ``/metrics`` (URL positional)
                and print it; ``--selftest`` exercises the registry +
                exposition pipeline in-process instead (CI smoke).
``trace``       validate a dumped Chrome trace_event JSON file and print
                a per-span summary; ``--selftest`` records demo spans
                and dumps a valid trace (to ``--out`` or stdout).
``scoreboard``  the automated serving scoreboard
                (``telemetry/scoreboard.py``): drive the seeded Zipf
                workload in-process (``scoreboard``, needs jax), snapshot
                a live server (``scoreboard scrape <url>``), or gate two
                artifacts (``scoreboard diff <old> <new>`` — exit 1 on a
                regression past the thresholds).

Exit status: 0 ok, 1 invalid trace / failed scrape / regression,
2 usage errors. metrics/trace/scoreboard-diff are jax-free.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from bigdl_tpu.telemetry.exposition import render_json, render_prometheus
from bigdl_tpu.telemetry.registry import MetricsRegistry
from bigdl_tpu.telemetry import tracing
from bigdl_tpu.telemetry.catalogue import instruments


def _selftest_registry() -> MetricsRegistry:
    """A private registry exercising every metric kind through the
    catalogue specs (never the global one: a selftest must not pollute a
    live process's scrape)."""
    reg = MetricsRegistry()
    ins = instruments(reg)
    ins.serving_admissions_total.inc(3)
    ins.serving_queue_depth.set(1)
    ins.serving_slots_total.set(8)
    ins.serving_slots_occupied.set(2)
    for v in (0.004, 0.012, 0.03):
        ins.serving_ttft_seconds.observe(v)
    ins.train_steps_total.labels(mode="local").inc(5)
    ins.train_step_seconds.labels(mode="local").observe(0.02)
    return reg


def cmd_metrics(args) -> int:
    if args.selftest:
        reg = _selftest_registry()
        if args.format == "json":
            print(render_json(reg, indent=2))
        else:
            sys.stdout.write(render_prometheus(reg))
        return 0
    if not args.url:
        print("metrics: give a scrape URL (e.g. "
              "http://127.0.0.1:8000/metrics) or --selftest", file=sys.stderr)
        return 2
    import urllib.request
    url = args.url
    if "://" not in url:
        url = "http://" + url
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            body = resp.read().decode("utf-8", errors="replace")
    except Exception as e:  # noqa: BLE001 — report, don't traceback
        print(f"metrics: scrape of {url} failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 1
    sys.stdout.write(body)
    if body and not body.endswith("\n"):
        sys.stdout.write("\n")
    return 0


def _validate_chrome_trace(obj) -> List[str]:
    """Schema errors ([] == valid): the subset chrome://tracing/Perfetto
    require of the JSON object form."""
    errors = []
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return ["top level must be an object with a 'traceEvents' array"]
    evs = obj["traceEvents"]
    if not isinstance(evs, list):
        return ["'traceEvents' must be an array"]
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            errors.append(f"event {i}: not an object")
            continue
        for key in ("name", "ph", "ts", "pid", "tid"):
            if key not in ev:
                errors.append(f"event {i}: missing {key!r}")
        if ev.get("ph") == "X" and "dur" not in ev:
            errors.append(f"event {i}: complete event missing 'dur'")
        if errors and len(errors) > 10:
            errors.append("... (truncated)")
            break
    return errors


def _trace_summary(evs: List[dict]) -> str:
    by_name = {}
    for ev in evs:
        tot, n = by_name.get(ev.get("name", "?"), (0.0, 0))
        by_name[ev.get("name", "?")] = (tot + float(ev.get("dur", 0.0)),
                                        n + 1)
    lines = [f"{len(evs)} events, {len(by_name)} span names"]
    width = max((len(n) for n in by_name), default=4)
    for name, (tot, n) in sorted(by_name.items(),
                                 key=lambda kv: -kv[1][0]):
        lines.append(f"  {name:<{width}}  n={n:<6} total={tot / 1e3:.3f}ms "
                     f"mean={tot / n / 1e3:.3f}ms")
    return "\n".join(lines)


def cmd_trace(args) -> int:
    if args.selftest:
        was_enabled = tracing.is_enabled()
        tracing.enable()
        try:
            with tracing.span("selftest.outer", kind="demo"):
                for i in range(3):
                    with tracing.span("selftest.inner", i=i):
                        time.sleep(0.001)
        finally:
            if not was_enabled:
                tracing.disable()
        obj = tracing.to_chrome_trace()
        errors = _validate_chrome_trace(obj)
        if errors:
            print("trace selftest produced an INVALID trace:",
                  file=sys.stderr)
            print("\n".join(errors), file=sys.stderr)
            return 1
        if args.out:
            tracing.dump(args.out)
            print(f"wrote {args.out}: {_trace_summary(obj['traceEvents'])}")
        else:
            print(json.dumps(obj))
        return 0
    if not args.file:
        print("trace: give a dumped trace file to validate, or --selftest",
              file=sys.stderr)
        return 2
    try:
        with open(args.file) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"trace: cannot read {args.file}: {e}", file=sys.stderr)
        return 1
    errors = _validate_chrome_trace(obj)
    if errors:
        print(f"{args.file}: INVALID Chrome trace:", file=sys.stderr)
        print("\n".join(errors), file=sys.stderr)
        return 1
    print(f"{args.file}: valid Chrome trace_event JSON")
    print(_trace_summary(obj["traceEvents"]))
    return 0


def cmd_scoreboard(args) -> int:
    from bigdl_tpu.telemetry import scoreboard as sb

    if args.mode == "diff":
        if len(args.paths) != 2:
            print("scoreboard diff: give exactly two artifact paths "
                  "(old new)", file=sys.stderr)
            return 2
        try:
            old = sb.load_artifact(args.paths[0])
            new = sb.load_artifact(args.paths[1])
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"scoreboard diff: {e}", file=sys.stderr)
            return 2
        thresholds = {
            "tok_s_drop": args.max_tok_drop,
            "ttft_p50_rise": args.max_ttft_rise,
            "ttft_p95_rise": args.max_ttft_rise,
            "token_latency_rise": args.max_latency_rise,
            "compiles_rise": args.max_compile_rise,
            "peak_memory_rise": args.max_mem_rise,
        }
        regressions = sb.diff(old, new, thresholds)
        if regressions:
            print("scoreboard REGRESSIONS:", file=sys.stderr)
            for r in regressions:
                print(f"  {r}", file=sys.stderr)
            return 1
        print(f"scoreboard: no regressions across "
              f"{len(new.get('rows', []))} row(s)")
        return 0

    if args.mode == "scrape":
        if len(args.paths) != 1:
            print("scoreboard scrape: give the server URL", file=sys.stderr)
            return 2
        try:
            artifact = sb.scrape(args.paths[0], timeout=args.timeout)
        except Exception as e:      # noqa: BLE001 — report, don't traceback
            print(f"scoreboard scrape failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
    else:                           # run: drive the seeded workload
        if args.paths:
            print("scoreboard: the run mode takes no positional arguments "
                  "(use 'diff'/'scrape' as the first)", file=sys.stderr)
            return 2
        cfg = sb.ScoreboardConfig(
            slots=[int(s) for s in args.slots.split(",")],
            requests=args.requests, clients=args.clients, seed=args.seed,
            lmin=args.lmin, lmax=args.lmax, alpha=args.alpha,
            max_new=args.max_new, vocab=args.vocab, embed=args.embed,
            heads=args.heads, ffn=args.ffn, layers=args.layers,
            timeout=args.timeout, prefill_mode=args.prefill_mode,
            prefill_chunk=args.prefill_chunk, workload=args.workload,
            templates=args.templates, template_len=args.template_len,
            prefix_cache=(args.prefix_cache == "on"), draft=args.draft,
            spec_len=args.spec_len, replicas=args.replicas,
            disaggregate=args.disaggregate)
        artifact = sb.run(cfg)
    body = json.dumps(artifact, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(body + "\n")
        print(f"wrote {args.out}")
    else:
        print(body)
    if args.markdown:
        print(sb.render_markdown(artifact))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.telemetry",
        description="metrics scrape + trace validation tools "
                    "(docs/OBSERVABILITY.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    pm = sub.add_parser("metrics", help="scrape and print /metrics")
    pm.add_argument("url", nargs="?", default="",
                    help="server base URL or host:port (the /metrics path "
                         "is appended if missing)")
    pm.add_argument("--format", choices=("prometheus", "json"),
                    default="prometheus",
                    help="--selftest output format (scrapes print the "
                         "server's body verbatim)")
    pm.add_argument("--timeout", type=float, default=5.0)
    pm.add_argument("--selftest", action="store_true",
                    help="exercise registry+exposition in-process (CI "
                         "smoke; no server)")
    pm.set_defaults(fn=cmd_metrics)

    pt = sub.add_parser("trace", help="validate/summarize a Chrome trace "
                                      "dump")
    pt.add_argument("file", nargs="?", default="",
                    help="trace_event JSON file to validate")
    pt.add_argument("--out", default="",
                    help="--selftest: write the demo trace here instead "
                         "of stdout")
    pt.add_argument("--selftest", action="store_true",
                    help="record demo spans and dump a valid trace")
    pt.set_defaults(fn=cmd_trace)

    ps = sub.add_parser(
        "scoreboard",
        help="serving scoreboard: run the seeded workload, scrape a live "
             "server, or diff two artifacts (docs/OBSERVABILITY.md)")
    ps.add_argument("mode", nargs="?", default="run",
                    choices=("run", "diff", "scrape"),
                    help="run (default): drive the workload in-process; "
                         "diff OLD NEW: regression gate; scrape URL: "
                         "snapshot a live /metrics")
    ps.add_argument("paths", nargs="*", default=[],
                    help="diff: two artifact files; scrape: server URL")
    ps.add_argument("--slots", default="8,16,32",
                    help="comma-separated slot counts, one row each")
    ps.add_argument("--requests", type=int, default=48,
                    help="requests per slot count")
    ps.add_argument("--clients", type=int, default=8,
                    help="concurrent submitter threads")
    ps.add_argument("--seed", type=int, default=0)
    ps.add_argument("--lmin", type=int, default=4)
    ps.add_argument("--lmax", type=int, default=24)
    ps.add_argument("--alpha", type=float, default=1.1,
                    help="Zipf exponent over the prompt-length ranks")
    ps.add_argument("--max-new", type=int, default=16, dest="max_new")
    ps.add_argument("--vocab", type=int, default=256)
    ps.add_argument("--embed", type=int, default=32)
    ps.add_argument("--heads", type=int, default=2)
    ps.add_argument("--ffn", type=int, default=64)
    ps.add_argument("--layers", type=int, default=2)
    ps.add_argument("--timeout", type=float, default=600.0)
    ps.add_argument("--prefill-mode", dest="prefill_mode",
                    choices=("chunked", "bucketed"), default="chunked",
                    help="serving prefill strategy (both O(1)-compile; "
                         "chunked = fixed-size chunks, bucketed = pow2 "
                         "length buckets)")
    ps.add_argument("--prefill-chunk", type=int, dest="prefill_chunk",
                    default=16, help="chunked-mode chunk width")
    ps.add_argument("--workload", choices=("zipf", "shared-prefix"),
                    default="zipf",
                    help="zipf (default): mixed-length random prompts; "
                         "shared-prefix: Zipf draws over a small pool of "
                         "long shared templates + unique tails (the "
                         "prefix-cache stress profile)")
    ps.add_argument("--templates", type=int, default=4,
                    help="shared-prefix: template pool size")
    ps.add_argument("--template-len", type=int, dest="template_len",
                    default=48, help="shared-prefix: shared-prefix length "
                                     "in tokens")
    ps.add_argument("--prefix-cache", dest="prefix_cache",
                    choices=("on", "off"), default="on",
                    help="cross-request KV prefix cache (chunked prefill)")
    ps.add_argument("--draft", nargs="?", const="identical", default=None,
                    choices=("identical", "int8"),
                    help="speculative decode: 'identical' (same-weights "
                         "draft — the acceptance-rate ceiling) or 'int8' "
                         "(quantized-twin self-speculation)")
    ps.add_argument("--replicas", type=int, default=1,
                    help="route the workload over N in-process replicas "
                    "behind the fleet router (models.router.LMRouter)")
    ps.add_argument("--disaggregate", default=None, metavar="P:D",
                    help="prefill:decode replica split, e.g. 1:2 — "
                    "dedicated prefill replicas ship serialized state "
                    "partitions to decode replicas (overrides --replicas)")
    ps.add_argument("--spec-len", type=int, dest="spec_len", default=4,
                    help="draft tokens proposed per speculative round")
    ps.add_argument("--out", default="",
                    help="write the JSON artifact here (default: stdout)")
    ps.add_argument("--markdown", action="store_true",
                    help="also print the PERF.md table")
    ps.add_argument("--max-tok-drop", type=float, dest="max_tok_drop",
                    default=0.15, help="diff: allowed tok/s drop fraction")
    ps.add_argument("--max-ttft-rise", type=float, dest="max_ttft_rise",
                    default=0.30, help="diff: allowed TTFT rise fraction")
    ps.add_argument("--max-latency-rise", type=float,
                    dest="max_latency_rise", default=0.30,
                    help="diff: allowed per-token latency rise fraction")
    ps.add_argument("--max-compile-rise", type=float,
                    dest="max_compile_rise", default=0,
                    help="diff: allowed absolute extra compiles")
    ps.add_argument("--max-mem-rise", type=float, dest="max_mem_rise",
                    default=0.10, help="diff: allowed peak-memory rise")
    ps.set_defaults(fn=cmd_scoreboard)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
