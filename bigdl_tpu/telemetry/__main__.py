"""Telemetry CLI: ``python -m bigdl_tpu.telemetry {metrics|trace} ...``
(wrapped by ``scripts/bigdl-tpu.sh metrics|trace``).

``metrics``  scrape a running server's ``/metrics`` (URL positional) and
             print it; ``--selftest`` exercises the registry + exposition
             pipeline in-process instead (CI smoke, no server needed).
``trace``    validate a dumped Chrome trace_event JSON file and print a
             per-span summary; ``--selftest`` records demo spans and
             dumps a valid trace (to ``--out`` or stdout).

Exit status: 0 ok, 1 invalid trace / failed scrape, 2 usage errors.
jax-free: both subcommands run in milliseconds on a bare host.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from bigdl_tpu.telemetry.exposition import render_json, render_prometheus
from bigdl_tpu.telemetry.registry import MetricsRegistry
from bigdl_tpu.telemetry import tracing
from bigdl_tpu.telemetry.catalogue import instruments


def _selftest_registry() -> MetricsRegistry:
    """A private registry exercising every metric kind through the
    catalogue specs (never the global one: a selftest must not pollute a
    live process's scrape)."""
    reg = MetricsRegistry()
    ins = instruments(reg)
    ins.serving_admissions_total.inc(3)
    ins.serving_queue_depth.set(1)
    ins.serving_slots_total.set(8)
    ins.serving_slots_occupied.set(2)
    for v in (0.004, 0.012, 0.03):
        ins.serving_ttft_seconds.observe(v)
    ins.train_steps_total.labels(mode="local").inc(5)
    ins.train_step_seconds.labels(mode="local").observe(0.02)
    return reg


def cmd_metrics(args) -> int:
    if args.selftest:
        reg = _selftest_registry()
        if args.format == "json":
            print(render_json(reg, indent=2))
        else:
            sys.stdout.write(render_prometheus(reg))
        return 0
    if not args.url:
        print("metrics: give a scrape URL (e.g. "
              "http://127.0.0.1:8000/metrics) or --selftest", file=sys.stderr)
        return 2
    import urllib.request
    url = args.url
    if "://" not in url:
        url = "http://" + url
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            body = resp.read().decode("utf-8", errors="replace")
    except Exception as e:  # noqa: BLE001 — report, don't traceback
        print(f"metrics: scrape of {url} failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 1
    sys.stdout.write(body)
    if body and not body.endswith("\n"):
        sys.stdout.write("\n")
    return 0


def _validate_chrome_trace(obj) -> List[str]:
    """Schema errors ([] == valid): the subset chrome://tracing/Perfetto
    require of the JSON object form."""
    errors = []
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return ["top level must be an object with a 'traceEvents' array"]
    evs = obj["traceEvents"]
    if not isinstance(evs, list):
        return ["'traceEvents' must be an array"]
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            errors.append(f"event {i}: not an object")
            continue
        for key in ("name", "ph", "ts", "pid", "tid"):
            if key not in ev:
                errors.append(f"event {i}: missing {key!r}")
        if ev.get("ph") == "X" and "dur" not in ev:
            errors.append(f"event {i}: complete event missing 'dur'")
        if errors and len(errors) > 10:
            errors.append("... (truncated)")
            break
    return errors


def _trace_summary(evs: List[dict]) -> str:
    by_name = {}
    for ev in evs:
        tot, n = by_name.get(ev.get("name", "?"), (0.0, 0))
        by_name[ev.get("name", "?")] = (tot + float(ev.get("dur", 0.0)),
                                        n + 1)
    lines = [f"{len(evs)} events, {len(by_name)} span names"]
    width = max((len(n) for n in by_name), default=4)
    for name, (tot, n) in sorted(by_name.items(),
                                 key=lambda kv: -kv[1][0]):
        lines.append(f"  {name:<{width}}  n={n:<6} total={tot / 1e3:.3f}ms "
                     f"mean={tot / n / 1e3:.3f}ms")
    return "\n".join(lines)


def cmd_trace(args) -> int:
    if args.selftest:
        was_enabled = tracing.is_enabled()
        tracing.enable()
        try:
            with tracing.span("selftest.outer", kind="demo"):
                for i in range(3):
                    with tracing.span("selftest.inner", i=i):
                        time.sleep(0.001)
        finally:
            if not was_enabled:
                tracing.disable()
        obj = tracing.to_chrome_trace()
        errors = _validate_chrome_trace(obj)
        if errors:
            print("trace selftest produced an INVALID trace:",
                  file=sys.stderr)
            print("\n".join(errors), file=sys.stderr)
            return 1
        if args.out:
            tracing.dump(args.out)
            print(f"wrote {args.out}: {_trace_summary(obj['traceEvents'])}")
        else:
            print(json.dumps(obj))
        return 0
    if not args.file:
        print("trace: give a dumped trace file to validate, or --selftest",
              file=sys.stderr)
        return 2
    try:
        with open(args.file) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"trace: cannot read {args.file}: {e}", file=sys.stderr)
        return 1
    errors = _validate_chrome_trace(obj)
    if errors:
        print(f"{args.file}: INVALID Chrome trace:", file=sys.stderr)
        print("\n".join(errors), file=sys.stderr)
        return 1
    print(f"{args.file}: valid Chrome trace_event JSON")
    print(_trace_summary(obj["traceEvents"]))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.telemetry",
        description="metrics scrape + trace validation tools "
                    "(docs/OBSERVABILITY.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    pm = sub.add_parser("metrics", help="scrape and print /metrics")
    pm.add_argument("url", nargs="?", default="",
                    help="server base URL or host:port (the /metrics path "
                         "is appended if missing)")
    pm.add_argument("--format", choices=("prometheus", "json"),
                    default="prometheus",
                    help="--selftest output format (scrapes print the "
                         "server's body verbatim)")
    pm.add_argument("--timeout", type=float, default=5.0)
    pm.add_argument("--selftest", action="store_true",
                    help="exercise registry+exposition in-process (CI "
                         "smoke; no server)")
    pm.set_defaults(fn=cmd_metrics)

    pt = sub.add_parser("trace", help="validate/summarize a Chrome trace "
                                      "dump")
    pt.add_argument("file", nargs="?", default="",
                    help="trace_event JSON file to validate")
    pt.add_argument("--out", default="",
                    help="--selftest: write the demo trace here instead "
                         "of stdout")
    pt.add_argument("--selftest", action="store_true",
                    help="record demo spans and dump a valid trace")
    pt.set_defaults(fn=cmd_trace)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
