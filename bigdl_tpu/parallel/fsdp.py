"""FSDP (ZeRO-3) parameter sharding over the data axis.

The reference's parameter plane shards only the OPTIMIZER's view of the
flat vector (``parameters/AllReduceParameter.scala:62``: each partition
owns slice p, weights are re-broadcast every iteration) — parameters and
gradients are materialized in full on every node. ``sync_mode="fsdp"``
extends the ownership to the parameters themselves, the TPU-native way:

- every parameter leaf is sharded over the ``data`` mesh axis along its
  largest evenly-divisible dimension (leaves too small to split stay
  replicated — biases, scalars);
- the training step is jitted with those shardings on params AND optimizer
  state; XLA's SPMD partitioner inserts a per-operand ``all-gather`` right
  where each layer consumes its weight (the per-layer gather of
  FSDP/ZeRO-3 — not one monolithic gather) and overlaps them with compute
  via its latency-hiding scheduler;
- a sharding constraint on the gradient tree makes the backward's psum
  land as ``reduce-scatter`` (each device keeps only its shard), and the
  optimizer update runs shard-local.

Per-device parameter memory is ~1/P of the model (verified by
``tests/test_fsdp.py::test_per_device_bytes``); the collective pattern is
asserted by the comm-contract tests.

Used by ``parallel/distri_optimizer.py`` (``sync_mode="fsdp"``) and the
driver dryrun (``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.parallel.mesh import DATA_AXIS


def fsdp_param_specs(params: Any, n_dev: int, axis: str = DATA_AXIS,
                     base_specs: Any = None) -> Any:
    """PartitionSpec tree matching ``params``: each leaf sharded on its
    canonical OUTPUT-feature dimension — dim 0 for 1-2D leaves (Linear is
    ``(out, in)``, biases ``(out,)``), the last dim for >=3D (conv HWIO's
    O). Leaves whose output dim doesn't divide ``n_dev`` stay replicated.

    Output-dim-only, rather than largest-divisible-dim: sharding an INPUT
    dim makes the backward's dx come out feature-sharded, and that
    sharding propagating through a flatten/Reshape boundary triggers
    GSPMD's involuntary-full-rematerialization path (observed on LeNet's
    conv->fc flatten). Contracting over the output dim instead leaves
    dx replicated-in-features, so activations keep their batch sharding
    both ways.

    ``base_specs`` (fsdp x tp composition): a spec tree from
    ``infer_param_specs`` whose tensor-axis entries are kept; ``axis``
    lands on a dim the base spec leaves free — the canonical output dim
    when it is free and divisible, else the first free divisible dim.
    A leaf with no free divisible dim keeps just its base sharding."""

    def spec(leaf, base=None):
        shape = np.shape(leaf)
        if base is None:
            if not shape:
                return P()
            d = 0 if len(shape) <= 2 else len(shape) - 1
            if shape[d] >= n_dev and shape[d] % n_dev == 0:
                return P(*([None] * d + [axis]))
            return P()
        if not shape:
            return base
        entries = list(base) + [None] * (len(shape) - len(base))
        canonical = 0 if len(shape) <= 2 else len(shape) - 1
        for d in [canonical] + [i for i in range(len(shape))
                                if i != canonical]:
            if (entries[d] is None and shape[d] >= n_dev
                    and shape[d] % n_dev == 0):
                entries[d] = axis
                return P(*entries)
        return base

    if base_specs is None:
        return jax.tree_util.tree_map(spec, params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    base_leaves = jax.tree_util.tree_leaves(
        base_specs, is_leaf=lambda x: isinstance(x, P))
    return jax.tree_util.tree_unflatten(
        treedef, [spec(l, b) for l, b in zip(leaves, base_leaves)])


def shard_fraction(params: Any, n_dev: int) -> float:
    """Fraction of parameter bytes that fsdp_param_specs shards (the rest
    stays replicated): the memory-table denominator for PERF.md."""
    total = sharded = 0
    for leaf, spec in zip(jax.tree_util.tree_leaves(params),
                          jax.tree_util.tree_leaves(
                              fsdp_param_specs(params, n_dev),
                              is_leaf=lambda x: isinstance(x, P))):
        nbytes = int(np.size(leaf)) * np.dtype(
            getattr(leaf, "dtype", np.float32)).itemsize
        total += nbytes
        if any(ax is not None for ax in spec):
            sharded += nbytes
    return sharded / max(1, total)


def named_tree(mesh, spec_tree: Any) -> Any:
    """PartitionSpec tree -> NamedSharding tree on ``mesh``."""
    return jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), spec_tree,
        is_leaf=lambda x: isinstance(x, P))
