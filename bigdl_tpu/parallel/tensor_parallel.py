"""Tensor (model) parallelism as parameter sharding rules.

New capability — the reference has none (SURVEY §2.5: "Tensor parallelism:
ABSENT"). The TPU-native design is NOT manual collective placement: each
parameter leaf gets a ``PartitionSpec`` over the mesh ``tensor`` axis and
GSPMD inserts the all-gathers/reduce-scatters (the scaling-book recipe:
pick a mesh, annotate shardings, let XLA place collectives on ICI).

The rules encode the Megatron pattern:

- **column-parallel Linear** — weight (out, in) sharded on ``out``; the
  matmul's output activation comes out sharded on features, no comm.
- **row-parallel Linear** — weight sharded on ``in``; XLA inserts one psum
  over the partial products. Column→row pairs (FFN up/down, attention
  qkv/out) therefore cost exactly one all-reduce each, the Megatron layout.
- **MultiHeadAttention** — fused qkv (3E, E) column-sharded (head split),
  out-proj row-sharded.
- **LookupTable** — embedding dim sharded.
- **SpatialConvolution** — output channels sharded.
- everything else (norms, biases-of-row-layers, scalars) replicated.

Usage: automatic for known layer types via ``infer_param_specs(model)``;
override per-module with ``module.tp_mode = "column" | "row" | "replicate"``.

**Sequence-parallel regions** (Megatron-SP, Korthikanti et al.): between a
row-parallel output and the next column-parallel input sit norm / dropout /
residual segments whose activations would otherwise be fully replicated
across the tensor group. ``enable_sequence_parallel(model, mesh)`` tags
every transformer block so its residual stream carries a
``with_sharding_constraint`` sharding the SEQUENCE dim over the tensor
axis — GSPMD then lowers the boundary collectives as reduce-scatter (into
the region) + all-gather (back out), the same total bytes as the Megatron
all-reduce but with region activations and elementwise FLOPs divided by
the axis size (contract-tested in tests/test_tensor_parallel.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np
from jax.sharding import PartitionSpec as P

from bigdl_tpu.parallel.mesh import TENSOR_AXIS

COLUMN, ROW, REPLICATE = "column", "row", "replicate"


def enable_sequence_parallel(model, mesh, axis: str = TENSOR_AXIS,
                             seq_dim: int = 1, batch_axis: str = "data",
                             batch_dim: int = 0) -> int:
    """Tag every ``TransformerEncoderLayer`` under ``model`` to constrain
    its residual stream seq-sharded over ``axis``. Returns the number of
    blocks tagged. Requires seq_len % mesh.shape[axis] == 0 at call sites
    (GSPMD would otherwise pad unevenly).

    The batch dim keeps its data-parallel sharding (``batch_axis``, when
    that axis exists in the mesh): constraining it to None would FORCE
    batch replication at every region boundary, fighting the upstream dp
    sharding — measured as XLA "involuntary full rematerialization"
    (replicate-then-reshard) on every block entry in the dp x tp dryrun."""
    from bigdl_tpu import nn
    count = 0
    batch = batch_axis if batch_axis in mesh.shape else None
    stack = [model]
    while stack:
        m = stack.pop()
        if isinstance(m, nn.TransformerEncoderLayer):
            m._sp = (mesh, axis, seq_dim, batch, batch_dim)
            count += 1
        stack.extend(m._modules.values())
    return count


def sp_constrain(x, sp):
    """Apply the sequence-parallel sharding constraint (no-op when
    ``sp`` is None)."""
    if sp is None:
        return x
    import jax
    from jax.sharding import NamedSharding
    mesh, axis, seq_dim, batch, batch_dim = sp
    spec = [None] * x.ndim
    spec[seq_dim] = axis
    spec[batch_dim] = batch
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))


def _linear_specs(mode: Optional[str], axis: str) -> Dict[str, P]:
    if mode == COLUMN:
        return {"weight": P(axis, None), "bias": P(axis)}
    if mode == ROW:
        # Bias replicated: it is added after the partial-product psum.
        return {"weight": P(None, axis), "bias": P()}
    return {}


def _module_specs(module, axis: str) -> Dict[str, P]:
    """Specs for the module's OWN parameters (not children)."""
    from bigdl_tpu import nn
    from bigdl_tpu.parallel.expert import MoE, expert_param_specs

    mode = getattr(module, "tp_mode", None)
    if mode == REPLICATE:
        return {}
    if isinstance(module, MoE):
        return expert_param_specs(module)
    if isinstance(module, nn.Linear):
        return _linear_specs(mode, axis)
    if isinstance(module, nn.MultiHeadAttention):
        return {"in_proj_weight": P(axis, None), "in_proj_bias": P(axis),
                "out_proj_weight": P(None, axis), "out_proj_bias": P()}
    if isinstance(module, nn.LookupTable):
        return {"weight": P(None, axis)}
    if isinstance(module, (nn.SpatialConvolution, nn.SpatialShareConvolution,
                           nn.SpaceToDepthConv7)):
        # HWIO weight layout: shard output channels (SpaceToDepthConv7
        # stores the same (7,7,C,O) weight as the plain stem it replaces).
        return {"weight": P(None, None, None, axis), "bias": P(axis)}
    return {}


def _tag_children(module) -> None:
    """Auto-tag Megatron column→row pairs inside known blocks:

    - ``TransformerEncoderLayer``: FFN up = column, down = row;
    - plain MLP stacks (``Sequential``): consecutive Linear pairs separated
      only by parameter-free elementwise modules get column→row;
    - ``TimeDistributed(Linear)`` heads (the causal-LM vocab projection):
      column-parallel — the (T, V/P) logits stay sharded into LogSoftMax,
      whose vocab reduction GSPMD turns into a small all-reduce while the
      big logits tensor never materializes replicated.
    """
    from bigdl_tpu import nn
    if isinstance(module, nn.TransformerEncoderLayer):
        if getattr(module, "moe_experts", 0):
            return  # MoE FFN: _module_specs shards the expert leaves
        if not hasattr(module.linear1, "tp_mode"):
            module.linear1.tp_mode = COLUMN
        if not hasattr(module.linear2, "tp_mode"):
            module.linear2.tp_mode = ROW
        gate = module._modules.get("linear_gate")
        if gate is not None and not hasattr(gate, "tp_mode"):
            gate.tp_mode = COLUMN  # swiglu gate: second column projection
        return
    if isinstance(module, nn.TimeDistributed):
        inner = getattr(module, "inner", None) or \
            next(iter(module._modules.values()), None)
        if isinstance(inner, nn.Linear) and not hasattr(inner, "tp_mode"):
            inner.tp_mode = COLUMN
        return
    if isinstance(module, nn.Sequential):
        children = list(module._modules.values())
        i = 0
        while i < len(children):
            c = children[i]
            if isinstance(c, nn.Linear) and not hasattr(c, "tp_mode"):
                # scan past parameter-free elementwise modules for the
                # row partner; tag only when the pair completes
                j = i + 1
                while (j < len(children)
                       and not children[j]._parameters
                       and not children[j]._modules):
                    j += 1
                if (j < len(children)
                        and isinstance(children[j], nn.Linear)
                        and not hasattr(children[j], "tp_mode")):
                    c.tp_mode = COLUMN
                    children[j].tp_mode = ROW
                    i = j
            i += 1


def infer_param_specs(model, axis: str = TENSOR_AXIS,
                      axis_size=None) -> Any:
    """Pytree of PartitionSpec matching ``model.parameter_tree()``.

    ``axis_size``: when given, a would-be sharded dimension not divisible by
    it falls back to replicated (GSPMD would otherwise pad-and-mask with
    uneven shards; explicit replication is cheaper and predictable). Either
    an int (applies to every named axis) or a dict {axis_name: size} — pass
    ``dict(mesh.shape)`` to validate mixed tensor/expert specs.
    """
    _tag_children(model)

    def divisible(spec: P, shape) -> bool:
        if axis_size is None:
            return True
        for dim, name in enumerate(spec):
            if name is None:
                continue
            size = (axis_size.get(name) if isinstance(axis_size, dict)
                    else axis_size)
            if size is None:
                return False  # axis absent from the mesh → replicate
            if size and shape[dim] % size != 0:
                return False
        return True

    specs = {}
    own = _module_specs(model, axis)
    for name, value in model._parameters.items():
        spec = own.get(name, P())
        if spec != P() and not divisible(spec, np.shape(value)):
            spec = P()
        specs[name] = spec
    for name, child in model._modules.items():
        sub = infer_param_specs(child, axis, axis_size)
        if sub:
            specs[name] = sub
    return specs


def opt_state_specs(state_template, params_template, param_specs) -> Any:
    """Specs for an OptimMethod state dict: any top-level entry whose tree
    structure mirrors the params (velocity, m, v, ...) inherits the param
    specs; scalars and counters stay replicated."""
    import jax

    p_struct = jax.tree_util.tree_structure(params_template)
    out = {}
    for key, val in state_template.items():
        if jax.tree_util.tree_structure(val) == p_struct:
            out[key] = param_specs
        else:
            out[key] = jax.tree_util.tree_map(lambda _: P(), val)
    return out
