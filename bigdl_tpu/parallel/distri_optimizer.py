"""Distributed synchronous-SGD training over a device mesh
(reference ``optim/DistriOptimizer.scala:669`` + ``parameters/AllReduceParameter.scala:62``).

The reference runs, per iteration, two Spark jobs and three BlockManager
block exchanges: fetch weight slices → local fwd/bwd → publish fp16 gradient
slices → owners aggregate + update their slice → republish. On TPU the entire
iteration is ONE jitted SPMD program; the exchanges become XLA collectives
riding ICI:

- ``sync_mode="allreduce"`` — replicated parameters, batch sharded over the
  ``data`` axis; XLA's SPMD partitioner inserts the gradient psum. The two
  intra-node tiers of the reference (executor slice exchange + per-core
  replica reduce, ``DistriOptimizer.scala:112-115,229-246``) collapse into
  this single psum.

- ``sync_mode="sharded"`` — the AllReduceParameter slice-ownership model,
  TPU-native (≙ ZeRO-1): the flat parameter vector is conceptually cut into
  P slices; gradients ``psum_scatter`` so each device reduces only its own
  slice, the optimizer updates that slice (optimizer state stays sharded —
  P× less optimizer memory), and ``all_gather`` republishes the weights.
  This is bit-for-bit the reference's protocol with BlockManager fetches
  replaced by reduce-scatter/all-gather.

bf16 gradient compression (reference ``FP16CompressedTensor``: fp32 truncated
to its top 16 bits == bfloat16) maps to casting the collective payload to
``jnp.bfloat16`` — ``compress_gradients=True``.

BatchNorm note: in allreduce mode batch-stat means over the sharded batch are
computed globally by XLA → synchronized BN across replicas (an upgrade over
the reference's per-replica stats); in sharded mode new buffers are pmean'd.

Multi-host: when ``Engine.init`` joined a jax.distributed topology (env
``BIGDL_COORDINATOR_ADDRESS``/..., or TPU-pod auto-detect), the same jitted
step spans every host's chips. Per-process ingest (``DistributedDataSet``
record slices ≙ executor-pinned partitions) feeds
``jax.make_array_from_process_local_data``; state is committed to the global
mesh by ``_place_state``; checkpoints gather sharded leaves and write on
process 0 only; validation merges per-host (numerator, count) pairs with one
allgather. Verified by ``tests/test_multihost.py`` (2 real processes, gloo).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import functional_apply
from bigdl_tpu.optim.optimizer import (LocalOptimizer, Optimizer,
                                       _regularizer_pairs, _reg_loss,
                                       make_grad_clipper,
                                       make_training_loss_fn)
from bigdl_tpu.parallel.mesh import DATA_AXIS, TENSOR_AXIS, MeshTopology
from bigdl_tpu.telemetry.profiling import tracked_jit

logger = logging.getLogger("bigdl_tpu.optim")


class DistriOptimizer(LocalOptimizer):
    """Mesh data-parallel optimizer (reference ``DistriOptimizer``)."""

    # set_steps_per_dispatch: the K-fused path jnp.stack's raw batches,
    # which would collapse the mesh placement _place_batch establishes
    supports_multi_dispatch = False

    def __init__(self, model, dataset, criterion,
                 topology: Optional[MeshTopology] = None,
                 sync_mode: str = "allreduce",
                 compress_gradients: bool = False,
                 **kwargs):
        super().__init__(model, dataset, criterion, **kwargs)
        self.topology = topology or MeshTopology.data_parallel()
        self.sync_mode = sync_mode
        self.compress_gradients = compress_gradients
        if topology and any(
                topology.sizes.get(ax, 1) > 1 for ax in ("tensor", "expert")):
            # fsdp composes with tensor parallelism (weight shards carry
            # both axes); the ZeRO-1 flat vector and expert stacking are
            # data-axis-only layouts
            if sync_mode == "sharded" or (
                    sync_mode == "fsdp"
                    and topology.sizes.get("expert", 1) > 1):
                raise ValueError(f"sync_mode={sync_mode!r} does not "
                                 "compose with this topology; combine "
                                 "expert parallelism with "
                                 "sync_mode='allreduce' (fsdp x tensor "
                                 "is supported)")
        self.mesh: Mesh = self.topology.build()
        self._n_data = self.mesh.shape.get(DATA_AXIS, 1)
        self._n_tensor = self.mesh.shape.get(TENSOR_AXIS, 1)
        batch_spec = P(DATA_AXIS) if DATA_AXIS in self.mesh.shape else P()
        self._batch_sharding = NamedSharding(self.mesh, batch_spec)
        self._replicated = NamedSharding(self.mesh, P())
        # a DeviceCachedDataSet shards its cache over our data axis
        # (per-partition cache ≙ reference CachedDistriDataSet)
        from bigdl_tpu.dataset.device_cache import DeviceCachedDataSet
        if isinstance(dataset, DeviceCachedDataSet):
            dataset.set_mesh(self.mesh, DATA_AXIS)

    def _telemetry_mode(self) -> str:
        """Distributed step breakdowns scrape as their own series:
        ``bigdl_train_*{mode="mesh-allreduce|sharded|fsdp"}`` next to the
        local loop's ``mode="local"`` (docs/OBSERVABILITY.md)."""
        return f"mesh-{self.sync_mode}"

    def _mesh_descriptor(self):
        """RESUME-marker topology record: elastic-resume detection compares
        the saving run's process/device counts against the restarting
        run's, and the mesh shape documents what the snapshot's shard
        layout meant (docs/RESILIENCE.md)."""
        return {"process_count": int(jax.process_count()),
                "device_count": int(jax.device_count()),
                "mesh_shape": {ax: int(n)
                               for ax, n in self.mesh.shape.items()},
                "sync_mode": self.sync_mode}

    # ------------------------------------------------------------- placement
    def _place_batch(self, batch):
        """Commit one batch onto the mesh's data axis.

        Single-host: the pipeline's batch IS the global batch — device_put
        shards it. Multi-host: the pipeline yields this process's LOCAL
        records only (``DistributedDataSet`` per-process slice ≙ the
        reference's executor-pinned partitions, ``CachedDistriDataSet``);
        ``jax.make_array_from_process_local_data`` assembles the global
        array without any host ever holding the full batch."""
        data = batch.data
        if (isinstance(data, jax.Array) and hasattr(data, "sharding")
                and isinstance(data.sharding, NamedSharding)
                and data.sharding.mesh is self.mesh):
            # sharded-cache batches arrive already placed on this mesh
            # (shard_map gather output) — re-placing would force a gather
            # of non-addressable shards under multi-host
            return data, batch.labels
        if jax.process_count() > 1:
            data = jax.make_array_from_process_local_data(
                self._batch_sharding, np.asarray(batch.data))
            labels = jax.make_array_from_process_local_data(
                self._batch_sharding, np.asarray(batch.labels))
            return data, labels
        data = jax.device_put(jnp.asarray(batch.data), self._batch_sharding)
        labels = jax.device_put(jnp.asarray(batch.labels), self._batch_sharding)
        return data, labels

    def _place_state(self, params, buffers, opt_state):
        """Commit training state onto the mesh (multi-host: host-local values
        become global arrays; required before jit sees cross-process
        shardings)."""
        if jax.process_count() <= 1:
            return params, buffers, opt_state
        rep = self._replicated

        def put_rep(x):
            return jax.device_put(jnp.asarray(x), rep)

        n_params = sum(int(np.size(l))
                       for l in jax.tree_util.tree_leaves(params))
        full = n_params + ((-n_params) % self._n_data)
        params = jax.tree_util.tree_map(put_rep, params)
        buffers = jax.tree_util.tree_map(put_rep, buffers)
        if self.sync_mode != "sharded":
            opt_state = jax.tree_util.tree_map(put_rep, opt_state)
        else:
            # slice-shaped vector state lives over the data axis (ZeRO-1);
            # scalar counters are replicated — same rule as _init_opt_state,
            # applied to full-length (possibly checkpoint-resumed) leaves.
            sliced = NamedSharding(self.mesh, P(DATA_AXIS))

            def put_opt(x):
                x = jnp.asarray(x)
                if x.ndim >= 1 and x.shape[0] == full:
                    return jax.device_put(x, sliced)
                return put_rep(x)

            opt_state = jax.tree_util.tree_map(put_opt, opt_state)
        return params, buffers, opt_state

    @staticmethod
    def _fetch_host(x):
        """Global array -> host value (multi-host safe): replicated arrays
        read locally, axis-sharded ones gather via a process allgather."""
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            if not x.is_fully_replicated:
                from jax.experimental import multihost_utils
                return multihost_utils.process_allgather(x, tiled=True)
        return np.asarray(x)

    def _save_checkpoint(self, params, buffers, opt_state, driver_state):
        if self.checkpoint_path is None:
            return
        if getattr(self, "_ckpt_sharded", False):
            if self.sync_mode == "sharded":
                raise ValueError(
                    "set_checkpoint(sharded=True) is not supported with "
                    "sync_mode='sharded' (ZeRO-1 state is device-count-"
                    "shaped; its restore cannot reshard) — use 'fsdp' or "
                    "'allreduce'")
            # every process writes its own shards; no gather at all
            super()._save_checkpoint(params, buffers, opt_state,
                                     driver_state)
            return
        if jax.process_count() > 1:
            fetch = lambda t: jax.tree_util.tree_map(self._fetch_host, t)
            # every process participates in the gather; only the 'driver'
            # writes (reference: checkpoint written by the Spark driver)
            params, buffers, opt_state = (fetch(params), fetch(buffers),
                                          fetch(opt_state))
            if jax.process_index() != 0:
                return
        super()._save_checkpoint(params, buffers, opt_state, driver_state)

    def _resume_shardings(self, params_tpl, buffers_tpl):
        """Sharded-checkpoint restore targets for THIS run's mesh — which
        may differ from the saving run's (the resharding-restore contract):
        fsdp reshards params+state onto its specs; allreduce replicates.
        sync_mode='sharded' (ZeRO-1) keeps flat padded state whose length
        depends on the device count — unsupported for cross-mesh restore,
        use the gathered checkpoint there."""
        if self.sync_mode == "sharded":
            raise ValueError(
                "sharded checkpoints cannot restore into sync_mode="
                "'sharded' (ZeRO-1 flat state is device-count-shaped); "
                "use sync_mode='fsdp' or 'allreduce', or a plain "
                "(gathered) checkpoint")
        rep = self._replicated
        state_tpl = jax.eval_shape(self.optim_method.init_state, params_tpl)
        if self.sync_mode == "fsdp":
            from bigdl_tpu.parallel.fsdp import fsdp_param_specs, named_tree
            from bigdl_tpu.parallel.tensor_parallel import opt_state_specs
            p_specs = fsdp_param_specs(
                params_tpl, self._n_data,
                base_specs=self._tp_base_specs(self.model))
            p_sh = named_tree(self.mesh, p_specs)
            s_sh = named_tree(self.mesh, opt_state_specs(
                state_tpl, params_tpl, p_specs))
            b_sh = jax.tree_util.tree_map(lambda _: rep, buffers_tpl)
            return p_sh, b_sh, s_sh
        rep_of = lambda tpl: jax.tree_util.tree_map(lambda _: rep, tpl)
        return rep_of(params_tpl), rep_of(buffers_tpl), rep_of(state_tpl)

    def _run_validation(self, params, buffers, fwd):
        """Multi-host: each process runs forward over ITS shard of the
        validation set (the dataset must be distributed so records split by
        process), then per-method (numerator, count) pairs merge via one
        allgather — the TPU-native form of ``ValidationResult.+`` reduce
        over executors (``optim/Evaluator.scala:48-73``)."""
        if jax.process_count() <= 1:
            return super()._run_validation(params, buffers, fwd)
        from bigdl_tpu.dataset.device_cache import DeviceCachedDataSet
        if (isinstance(self.validation_dataset, DeviceCachedDataSet)
                and self.validation_dataset._mesh is not None):
            # the sharded cache yields GLOBAL arrays; this path evaluates
            # host-locally per process and allgather-merges, so it needs a
            # per-process host dataset — mixing the two would crash on
            # non-addressable shards (or double-count every record)
            raise ValueError(
                "multi-host validation needs a host-path distributed "
                "dataset (per-process record slices), not a sharded "
                "DeviceCachedDataSet; pass the un-cached pipeline to "
                "set_validation")
        from jax.experimental import multihost_utils
        from bigdl_tpu.optim.evaluator import evaluate_batches

        params_h = jax.tree_util.tree_map(
            self._fetch_host, self._finalize_params(params))
        buffers_h = jax.tree_util.tree_map(self._fetch_host, buffers)
        if getattr(self, "_local_eval_fwd", None) is None:
            model = self.model

            def local_fwd(p, b, x):
                out, _ = functional_apply(model, p, b, x, training=False)
                return out

            self._local_eval_fwd = tracked_jit(local_fwd,
                                               site="eval.forward")
        results, count = evaluate_batches(
            self._local_eval_fwd, params_h, buffers_h,
            self.validation_dataset.data(train=False),
            self.validation_methods, cache=self._eval_cache)
        states = np.array(
            [list(r.state()) if r is not None else [0.0, 0.0]
             for r in results] + [[float(count), 0.0]], np.float64)
        summed = multihost_utils.process_allgather(states).sum(axis=0)
        # Rebuild results from the METHOD (identical on every host), not the
        # local result object: a host whose shard was empty must still see
        # the merged value, or driver_state['score'] diverges across hosts
        # and score-triggered stops deadlock the pod.
        merged = [
            m.to_result(num, int(cnt)) if cnt > 0 else None
            for m, (num, cnt) in zip(self.validation_methods, summed[:-1])]
        return merged, int(summed[-1][0])

    def _tp_base_specs(self, model):
        """Tensor-parallel base specs for the fsdp composition (fsdp x tp:
        weight shards carry both mesh axes), or None on a pure data mesh."""
        if self._n_tensor <= 1:
            return None
        from bigdl_tpu.parallel.tensor_parallel import infer_param_specs
        return infer_param_specs(model, axis_size=dict(self.mesh.shape))

    # ------------------------------------------------------------------ step
    def _build_step(self) -> Callable:
        if self.sync_mode == "sharded":
            return self._build_sharded_step()
        if self.sync_mode == "fsdp":
            return self._build_fsdp_step()
        return self._build_allreduce_step()

    def _build_allreduce_step(self) -> Callable:
        model, criterion, optim = self.model, self.criterion, self.optim_method
        reg_pairs = _regularizer_pairs(model)
        compress = self.compress_gradients
        policy = self.precision
        remat = self._remat

        clip = make_grad_clipper(self._grad_clip)

        def step(params, buffers, opt_state, rng, data, labels):
            loss_fn = make_training_loss_fn(
                model, criterion, policy, reg_pairs, remat,
                buffers, rng, data, labels)

            grads, (new_buf, loss) = jax.grad(loss_fn, has_aux=True)(params)
            if compress:
                # bf16 payload ≙ reference FP16CompressedTensor (truncated fp32)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.bfloat16).astype(g.dtype), grads)
            # clip the GLOBAL (GSPMD-allreduced) gradient, post-compression,
            # so the update sees the same clipped grad on every device
            new_params, new_opt_state = optim.update(clip(grads), opt_state,
                                                     params)
            return new_params, new_buf, new_opt_state, loss

        rep, bat = self._replicated, self._batch_sharding
        if self._n_tensor > 1 or self.mesh.shape.get("expert", 1) > 1:
            # Tensor/expert parallelism: per-leaf parameter shardings
            # (Megatron column/row rules, MoE expert stacking); GSPMD
            # inserts the activation collectives/all_to_alls. Optimizer
            # state mirrors the param specs.
            from bigdl_tpu.parallel.tensor_parallel import (
                infer_param_specs, opt_state_specs)
            params0 = self.model.parameter_tree()
            p_specs = infer_param_specs(self.model,
                                        axis_size=dict(self.mesh.shape))
            state_tpl = jax.eval_shape(optim.init_state, params0)
            s_specs = opt_state_specs(state_tpl, params0, p_specs)
            named = lambda tree: jax.tree_util.tree_map(
                lambda sp: NamedSharding(self.mesh, sp), tree,
                is_leaf=lambda x: isinstance(x, P))
            p_sh, s_sh = named(p_specs), named(s_specs)
            return tracked_jit(
                step, site="train.step",
                in_shardings=(p_sh, rep, s_sh, rep, bat, bat),
                out_shardings=(p_sh, rep, s_sh, rep),
                donate_argnums=(0, 1, 2))
        return tracked_jit(
            step, site="train.step",
            in_shardings=(rep, rep, rep, rep, bat, bat),
            out_shardings=(rep, rep, rep, rep),
            donate_argnums=(0, 1, 2))

    def _build_fsdp_step(self) -> Callable:
        """ZeRO-3: parameters + optimizer state sharded at rest over the
        data axis (``parallel/fsdp.py``); XLA inserts the per-layer weight
        all-gathers and the gradient reduce-scatter. Subsumes the
        reference's slice-ownership protocol
        (``parameters/AllReduceParameter.scala:62``) with the ownership
        extended to the weights themselves."""
        from bigdl_tpu.parallel.fsdp import fsdp_param_specs, named_tree
        from bigdl_tpu.parallel.tensor_parallel import opt_state_specs

        model, criterion, optim = self.model, self.criterion, self.optim_method
        reg_pairs = _regularizer_pairs(model)
        compress = self.compress_gradients
        policy = self.precision
        remat = self._remat
        clip = make_grad_clipper(self._grad_clip)

        params0 = model.parameter_tree()
        p_specs = fsdp_param_specs(params0, self._n_data,
                                   base_specs=self._tp_base_specs(model))
        state_tpl = jax.eval_shape(optim.init_state, params0)
        s_specs = opt_state_specs(state_tpl, params0, p_specs)
        p_sh = named_tree(self.mesh, p_specs)
        s_sh = named_tree(self.mesh, s_specs)
        self._param_sharding = p_sh

        def step(params, buffers, opt_state, rng, data, labels):
            loss_fn = make_training_loss_fn(
                model, criterion, policy, reg_pairs, remat,
                buffers, rng, data, labels)

            grads, (new_buf, loss) = jax.grad(loss_fn, has_aux=True)(params)
            if compress:
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.bfloat16).astype(g.dtype), grads)
            # constrain grads to the param shardings: the backward's psum
            # lowers to reduce-scatter (each device keeps its shard) instead
            # of all-reduce + slice
            grads = jax.lax.with_sharding_constraint(grads, p_sh)
            new_params, new_opt_state = optim.update(clip(grads), opt_state,
                                                     params)
            return new_params, new_buf, new_opt_state, loss

        rep, bat = self._replicated, self._batch_sharding
        return tracked_jit(
            step, site="train.step",
            in_shardings=(p_sh, rep, s_sh, rep, bat, bat),
            out_shardings=(p_sh, rep, s_sh, rep),
            donate_argnums=(0, 1, 2))

    def _build_sharded_step(self) -> Callable:
        from jax.flatten_util import ravel_pytree
        from bigdl_tpu.utils.jax_compat import shard_map

        model, criterion, optim = self.model, self.criterion, self.optim_method
        reg_pairs = _regularizer_pairs(model)
        compress = self.compress_gradients
        clip = make_grad_clipper(self._grad_clip)
        mesh, n_dev = self.mesh, self._n_data

        # Flat-parameter geometry (reference AllReduceParameter slice layout).
        params0 = model.parameter_tree()
        flat0, unravel = ravel_pytree(params0)
        n = flat0.shape[0]
        pad = (-n) % n_dev
        chunk = (n + pad) // n_dev
        self._unravel, self._n, self._pad = unravel, n, pad

        # Per-leaf specs for the optimizer state: slice-shaped vector leaves
        # are sharded over the data axis, scalar counters stay replicated.
        opt_template = optim.init_state(jnp.zeros((chunk,), flat0.dtype))
        opt_specs = jax.tree_util.tree_map(
            lambda x: P(DATA_AXIS)
            if (hasattr(x, "ndim") and np.ndim(x) >= 1 and np.shape(x)[0] == chunk)
            else P(),
            opt_template)

        policy = self.precision

        remat = self._remat

        def spmd_step(flat_params, buffers, opt_state, rng, data, labels):
            # flat_params: full replicated flat vector (post all-gather state).
            params = unravel(flat_params[:n])
            loss_fn = make_training_loss_fn(
                model, criterion, policy, reg_pairs, remat,
                buffers, rng, data, labels)

            grads, (new_buf, loss) = jax.grad(loss_fn, has_aux=True)(params)
            flat_grads, _ = ravel_pytree(grads)
            flat_grads = jnp.pad(flat_grads, (0, pad))
            if compress:
                flat_grads = flat_grads.astype(jnp.bfloat16)
            # reduce-scatter: each device reduces ONLY its own slice
            # (≙ aggregrateGradientPartition, AllReduceParameter.scala:172-210)
            grad_slice = jax.lax.psum_scatter(
                flat_grads, DATA_AXIS, scatter_dimension=0, tiled=True) / n_dev
            grad_slice = grad_slice.astype(jnp.float32)
            rank = jax.lax.axis_index(DATA_AXIS)
            # clip on the slice: the global L2 norm psums the per-slice
            # squared norms (each device owns 1/P of the flat gradient);
            # the mask keeps PAD lanes at zero through the clamp so the
            # norm matches the allreduce path exactly
            lane = rank * chunk + jnp.arange(chunk)
            grad_slice = clip(grad_slice, axis_name=DATA_AXIS,
                              valid_mask=(lane < n).astype(jnp.float32))
            param_slice = jax.lax.dynamic_slice(flat_params, (rank * chunk,), (chunk,))
            new_slice, new_opt_state = optim.update(grad_slice, opt_state, param_slice)
            # republish slices (≙ sendWeightPartition + getWeights)
            new_flat = jax.lax.all_gather(new_slice, DATA_AXIS, tiled=True)
            new_buf = jax.tree_util.tree_map(
                lambda b: jax.lax.pmean(b, DATA_AXIS), new_buf)
            loss = jax.lax.pmean(loss, DATA_AXIS)
            return new_flat, new_buf, new_opt_state, loss

        sharded = shard_map(
            spmd_step, mesh=mesh,
            in_specs=(P(), P(), opt_specs, P(), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(), P(), opt_specs, P()),
            check_vma=False)
        jitted = tracked_jit(sharded, site="train.step",
                             donate_argnums=(0, 1, 2))

        def step(params, buffers, opt_state, rng, data, labels):
            # params arrives as a pytree on the first call; thereafter flat.
            if not isinstance(params, jax.Array):
                flat, _ = ravel_pytree(params)
                flat = jnp.pad(flat, (0, pad))
                params = jax.device_put(flat, self._replicated)
            new_flat, new_buf, new_opt, loss = jitted(
                params, buffers, opt_state, rng, data, labels)
            return new_flat, new_buf, new_opt, loss

        # surface the flight recorder through the wrapper (the MFU gauge
        # follows .tracked to read cost analysis off what flush() ran)
        step.tracked = jitted

        step.finalize = lambda flat: unravel(flat[:n])  # flat -> pytree
        step.jitted = jitted  # inspectable (HLO contract tests, debugging)
        return step

    def _build_forward(self) -> Callable:
        model = self.model
        unravel = getattr(self, "_unravel", None)
        n = getattr(self, "_n", None)

        def fwd(params, buffers, data):
            if unravel is not None and isinstance(params, jax.Array):
                params = unravel(params[:n])
            out, _ = functional_apply(model, params, buffers, data, training=False)
            return out

        rep, bat = self._replicated, self._batch_sharding
        # fsdp: validation forward keeps the weights sharded too (XLA
        # gathers per layer); _build_step runs first and records the specs
        p_sh = getattr(self, "_param_sharding", rep)
        return tracked_jit(fwd, site="train.forward",
                           in_shardings=(p_sh, rep, bat), out_shardings=bat)

    # ------------------------------------------------------- optimizer state
    def _init_opt_state(self, params):
        if self.sync_mode != "sharded":
            return super()._init_opt_state(params)
        # Per-slice optimizer state: P× less memory (ZeRO-1), sharded layout.
        from jax.flatten_util import ravel_pytree
        flat, _ = ravel_pytree(params)
        n = flat.shape[0]
        pad = (-n) % self._n_data
        chunk = (n + pad) // self._n_data
        slice_proto = jnp.zeros((chunk,), flat.dtype)
        state = self.optim_method.init_state(slice_proto)
        # Broadcast scalar counters, shard vector state over the data axis.

        def place(x):
            x = jnp.asarray(x)
            if x.ndim >= 1 and x.shape[0] == chunk:
                tiled = jnp.tile(x, (self._n_data,) + (1,) * (x.ndim - 1)) \
                    if x.ndim > 1 else jnp.tile(x, self._n_data)
                return jax.device_put(tiled, NamedSharding(self.mesh, P(DATA_AXIS)))
            return jax.device_put(x, self._replicated)

        return jax.tree_util.tree_map(place, state)

    def _finalize_params(self, params):
        if self.sync_mode == "sharded" and isinstance(params, jax.Array):
            return self._unravel(np.asarray(params)[:self._n])
        return params
