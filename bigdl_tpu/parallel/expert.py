"""Expert parallelism: distributed mixture-of-experts.

The reference's ``MixtureTable`` (``nn/MixtureTable.scala:1``) is a
single-node MoE *gating container* — SURVEY §2.5 records "Expert
parallelism: ABSENT". ``MoE`` is its distributed descendant, built the
GShard/Switch way for TPU:

- top-k softmax gating with capacity limiting;
- sort-based ragged dispatch (default, round 10): ONE stable argsort of
  the round-major token→expert picks replaces the k× one-hot + cumsum +
  scatter-add position bookkeeping — capacity slots fall out of segment
  offsets (rank within the expert's sorted run), tokens GATHER into the
  (expert, capacity, d) buffers, and the combine reads back through the
  same indices. Static shapes, O(E·C·D) memory, and no (T, E)-wide
  cumsum chains or scatter traffic on the hot path;
- ``dispatch="scatter"`` keeps the round-5 scatter-add formulation and
  ``dispatch="einsum"`` the dense GShard-paper (T, E, C) masks, both for
  A/B comparison/debug — all three are bit-equivalent (same routing,
  same drop semantics, same combine op order);
- expert FFN weights STACKED on a leading expert axis; under expert
  parallelism those leaves are sharded ``P('expert', ...)`` and GSPMD turns
  the dispatch einsums into all_to_alls over the mesh ``expert`` axis —
  layout-as-strategy, same arrays as single-chip execution
  (``expert_param_specs``).
- the Switch load-balance auxiliary loss is folded into the backward pass
  via ``inject_loss`` (the autodiff analogue of the reference
  ``L1Penalty``'s gradient-injection trick), so training loops need no
  MoE-specific loss plumbing.

Tokens over capacity are dropped (their combine weight is zero and they
pass through the residual connection unchanged when used inside a
transformer block).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from bigdl_tpu.nn import initialization as init
from bigdl_tpu.nn.module import Module
from bigdl_tpu.parallel.mesh import EXPERT_AXIS


@jax.custom_vjp
def inject_loss(y, aux):
    """Identity on ``y`` that adds ``aux`` to the total loss through the
    backward pass (cotangent 1.0 regardless of downstream), so auxiliary
    losses compose without touching the training loop."""
    return y


def _inject_fwd(y, aux):
    return y, None


def _inject_bwd(_, g):
    return g, jnp.ones(())


inject_loss.defvjp(_inject_fwd, _inject_bwd)


class MoE(Module):
    """Top-k gated mixture of expert FFNs (distributed ``MixtureTable``).

    Input (..., D) — leading axes are flattened into a token axis. Each
    expert is a two-layer FFN D -> H -> D.
    """

    def __init__(self, input_size: int, hidden_size: int, n_experts: int,
                 k: int = 2, capacity_factor: float = 1.25,
                 activation: str = "gelu", aux_loss_weight: float = 1e-2,
                 dispatch: str = "sort"):
        super().__init__()
        if dispatch not in ("sort", "scatter", "einsum"):
            raise ValueError(f"dispatch must be 'sort', 'scatter' or "
                             f"'einsum', got {dispatch!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.n_experts = n_experts
        self.k = min(k, n_experts)
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.aux_loss_weight = aux_loss_weight
        self.dispatch = dispatch
        d, h, e = input_size, hidden_size, n_experts
        self.register_parameter("gate_weight", init.xavier((d, e), d, e))
        self.register_parameter(
            "w1", np.stack([init.xavier((d, h), d, h) for _ in range(e)]))
        self.register_parameter("b1", init.zeros((e, h)))
        self.register_parameter(
            "w2", np.stack([init.xavier((h, d), h, d) for _ in range(e)]))
        self.register_parameter("b2", init.zeros((e, d)))

    def _act(self, x):
        return jax.nn.gelu(x) if self.activation == "gelu" else jax.nn.relu(x)

    def update_output(self, input):
        orig_shape = input.shape
        d, e, k = self.input_size, self.n_experts, self.k
        x = input.reshape(-1, d)
        t = x.shape[0]
        capacity = max(1, int(np.ceil(t / e * self.capacity_factor * k)))
        capacity = min(capacity, t)

        from bigdl_tpu.telemetry import get_registry, instruments
        # trace-time count (like bigdl_int8_fallbacks_total): which
        # dispatch formulation each compiled MoE forward uses
        instruments(get_registry()).moe_dispatch_total.labels(
            path=self.dispatch).inc()

        logits = x @ self.gate_weight                      # (T, E)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

        # Iterative top-k routing: the pick/gate loop is shared by all
        # dispatch paths (identical argmax tie-breaking). Slot/keep
        # bookkeeping differs: sort derives it from ONE stable argsort
        # below; scatter/einsum keep the O(T·E) running-count cumsums.
        use_sort = self.dispatch == "sort"
        masked = probs
        fill = jnp.zeros((e,), jnp.int32)
        topk_mask = jnp.zeros_like(probs)
        picks = []  # (expert (T,), slot (T,), keep, gate weight w/ drops 0)
        for _ in range(k):
            pick = jnp.argmax(masked, axis=-1)             # (T,)
            onehot = jax.nn.one_hot(pick, e, dtype=jnp.float32)
            topk_mask = topk_mask + onehot
            gate = jnp.sum(probs * onehot, axis=-1)        # (T,)
            if use_sort:
                picks.append((pick, None, None, gate))
            else:
                # Position of each token in its expert's capacity buffer:
                # running count of earlier tokens routed to the same
                # expert; slots used accumulate across the k picks.
                pos = (jnp.cumsum(onehot, axis=0) - onehot) + fill[None, :]
                pos_t = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)
                keep = pos_t < capacity
                w = gate * keep                            # (T,)
                picks.append((pick, jnp.where(keep, pos_t, 0), keep, w))
                fill = fill + jnp.sum(onehot * keep[:, None],
                                      axis=0).astype(jnp.int32)
            masked = masked * (1.0 - onehot)

        if use_sort:
            # Sort-based slot assignment: flatten the picks round-major
            # (flat index j*T + t) and stable-argsort by expert. A pick's
            # rank within its expert's sorted run IS its capacity slot —
            # identical to the scatter bookkeeping, because positions
            # within a round count all of that round's picks and an
            # earlier-round drop implies the expert already saturated
            # (so later rounds drop under both schemes).
            kt = k * t
            expert_flat = jnp.concatenate([p for p, _, _, _ in picks])
            order = jnp.argsort(expert_flat, stable=True)   # (kT,)
            counts = jnp.bincount(expert_flat, length=e)    # (E,)
            offsets = (jnp.cumsum(counts) - counts).astype(jnp.int32)
            # inverse permutation: sorted position of each flat pick
            inv = jnp.zeros((kt,), jnp.int32).at[order].set(
                jnp.arange(kt, dtype=jnp.int32))
            slot_flat = inv - offsets[expert_flat]          # rank in expert
            keep_flat = slot_flat < capacity
            gate_flat = jnp.concatenate([g for _, _, _, g in picks])
            w_flat = gate_flat * keep_flat
            slot_flat = jnp.where(keep_flat, slot_flat, 0)
            picks = [(picks[j][0], slot_flat[j * t:(j + 1) * t],
                      keep_flat[j * t:(j + 1) * t],
                      w_flat[j * t:(j + 1) * t]) for j in range(k)]

        # Renormalise the k kept gate weights to sum 1 per token, then
        # rescale by the FULL top-k probability mass (drops included) —
        # GShard combine semantics.
        denom = sum(w for _, _, _, w in picks)             # (T,)
        scale = jnp.sum(probs * topk_mask, axis=-1)        # (T,)
        coef = scale / jnp.maximum(denom, 1e-9)

        # Dispatch + expert matmuls run in the COMPUTE dtype (bf16 under
        # the training policy: the MXU's native rate; round-4's forced-f32
        # dispatch was measured at 24.2% MFU — half the matmul rate was
        # left on the table). Gating/combine coefficients stay f32.
        cd = input.dtype
        xc = x
        if use_sort:
            # Pure-gather dispatch: expert e's capacity row c holds the
            # token of its c-th sorted pick (exactly the pick that got
            # slot c), zero-masked past the expert's real count. No
            # scatter traffic at all — XLA lowers this to gathers, and
            # under EP sharding the gather feeding the sharded expert
            # einsum still becomes the all_to_all over the expert axis.
            token_flat = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)
            sorted_tokens = token_flat[order]               # (kT,)
            src = offsets[:, None] + jnp.arange(capacity,
                                                dtype=jnp.int32)[None, :]
            valid = (jnp.arange(capacity, dtype=jnp.int32)[None, :]
                     < jnp.minimum(counts, capacity)[:, None])  # (E, C)
            gathered = sorted_tokens[jnp.clip(src, 0, kt - 1)]  # (E, C)
            xe = jnp.where(valid[:, :, None], xc[gathered], 0).astype(cd)
        elif self.dispatch == "scatter":
            # Ragged dispatch: dropped picks have w=0 and slot clamped to 0,
            # so their scatter contribution is zeroed and their gather-back
            # is weighted out.
            xe = jnp.zeros((e, capacity, d), cd)
            for pick, slot, keep, _ in picks:
                xe = xe.at[pick, slot].add(
                    xc * keep[:, None].astype(cd))
        else:
            dispatch_t = jnp.zeros((t, e, capacity), cd)
            for pick, slot, keep, _ in picks:
                dc = (jax.nn.one_hot(pick, e, dtype=cd)[:, :, None]
                      * jax.nn.one_hot(slot, capacity, dtype=cd)[:, None, :]
                      * keep[:, None, None].astype(cd))
                dispatch_t = dispatch_t + dc
            xe = jnp.einsum("tec,td->ecd", dispatch_t, xc)  # (E, C, D)

        hdn = self._act(jnp.einsum("ecd,edh->ech", xe,
                                   self.w1.astype(cd))
                        + self.b1.astype(cd)[:, None, :])
        ye = (jnp.einsum("ech,ehd->ecd", hdn, self.w2.astype(cd))
              + self.b2.astype(cd)[:, None, :])

        if self.dispatch in ("sort", "scatter"):
            # combine by (expert, slot) gather-back — same op order on
            # both paths, so sort is bit-equivalent to scatter
            y = jnp.zeros((t, d), jnp.float32)
            for pick, slot, _, w in picks:
                y = y + (w * coef)[:, None] * ye[pick, slot].astype(
                    jnp.float32)
            y = y.astype(input.dtype)
        else:
            combine = jnp.zeros((t, e, capacity), jnp.float32)
            for pick, slot, keep, w in picks:
                dc = (jax.nn.one_hot(pick, e)[:, :, None]
                      * jax.nn.one_hot(slot, capacity)[:, None, :]
                      * keep[:, None, None])
                combine = combine + dc * (w * coef)[:, None, None]
            y = jnp.einsum("tec,ecd->td", combine,
                           ye.astype(jnp.float32)).astype(input.dtype)

        if self.aux_loss_weight and self.training:
            # Switch-style load balance: E * sum_e f_e * p_e.
            frac = jnp.mean(topk_mask / k, axis=0)          # tokens per expert
            mean_p = jnp.mean(probs, axis=0)
            aux = e * jnp.sum(frac * mean_p) * self.aux_loss_weight
            y = inject_loss(y, aux)
        return y.reshape(orig_shape)

    def __repr__(self):
        return (f"MoE({self.input_size}->{self.hidden_size}, "
                f"experts={self.n_experts}, k={self.k})")


def expert_param_specs(moe: MoE, axis: str = EXPERT_AXIS):
    """PartitionSpecs sharding the stacked expert leaves over ``expert``."""
    return {"gate_weight": P(),
            "w1": P(axis, None, None), "b1": P(axis, None),
            "w2": P(axis, None, None), "b2": P(axis, None)}
