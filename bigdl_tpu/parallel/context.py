"""Sequence/context parallelism: ring attention and Ulysses (all-to-all).

New TPU-native capability — the reference has none (SURVEY §5.7: "Sequence
dim is never sharded across workers"; its long-sequence story stops at
pad-to-max batching, ``dataset/Transformer.scala:105-275``). Here the
sequence axis of attention is sharded over the mesh ``seq`` axis so context
length scales with the number of chips:

- **Ring attention** (`ring_attention`): every device keeps its query shard
  resident and streams key/value shards around the ICI ring with
  ``lax.ppermute``, folding each hop's partial attention into an
  online-softmax accumulator. Peak memory per chip is O(S/P); the ring
  overlaps compute with neighbor-to-neighbor ICI traffic, the layout
  collective-free XLA can't derive itself. On TPU each hop's partial runs
  the Pallas flash kernel (``flash_attention_with_lse`` — the LSE output
  plus its differentiable cotangent is exactly the statistic the
  cross-device combine needs); elsewhere the XLA ``attention_partial``
  path is used (``use_kernel`` overrides).
- **Ulysses** (`ulysses_attention`): two ``lax.all_to_all``s re-shard
  (seq-sharded -> head-sharded), run ordinary full-sequence attention
  locally per head group, and shard back. Cheaper for moderate S with
  enough heads (head count must divide by the axis size).

Both are called INSIDE ``shard_map`` bodies (the per-device view), with
arrays sharded (B, S/P, N, D) on the named axis. ``ring_self_attention``
wraps the whole thing in ``shard_map`` for single-call use and tests.

Causal layouts: with ``layout="contiguous"`` shards are consecutive
sequence chunks, so later devices do more causal work than earlier ones
and the ring serialises on the last. ``layout="zigzag"`` gives every
device an (early, late) chunk pair — chunk ``i`` and chunk ``2P-1-i`` —
balancing per-hop FLOPs across the ring (the standard striped fix).
Zigzag composes with the kernel hops too: each hop runs the flash kernel
on the 4 contiguous half-chunk pairs and folds them with the LSE
combine; the XLA partial path instead masks with explicit global
position vectors.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops.attention_core import (
    attention_partial, finalize_partial, online_softmax_combine)
from bigdl_tpu.utils.jax_compat import axis_size, pcast

_NEG = float(jnp.finfo(jnp.float32).min)


def _lse_combine(o_a, l_a, o_b, l_b):
    """Merge two (output, logsumexp) attention partials over disjoint key
    sets. o: (B, S, N, D) f32; l: (B, N, S) f32 with the finite ``_NEG``
    sentinel (not -inf) on all-masked rows, keeping this NaN-free."""
    m = jnp.maximum(l_a, l_b)
    ca = jnp.exp(l_a - m)
    cb = jnp.exp(l_b - m)
    s = ca + cb
    l_new = m + jnp.log(s)
    ca, cb = ca / s, cb / s
    o_new = (o_a * ca.transpose(0, 2, 1)[..., None]
             + o_b * cb.transpose(0, 2, 1)[..., None])
    return o_new, l_new


def _ring_hop_kernel(q, kc, vc, scale, kv_chunk, q_chunk, causal, interpret):
    """One (q chunk, kv chunk) pair's (o, lse) partial via the Pallas flash
    kernel.

    Causal classification: kv chunks strictly in the past are unmasked, the
    diagonal chunk runs the kernel's causal path, future chunks contribute
    the empty partial — all three as ``lax.switch`` branches since the
    chunk ids are traced. Chunks must be CONTIGUOUS sequence spans (the
    kernel's causal mask is positional within the pair); zigzag callers
    pass each contiguous half separately.
    """
    from bigdl_tpu.ops.flash_attention import flash_attention_with_lse

    def full(_):
        o, l = flash_attention_with_lse(q, kc, vc, causal=False, scale=scale,
                                        interpret=interpret)
        return o.astype(jnp.float32), l

    if not causal:
        return full(None)

    def diag(_):
        o, l = flash_attention_with_lse(q, kc, vc, causal=True, scale=scale,
                                        interpret=interpret)
        return o.astype(jnp.float32), l

    def skip(_):
        o = (q * 0.0).astype(jnp.float32)
        l = jnp.sum(o, axis=-1).transpose(0, 2, 1) + _NEG
        return o, l

    idx = jnp.where(kv_chunk < q_chunk, 0,
                    jnp.where(kv_chunk == q_chunk, 1, 2))
    return lax.switch(idx, [full, diag, skip], None)


def _zigzag_hop_kernel(q, kc, vc, scale, src, my, p, causal, interpret):
    """One zigzag hop's (o, lse) partial: the local shard is the
    contiguous-chunk pair (my, 2P-1-my) and the kv shard is the pair
    (src, 2P-1-src); run the flash kernel on the 4 contiguous half-chunk
    combinations and fold the kv halves per q half."""
    if not causal:
        # position-independent: one full-chunk launch, no split/fold cost
        return _ring_hop_kernel(q, kc, vc, scale, 0, 0, False, interpret)
    c2 = q.shape[1] // 2
    halves_q = ((q[:, :c2], my), (q[:, c2:], 2 * p - 1 - my))
    halves_kv = ((kc[:, :c2], vc[:, :c2], src),
                 (kc[:, c2:], vc[:, c2:], 2 * p - 1 - src))
    outs = []
    for qh, qid in halves_q:
        o, l = None, None
        for kh, vh, kid in halves_kv:
            oh, lh = _ring_hop_kernel(qh, kh, vh, scale, kid, qid, causal,
                                      interpret)
            if o is None:
                o, l = oh, lh
            else:
                o, l = _lse_combine(o, l, oh, lh)
        outs.append((o, l))
    return (jnp.concatenate([outs[0][0], outs[1][0]], axis=1),
            jnp.concatenate([outs[0][1], outs[1][1]], axis=2))


def zigzag_permutation(seq_len: int, p: int) -> np.ndarray:
    """Index permutation putting the zigzag layout into contiguous shards:
    after ``x[:, perm]`` a P-way contiguous split hands device ``i`` the
    global chunks ``(i, 2P-1-i)``. Requires ``seq_len % (2*p) == 0``."""
    assert seq_len % (2 * p) == 0, \
        f"zigzag needs seq ({seq_len}) divisible by 2*devices ({2 * p})"
    c2 = seq_len // (2 * p)
    idx = []
    for i in range(p):
        idx.extend(range(i * c2, (i + 1) * c2))
        j = 2 * p - 1 - i
        idx.extend(range(j * c2, (j + 1) * c2))
    return np.asarray(idx, dtype=np.int32)


def zigzag_inverse(seq_len: int, p: int) -> np.ndarray:
    perm = zigzag_permutation(seq_len, p)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len, dtype=np.int32)
    return inv


def _zigzag_positions(dev, chunk, p):
    """Global positions of a device's zigzag shard (device ``dev`` holds
    chunks ``dev`` and ``2P-1-dev``, each of ``chunk // 2``)."""
    c2 = chunk // 2
    ar = jnp.arange(c2)
    return jnp.concatenate([dev * c2 + ar, (2 * p - 1 - dev) * c2 + ar])


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = False,
                   scale: Optional[float] = None,
                   use_kernel: Optional[bool] = None,
                   interpret: Optional[bool] = None,
                   layout: str = "contiguous") -> jax.Array:
    """Ring attention over the named mesh axis (call inside shard_map).

    q, k, v: the local shard, (B, S/P, N, D); global sequence = P shards in
    axis-index order (``layout="contiguous"``) or the zigzag striping
    (``layout="zigzag"``, see ``zigzag_permutation``). Returns the local
    (B, S/P, N, D) output shard — the same math as full attention on the
    gathered sequence.
    """
    if scale is None:
        scale = 1.0 / float(q.shape[-1]) ** 0.5
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring layout {layout!r}")
    if use_kernel is None:
        import os
        # BIGDL_TPU_FLASH_XLA_BWD's recompute backward has no LSE-cotangent
        # plumbing, and the kernel-hop combine differentiates through lse —
        # the A/B lever must push the ring back to the XLA partial path.
        use_kernel = (jax.default_backend() == "tpu"
                      and not os.environ.get("BIGDL_TPU_FLASH_XLA_BWD"))
    p = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    chunk = q.shape[1]

    # Start with the local chunk, then pull each neighbour's around the ring.
    perm = [(i, (i + 1) % p) for i in range(p)]  # shard s lives on dev s+t at hop t

    b, s_loc, n, d = q.shape

    if use_kernel:
        def hop(t, carry):
            o, lse, kc, vc = carry
            src = (my - t) % p
            if layout == "zigzag":
                oh, lh = _zigzag_hop_kernel(q, kc, vc, scale, src, my, p,
                                            causal, interpret)
            else:
                oh, lh = _ring_hop_kernel(q, kc, vc, scale, src, my,
                                          causal, interpret)
            o, lse = _lse_combine(o, lse, oh, lh)
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            return o, lse, kc, vc

        # Derive zero carries from q so they inherit its device-varying
        # type under shard_map's vma checking.
        o0 = (q * 0.0).astype(jnp.float32)
        l0 = jnp.sum(o0, axis=-1).transpose(0, 2, 1) + _NEG
        o, lse, _, _ = lax.fori_loop(0, p, hop, (o0, l0, k, v))
        return o.astype(q.dtype)

    if layout == "zigzag":
        q_pos = _zigzag_positions(my, chunk, p)
    else:
        q_pos = my * chunk + jnp.arange(chunk)

    def hop(t, carry):
        acc, rsum, rmax, kc, vc = carry
        src = (my - t) % p  # which global chunk we hold at hop t
        if layout == "zigzag":
            k_pos = _zigzag_positions(src, chunk, p)
        else:
            k_pos = src * chunk + jnp.arange(chunk)
        pa, ps, pm = attention_partial(q, kc, vc, scale, k_offset=0,
                                       q_offset=0, causal=causal,
                                       q_pos=q_pos, k_pos=k_pos)
        acc, rsum, rmax = online_softmax_combine(acc, rsum, rmax, pa, ps, pm)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return acc, rsum, rmax, kc, vc

    acc = jnp.zeros((b, s_loc, n, d), jnp.float32)
    rsum = jnp.zeros((b, n, s_loc), jnp.float32)
    rmax = jnp.full((b, n, s_loc), _NEG, jnp.float32)
    # Mark the zero-init carries as device-varying over the ring axis —
    # required by shard_map's vma typing (the loop outputs vary over 'seq').
    acc, rsum, rmax = (pcast(x, (axis_name,), to="varying")
                       for x in (acc, rsum, rmax))
    acc, rsum, rmax, _, _ = lax.fori_loop(
        0, p, hop, (acc, rsum, rmax, k, v))
    return finalize_partial(acc, rsum).astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = False,
                      scale: Optional[float] = None) -> jax.Array:
    """Ulysses sequence parallelism (call inside shard_map).

    all_to_all turns the seq-sharded (B, S/P, N, D) into head-sharded
    (B, S, N/P, D), runs full attention locally, and reverses. Requires
    num_heads % axis_size == 0.
    """
    from bigdl_tpu.ops.attention_core import blockwise_attention
    p = axis_size(axis_name)
    n = q.shape[2]
    assert n % p == 0, f"heads {n} must divide seq axis size {p}"

    def to_heads(x):   # (B, S/P, N, D) -> (B, S, N/P, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):     # (B, S, N/P, D) -> (B, S/P, N, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = blockwise_attention(qh, kh, vh, causal=causal, scale=scale,
                              block_size=max(128, qh.shape[1] // 8))
    return to_seq(out)


def _wrap_shard_map(fn, mesh, axis_name):
    from jax.sharding import PartitionSpec as P
    from bigdl_tpu.utils.jax_compat import shard_map
    spec = P(None, axis_name, None, None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)


def ring_self_attention(q, k, v, mesh, axis_name: str = "seq",
                        causal: bool = False,
                        scale: Optional[float] = None,
                        mode: str = "ring",
                        use_kernel: Optional[bool] = None,
                        interpret: Optional[bool] = None,
                        layout: str = "contiguous") -> jax.Array:
    """Whole-array convenience: shards (B, S, N, D) over ``axis_name`` of
    ``mesh``, runs ring/Ulysses attention, returns the full array view.

    ``layout="zigzag"`` permutes the sequence into the balanced striping
    before sharding and permutes the output back — callers see normal
    sequence order in and out.
    """
    if mode == "ring":
        impl = functools.partial(ring_attention, use_kernel=use_kernel,
                                 interpret=interpret, layout=layout)
    else:
        impl = ulysses_attention
    fn = functools.partial(impl, axis_name=axis_name, causal=causal,
                           scale=scale)
    wrapped = _wrap_shard_map(fn, mesh, axis_name)
    if mode == "ring" and layout == "zigzag":
        s = q.shape[1]
        p = mesh.shape[axis_name]
        fwd = jnp.asarray(zigzag_permutation(s, p))
        inv = jnp.asarray(zigzag_inverse(s, p))
        out = wrapped(jnp.take(q, fwd, axis=1), jnp.take(k, fwd, axis=1),
                      jnp.take(v, fwd, axis=1))
        return jnp.take(out, inv, axis=1)
    return wrapped(q, k, v)
