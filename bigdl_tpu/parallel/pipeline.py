"""Pipeline parallelism: GPipe + interleaved (circular) schedules over the
mesh ``pipe`` axis.

New capability — the reference has none (SURVEY §2.5: "Pipeline parallelism:
ABSENT"). TPU-native design:

- A deep model is expressed as ``PipelineStack``: ``depth`` repetitions of a
  homogeneous block whose parameters are STACKED on a leading layer axis
  (leaves shaped (depth, ...)). Single-device forward is a ``lax.scan`` over
  the layer axis (this is also the memory-friendly way to run deep
  transformers on one chip — one compiled block body, not ``depth`` inlined
  copies). Blocks MAY carry buffers (BatchNorm running stats): buffers are
  stacked per layer and updated microbatch-sequentially, the same semantics
  gradient-accumulation frameworks use.
- Under pipeline parallelism the layer axis is simply SHARDED over the mesh
  ``pipe`` axis (spec ``P('pipe', ...)``): each device owns ``depth/P``
  stacked layers. ``gpipe_loss_fn`` runs the schedule inside ``shard_map``:
  microbatches enter stage 0 and march stage-to-stage via ``lax.ppermute``
  (neighbour ICI hops). ``jax.grad`` through the schedule IS the backward
  pipeline — ppermute's transpose reverses the ring.
- The schedule loop is a ``lax.scan`` over time steps (NOT a Python-unrolled
  loop): trace/compile time is flat in the microbatch count, so deep
  pipelines can run n_micro >> stages, where the GPipe bubble
  (P-1)/(M+P-1) vanishes.
- ``interleave=V`` selects the circular schedule: each device owns V
  round-robin layer chunks (layer l lives on device l % P), a microbatch
  rides the ring V times, and the bubble shrinks V-fold to
  (P-1)/(V*M+P-1) at the cost of buffering up to M-P in-flight microbatch
  activations on stage 0. Requires n_micro >= P. Use
  ``circular_permutation`` to pre-permute the stacked layer axis so the
  plain ``P('pipe')`` sharding hands each device its V chunks.

The stacked layout means pipeline parallelism here is a *sharding choice*
over the same arrays as single-chip execution — switching P (or V) requires
no re-partitioning of the model definition, matching the framework's "one
mesh, many layouts" design.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.module import Module, functional_apply
from bigdl_tpu.parallel.mesh import PIPELINE_AXIS
from bigdl_tpu.utils.jax_compat import axis_size, pcast


class PipelineStack(Module):
    """``depth`` copies of ``block`` with parameters stacked on axis 0.

    ``block_factory()`` must build a block whose output shape equals its
    input shape (transformer blocks, residual conv blocks). Blocks may
    carry buffers (BatchNorm running stats): buffer leaves are stacked per
    layer like parameters and updated as each microbatch passes.
    """

    def __init__(self, block_factory: Callable[[], Module], depth: int):
        super().__init__()
        self.depth = depth
        self.block = block_factory()
        per_layer, per_layer_buf = [], []
        for _ in range(depth):
            b = block_factory()
            per_layer.append(b.parameter_tree())
            per_layer_buf.append(b.buffer_tree())
        self._stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_layer)
        self._stacked_buf = (jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_layer_buf)
            if per_layer_buf[0] else {})

    # The stacked trees ARE this module's parameters/buffers.
    def parameter_tree(self) -> Dict[str, Any]:
        return self._stacked

    def load_parameter_tree(self, tree) -> None:
        self._stacked = tree

    def buffer_tree(self) -> Dict[str, Any]:
        return self._stacked_buf

    def load_buffer_tree(self, tree) -> None:
        self._stacked_buf = tree

    @property
    def has_buffers(self) -> bool:
        return bool(self._stacked_buf)

    def scan_apply(self, params, x, training: bool = False, buffers=None):
        """Sequential forward: scan over the layer axis. Returns ``out`` or
        ``(out, new_buffers)`` when the stack carries buffers."""
        block = self.block
        with_buf = buffers is not None and self.has_buffers

        def body(h, xs):
            if with_buf:
                layer_params, layer_buf = xs
                out, new_buf = functional_apply(block, layer_params,
                                                layer_buf, h,
                                                training=training)
                return out, new_buf
            out, _ = functional_apply(block, xs, {}, h, training=training)
            return out, None

        xs = (params, buffers) if with_buf else params
        out, ys = lax.scan(body, x, xs)
        if with_buf:
            return out, ys
        return out

    def update_output(self, input):
        if self.has_buffers:
            out, new_buf = self.scan_apply(self.parameter_tree(), input,
                                           training=self.training,
                                           buffers=self.buffer_tree())
            if self.training:
                self._stacked_buf = new_buf
            return out
        return self.scan_apply(self.parameter_tree(), input,
                               training=self.training)

    def __repr__(self):
        return f"PipelineStack(depth={self.depth}, block={self.block!r})"


def pipeline_spec_tree(stack: PipelineStack, axis: str = PIPELINE_AXIS):
    """PartitionSpecs sharding the stacked layer axis over ``pipe``."""
    from jax.sharding import PartitionSpec as P
    return jax.tree_util.tree_map(
        lambda leaf: P(axis, *([None] * (leaf.ndim - 1))),
        stack.parameter_tree())


def circular_permutation(depth: int, p: int, interleave: int) -> np.ndarray:
    """Layer permutation for the circular schedule: the plain contiguous
    ``P('pipe')`` shard of device ``d`` then contains its V round-robin
    chunks in chunk order — chunk ``v`` of device ``d`` holds true layers
    ``[(v*p + d)*c, (v*p + d + 1)*c)`` with ``c = depth / (p*V)``."""
    assert depth % (p * interleave) == 0, (depth, p, interleave)
    c = depth // (p * interleave)
    return np.asarray([(v * p + d) * c + j
                       for d in range(p)
                       for v in range(interleave)
                       for j in range(c)], dtype=np.int32)


def schedule_length(n_micro: int, p: int, interleave: int = 1) -> int:
    """Time steps of the schedule: bubble fraction = (P-1)/length."""
    return n_micro * interleave + p - 1


def gpipe_apply(stack: PipelineStack, local_params, x,
                n_micro: int, axis_name: str = PIPELINE_AXIS,
                training: bool = False, remat: bool = False,
                local_buffers=None):
    """GPipe forward INSIDE shard_map.

    local_params: this stage's slice, leaves (depth/P, ...).
    x: full batch (replicated over the pipe axis); batch size must divide
    by ``n_micro``. Returns the model output (replicated over the axis), or
    ``(output, new_local_buffers)`` when buffers are passed.
    ``remat=True`` recomputes each stage's internals in the backward
    (``jax.checkpoint``), bounding live activation memory at one microbatch
    boundary per schedule slot — the standard deep-pipeline recipe.

    The time loop is a ``lax.scan``: one compiled step body regardless of
    ``n_micro`` (compile time flat in microbatch count).
    """
    p = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} must divide into {n_micro} microbatches"
    mbs = x.reshape(n_micro, b // n_micro, *x.shape[1:])
    with_buf = local_buffers is not None and stack.has_buffers

    def stage_fn(h, bufs):
        if with_buf:
            return stack.scan_apply(local_params, h, training=training,
                                    buffers=bufs)
        return stack.scan_apply(local_params, h, training=training), bufs

    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    perm = [(i, (i + 1) % p) for i in range(p)]
    state0 = pcast(jnp.zeros_like(mbs[0]), (axis_name,), to="varying")
    out_buf0 = pcast(jnp.zeros_like(mbs), (axis_name,), to="varying")
    is_first = (idx == 0)
    is_last = (idx == p - 1)

    def step(carry, t):
        state, out_buf, bufs = carry
        feed = lax.dynamic_index_in_dim(
            mbs, jnp.minimum(t, n_micro - 1), 0, keepdims=False)
        inp = jnp.where(is_first & (t < n_micro), feed, state)
        out, new_bufs = stage_fn(inp, bufs)
        if with_buf:
            # Idle (bubble) steps see garbage activations: a stage's
            # buffers may only advance while it holds a real microbatch.
            active = (t >= idx) & (t < idx + n_micro)
            bufs = jax.tree_util.tree_map(
                lambda nb, ob: jnp.where(active, nb, ob), new_bufs, bufs)
        w = t - (p - 1)
        upd = lax.dynamic_update_index_in_dim(out_buf, out,
                                              jnp.maximum(w, 0), 0)
        out_buf = jnp.where(is_last & (w >= 0), upd, out_buf)
        state = lax.ppermute(out, axis_name, perm)
        return (state, out_buf, bufs), None

    (_, out_buf, bufs), _ = lax.scan(
        step, (state0, out_buf0, local_buffers),
        jnp.arange(schedule_length(n_micro, p)))

    # Only the last stage holds real outputs; psum replicates them (its
    # transpose broadcasts the output cotangent back to the last stage).
    out_buf = lax.psum(out_buf, axis_name)
    out = out_buf.reshape(b, *out_buf.shape[2:])
    if with_buf:
        return out, bufs
    return out


def circular_apply(stack: PipelineStack, local_params, x, n_micro: int,
                   interleave: int, axis_name: str = PIPELINE_AXIS,
                   training: bool = False, remat: bool = False):
    """Interleaved (circular) pipeline forward INSIDE shard_map.

    Device ``d`` holds ``interleave`` (=V) round-robin layer chunks (the
    ``circular_permutation`` layout); items ride the ring V times in a
    chunk-major conveyor (all microbatches of chunk v, then chunk v+1),
    so the steady-state bubble is ``(P-1)/(V*M+P-1)`` — V times smaller
    than GPipe. Requires ``n_micro >= P`` (the wrap-around latency) and a
    buffer of ``M-P+1`` in-flight activations. Buffered stacks are not
    supported here (use the GPipe schedule for BatchNorm stacks).
    """
    assert not stack.has_buffers, \
        "circular schedule supports buffer-free stacks only"
    p = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    v = interleave
    b = x.shape[0]
    m = n_micro
    assert b % m == 0, f"batch {b} must divide into {m} microbatches"
    assert m >= p, f"circular schedule needs n_micro ({m}) >= stages ({p})"
    mbs = x.reshape(m, b // m, *x.shape[1:])

    local_depth = jax.tree_util.tree_leaves(local_params)[0].shape[0]
    assert local_depth % v == 0, (local_depth, v)
    lc = local_depth // v

    def chunk_fn(vv, h):
        chunk_params = jax.tree_util.tree_map(
            lambda leaf: lax.dynamic_slice_in_dim(leaf, vv * lc, lc, 0),
            local_params)
        return stack.scan_apply(chunk_params, h, training=training)

    if remat:
        chunk_fn = jax.checkpoint(chunk_fn)

    perm = [(i, (i + 1) % p) for i in range(p)]
    delay = m - p  # steps a wrapped activation waits before stage 0 reuses it
    state0 = pcast(jnp.zeros_like(mbs[0]), (axis_name,), to="varying")
    fifo0 = pcast(
        jnp.zeros((delay + 1,) + mbs.shape[1:], mbs.dtype),
        (axis_name,), to="varying")
    out_buf0 = pcast(jnp.zeros_like(mbs), (axis_name,), to="varying")
    is_first = (idx == 0)
    is_last = (idx == p - 1)

    def step(carry, t):
        state, fifo, out_buf = carry
        # Item s = v*M + m_i on device d at time t = s + d.
        s = jnp.clip(t - idx, 0, v * m - 1)
        vv, mi = s // m, s % m
        fresh = lax.dynamic_index_in_dim(mbs, mi, 0, keepdims=False)
        # Stage 0's chunk-v>0 input: the wrap-around delivery of item
        # s - M (written to the fifo at step s - M + P - 1) is consumed
        # ``delay`` steps later — which is exactly when its slot comes up
        # for rewrite, so read slot t BEFORE this step's write below.
        recycled = lax.dynamic_index_in_dim(
            fifo, t % (delay + 1), 0, keepdims=False)
        inp = jnp.where(is_first, jnp.where(vv == 0, fresh, recycled), state)
        out = chunk_fn(vv, inp)
        # Last chunk done on last device: record microbatch output.
        w = jnp.maximum(s - (v - 1) * m, 0)
        upd = lax.dynamic_update_index_in_dim(out_buf, out, w, 0)
        out_buf = jnp.where(is_last & (vv == v - 1) & (t - idx >= 0),
                            upd, out_buf)
        nxt = lax.ppermute(out, axis_name, perm)
        fifo = lax.dynamic_update_index_in_dim(fifo, nxt,
                                               t % (delay + 1), 0)
        return (nxt, fifo, out_buf), None

    (_, _, out_buf), _ = lax.scan(
        step, (state0, fifo0, out_buf0),
        jnp.arange(schedule_length(m, p, v)))
    out_buf = lax.psum(out_buf, axis_name)
    return out_buf.reshape(b, *out_buf.shape[2:])


def gpipe_loss_fn(stack: PipelineStack, criterion, mesh,
                  n_micro: int, axis_name: str = PIPELINE_AXIS,
                  head: Optional[Callable] = None, remat: bool = False,
                  interleave: int = 1,
                  data_axis: Optional[str] = None):
    """(stacked_params, head_params, x, labels) -> scalar loss, jittable;
    with a buffered stack the signature gains a buffers argument and the
    return becomes ``(loss, new_buffers)``.

    Wraps the schedule in shard_map over ``mesh``; ``head`` is an optional
    pure fn (head_params, features) -> logits applied after the stack
    (replicated — run it on every stage; it is tiny relative to the stack).
    ``interleave=V > 1`` selects the circular schedule (pass parameters
    pre-permuted with ``circular_permutation``).

    ``data_axis``: dp x pp composition — the batch shards over this mesh
    axis (each data group runs an independent pipeline over its slice)
    and the per-group mean losses ``pmean`` into the global loss, so
    ``jax.grad`` yields dp-averaged gradients exactly like
    DistriOptimizer's allreduce plane.
    """
    from bigdl_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    p_specs = pipeline_spec_tree(stack, axis_name)
    x_spec = P(data_axis) if data_axis else P()

    if stack.has_buffers:
        assert interleave == 1, \
            "circular schedule supports buffer-free stacks only"
        assert data_axis is None, (
            "buffered stacks under dp would need cross-group stat "
            "merging; use buffer-free blocks with data_axis")
        b_specs = jax.tree_util.tree_map(
            lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))),
            stack.buffer_tree())

        def local_fn_buf(stacked, bufs, head_params, x, labels):
            feats, new_bufs = gpipe_apply(stack, stacked, x, n_micro,
                                          axis_name, training=True,
                                          remat=remat, local_buffers=bufs)
            logits = head(head_params, feats) if head is not None else feats
            loss = criterion.apply(logits, labels).astype(jnp.float32)
            return loss, new_bufs

        return shard_map(
            local_fn_buf, mesh=mesh,
            in_specs=(p_specs, b_specs, P(), P(), P()),
            out_specs=(P(), b_specs),
            check_vma=False)

    def local_fn(stacked, head_params, x, labels):
        if interleave > 1:
            feats = circular_apply(stack, stacked, x, n_micro, interleave,
                                   axis_name, training=True, remat=remat)
        else:
            feats = gpipe_apply(stack, stacked, x, n_micro, axis_name,
                                training=True, remat=remat)
        logits = head(head_params, feats) if head is not None else feats
        loss = criterion.apply(logits, labels).astype(jnp.float32)
        if data_axis:
            loss = lax.pmean(loss, data_axis)
        return loss

    return shard_map(
        local_fn, mesh=mesh,
        in_specs=(p_specs, P(), x_spec, x_spec),
        out_specs=P(),
        check_vma=False)


# ---------------------------------------------------------------------------
# Heterogeneous stage-list pipelining (round 4)
# ---------------------------------------------------------------------------

class StagePipeline:
    """GPipe over a LIST of arbitrary, shape-heterogeneous stages — the API
    that pipelines a REAL model end-to-end: ``[embedding+blocks, blocks,
    blocks+norm+head]`` for an LM, or ResNet-50's four stages (each with a
    different activation shape).

    ``PipelineStack`` requires homogeneous blocks because its schedule
    scans one block body over a stacked layer axis and ships one
    fixed-shape activation around the ring. Heterogeneity breaks both, so
    this class restores the two invariants XLA needs by construction:

    - per-device COMPUTE: each device runs its own stage through
      ``lax.switch`` on the stage index — one compiled program containing
      every stage body, each device executing only its own at runtime
      (SPMD programs must be identical; the switch makes them so);
    - fixed-shape TRANSPORT: per-stage parameters ravel into one
      (P, max_param_len) array (sharded over ``pipe`` — each device holds
      only its own stage's weights, preserving pipeline memory scaling),
      and inter-stage activations travel as a flat conduit padded to the
      LARGEST boundary activation, unpacked per stage to its static shape
      inside the switch branch.

    Stage modules may carry CONSTANT buffers (a PositionalEncoding table)
    — they ride along as compile-time constants — but not step-MUTABLE
    ones (BatchNorm running stats, decode caches): bubble steps would
    corrupt them, so mutation is detected at construction (one real
    forward per stage on the sample microbatch, before/after comparison)
    and rejected; use norm-free/LayerNorm stages, or the homogeneous
    ``PipelineStack`` which threads buffers. Shapes are discovered on the
    same probe forward, so stages may change the activation shape
    arbitrarily (downsampling convs, vocab heads). ``jax.grad`` through
    the schedule is the backward pipeline, exactly as for
    ``PipelineStack``.
    """

    def __init__(self, stages, sample_microbatch):
        if len(stages) < 2:
            raise ValueError("need at least 2 stages to pipeline")
        self.stages = list(stages)
        p = len(stages)
        from jax.flatten_util import ravel_pytree
        flats, self._unravels, lens = [], [], []
        for st in stages:
            flat, unravel = ravel_pytree(st.parameter_tree())
            flats.append(flat)
            self._unravels.append(unravel)
            lens.append(flat.shape[0])
        self._param_lens = lens
        self.max_param_len = max(lens)
        # HOST-side stack (numpy): the full (P, max_len) array must never
        # materialise on one device — pipelining exists precisely for
        # models that exceed one chip's HBM. The caller device_puts it
        # with pipe.spec(), so each device only ever receives its row.
        self._stacked = np.stack([
            np.pad(np.asarray(f), (0, self.max_param_len - f.shape[0]))
            for f in flats])

        # probe forward per stage: discovers boundary shapes AND proves the
        # stage's buffers are step-constant (mutable state cannot survive
        # the schedule's bubble steps)
        x = jnp.asarray(sample_microbatch)
        self._in_shapes, self._in_dtypes, self._const_bufs = [], [], []
        for i, st in enumerate(stages):
            self._in_shapes.append(tuple(x.shape))
            self._in_dtypes.append(x.dtype)
            bufs = st.buffer_tree()
            self._const_bufs.append(bufs)
            x, new_bufs = functional_apply(st, st.parameter_tree(), bufs, x,
                                           training=True)
            changed = [
                k for k, (a, b) in enumerate(zip(
                    jax.tree_util.tree_leaves(bufs),
                    jax.tree_util.tree_leaves(new_bufs)))
                if not np.allclose(np.asarray(a), np.asarray(b))]
            if changed:
                raise ValueError(
                    f"stage {i} mutates buffers during forward (BatchNorm "
                    "running stats?); StagePipeline needs step-constant "
                    "stages — use LayerNorm/GroupNorm, or the homogeneous "
                    "PipelineStack which threads buffers")
        self.out_shape, self.out_dtype = tuple(x.shape), x.dtype
        # the conduit carries stage-boundary activations AND stage 0's
        # fresh feed (same buffer via the is_first select), so size to the
        # largest of all of them
        sizes = [int(np.prod(s)) for s in self._in_shapes]
        sizes.append(int(np.prod(self.out_shape)))
        self.conduit_len = max(sizes)

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    def parameter_tree(self):
        """(P, max_param_len) — shard row-wise over the ``pipe`` axis."""
        return self._stacked

    def spec(self, axis: str = PIPELINE_AXIS):
        from jax.sharding import PartitionSpec as P
        return P(axis, None)

    def unstack_parameter_trees(self, stacked):
        """Inverse of the stacked layout: per-stage pytrees (for moving
        trained weights back into the stage modules / checkpoints)."""
        return [self._unravels[i](stacked[i, :self._param_lens[i]])
                for i in range(len(self.stages))]

    def sequential_apply(self, stacked, x, training: bool = True):
        """Reference forward (no pipelining): the exact math the schedule
        must reproduce; used by differential tests and single-device runs."""
        h = x
        for i, st in enumerate(self.stages):
            params = self._unravels[i](stacked[i, :self._param_lens[i]])
            h, _ = functional_apply(st, params, self._const_bufs[i], h,
                                    training=training)
        return h

    def _branch(self, i, training: bool):
        """Stage i body: flat conduit in -> flat conduit out."""
        st = self.stages[i]
        in_shape, in_dtype = self._in_shapes[i], self._in_dtypes[i]
        n_in = int(np.prod(in_shape))
        bufs = self._const_bufs[i]  # step-constant, proven at __init__

        def body(flat_params, conduit):
            params = self._unravels[i](flat_params[:self._param_lens[i]])
            h = conduit[:n_in].reshape(in_shape).astype(in_dtype)
            out, _ = functional_apply(st, params, bufs, h,
                                      training=training)
            flat = out.astype(jnp.float32).reshape(-1)
            return jnp.pad(flat, (0, self.conduit_len - flat.shape[0]))

        return body

    def pipeline_apply(self, local_stacked, x, n_micro: int,
                       axis_name: str = PIPELINE_AXIS,
                       remat: bool = False, training: bool = True):
        """GPipe schedule INSIDE shard_map: microbatches enter stage 0,
        march stage-to-stage via ``lax.ppermute`` in the flat conduit, and
        the last stage's outputs are psum-replicated (transpose: the
        output cotangent re-enters the backward ring at the last stage)."""
        p = axis_size(axis_name)
        assert p == len(self.stages), (
            f"mesh '{axis_name}' axis ({p}) must equal the stage count "
            f"({len(self.stages)})")
        idx = lax.axis_index(axis_name)
        b = x.shape[0]
        assert b % n_micro == 0, (b, n_micro)
        mb = self._in_shapes[0][0]
        assert b // n_micro == mb, (
            f"microbatch {b}//{n_micro}={b // n_micro} != sample_microbatch "
            f"batch {mb} used at construction (conduit sizes are static)")
        mbs = x.reshape(n_micro, b // n_micro, *x.shape[1:])
        n_in0 = int(np.prod(self._in_shapes[0]))
        out_len = int(np.prod(self.out_shape))

        branches = [self._branch(i, training) for i in range(p)]
        if remat:
            branches = [jax.checkpoint(fn) for fn in branches]

        def compute(flat_params, conduit):
            return lax.switch(idx, branches, flat_params[0], conduit)

        perm = [(i, (i + 1) % p) for i in range(p)]
        state0 = pcast(jnp.zeros((self.conduit_len,), jnp.float32),
                           (axis_name,), to="varying")
        out_buf0 = pcast(
            jnp.zeros((n_micro, out_len), jnp.float32),
            (axis_name,), to="varying")
        is_first = (idx == 0)
        is_last = (idx == p - 1)

        def step(carry, t):
            state, out_buf = carry
            feed = lax.dynamic_index_in_dim(
                mbs, jnp.minimum(t, n_micro - 1), 0,
                keepdims=False).astype(jnp.float32).reshape(-1)
            feed = jnp.pad(feed, (0, self.conduit_len - n_in0))
            inp = jnp.where(is_first & (t < n_micro), feed, state)
            out = compute(local_stacked, inp)
            w = t - (p - 1)
            upd = lax.dynamic_update_index_in_dim(
                out_buf, out[:out_len], jnp.maximum(w, 0), 0)
            out_buf = jnp.where(is_last & (w >= 0), upd, out_buf)
            state = lax.ppermute(out, axis_name, perm)
            return (state, out_buf), None

        (_, out_buf), _ = lax.scan(
            step, (state0, out_buf0),
            jnp.arange(schedule_length(n_micro, p)))
        out_buf = lax.psum(out_buf, axis_name)
        mb = b // n_micro
        return out_buf.reshape(n_micro * mb, *self.out_shape[1:]) \
            .astype(self.out_dtype)


def stage_pipeline_loss_fn(pipe: StagePipeline, criterion, mesh,
                           n_micro: int, axis_name: str = PIPELINE_AXIS,
                           remat: bool = False,
                           data_axis: Optional[str] = None):
    """(stacked_params (P, L), x, labels) -> scalar loss, jittable.

    The heterogeneous counterpart of ``gpipe_loss_fn``: pass
    ``pipe.parameter_tree()`` placed with ``pipe.spec()`` so each device
    holds only its stage's weights. ``data_axis`` composes dp x pp the
    same way (independent pipelines per data group, pmean'd loss)."""
    from bigdl_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    x_spec = P(data_axis) if data_axis else P()

    def local_fn(stacked, x, labels):
        feats = pipe.pipeline_apply(stacked, x, n_micro, axis_name,
                                    remat=remat)
        loss = criterion.apply(feats, labels).astype(jnp.float32)
        if data_axis:
            loss = lax.pmean(loss, data_axis)
        return loss

    return shard_map(local_fn, mesh=mesh,
                     in_specs=(pipe.spec(axis_name), x_spec, x_spec),
                     out_specs=P(), check_vma=False)
