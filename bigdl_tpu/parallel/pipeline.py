"""Pipeline parallelism: GPipe microbatch schedule over the mesh ``pipe`` axis.

New capability — the reference has none (SURVEY §2.5: "Pipeline parallelism:
ABSENT"). TPU-native design:

- A deep model is expressed as ``PipelineStack``: ``depth`` repetitions of a
  homogeneous block whose parameters are STACKED on a leading layer axis
  (leaves shaped (depth, ...)). Single-device forward is a ``lax.scan`` over
  the layer axis (this is also the memory-friendly way to run deep
  transformers on one chip — one compiled block body, not ``depth`` inlined
  copies).
- Under pipeline parallelism the layer axis is simply SHARDED over the mesh
  ``pipe`` axis (spec ``P('pipe', ...)``): each device owns
  ``depth/P`` contiguous layers = one stage. ``gpipe_loss_fn`` runs the
  GPipe schedule inside ``shard_map``: microbatches enter stage 0, march
  stage-to-stage via ``lax.ppermute`` (neighbour ICI hops), and the bubble
  costs (P-1)/(M+P-1) of the wall clock. ``jax.grad`` through the schedule
  IS the backward pipeline — ppermute's transpose reverses the ring, so the
  1F1B-style reverse traffic needs no extra code.

The stacked layout means pipeline parallelism here is a *sharding choice*
over the same arrays as single-chip execution — switching P requires no
re-partitioning of the model definition, matching the framework's "one mesh,
many layouts" design.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module, functional_apply
from bigdl_tpu.parallel.mesh import PIPELINE_AXIS


class PipelineStack(Module):
    """``depth`` copies of ``block`` with parameters stacked on axis 0.

    ``block_factory()`` must build a block whose output shape equals its
    input shape (transformer blocks, residual conv blocks) and which carries
    no buffers (BatchNorm: use LayerNorm/GroupNorm instead — running stats
    across pipeline stages are not well-defined under microbatching).
    """

    def __init__(self, block_factory: Callable[[], Module], depth: int):
        super().__init__()
        self.depth = depth
        self.block = block_factory()
        assert not self.block.buffer_tree(), (
            "PipelineStack blocks must be buffer-free (no BatchNorm)")
        per_layer = []
        for _ in range(depth):
            per_layer.append(block_factory().parameter_tree())
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_layer)
        self._stacked = stacked  # dict tree; leaves (depth, ...)

    # The stacked tree IS this module's parameters.
    def parameter_tree(self) -> Dict[str, Any]:
        return self._stacked

    def load_parameter_tree(self, tree) -> None:
        self._stacked = tree

    def buffer_tree(self) -> Dict[str, Any]:
        return {}

    def load_buffer_tree(self, tree) -> None:
        pass

    def scan_apply(self, params, x, training: bool = False):
        """Sequential (single-device) forward: scan over the layer axis."""
        block = self.block

        def body(h, layer_params):
            out, _ = functional_apply(block, layer_params, {}, h,
                                      training=training)
            return out, None

        out, _ = lax.scan(body, x, params)
        return out

    def update_output(self, input):
        return self.scan_apply(self.parameter_tree(), input,
                               training=self.training)

    def __repr__(self):
        return f"PipelineStack(depth={self.depth}, block={self.block!r})"


def pipeline_spec_tree(stack: PipelineStack, axis: str = PIPELINE_AXIS):
    """PartitionSpecs sharding the stacked layer axis over ``pipe``."""
    from jax.sharding import PartitionSpec as P
    return jax.tree_util.tree_map(
        lambda leaf: P(axis, *([None] * (leaf.ndim - 1))),
        stack.parameter_tree())


def gpipe_apply(stack: PipelineStack, local_params, x,
                n_micro: int, axis_name: str = PIPELINE_AXIS,
                training: bool = False, remat: bool = False):
    """GPipe forward INSIDE shard_map.

    local_params: this stage's slice, leaves (depth/P, ...).
    x: full batch (replicated over the pipe axis); batch size must divide
    by ``n_micro``. Returns the model output, replicated over the axis.
    ``remat=True`` recomputes each stage's internals in the backward
    (``jax.checkpoint``), bounding live activation memory at one microbatch
    boundary per schedule slot — the standard deep-pipeline recipe.
    """
    p = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} must divide into {n_micro} microbatches"
    mbs = x.reshape(n_micro, b // n_micro, *x.shape[1:])

    def stage_fn(h):
        return stack.scan_apply(local_params, h, training=training)

    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    perm = [(i, (i + 1) % p) for i in range(p)]
    state = jnp.zeros_like(mbs[0])
    state = lax.pcast(state, (axis_name,), to="varying")
    out_buf = lax.pcast(jnp.zeros_like(mbs), (axis_name,), to="varying")
    is_first = (idx == 0)
    is_last = (idx == p - 1)

    for t in range(n_micro + p - 1):
        feed = mbs[min(t, n_micro - 1)]
        inp = jnp.where(is_first & (t < n_micro), feed, state)
        out = stage_fn(inp)
        w = t - (p - 1)
        if w >= 0:
            upd = lax.dynamic_update_index_in_dim(out_buf, out, w, 0)
            out_buf = jnp.where(is_last, upd, out_buf)
        state = lax.ppermute(out, axis_name, perm)

    # Only the last stage holds real outputs; psum replicates them (its
    # transpose broadcasts the output cotangent back to the last stage).
    out_buf = lax.psum(out_buf, axis_name)
    return out_buf.reshape(b, *out_buf.shape[2:])


def gpipe_loss_fn(stack: PipelineStack, criterion, mesh,
                  n_micro: int, axis_name: str = PIPELINE_AXIS,
                  head: Optional[Callable] = None, remat: bool = False):
    """(stacked_params, head_params, x, labels) -> scalar loss, jittable.

    Wraps the schedule in shard_map over ``mesh``; ``head`` is an optional
    pure fn (head_params, features) -> logits applied after the stack
    (replicated — run it on every stage; it is tiny relative to the stack).
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    p_specs = pipeline_spec_tree(stack, axis_name)

    def local_fn(stacked, head_params, x, labels):
        feats = gpipe_apply(stack, stacked, x, n_micro, axis_name,
                            training=True, remat=remat)
        logits = head(head_params, feats) if head is not None else feats
        loss = criterion.apply(logits, labels).astype(jnp.float32)
        return loss

    fn = shard_map(
        local_fn, mesh=mesh,
        in_specs=(p_specs, P(), P(), P()),
        out_specs=P(),
        check_vma=False)
    return fn
