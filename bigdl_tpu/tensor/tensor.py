"""The Tensor/Storage façade (reference ``tensor/Tensor.scala:35`` API,
``tensor/TensorMath.scala:28`` math surface, ``tensor/Storage.scala:27``).

Semantics contract:
- dimension and index arguments are **1-based** (Torch convention), as in
  the reference API; negative values are not supported (matching it).
- mutating methods (``fill``, ``zero``, ``copy``, ``add``, ``mul`` …) mutate
  *this* tensor in the API sense and return ``self`` — underneath, the
  backing ``jax.Array`` is replaced functionally.
- views (``select``/``narrow``/``view``/``t``/``transpose``) return NEW
  tensors that do NOT alias (XLA arrays are immutable — the reference's
  shared-storage aliasing is an implementation detail its API never
  guarantees for correctness, only for performance).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Scalar = Union[int, float]


class Storage:
    """1-D view of a tensor's elements (reference ``Storage.scala:27``,
    ``ArrayStorage.scala:22``)."""

    def __init__(self, data: np.ndarray):
        # always a host copy — jax arrays surface as read-only numpy views
        self._data = np.array(data).ravel()

    def __len__(self) -> int:
        return self._data.size

    def _check(self, i: int) -> int:
        if not 1 <= i <= self._data.size:
            raise IndexError(f"storage index {i} out of range "
                             f"[1, {self._data.size}] (1-based)")
        return i - 1

    def __getitem__(self, i: int) -> Scalar:
        return self._data[self._check(i)]  # 1-based, as the reference

    def __setitem__(self, i: int, v: Scalar) -> None:
        self._data[self._check(i)] = v

    def array(self) -> np.ndarray:
        return self._data

    def __iter__(self):
        return iter(self._data)


def _promote(value) -> jnp.ndarray:
    if isinstance(value, Tensor):
        return value.data
    return jnp.asarray(value)


class Tensor:
    """N-d tensor with the reference's Torch-style API
    (reference ``Tensor.scala:35``; math mix-in ``TensorMath.scala:28``).

    Examples (1-based Torch semantics; the reference's pyspark docs embed
    runnable snippets the same way)::

        >>> t = Tensor(2, 3)
        >>> t.size()
        (2, 3)
        >>> t.fill(2.0).sum()
        12.0
        >>> t.select(1, 1).size()       # first ROW (1-based)
        (3,)
        >>> t.narrow(2, 2, 2).size()    # columns 2..3
        (2, 2)
        >>> int(Tensor([[1.0, 5.0]]).max(2)[1][1, 1])  # argmax, 1-based
        2
    """

    __array_priority__ = 100  # numpy defers to our __r*__ ops

    def __init__(self, *args, dtype=None):
        if len(args) == 1 and isinstance(args[0], (np.ndarray, jnp.ndarray)):
            # array input: PRESERVE its dtype (int index tensors, float64,
            # bf16 must survive clone/view/operator round-trips)
            self.data = jnp.asarray(args[0], dtype=dtype)
        elif len(args) == 1 and isinstance(args[0], (list, tuple)):
            self.data = jnp.asarray(args[0], dtype=dtype or jnp.float32)
        elif len(args) == 1 and isinstance(args[0], Tensor):
            self.data = args[0].data
        elif args:
            if not all(isinstance(a, (int, np.integer)) for a in args):
                raise TypeError(f"bad Tensor(...) arguments {args!r}")
            self.data = jnp.zeros(tuple(int(a) for a in args),
                                  dtype=dtype or jnp.float32)
        else:
            self.data = jnp.zeros((0,), dtype=dtype or jnp.float32)

    # ------------------------------------------------------------ structure
    def dim(self) -> int:
        return self.data.ndim

    n_dimension = dim

    def size(self, dim: Optional[int] = None):
        """size() → tuple; size(d) → int, d 1-based (``Tensor.scala``)."""
        if dim is None:
            return tuple(self.data.shape)
        return self.data.shape[self._dim(dim)]

    def n_element(self) -> int:
        return int(self.data.size)

    def _dim(self, d: int) -> int:
        if not 1 <= d <= max(1, self.data.ndim):
            raise IndexError(f"dimension {d} out of range for "
                             f"{self.data.ndim}-d tensor (1-based)")
        return d - 1

    @staticmethod
    def _index(i: int, size: int, what: str = "index") -> int:
        """Validate a 1-based index — Torch raises on 0/out-of-range; jnp
        would silently clip/wrap, corrupting results."""
        if not 1 <= i <= size:
            raise IndexError(f"{what} {i} out of range [1, {size}] (1-based)")
        return i - 1

    def is_same_size_as(self, other: "Tensor") -> bool:
        return self.data.shape == other.data.shape

    def is_contiguous(self) -> bool:
        return True  # XLA arrays: always logically contiguous

    def contiguous(self) -> "Tensor":
        return self

    # ------------------------------------------------------------- indexing
    def select(self, dim: int, index: int) -> "Tensor":
        """Drop ``dim`` at 1-based ``index`` (reference ``select``)."""
        ax = self._dim(dim)
        return Tensor(jnp.take(
            self.data, self._index(index, self.data.shape[ax]), axis=ax))

    def narrow(self, dim: int, index: int, size: int) -> "Tensor":
        """Slice [index, index+size) on ``dim`` (1-based)."""
        ax = self._dim(dim)
        start = self._index(index, self.data.shape[ax])
        if start + size > self.data.shape[ax]:
            raise IndexError(f"narrow({dim},{index},{size}) exceeds size "
                             f"{self.data.shape[ax]}")
        sl = [slice(None)] * self.data.ndim
        sl[ax] = slice(start, start + size)
        return Tensor(self.data[tuple(sl)])

    def view(self, *sizes: int) -> "Tensor":
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        return Tensor(jnp.reshape(self.data, sizes))

    reshape = view

    def transpose(self, dim1: int, dim2: int) -> "Tensor":
        return Tensor(jnp.swapaxes(self.data, self._dim(dim1),
                                   self._dim(dim2)))

    def t(self) -> "Tensor":
        if self.data.ndim != 2:
            raise ValueError("t() expects a 2-d tensor")
        return Tensor(self.data.T)

    def squeeze(self, dim: Optional[int] = None) -> "Tensor":
        if dim is None:
            return Tensor(jnp.squeeze(self.data))
        ax = self._dim(dim)
        if self.data.shape[ax] != 1:
            return Tensor(self.data)
        return Tensor(jnp.squeeze(self.data, axis=ax))

    def unsqueeze(self, dim: int) -> "Tensor":
        return Tensor(jnp.expand_dims(self.data, dim - 1))

    def expand(self, *sizes: int) -> "Tensor":
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        return Tensor(jnp.broadcast_to(self.data, sizes))

    def repeat_tensor(self, *sizes: int) -> "Tensor":
        return Tensor(jnp.tile(self.data, sizes))

    def index_select(self, dim: int, indices) -> "Tensor":
        ax = self._dim(dim)
        idx = np.asarray(_promote(indices)).astype(np.int64)
        if idx.size and (idx.min() < 1 or idx.max() > self.data.shape[ax]):
            raise IndexError(f"index_select indices out of range "
                             f"[1, {self.data.shape[ax]}] (1-based)")
        return Tensor(jnp.take(self.data, jnp.asarray(idx - 1), axis=ax))

    def masked_select(self, mask) -> "Tensor":
        m = np.asarray(_promote(mask)).astype(bool)
        return Tensor(np.asarray(self.data)[m])

    def __getitem__(self, idx):
        """1-based scalar/select indexing like the reference's ``apply``."""
        if isinstance(idx, int):
            if self.data.ndim == 1:
                return float(self.data[self._index(idx, self.data.shape[0])])
            return self.select(1, idx)
        if isinstance(idx, tuple) and all(isinstance(i, int) for i in idx):
            zero_based = tuple(self._index(i, s) for i, s in
                               zip(idx, self.data.shape))
            return float(self.data[zero_based])
        raise TypeError("Tensor indexing is 1-based ints (Torch apply "
                        "semantics); use .data for numpy-style slicing")

    def set_value(self, *args) -> "Tensor":
        *idx, value = args
        zero_based = tuple(self._index(i, s) for i, s in
                           zip(idx, self.data.shape))
        self.data = self.data.at[zero_based].set(value)
        return self

    # ------------------------------------------------------------- mutation
    def fill(self, value: Scalar) -> "Tensor":
        self.data = jnp.full_like(self.data, value)
        return self

    def zero(self) -> "Tensor":
        return self.fill(0)

    def copy(self, other: "Tensor") -> "Tensor":
        src = _promote(other)
        if src.size != self.data.size:
            raise ValueError(f"copy size mismatch {src.size} vs "
                             f"{self.data.size}")
        self.data = jnp.reshape(src, self.data.shape).astype(self.data.dtype)
        return self

    def resize(self, *sizes: int) -> "Tensor":
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        n_new = int(np.prod(sizes))
        flat = jnp.ravel(self.data)
        if n_new <= flat.size:
            flat = flat[:n_new]
        else:
            flat = jnp.concatenate(
                [flat, jnp.zeros(n_new - flat.size, self.data.dtype)])
        self.data = jnp.reshape(flat, sizes)
        return self

    resize_as = lambda self, other: self.resize(*other.size())

    def apply1(self, fn: Callable[[float], float]) -> "Tensor":
        """Elementwise python fn (reference ``apply1``) — host roundtrip;
        for compiled elementwise math use the jnp-backed ops instead."""
        host = np.asarray(self.data)
        self.data = jnp.asarray(np.vectorize(fn)(host), self.data.dtype)
        return self

    # ----------------------------------------------------------------- math
    def _binary(self, other, fn) -> "Tensor":
        self.data = fn(self.data, _promote(other)).astype(self.data.dtype)
        return self

    def add(self, *args) -> "Tensor":
        """add(value) | add(tensor) | add(scalar, tensor) — in-place,
        reference ``TensorMath.add``."""
        if len(args) == 1:
            return self._binary(args[0], jnp.add)
        scalar, tensor = args
        self.data = self.data + scalar * _promote(tensor)
        return self

    def sub(self, *args) -> "Tensor":
        if len(args) == 1:
            return self._binary(args[0], jnp.subtract)
        scalar, tensor = args
        self.data = self.data - scalar * _promote(tensor)
        return self

    def mul(self, other) -> "Tensor":
        return self._binary(other, jnp.multiply)

    def div(self, other) -> "Tensor":
        return self._binary(other, jnp.divide)

    def cmul(self, other) -> "Tensor":
        return self._binary(other, jnp.multiply)

    def cdiv(self, other) -> "Tensor":
        return self._binary(other, jnp.divide)

    def cadd(self, scalar, other) -> "Tensor":
        return self.add(scalar, other)

    def pow(self, exponent: Scalar) -> "Tensor":
        self.data = jnp.power(self.data, exponent)
        return self

    def sqrt(self) -> "Tensor":
        self.data = jnp.sqrt(self.data)
        return self

    def abs(self) -> "Tensor":
        self.data = jnp.abs(self.data)
        return self

    def log(self) -> "Tensor":
        self.data = jnp.log(self.data)
        return self

    def log1p(self) -> "Tensor":
        self.data = jnp.log1p(self.data)
        return self

    def exp(self) -> "Tensor":
        self.data = jnp.exp(self.data)
        return self

    # non-mutating reductions / products
    def sum(self, dim: Optional[int] = None):
        if dim is None:
            return float(jnp.sum(self.data))
        return Tensor(jnp.sum(self.data, axis=self._dim(dim), keepdims=True))

    def mean(self, dim: Optional[int] = None):
        if dim is None:
            return float(jnp.mean(self.data))
        return Tensor(jnp.mean(self.data, axis=self._dim(dim), keepdims=True))

    def max(self, dim: Optional[int] = None):
        """max() → scalar; max(d) → (values, 1-based indices) like Torch."""
        if dim is None:
            return float(jnp.max(self.data))
        ax = self._dim(dim)
        values = jnp.max(self.data, axis=ax, keepdims=True)
        indices = jnp.expand_dims(jnp.argmax(self.data, axis=ax) + 1, ax)
        return Tensor(values), Tensor(indices.astype(jnp.int32))

    def min(self, dim: Optional[int] = None):
        if dim is None:
            return float(jnp.min(self.data))
        ax = self._dim(dim)
        values = jnp.min(self.data, axis=ax, keepdims=True)
        indices = jnp.expand_dims(jnp.argmin(self.data, axis=ax) + 1, ax)
        return Tensor(values), Tensor(indices.astype(jnp.int32))

    def norm(self, p: Scalar = 2) -> float:
        if p == 1:
            return float(jnp.sum(jnp.abs(self.data)))
        return float(jnp.sum(jnp.abs(self.data) ** p) ** (1.0 / p))

    def dot(self, other: "Tensor") -> float:
        return float(jnp.vdot(self.data, _promote(other)))

    def mm(self, a: "Tensor", b: "Tensor") -> "Tensor":
        """self = a @ b (reference ``mm`` writes into the receiver)."""
        self.data = jnp.matmul(_promote(a), _promote(b))
        return self

    def mv(self, a: "Tensor", x: "Tensor") -> "Tensor":
        self.data = jnp.matmul(_promote(a), _promote(x))
        return self

    def addmm(self, *args) -> "Tensor":
        """addmm([beta,] [M,] [alpha,] mat1, mat2): β·M + α·mat1@mat2
        (reference ``TensorMath.addmm`` overload family). Overloads are
        resolved by scalar-vs-tensor TYPE, not just arity — a leading scalar
        is β, a leading tensor is M."""
        beta, alpha, m = 1.0, 1.0, self
        rest = list(args)

        def is_scalar(x):
            return isinstance(x, (int, float, np.floating, np.integer))

        mat1, mat2 = rest[-2], rest[-1]
        head = rest[:-2]
        if head and is_scalar(head[0]):
            beta = head.pop(0)
        if head and not is_scalar(head[0]):
            m = head.pop(0)
        if head and is_scalar(head[0]):
            alpha = head.pop(0)
        if head:
            raise TypeError(f"unsupported addmm argument shape {args!r}")
        self.data = (beta * _promote(m)
                     + alpha * jnp.matmul(_promote(mat1), _promote(mat2)))
        return self

    def addmv(self, beta: Scalar, alpha: Scalar, mat, vec) -> "Tensor":
        self.data = beta * self.data + alpha * jnp.matmul(
            _promote(mat), _promote(vec))
        return self

    def addr(self, alpha: Scalar, vec1, vec2) -> "Tensor":
        self.data = self.data + alpha * jnp.outer(_promote(vec1),
                                                  _promote(vec2))
        return self

    def bmm(self, a: "Tensor", b: "Tensor") -> "Tensor":
        """self = batched a @ b over a leading batch dim (reference
        ``baddbmm`` family with β=0, α=1)."""
        self.data = jnp.matmul(_promote(a), _promote(b))
        return self

    def cinv(self) -> "Tensor":
        """Elementwise reciprocal in place (reference ``TensorMath.inv``)."""
        self.data = (1.0 / self.data).astype(self.data.dtype)
        return self

    def stride(self, dim: Optional[int] = None):
        """Row-major element strides (reference ``Tensor.stride``); XLA
        arrays are always logically contiguous, so strides derive from the
        shape."""
        shape = self.data.shape
        strides = []
        acc = 1
        for s in reversed(shape):
            strides.append(acc)
            acc *= s
        strides = tuple(reversed(strides))
        if dim is None:
            return strides
        return strides[self._dim(dim)]

    def uniform(self, a: float = 0.0, b: float = 1.0) -> "Tensor":
        """Fill in place with U[a, b) draws from the framework RNG stream
        (reference ``rand``/Torch ``uniform``)."""
        from bigdl_tpu.utils.rng import RandomGenerator
        draws = RandomGenerator.RNG().uniform(a, b, size=self.data.shape)
        self.data = jnp.asarray(draws, self.data.dtype)
        return self

    def sort(self, dim: Optional[int] = None, descending: bool = False):
        """(sorted values, 1-based indices) along ``dim`` (default: last),
        reference ``TensorMath.topk``'s full-sort sibling."""
        ax = self._dim(dim) if dim is not None else self.data.ndim - 1
        idx = jnp.argsort(self.data, axis=ax)
        if descending:
            idx = jnp.flip(idx, axis=ax)
        values = jnp.take_along_axis(self.data, idx, axis=ax)
        return Tensor(values), Tensor((idx + 1).astype(jnp.int32))

    def topk(self, k: int, dim: Optional[int] = None,
             increase: bool = True):
        """k smallest (``increase=True``, the reference default) or largest
        values + 1-based indices along ``dim`` (reference
        ``TensorMath.topk``)."""
        ax = self._dim(dim) if dim is not None else self.data.ndim - 1
        if not 1 <= k <= self.data.shape[ax]:
            raise IndexError(f"k={k} out of range [1, {self.data.shape[ax]}]")
        values, idx = self.sort(dim=(ax + 1), descending=not increase)
        sl = [slice(None)] * self.data.ndim
        sl[ax] = slice(0, k)
        return Tensor(values.data[tuple(sl)]), \
            Tensor(idx.data[tuple(sl)])

    def kthvalue(self, k: int, dim: Optional[int] = None):
        """k-th smallest value (+ 1-based index) along ``dim`` (reference
        quickselect ``Util.kthLargest`` kin; here a sort slice)."""
        values, idx = self.topk(k, dim=dim, increase=True)
        ax = self._dim(dim) if dim is not None else self.data.ndim - 1
        sl = [slice(None)] * self.data.ndim
        sl[ax] = slice(k - 1, k)
        return Tensor(values.data[tuple(sl)]), Tensor(idx.data[tuple(sl)])

    def _checked_index(self, index, ax: int) -> jnp.ndarray:
        """Validate a 1-based index tensor — jnp would silently wrap/clip
        out-of-range indices (same rationale as ``_index``)."""
        idx = np.asarray(_promote(index)).astype(np.int64)
        if idx.size and (idx.min() < 1 or idx.max() > self.data.shape[ax]):
            raise IndexError(f"index out of range [1, {self.data.shape[ax]}]"
                             " (1-based)")
        return jnp.asarray(idx - 1, jnp.int32)

    def gather(self, dim: int, index) -> "Tensor":
        """Gather along ``dim`` with 1-based index tensor (reference
        ``Tensor.gather``)."""
        ax = self._dim(dim)
        return Tensor(jnp.take_along_axis(
            self.data, self._checked_index(index, ax), axis=ax))

    def scatter(self, dim: int, index, src) -> "Tensor":
        """Scatter ``src`` along ``dim`` at 1-based ``index`` positions, in
        place (reference ``Tensor.scatter``)."""
        ax = self._dim(dim)
        self.data = jnp.put_along_axis(
            self.data, self._checked_index(index, ax),
            jnp.asarray(_promote(src), self.data.dtype),
            axis=ax, inplace=False)
        return self

    def split(self, size: int, dim: int = 1):
        """List of Tensors of width ``size`` along 1-based ``dim`` (last
        piece may be smaller), reference ``Tensor.split``."""
        ax = self._dim(dim)
        n = self.data.shape[ax]
        out = []
        for start in range(0, n, size):
            sl = [slice(None)] * self.data.ndim
            sl[ax] = slice(start, min(start + size, n))
            out.append(Tensor(self.data[tuple(sl)]))
        return out

    def chunk(self, n: int, dim: int = 1):
        """Split into ``n`` near-equal pieces (reference ``Tensor.chunk``)."""
        ax = self._dim(dim)
        size = -(-self.data.shape[ax] // n)  # ceil
        return self.split(size, dim)

    def _conv2_like(self, kernel, conv_type: str, flip: bool) -> "Tensor":
        k = jnp.asarray(_promote(kernel))
        if self.data.ndim != 2 or k.ndim != 2:
            raise ValueError("conv2/xcorr2 expect 2-d tensors")
        if flip:  # convolution = correlation with the flipped kernel
            k = jnp.flip(k, (0, 1))
        if conv_type not in ("V", "F"):
            raise ValueError("conv type must be 'V' (valid) or 'F' (full)")
        pad = "VALID" if conv_type == "V" else \
            [(k.shape[0] - 1,) * 2, (k.shape[1] - 1,) * 2]
        out = jax.lax.conv_general_dilated(
            self.data[None, None].astype(jnp.float32),
            k[None, None].astype(jnp.float32),
            window_strides=(1, 1), padding=pad)
        return Tensor(out[0, 0].astype(self.data.dtype))

    def conv2(self, kernel, conv_type: str = "V") -> "Tensor":
        """2-D convolution, 'V'alid or 'F'ull (reference
        ``TensorMath.conv2`` backed by ``DenseTensorConv.scala:23``; here a
        1-channel ``lax.conv`` that XLA maps to the MXU)."""
        return self._conv2_like(kernel, conv_type, flip=True)

    def xcorr2(self, kernel, conv_type: str = "V") -> "Tensor":
        """2-D cross-correlation (reference ``TensorMath.xcorr2``)."""
        return self._conv2_like(kernel, conv_type, flip=False)

    # ------------------------------------------------------------ operators
    def __add__(self, other):
        return Tensor(self.data + _promote(other))

    __radd__ = __add__

    def __sub__(self, other):
        return Tensor(self.data - _promote(other))

    def __rsub__(self, other):
        return Tensor(_promote(other) - self.data)

    def __mul__(self, other):
        return Tensor(self.data * _promote(other))

    __rmul__ = __mul__

    def __truediv__(self, other):
        return Tensor(self.data / _promote(other))

    def __neg__(self):
        return Tensor(-self.data)

    def __eq__(self, other):
        if isinstance(other, Tensor):
            return (self.data.shape == other.data.shape
                    and bool(jnp.all(self.data == other.data)))
        return NotImplemented

    def __hash__(self):
        return id(self)

    def almost_equal(self, other: "Tensor", tol: float = 1e-6) -> bool:
        return (self.data.shape == _promote(other).shape
                and bool(jnp.all(jnp.abs(self.data - _promote(other)) <= tol)))

    # ---------------------------------------------------------------- misc
    def clone(self) -> "Tensor":
        return Tensor(self.data)

    def storage(self) -> Storage:
        """Host-side element view (reference ``storage()``). Mutations to the
        returned Storage are NOT reflected back (XLA arrays are immutable);
        call ``set_storage`` to write it back."""
        return Storage(np.asarray(self.data))

    def set_storage(self, storage: Storage) -> "Tensor":
        self.data = jnp.reshape(jnp.asarray(storage.array()),
                                self.data.shape).astype(self.data.dtype)
        return self

    def to_jax(self) -> jnp.ndarray:
        return self.data

    def numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def rand(self) -> "Tensor":
        from bigdl_tpu.utils.rng import RandomGenerator
        self.data = jnp.asarray(
            RandomGenerator.RNG().uniform(0, 1, self.data.shape),
            self.data.dtype)
        return self

    def randn(self) -> "Tensor":
        from bigdl_tpu.utils.rng import RandomGenerator
        self.data = jnp.asarray(
            RandomGenerator.RNG().normal(0, 1, self.data.shape),
            self.data.dtype)
        return self

    def bernoulli(self, p: float) -> "Tensor":
        from bigdl_tpu.utils.rng import RandomGenerator
        self.data = jnp.asarray(
            RandomGenerator.RNG().bernoulli(p, self.data.shape),
            self.data.dtype)
        return self

    def __repr__(self) -> str:
        return (f"Tensor(size={tuple(self.data.shape)}, "
                f"dtype={self.data.dtype})\n{np.asarray(self.data)}")

    # ---------------------------------------------------------- conversions
    @staticmethod
    def from_numpy(arr: np.ndarray) -> "Tensor":
        return Tensor(jnp.asarray(arr))

    @staticmethod
    def range(start: Scalar, stop: Scalar, step: Scalar = 1) -> "Tensor":
        """Inclusive range like Torch's ``Tensor.range``."""
        return Tensor(jnp.arange(start, stop + step * 0.5, step))

    @staticmethod
    def ones(*sizes: int) -> "Tensor":
        return Tensor(jnp.ones(sizes, jnp.float32))

    @staticmethod
    def zeros(*sizes: int) -> "Tensor":
        return Tensor(jnp.zeros(sizes, jnp.float32))
