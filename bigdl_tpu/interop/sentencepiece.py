"""SentencePiece ``tokenizer.model`` reader — torch- and
sentencepiece-free (round 5, VERDICT #7).

The reference tokenizes text through OpenNLP binary models
(``dataset/text/SentenceTokenizer.scala:1``); its modern analogue — and the
missing half of the Llama-family ``--fromHF`` story — is the SentencePiece
model file every Llama-2-style checkpoint ships. This module parses the
``ModelProto`` protobuf directly (same hand-rolled wire walking as
``interop/caffe.py``, via ``utils/protowire``) and reimplements both
segmentation algorithms:

- **unigram**: Viterbi over the normalized text with per-piece log scores
  (ties by longest-match-first, matching the C++ lattice ordering);
  unknown characters take the unk penalty and, under ``byte_fallback``,
  expand to ``<0xNN>`` byte pieces (the Llama configuration).
- **bpe**: iterative best-scoring adjacent merge (SentencePiece BPE stores
  merge priority as piece score; ties resolve leftmost).

Normalization: ``identity`` (Llama) is exact; models with a precompiled
charsmap (``nmt_nfkc``) are approximated with unicodedata NFKC and warn
once. ``add_dummy_prefix`` / ``escape_whitespaces`` /
``remove_extra_whitespaces`` follow the NormalizerSpec flags.

``encode``/``decode``/``eos_id`` use FRAMEWORK 1-based ids (spm id + 1) —
drop-in where ``interop.hf_tokenizer.HFTokenizer`` is used
(``apps.transformer generate|serve``). Id-exact parity is tested against
the ``tokenizers`` library's Unigram/BPE implementation (what HF fast
tokenizers actually run for these models) in
``tests/test_sentencepiece.py``.
"""

from __future__ import annotations

import os
import struct
import warnings
from typing import Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.utils.protowire import WT_LEN, WT_VARINT, iter_fields

# SentencePiece piece types (sentencepiece_model.proto)
NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6
_UNIGRAM, _BPE = 1, 2
_WS = "▁"  # the metaspace word-boundary mark
_UNK_PENALTY = 10.0  # C++ kUnkPenalty: unk score = min_score - 10


class SentencePieceModel:
    """Parsed ModelProto: pieces, scores, types + the spec flags that
    affect encoding."""

    def __init__(self):
        self.pieces: List[str] = []
        self.scores: List[float] = []
        self.types: List[int] = []
        self.model_type = _UNIGRAM
        self.unk_id = 0
        self.bos_id: Optional[int] = 1
        self.eos_id: Optional[int] = 2
        self.pad_id: Optional[int] = -1
        self.byte_fallback = False
        self.normalizer = "identity"
        self.has_charsmap = False
        self.add_dummy_prefix = True
        self.remove_extra_whitespaces = True
        self.escape_whitespaces = True

    @classmethod
    def from_file(cls, path: str) -> "SentencePieceModel":
        with open(path, "rb") as f:
            buf = memoryview(f.read())
        m = cls()
        for field, wt, val in iter_fields(buf):
            if field == 1 and wt == WT_LEN:  # SentencePiece
                piece, score, typ = "", 0.0, NORMAL
                for f2, w2, v2 in iter_fields(val):
                    if f2 == 1 and w2 == WT_LEN:
                        piece = bytes(v2).decode("utf-8")
                    elif f2 == 2:  # float (I32)
                        score = struct.unpack("<f", bytes(v2))[0]
                    elif f2 == 3 and w2 == WT_VARINT:
                        typ = v2
                m.pieces.append(piece)
                m.scores.append(score)
                m.types.append(typ)
            elif field == 2 and wt == WT_LEN:  # TrainerSpec
                for f2, w2, v2 in iter_fields(val):
                    if w2 != WT_VARINT:
                        continue
                    if f2 == 3:
                        m.model_type = v2
                    elif f2 == 35:
                        m.byte_fallback = bool(v2)
                    elif f2 == 40:
                        m.unk_id = _signed(v2)
                    elif f2 == 41:
                        m.bos_id = _signed(v2)
                    elif f2 == 42:
                        m.eos_id = _signed(v2)
                    elif f2 == 43:
                        m.pad_id = _signed(v2)
            elif field == 3 and wt == WT_LEN:  # NormalizerSpec
                for f2, w2, v2 in iter_fields(val):
                    if f2 == 1 and w2 == WT_LEN:
                        m.normalizer = bytes(v2).decode()
                    elif f2 == 2 and w2 == WT_LEN and len(v2):
                        m.has_charsmap = True
                    elif f2 == 3 and w2 == WT_VARINT:
                        m.add_dummy_prefix = bool(v2)
                    elif f2 == 4 and w2 == WT_VARINT:
                        m.remove_extra_whitespaces = bool(v2)
                    elif f2 == 5 and w2 == WT_VARINT:
                        m.escape_whitespaces = bool(v2)
        if m.model_type not in (_UNIGRAM, _BPE):
            raise ValueError(
                f"unsupported sentencepiece model_type {m.model_type} "
                "(unigram=1 and bpe=2 are implemented)")
        return m


def _signed(v: int) -> int:
    """proto int32 negatives arrive as 2^64-complement varints."""
    return v - (1 << 64) if v >= (1 << 63) else v


class SentencePieceTokenizer:
    """Encoder/decoder over a parsed model (unigram Viterbi or BPE)."""

    def __init__(self, model: SentencePieceModel):
        self.m = model
        self.vocab: Dict[str, int] = {}
        for i, (p, t) in enumerate(zip(model.pieces, model.types)):
            if t in (NORMAL, USER_DEFINED) and p not in self.vocab:
                self.vocab[p] = i
        self._byte_ids = {}
        for i, (p, t) in enumerate(zip(model.pieces, model.types)):
            if t == BYTE:  # "<0xNN>"
                self._byte_ids[int(p[3:5], 16)] = i
        self._max_len = max((len(p) for p in self.vocab), default=1)
        min_score = min(self.m.scores) if self.m.scores else 0.0
        self._unk_score = min_score - _UNK_PENALTY
        if model.has_charsmap:
            warnings.warn(
                "sentencepiece model carries a precompiled charsmap "
                f"(normalizer {model.normalizer!r}); approximating with "
                "unicodedata NFKC — ids may differ on exotic codepoints",
                stacklevel=2)

    @classmethod
    def from_file(cls, path: str) -> "SentencePieceTokenizer":
        return cls(SentencePieceModel.from_file(path))

    @staticmethod
    def present_in(path: str) -> bool:
        return os.path.exists(os.path.join(path, "tokenizer.model"))

    @classmethod
    def from_dir(cls, path: str) -> "SentencePieceTokenizer":
        return cls.from_file(os.path.join(path, "tokenizer.model"))

    # ------------------------------------------------------------ normalize
    def _normalize(self, text: str) -> str:
        if self.m.has_charsmap:
            import unicodedata
            text = unicodedata.normalize("NFKC", text)
        if self.m.remove_extra_whitespaces:
            text = " ".join(s for s in text.split(" ") if s) \
                if text.strip(" ") else ""
        if self.m.add_dummy_prefix and text:
            text = " " + text
        if self.m.escape_whitespaces:
            text = text.replace(" ", _WS)
        return text

    # -------------------------------------------------------------- unigram
    def _viterbi(self, s: str) -> List[Tuple[str, Optional[int]]]:
        """Best segmentation: [(piece_text, piece_id_or_None_for_unk)].
        Scores accumulate piece log-probs; an unknown single char costs
        unk_score. Ties prefer the LONGER piece (C++ lattice iteration
        order inserts longer arcs first and keeps strict improvement)."""
        n = len(s)
        NEG = float("-inf")
        best = [NEG] * (n + 1)
        back: List[Optional[Tuple[int, Optional[int]]]] = [None] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] == NEG:
                continue
            # unknown single character (merged runs handled at emit time)
            cand = best[i] + self._unk_score
            if cand > best[i + 1]:
                best[i + 1] = cand
                back[i + 1] = (i, None)
            for j in range(i + 1, min(n, i + self._max_len) + 1):
                pid = self.vocab.get(s[i:j])
                if pid is None:
                    continue
                cand = best[i] + self.m.scores[pid]
                if cand > best[j] or (cand == best[j] and back[j] is not None
                                      and back[j][0] > i):
                    best[j] = cand
                    back[j] = (i, pid)
        out: List[Tuple[str, Optional[int]]] = []
        pos = n
        while pos > 0:
            i, pid = back[pos]
            out.append((s[i:pos], pid))
            pos = i
        return out[::-1]

    # ------------------------------------------------------------------ bpe
    def _bpe(self, s: str) -> List[Tuple[str, Optional[int]]]:
        parts: List[str] = list(s)
        while len(parts) > 1:
            best_score, best_i = None, None
            for i in range(len(parts) - 1):
                pid = self.vocab.get(parts[i] + parts[i + 1])
                if pid is None:
                    continue
                sc = self.m.scores[pid]
                if best_score is None or sc > best_score:
                    best_score, best_i = sc, i
            if best_i is None:
                break
            parts[best_i: best_i + 2] = [parts[best_i] + parts[best_i + 1]]
        return [(p, self.vocab.get(p)) for p in parts]

    # -------------------------------------------------------------- surface
    def encode(self, text: str) -> List[int]:
        """Text -> FRAMEWORK 1-based ids (spm id + 1)."""
        s = self._normalize(text)
        if not s:
            return []
        segment = self._viterbi if self.m.model_type == _UNIGRAM else self._bpe
        pieces = segment(s)
        ids: List[int] = []
        prev_unk = False
        for piece, pid in pieces:
            if pid is not None:
                ids.append(pid + 1)
                prev_unk = False
            elif self.m.byte_fallback and self._byte_ids:
                for b in piece.encode("utf-8"):
                    bid = self._byte_ids.get(b)
                    if bid is None:
                        raise ValueError(
                            f"byte piece <0x{b:02X}> missing from a "
                            "byte_fallback vocab")
                    ids.append(bid + 1)
                prev_unk = False
            else:
                # fuse consecutive unknown characters into ONE unk —
                # SentencePiece/tokenizers (fuse_unk) semantics; emitting
                # one per char would change the sequence length
                if not prev_unk:
                    ids.append(self.m.unk_id + 1)
                prev_unk = True
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        out: List[bytes] = []
        pending: List[int] = []

        def flush():
            if pending:
                out.append(bytes(pending))
                del pending[:]

        for i in ids:
            spm_id = int(i) - 1
            if not (0 <= spm_id < len(self.m.pieces)):
                continue
            t = self.m.types[spm_id]
            if t == BYTE:
                pending.append(int(self.m.pieces[spm_id][3:5], 16))
                continue
            flush()
            if t in (CONTROL, UNKNOWN, UNUSED):
                continue
            out.append(self.m.pieces[spm_id].encode("utf-8"))
        flush()
        text = b"".join(out).decode("utf-8", errors="replace") \
            .replace(_WS, " ")
        if self.m.add_dummy_prefix and text.startswith(" "):
            text = text[1:]
        return text

    @property
    def vocab_size(self) -> int:
        return len(self.m.pieces)

    @property
    def eos_id(self) -> Optional[int]:
        e = self.m.eos_id
        if e is None or e < 0 or e >= len(self.m.pieces):
            return None
        return e + 1

    @property
    def bos_id(self) -> Optional[int]:
        b = self.m.bos_id
        if b is None or b < 0 or b >= len(self.m.pieces):
            return None
        return b + 1

    def __repr__(self):
        kind = "unigram" if self.m.model_type == _UNIGRAM else "bpe"
        return (f"SentencePieceTokenizer({kind}, "
                f"vocab={len(self.m.pieces)})")


# ------------------------------------------------------------------- writer

def write_model(path: str, pieces: Sequence[Tuple[str, float, int]],
                model_type: str = "unigram", byte_fallback: bool = False,
                add_dummy_prefix: bool = True, unk_id: int = 0,
                bos_id: int = 1, eos_id: int = 2) -> str:
    """Serialize a ModelProto (tests + exporting framework vocabs to the
    ecosystem format). ``pieces``: (text, score, type) in id order."""
    from bigdl_tpu.visualization.proto import (_len_field, _varint_field,
                                               _float_field)

    blob = b""
    for text, score, typ in pieces:
        sp = (_len_field(1, text.encode("utf-8")) + _float_field(2, score)
              + _varint_field(3, typ))
        blob += _len_field(1, sp)
    trainer = (_varint_field(3, {"unigram": 1, "bpe": 2}[model_type])
               + _varint_field(35, int(byte_fallback))
               + _varint_field(40, unk_id) + _varint_field(41, bos_id)
               + _varint_field(42, eos_id))
    norm = (_len_field(1, b"identity")
            + _varint_field(3, int(add_dummy_prefix))
            + _varint_field(4, 0) + _varint_field(5, 1))
    blob += _len_field(2, trainer) + _len_field(3, norm)
    with open(path, "wb") as f:
        f.write(blob)
    return path
