"""GPT-2-style byte-level BPE tokenizer reader — the text half of the HF
checkpoint interop (``interop/hf.py`` loads the weights; this loads the
``tokenizer.json`` / ``vocab.json``+``merges.txt`` beside them, so
``--fromHF`` serving speaks TEXT, not raw ids).

Differences from the framework's own ``dataset.BPETokenizer`` (which keeps
raw bytes as symbols 0..255 and assigns merge ids by rank): the HF/GPT-2
scheme maps every byte through a printable-unicode table
(``bytes_to_unicode``), splits text with the GPT-2 regex pre-tokenizer,
and takes token ids from an ARBITRARY vocab assignment (``vocab.json``) —
ids must match the checkpoint's embedding rows exactly, so they cannot be
re-derived; they are read from the file.

``encode`` returns FRAMEWORK 1-based ids (HF id + 1, matching how
``interop.hf`` copies the embedding table verbatim) and ``decode`` takes
them back — the class is drop-in where ``dataset.BPETokenizer`` is used
(``apps.transformer generate/serve --tokenizer`` protocol: ``encode``,
``decode``, ``eos_id``).

Verified against the ``tokenizers`` library (the implementation HF runs)
on round-trip corpora in ``tests/test_hf_tokenizer.py``.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

# the GPT-2 pre-tokenizer pattern (contractions, letter runs, number runs,
# punctuation runs — each optionally space-prefixed — then whitespace)
_PAT = (r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"
        r" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")


@lru_cache(maxsize=1)
def bytes_to_unicode() -> Dict[int, str]:
    """The GPT-2 byte<->printable-unicode table: printable ASCII/Latin-1
    map to themselves, the rest shift into 256+ codepoints."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


class HFTokenizer:
    """Byte-level BPE with an explicit vocab-id table (GPT-2 scheme)."""

    def __init__(self, vocab: Dict[str, int],
                 merges: Sequence[Tuple[str, str]],
                 eos_token: str = "<|endoftext|>"):
        import regex
        self._pat = regex.compile(_PAT)
        self.vocab = dict(vocab)
        self._id_to_tok = {i: t for t, i in self.vocab.items()}
        self._merges = [tuple(m) for m in merges]
        self._ranks = {m: i for i, m in enumerate(self._merges)}
        self._byte_enc = bytes_to_unicode()
        self._byte_dec = {c: b for b, c in self._byte_enc.items()}
        self._cache: Dict[str, List[str]] = {}
        self._eos_tok = eos_token if eos_token in self.vocab else None

    # ----------------------------------------------------------------- load
    @classmethod
    def from_dir(cls, path: str) -> "HFTokenizer":
        """Read ``tokenizer.json`` (fast format) or ``vocab.json`` +
        ``merges.txt`` from an HF checkpoint directory."""
        tj = os.path.join(path, "tokenizer.json")
        if os.path.exists(tj):
            with open(tj, encoding="utf-8") as f:
                data = json.load(f)
            model = data.get("model", {})
            if model.get("type") != "BPE":
                raise ValueError(f"tokenizer.json model type "
                                 f"{model.get('type')!r} is not BPE")
            # refuse non-GPT-2 byte schemes (Llama SentencePiece-derived
            # vocabs are model.type BPE too, but use \u2581 word marks /
            # <0xNN> byte tokens and different pre-tokenizers — GPT-2
            # byte-mapping them would silently mis-tokenize)
            pre = data.get("pre_tokenizer") or {}
            pres = (pre.get("pretokenizers", [pre])
                    if pre.get("type") == "Sequence" else [pre])
            if not any(p.get("type") == "ByteLevel" for p in pres):
                raise ValueError(
                    "tokenizer.json is not a GPT-2-style ByteLevel BPE "
                    f"(pre_tokenizer {pre.get('type')!r}); Llama-family "
                    "tokenizers are not supported by this reader")
            merges = [tuple(m.split(" ", 1)) if isinstance(m, str)
                      else tuple(m) for m in model["merges"]]
            return cls(model["vocab"], merges)
        vj = os.path.join(path, "vocab.json")
        mt = os.path.join(path, "merges.txt")
        if os.path.exists(vj) and os.path.exists(mt):
            with open(vj, encoding="utf-8") as f:
                vocab = json.load(f)
            merges = []
            with open(mt, encoding="utf-8") as f:
                for line in f:
                    line = line.rstrip("\n")
                    if not line or line.startswith("#version"):
                        continue
                    merges.append(tuple(line.split(" ", 1)))
            return cls(vocab, merges)
        raise FileNotFoundError(
            f"no tokenizer.json or vocab.json+merges.txt in {path}")

    @staticmethod
    def present_in(path: str) -> bool:
        return (os.path.exists(os.path.join(path, "tokenizer.json"))
                or (os.path.exists(os.path.join(path, "vocab.json"))
                    and os.path.exists(os.path.join(path, "merges.txt"))))

    # ------------------------------------------------------------------ BPE
    def _bpe(self, mapped: str) -> List[str]:
        cached = self._cache.get(mapped)
        if cached is not None:
            return cached
        parts = list(mapped)
        while len(parts) > 1:
            ranked = [(self._ranks.get((parts[i], parts[i + 1])), i)
                      for i in range(len(parts) - 1)]
            ranked = [(r, i) for r, i in ranked if r is not None]
            if not ranked:
                break
            rank, _ = min(ranked)
            a, b = self._merges[rank]
            j = 0
            while j < len(parts) - 1:
                if parts[j] == a and parts[j + 1] == b:
                    parts[j: j + 2] = [a + b]
                else:
                    j += 1
        if len(self._cache) < 65536:
            self._cache[mapped] = parts
        return parts

    def encode(self, text: str) -> List[int]:
        """Text -> FRAMEWORK 1-based ids (HF id + 1)."""
        ids: List[int] = []
        for piece in self._pat.findall(text):
            mapped = "".join(self._byte_enc[b]
                             for b in piece.encode("utf-8"))
            for tok in self._bpe(mapped):
                tid = self.vocab.get(tok)
                if tid is None:  # byte fallback (unmerged byte runs)
                    for ch in tok:
                        cid = self.vocab.get(ch)
                        if cid is None:
                            raise ValueError(
                                f"byte token {ch!r} missing from the vocab "
                                "(tokenizer trained without the full "
                                "ByteLevel alphabet) — refusing to drop "
                                "input text silently")
                        ids.append(cid + 1)
                else:
                    ids.append(tid + 1)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        """FRAMEWORK 1-based ids -> text (unknown ids skipped)."""
        chars = []
        for i in ids:
            tok = self._id_to_tok.get(int(i) - 1)
            if tok is not None and tok != self._eos_tok:
                chars.append(tok)
        data = bytes(self._byte_dec[c] for c in "".join(chars)
                     if c in self._byte_dec)
        return data.decode("utf-8", errors="replace")

    # -------------------------------------------------------------- surface
    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @property
    def eos_id(self) -> Optional[int]:
        """Framework 1-based eos id (None when the vocab has no eos)."""
        if self._eos_tok is None:
            return None
        return self.vocab[self._eos_tok] + 1

    def __repr__(self):
        return (f"HFTokenizer(vocab={len(self.vocab)}, "
                f"merges={len(self._ranks)})")


def load_checkpoint_tokenizer(path: str):
    """The ``--fromHF`` text dispatcher: GPT-2-style byte-level BPE
    (``tokenizer.json``/``vocab.json``) via :class:`HFTokenizer`, else the
    Llama-family SentencePiece ``tokenizer.model`` via
    ``interop.sentencepiece`` — so both checkpoint families speak text end
    to end. Raises ``FileNotFoundError`` when the directory carries no
    known tokenizer, ``ValueError`` when one exists but is unreadable."""
    from bigdl_tpu.interop.sentencepiece import SentencePieceTokenizer
    if SentencePieceTokenizer.present_in(path):
        return SentencePieceTokenizer.from_dir(path)
    if HFTokenizer.present_in(path):
        return HFTokenizer.from_dir(path)
    raise FileNotFoundError(f"no tokenizer.model / tokenizer.json / "
                            f"vocab.json+merges.txt in {path}")
