"""Torch7 ``.t7`` binary serialization (reference ``utils/TorchFile.scala:67``).

Implements the Torch object-stream wire format — typed records with an
object-reuse index — and maps Lua ``nn.*`` module classes to/from
``bigdl_tpu.nn`` modules, mirroring the reference's ~30-class table.

Wire format (binary, little-endian):

    object  := int32 type_tag , payload
    tag 0 nil | 1 number (f64) | 2 string (i32 len + bytes) | 3 table |
    4 torch-object | 5 boolean (i32) | 6/7/8 function (unsupported)
    table   := i32 index , i32 count , count * (key object, value object)
    torch   := i32 index , [string version "V 1"] , string class , payload
    Tensor  := i32 ndim , i64[ndim] size , i64[ndim] stride ,
               i64 storageOffset (1-based) , object storage
    Storage := i64 size , raw elements

Tables and torch objects share one index space; a repeated index is a
back-reference to the already-decoded object.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5
TYPE_FUNCTION = 6
TYPE_LEGACY_RECUR_FUNCTION = 7
TYPE_RECUR_FUNCTION = 8

_TENSOR_DTYPES = {
    "torch.DoubleTensor": np.float64, "torch.FloatTensor": np.float32,
    "torch.LongTensor": np.int64, "torch.IntTensor": np.int32,
    "torch.ShortTensor": np.int16, "torch.ByteTensor": np.uint8,
    "torch.CharTensor": np.int8,
}
_STORAGE_DTYPES = {
    "torch.DoubleStorage": np.float64, "torch.FloatStorage": np.float32,
    "torch.LongStorage": np.int64, "torch.IntStorage": np.int32,
    "torch.ShortStorage": np.int16, "torch.ByteStorage": np.uint8,
    "torch.CharStorage": np.int8,
}
_DTYPE_TO_TENSOR = {np.dtype(v): k for k, v in _TENSOR_DTYPES.items()}
_TENSOR_TO_STORAGE = {
    t: t.replace("Tensor", "Storage") for t in _TENSOR_DTYPES}


class TorchObject:
    """A decoded ``torch.*``/``nn.*`` object: class name + field table."""

    def __init__(self, torch_type: str, fields: Any):
        self.torch_type = torch_type
        self.fields = fields

    def __getitem__(self, key):
        return self.fields[key]

    def get(self, key, default=None):
        try:
            return self.fields.get(key, default)
        except AttributeError:
            return default

    def __repr__(self):
        return f"TorchObject({self.torch_type})"


# ===================================================================== reader

class _Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.objects: Dict[int, Any] = {}

    def _read(self, fmt: str):
        size = struct.calcsize(fmt)
        data = self.f.read(size)
        if len(data) < size:
            raise EOFError("truncated .t7 file")
        return struct.unpack(fmt, data)[0]

    def read_int(self) -> int:
        return self._read("<i")

    def read_long(self) -> int:
        return self._read("<q")

    def read_string(self) -> str:
        n = self.read_int()
        return self.f.read(n).decode("latin-1")

    def read_object(self) -> Any:
        tag = self.read_int()
        if tag == TYPE_NIL:
            return None
        if tag == TYPE_NUMBER:
            return self._read("<d")
        if tag == TYPE_STRING:
            return self.read_string()
        if tag == TYPE_BOOLEAN:
            return bool(self.read_int())
        if tag in (TYPE_FUNCTION, TYPE_RECUR_FUNCTION,
                   TYPE_LEGACY_RECUR_FUNCTION):
            # size-prefixed dump + upvalue table; skip both
            n = self.read_int()
            self.f.read(n)
            self.read_object()
            return None
        if tag == TYPE_TABLE:
            index = self.read_int()
            if index in self.objects:
                return self.objects[index]
            out: Dict[Any, Any] = {}
            self.objects[index] = out
            count = self.read_int()
            for _ in range(count):
                key = self.read_object()
                val = self.read_object()
                if isinstance(key, float) and key.is_integer():
                    key = int(key)
                out[key] = val
            return out
        if tag == TYPE_TORCH:
            index = self.read_int()
            if index in self.objects:
                return self.objects[index]
            version = self.read_string()
            if version.startswith("V "):
                cls = self.read_string()
            else:
                cls = version
            obj = self._read_torch_payload(cls, index)
            return obj
        raise ValueError(f"unsupported .t7 type tag {tag}")

    def _read_torch_payload(self, cls: str, index: int) -> Any:
        if cls in _TENSOR_DTYPES:
            # reserve slot first; sub-reads can't reference the tensor itself
            self.objects[index] = None
            ndim = self.read_int()
            sizes = [self.read_long() for _ in range(ndim)]
            strides = [self.read_long() for _ in range(ndim)]
            offset = self.read_long() - 1
            storage = self.read_object()
            if storage is None or ndim == 0 or 0 in sizes:
                arr = np.zeros(sizes or (0,), dtype=_TENSOR_DTYPES[cls])
            else:
                # bound-check file-supplied geometry before as_strided — a
                # corrupt header must not address memory outside the storage
                last = offset + sum(st * (sz - 1)
                                    for sz, st in zip(sizes, strides))
                if (offset < 0 or any(s < 0 for s in sizes + strides)
                        or last >= storage.size
                        or offset >= storage.size):
                    raise ValueError(
                        f".t7 tensor geometry out of bounds: sizes={sizes} "
                        f"strides={strides} offset={offset} "
                        f"storage={storage.size}")
                arr = np.lib.stride_tricks.as_strided(
                    storage[offset:], shape=sizes,
                    strides=[s * storage.itemsize for s in strides]).copy()
            self.objects[index] = arr
            return arr
        if cls in _STORAGE_DTYPES:
            dtype = np.dtype(_STORAGE_DTYPES[cls])
            size = self.read_long()
            data = self.f.read(size * dtype.itemsize)
            arr = np.frombuffer(data, dtype=dtype).copy()
            self.objects[index] = arr
            return arr
        # generic torch class (nn.*): payload is one object (its field table)
        obj = TorchObject(cls, {})
        self.objects[index] = obj
        obj.fields = self.read_object()
        return obj


# ===================================================================== writer

class _Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.next_index = 1
        self.seen: Dict[int, int] = {}
        self._keepalive: List[Any] = []  # pin ids in `seen` against reuse

    def _write(self, fmt: str, value) -> None:
        self.f.write(struct.pack(fmt, value))

    def write_int(self, v: int) -> None:
        self._write("<i", v)

    def write_long(self, v: int) -> None:
        self._write("<q", v)

    def write_string(self, s: str) -> None:
        data = s.encode("latin-1")
        self.write_int(len(data))
        self.f.write(data)

    def write_object(self, obj: Any) -> None:
        if obj is None:
            self.write_int(TYPE_NIL)
        elif isinstance(obj, bool):
            self.write_int(TYPE_BOOLEAN)
            self.write_int(int(obj))
        elif isinstance(obj, (int, float)):
            self.write_int(TYPE_NUMBER)
            self._write("<d", float(obj))
        elif isinstance(obj, str):
            self.write_int(TYPE_STRING)
            self.write_string(obj)
        elif isinstance(obj, np.ndarray):
            self._write_tensor(obj)
        elif isinstance(obj, dict):
            self._write_table(obj)
        elif isinstance(obj, TorchObject):
            self._write_torch(obj)
        else:
            raise TypeError(f"cannot serialize {type(obj)} to .t7")

    def _alloc(self, obj: Any) -> Optional[int]:
        """Index bookkeeping; returns None if obj was already written."""
        key = id(obj)
        if key in self.seen:
            self.write_int(self.seen[key])
            return None
        idx = self.next_index
        self.next_index += 1
        self.seen[key] = idx
        self._keepalive.append(obj)  # a freed id could be recycled by a new
        self.write_int(idx)          # object, faking a back-reference
        return idx

    def _write_table(self, table: dict) -> None:
        self.write_int(TYPE_TABLE)
        if self._alloc(table) is None:
            return
        self.write_int(len(table))
        for k, v in table.items():
            self.write_object(float(k) if isinstance(k, int) else k)
            self.write_object(v)

    def _write_tensor(self, arr: np.ndarray) -> None:
        cls = _DTYPE_TO_TENSOR.get(arr.dtype)
        if cls is None:
            arr = arr.astype(np.float32)
            cls = "torch.FloatTensor"
        arr = np.ascontiguousarray(arr)
        self.write_int(TYPE_TORCH)
        if self._alloc(arr) is None:
            return
        self.write_string("V 1")
        self.write_string(cls)
        self.write_int(arr.ndim)
        for s in arr.shape:
            self.write_long(s)
        stride = 1
        strides = []
        for s in reversed(arr.shape):
            strides.append(stride)
            stride *= s
        for s in reversed(strides):
            self.write_long(s)
        self.write_long(1)  # storageOffset, 1-based
        # storage object
        self.write_int(TYPE_TORCH)
        storage_idx = self.next_index
        self.next_index += 1
        self.write_int(storage_idx)
        self.write_string("V 1")
        self.write_string(_TENSOR_TO_STORAGE[cls])
        self.write_long(arr.size)
        self.f.write(arr.tobytes())

    def _write_torch(self, obj: TorchObject) -> None:
        # the torch object and its payload table get distinct indices
        # (the table is written through write_object with its own _alloc)
        self.write_int(TYPE_TORCH)
        if self._alloc(obj) is None:
            return
        self.write_string("V 1")
        self.write_string(obj.torch_type)
        self.write_object(obj.fields)


# ============================================================ module mapping

def _empty() -> np.ndarray:
    return np.zeros((0,), dtype=np.float32)


def _base_fields(extra: dict) -> dict:
    out = {"output": _empty(), "gradInput": _empty(), "train": False}
    out.update(extra)
    return out


def _conv_to_torch(m) -> TorchObject:
    # ours HWIO (kH,kW,I/g,O) → torch (O, I/g, kH, kW) (groups folded flat,
    # matching reference TorchFile's nn.SpatialConvolution layout)
    w = np.transpose(np.asarray(m.weight), (3, 2, 0, 1)).astype(np.float64)
    fields = {
        "nInputPlane": m.n_input_plane, "nOutputPlane": m.n_output_plane,
        "kW": m.kernel_w, "kH": m.kernel_h, "dW": m.stride_w, "dH": m.stride_h,
        "padW": m.pad_w, "padH": m.pad_h, "nGroup": m.n_group,
        "weight": w, "gradWeight": np.zeros_like(w),
    }
    if getattr(m, "bias", None) is not None:
        b = np.asarray(m.bias).astype(np.float64)
        fields["bias"] = b
        fields["gradBias"] = np.zeros_like(b)
    return TorchObject("nn.SpatialConvolution", _base_fields(fields))


def _conv_from_torch(obj: TorchObject):
    from bigdl_tpu import nn
    f = obj.fields
    w = np.asarray(f["weight"], dtype=np.float32)
    n_group = w.shape[0] if w.ndim == 5 else int(f.get("nGroup", 1))
    m = nn.SpatialConvolution(
        int(f["nInputPlane"]), int(f["nOutputPlane"]),
        int(f["kW"]), int(f["kH"]), int(f["dW"]), int(f["dH"]),
        int(f.get("padW", 0)), int(f.get("padH", 0)), n_group=n_group)
    if w.ndim == 5:  # BigDL group layout (G, O/g, I/g, kH, kW) → flatten
        w = w.reshape(-1, *w.shape[2:])
    elif w.ndim == 2:  # nn.SpatialConvolutionMM: (O, I*kH*kW)
        w = w.reshape(int(f["nOutputPlane"]), -1,
                      int(f["kH"]), int(f["kW"]))
    # flat (O, I/g, kH, kW) → HWIO (kH, kW, I/g, O), groups preserved
    m.weight = np.transpose(w, (2, 3, 1, 0))
    if f.get("bias") is not None:
        m.bias = np.asarray(f["bias"], dtype=np.float32)
    return m


def _linear_to_torch(m) -> TorchObject:
    w = np.asarray(m.weight).astype(np.float64)  # ours (out,in) == torch
    fields = {"weight": w, "gradWeight": np.zeros_like(w)}
    if getattr(m, "bias", None) is not None:
        b = np.asarray(m.bias).astype(np.float64)
        fields["bias"] = b
        fields["gradBias"] = np.zeros_like(b)
    return TorchObject("nn.Linear", _base_fields(fields))


def _linear_from_torch(obj: TorchObject):
    from bigdl_tpu import nn
    w = np.asarray(obj["weight"], dtype=np.float32)
    m = nn.Linear(w.shape[1], w.shape[0],
                  with_bias=obj.get("bias") is not None)
    m.weight = w
    if obj.get("bias") is not None:
        m.bias = np.asarray(obj["bias"], dtype=np.float32)
    return m


def _bn_to_torch(m, cls: str) -> TorchObject:
    fields = {
        "nOutput": m.n_output, "eps": m.eps, "momentum": m.momentum,
        "running_mean": np.asarray(m.running_mean).astype(np.float64),
        "running_var": np.asarray(m.running_var).astype(np.float64),
        "affine": getattr(m, "weight", None) is not None,
    }
    if getattr(m, "weight", None) is not None:
        fields["weight"] = np.asarray(m.weight).astype(np.float64)
        fields["bias"] = np.asarray(m.bias).astype(np.float64)
        fields["gradWeight"] = np.zeros_like(fields["weight"])
        fields["gradBias"] = np.zeros_like(fields["bias"])
    return TorchObject(cls, _base_fields(fields))


def _bn_from_torch(obj: TorchObject, spatial: bool):
    from bigdl_tpu import nn
    mean = np.asarray(obj["running_mean"], dtype=np.float32)
    cls = nn.SpatialBatchNormalization if spatial else nn.BatchNormalization
    m = cls(mean.shape[0], eps=float(obj.get("eps", 1e-5)),
            momentum=float(obj.get("momentum", 0.1)),
            affine=obj.get("weight") is not None)
    m.running_mean = mean
    m.running_var = np.asarray(obj["running_var"], dtype=np.float32)
    if obj.get("weight") is not None:
        m.weight = np.asarray(obj["weight"], dtype=np.float32)
        m.bias = np.asarray(obj["bias"], dtype=np.float32)
    return m


def _pool_to_torch(m, cls: str) -> TorchObject:
    fields = {"kW": m.kw, "kH": m.kh, "dW": m.dw, "dH": m.dh,
              "padW": m.pad_w, "padH": m.pad_h,
              "ceil_mode": getattr(m, "ceil_mode", False)}
    return TorchObject(cls, _base_fields(fields))


def _pool_from_torch(obj: TorchObject, avg: bool):
    from bigdl_tpu import nn
    cls = nn.SpatialAveragePooling if avg else nn.SpatialMaxPooling
    m = cls(int(obj["kW"]), int(obj["kH"]), int(obj["dW"]), int(obj["dH"]),
            int(obj.get("padW", 0)), int(obj.get("padH", 0)))
    if obj.get("ceil_mode"):
        m.ceil_mode = True
    return m


def _seq_children(obj: TorchObject) -> List[Any]:
    mods = obj.get("modules", {}) or {}
    return [mods[k] for k in sorted(k for k in mods if isinstance(k, int))]


def _container_to_torch(m, cls: str) -> TorchObject:
    modules = {i + 1: to_torch_object(child)
               for i, child in enumerate(m._modules.values())}
    return TorchObject(cls, _base_fields({"modules": modules}))


def _reshape_from_torch(obj: TorchObject):
    from bigdl_tpu import nn
    size = np.asarray(obj["size"], dtype=np.int64).tolist()
    return nn.Reshape(tuple(int(s) for s in size))


def to_torch_object(m) -> TorchObject:
    """bigdl_tpu module → TorchObject tree (reference TorchFile writers)."""
    from bigdl_tpu import nn
    simple = {
        nn.Tanh: "nn.Tanh", nn.Sigmoid: "nn.Sigmoid",
        nn.SoftMax: "nn.SoftMax", nn.LogSoftMax: "nn.LogSoftMax",
        nn.Identity: "nn.Identity",
    }
    if isinstance(m, nn.Linear):
        return _linear_to_torch(m)
    if isinstance(m, (nn.SpatialConvolution, nn.SpaceToDepthConv7)):
        # the space-to-depth stem IS a 7x7/s2 conv: export as one
        return _conv_to_torch(m)
    if isinstance(m, nn.SpatialBatchNormalization):
        return _bn_to_torch(m, "nn.SpatialBatchNormalization")
    if isinstance(m, nn.BatchNormalization):
        return _bn_to_torch(m, "nn.BatchNormalization")
    if isinstance(m, nn.SpatialMaxPooling):
        return _pool_to_torch(m, "nn.SpatialMaxPooling")
    if isinstance(m, nn.SpatialAveragePooling):
        return _pool_to_torch(m, "nn.SpatialAveragePooling")
    if isinstance(m, nn.ReLU):
        return TorchObject("nn.ReLU", _base_fields(
            {"threshold": 0.0, "val": 0.0, "inplace": False}))
    if isinstance(m, nn.Dropout):
        return TorchObject("nn.Dropout", _base_fields({"p": m.p}))
    if isinstance(m, nn.View):  # subclass of Reshape — must test first
        return TorchObject("nn.View", _base_fields(
            {"size": np.asarray(m.size, dtype=np.int64)}))
    if isinstance(m, nn.Reshape):
        return TorchObject("nn.Reshape", _base_fields(
            {"size": np.asarray(m.size, dtype=np.int64),
             "nelement": float(int(np.prod(m.size)))}))
    if isinstance(m, nn.Sequential):
        return _container_to_torch(m, "nn.Sequential")
    if isinstance(m, nn.ConcatTable):
        return _container_to_torch(m, "nn.ConcatTable")
    if isinstance(m, nn.Concat):
        obj = _container_to_torch(m, "nn.Concat")
        obj.fields["dimension"] = float(m.dimension)
        return obj
    for cls, name in simple.items():
        if isinstance(m, cls):
            return TorchObject(name, _base_fields({}))
    raise ValueError(f"no .t7 mapping for module {type(m).__name__} "
                     f"(reference TorchFile supports a fixed class table)")


def from_torch_object(obj: Any):
    """TorchObject tree → bigdl_tpu module (reference TorchFile readers)."""
    from bigdl_tpu import nn
    if not isinstance(obj, TorchObject):
        raise ValueError(f"expected a torch nn object, got {type(obj)}")
    t = obj.torch_type
    simple = {
        "nn.Tanh": nn.Tanh, "nn.Sigmoid": nn.Sigmoid,
        "nn.SoftMax": nn.SoftMax, "nn.LogSoftMax": nn.LogSoftMax,
        "nn.Identity": nn.Identity, "nn.ReLU": nn.ReLU,
    }
    if t == "nn.Linear":
        return _linear_from_torch(obj)
    if t in ("nn.SpatialConvolution", "nn.SpatialConvolutionMM"):
        return _conv_from_torch(obj)
    if t == "nn.BatchNormalization":
        return _bn_from_torch(obj, spatial=False)
    if t == "nn.SpatialBatchNormalization":
        return _bn_from_torch(obj, spatial=True)
    if t == "nn.SpatialMaxPooling":
        return _pool_from_torch(obj, avg=False)
    if t == "nn.SpatialAveragePooling":
        return _pool_from_torch(obj, avg=True)
    if t == "nn.Dropout":
        return nn.Dropout(float(obj.get("p", 0.5)))
    if t == "nn.Reshape":
        return _reshape_from_torch(obj)
    if t == "nn.View":
        size = np.asarray(obj["size"], dtype=np.int64).tolist()
        return nn.View(tuple(int(s) for s in size))
    if t == "nn.Threshold":
        return nn.Threshold(float(obj.get("threshold", 0.0)),
                            float(obj.get("val", 0.0)))
    if t in ("nn.Sequential", "nn.ConcatTable", "nn.Concat"):
        children = [from_torch_object(c) for c in _seq_children(obj)]
        if t == "nn.Sequential":
            out = nn.Sequential()
        elif t == "nn.ConcatTable":
            out = nn.ConcatTable()
        else:
            out = nn.Concat(int(obj.get("dimension", 1)))
        for c in children:
            out.add(c)
        return out
    if t in simple:
        return simple[t]()
    raise ValueError(f"no bigdl_tpu mapping for torch class {t!r}")


# ==================================================================== facade

def load_torch(path: str, as_module: bool = True):
    """Read a ``.t7`` file (reference ``Module.loadTorch`` →
    ``TorchFile.load``). With ``as_module=False`` returns the raw decoded
    object tree (numbers/strings/dicts/arrays/TorchObjects)."""
    with open(path, "rb") as f:
        obj = _Reader(f).read_object()
    return from_torch_object(obj) if as_module else obj


def save_torch(obj, path: str, overwrite: bool = True) -> None:
    """Write a module (or raw object tree) as ``.t7`` (reference
    ``AbstractModule.saveTorch`` → ``TorchFile.save``)."""
    import os
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    from bigdl_tpu.nn.module import Module
    if isinstance(obj, Module):
        obj = to_torch_object(obj)
    with open(path, "wb") as f:
        _Writer(f).write_object(obj)
