"""Model interop: Torch ``.t7`` and Caffe ``.caffemodel`` import/export
(reference ``utils/TorchFile.scala:67`` and ``utils/CaffeLoader.scala:38``).

Like the reference — which implements the full Torch binary type system in
Scala and reads caffemodel protobufs through generated Java — this package
carries no third-party dependency: ``torch_file`` speaks the ``.t7`` wire
format directly and ``caffe`` walks the protobuf wire format by hand
(field-number table instead of 96 kLoC of generated code).
"""

from bigdl_tpu.interop.torch_file import load_torch, save_torch
from bigdl_tpu.interop.caffe import CaffeLoader, load_caffe
from bigdl_tpu.interop.state_dict import (export_lm_state_dict,
                                          import_lm_state_dict)
from bigdl_tpu.interop.hf import (load_gpt2, load_llama, load_qwen2,
                                  load_hf_checkpoint,
                                  save_hf_checkpoint,
                                  export_gpt2_state_dict,
                                  export_llama_state_dict,
                                  to_framework_ids, to_hf_ids)

__all__ = ["load_torch", "save_torch", "CaffeLoader", "load_caffe",
    "export_lm_state_dict", "import_lm_state_dict",
    "load_gpt2", "load_llama", "load_qwen2", "load_hf_checkpoint",
    "save_hf_checkpoint",
    "export_gpt2_state_dict", "export_llama_state_dict",
    "to_framework_ids", "to_hf_ids"]
