"""Caffe ``.caffemodel`` import (reference ``utils/CaffeLoader.scala:38``).

The reference parses caffemodel protobufs through 96 kLoC of generated Java
(``caffe/Caffe.java``) and copies weights **by layer name** into an existing
model (``CaffeLoader.copyParameters``, ``CaffeLoader.scala:132``). Here the
protobuf wire format is walked directly — the handful of field numbers needed
(NetParameter → LayerParameter/V1LayerParameter → BlobProto) is a table, not
a code generator.

Field numbers (caffe.proto):

    NetParameter:      name=1, layers(V1)=2, layer=100
    LayerParameter:    name=1, type=2 (string), blobs=7
    V1LayerParameter:  name=4, type=5 (enum), blobs=6
    BlobProto:         num=1 channels=2 height=3 width=4 (legacy 4-D),
                       data=5 (packed float), double_data=8, shape=7
    BlobShape:         dim=1 (packed int64)

Weight layouts: Caffe convolution blobs are (O, I/g, kH, kW) → converted to
our HWIO; Deconvolution blobs are (I, O/g, kH, kW); InnerProduct blobs are
(out, in) → matches our Linear directly.

Weight-copy coverage (round 5): Convolution, InnerProduct, Deconvolution,
BatchNorm (with the scale_factor accumulator convention; gamma/beta live
in caffe's separate Scale layer — mirror that structure with
``SpatialBatchNormalization(affine=False)`` + ``nn.Scale``), Scale, PReLU,
Embed. A name-matched, blob-carrying layer with no mapping raises instead
of silently keeping random weights.
"""

from __future__ import annotations

import logging
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("bigdl_tpu.interop")

from bigdl_tpu.utils.protowire import (  # noqa: E402
    WT_VARINT as _WT_VARINT, WT_I64 as _WT_I64, WT_LEN as _WT_LEN,
    WT_I32 as _WT_I32, iter_fields as _iter_fields,
    read_varint as _read_varint)

# V1LayerParameter.LayerType enum values (caffe.proto V1 enum; the ones a
# weight walk can encounter — others surface as their number)
_V1_TYPES = {0: "None", 4: "Convolution", 5: "Data", 14: "InnerProduct",
             17: "Pooling", 18: "ReLU", 19: "Sigmoid", 26: "Power",
             39: "Deconvolution"}


def _parse_blob(buf: memoryview) -> np.ndarray:
    shape: List[int] = []
    legacy = [0, 0, 0, 0]  # num, channels, height, width
    pieces: List[np.ndarray] = []
    for field, wt, val in _iter_fields(buf):
        if field == 7 and wt == _WT_LEN:  # BlobShape
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1:
                    if w2 == _WT_LEN:  # packed int64
                        pos = 0
                        while pos < len(v2):
                            d, pos = _read_varint(v2, pos)
                            shape.append(d)
                    elif w2 == _WT_VARINT:
                        shape.append(v2)
        elif field in (1, 2, 3, 4) and wt == _WT_VARINT:
            legacy[field - 1] = val
        elif field == 5 and wt == _WT_LEN:  # packed float data — protobuf
            # allows one packed field split across several LEN records;
            # parsers must concatenate (done once, below)
            pieces.append(np.frombuffer(bytes(val), dtype="<f4"))
        elif field == 8 and wt == _WT_LEN:  # packed double data
            pieces.append(np.frombuffer(bytes(val), dtype="<f8")
                          .astype(np.float32))
        elif field == 5 and wt == _WT_I32:  # unpacked float (rare)
            pieces.append(np.frombuffer(bytes(val), dtype="<f4"))
    if not pieces:
        return np.zeros((0,), dtype=np.float32)
    data = pieces[0] if len(pieces) == 1 else np.concatenate(pieces)
    if not shape and any(legacy):
        shape = [d for d in legacy]
        # legacy blobs are padded with 1s in the leading dims; keep all 4
        shape = [d if d else 1 for d in shape]
    if shape and int(np.prod(shape)) == data.size:
        data = data.reshape(shape)
    return data.astype(np.float32)


class CaffeLayer:
    def __init__(self, name: str, type_: str, blobs: List[np.ndarray]):
        self.name = name
        self.type = type_
        self.blobs = blobs

    def __repr__(self):
        return (f"CaffeLayer({self.name!r}, {self.type!r}, "
                f"blobs={[b.shape for b in self.blobs]})")


def parse_caffemodel(path: str) -> List[CaffeLayer]:
    """Extract every weight-carrying layer from a binary ``.caffemodel``."""
    with open(path, "rb") as f:
        buf = memoryview(f.read())
    layers: List[CaffeLayer] = []
    for field, wt, val in _iter_fields(buf):
        if wt != _WT_LEN or field not in (2, 100):
            continue
        name, type_, blobs = "", "", []
        if field == 100:  # LayerParameter
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == _WT_LEN:
                    name = bytes(v2).decode("utf-8", "replace")
                elif f2 == 2 and w2 == _WT_LEN:
                    type_ = bytes(v2).decode("utf-8", "replace")
                elif f2 == 7 and w2 == _WT_LEN:
                    blobs.append(_parse_blob(v2))
        else:  # V1LayerParameter
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 4 and w2 == _WT_LEN:
                    name = bytes(v2).decode("utf-8", "replace")
                elif f2 == 5 and w2 == _WT_VARINT:
                    type_ = _V1_TYPES.get(v2, str(v2))
                elif f2 == 6 and w2 == _WT_LEN:
                    blobs.append(_parse_blob(v2))
        if name:
            layers.append(CaffeLayer(name, type_, blobs))
    return layers


def parse_prototxt_layers(def_path: str) -> List[CaffeLayer]:
    """Layer definitions from a ``.prototxt`` model definition (reference
    ``CaffeLoader.loadBinary`` merges the text NetParameter first,
    ``CaffeLoader.scala:63-66``). Text-format blobs (rare, but legal — e.g.
    the reference test fixture ``caffe/test_persist.prototxt``) are decoded
    into arrays like their binary counterparts."""
    from bigdl_tpu.interop import prototxt as pt
    net = pt.parse_file(def_path)
    layers: List[CaffeLayer] = []
    for entry in net.get("layer", []) + net.get("layers", []):
        name = pt.first(entry, "name", "")
        type_ = pt.first(entry, "type", "")
        if isinstance(type_, int):  # V1 enum number
            type_ = _V1_TYPES.get(type_, str(type_))
        blobs = []
        for blob in entry.get("blobs", []):
            data = np.asarray(blob.get("data", []), np.float32)
            shape = blob.get("shape")
            if shape:
                dims = shape[0].get("dim", [])
            else:
                dims = [pt.first(blob, k, 0)
                        for k in ("num", "channels", "height", "width")]
                dims = [d if d else 1 for d in dims] if any(dims) else []
            if dims and int(np.prod(dims)) == data.size:
                data = data.reshape(dims)
            blobs.append(data)
        if name:
            layers.append(CaffeLayer(str(name), str(type_), blobs))
    return layers


class CaffeLoader:
    """Copy caffemodel weights by layer name into an existing model
    (reference ``CaffeLoader.copyParameters``). ``def_path`` merges the
    prototxt definition the way ``TextFormat.merge`` + binary ``mergeFrom``
    do: the definition contributes the layer-name universe (and any text
    blobs); binary blobs win when both exist."""

    def __init__(self, model, model_path: str, match_all: bool = True,
                 def_path: Optional[str] = None):
        self.model = model
        self.model_path = model_path
        self.match_all = match_all
        self.def_path = def_path

    def _copy_conv(self, module, layer: CaffeLayer) -> None:
        w = layer.blobs[0]
        if w.ndim != 4:
            w = w.reshape(module.n_output_plane, -1,
                          module.kernel_h, module.kernel_w)
        import jax.numpy as jnp
        module.weight = jnp.asarray(np.transpose(w, (2, 3, 1, 0)))  # OIHW→HWIO
        if len(layer.blobs) > 1 and getattr(module, "with_bias", True):
            module.bias = jnp.asarray(layer.blobs[1].reshape(-1))

    def _copy_linear(self, module, layer: CaffeLayer) -> None:
        import jax.numpy as jnp
        w = layer.blobs[0].reshape(module.output_size, module.input_size)
        module.weight = jnp.asarray(w)  # caffe (out,in) == ours
        if len(layer.blobs) > 1 and getattr(module, "with_bias", True):
            module.bias = jnp.asarray(layer.blobs[1].reshape(-1))

    def _copy_deconv(self, module, layer: CaffeLayer) -> None:
        import jax.numpy as jnp
        w = layer.blobs[0]
        if w.ndim != 4:  # caffe deconv blob: (I, O/g, kH, kW)
            w = w.reshape(module.n_input_plane,
                          module.n_output_plane // module.n_group,
                          module.kh, module.kw)
        # (I, O/g, kH, kW) -> ours (kH, kW, O/g, I)
        module.weight = jnp.asarray(np.transpose(w, (2, 3, 1, 0)))
        if len(layer.blobs) > 1 and getattr(module, "with_bias", True):
            module.bias = jnp.asarray(layer.blobs[1].reshape(-1))

    def _copy_batchnorm(self, module, layer: CaffeLayer) -> None:
        """Caffe "BatchNorm": blobs = [mean, var, scale_factor]; the stored
        statistics must be divided by the scalar scale_factor (caffe's
        moving-average accumulator convention). Gamma/beta live in a
        SEPARATE caffe "Scale" layer — build the model with
        ``SpatialBatchNormalization(affine=False)`` followed by an
        ``nn.Scale`` named after the caffe Scale layer, mirroring the
        caffemodel's own two-layer structure."""
        import jax.numpy as jnp
        mean = layer.blobs[0].reshape(-1)
        var = layer.blobs[1].reshape(-1)
        sf = 1.0
        if len(layer.blobs) > 2 and layer.blobs[2].size:
            raw = float(layer.blobs[2].reshape(-1)[0])
            sf = 0.0 if raw == 0.0 else 1.0 / raw
        module.running_mean = jnp.asarray(mean * sf)
        module.running_var = jnp.asarray(var * sf)

    def _copy_scale(self, module, layer: CaffeLayer) -> None:
        import jax.numpy as jnp
        gamma = layer.blobs[0].reshape(-1)
        module.cmul.weight = jnp.asarray(
            gamma.reshape(module.cmul.weight.shape))
        if len(layer.blobs) > 1:
            beta = layer.blobs[1].reshape(-1)
            module.cadd.bias = jnp.asarray(
                beta.reshape(module.cadd.bias.shape))

    def _copy_prelu(self, module, layer: CaffeLayer) -> None:
        import jax.numpy as jnp
        slopes = layer.blobs[0].reshape(-1)
        module.weight = jnp.asarray(slopes.reshape(module.weight.shape))

    def _copy_embed(self, module, layer: CaffeLayer) -> None:
        import jax.numpy as jnp
        if len(layer.blobs) > 1:
            # caffe Embed defaults bias_term=true; LookupTable has no bias
            # slot — refuse rather than silently drop the bias add
            raise ValueError(
                f"caffe Embed layer {layer.name!r} carries a bias blob; "
                "LookupTable cannot represent it — follow the embedding "
                "with nn.CAdd (named to a Scale/Bias layer) or retrain "
                "with bias_term=false")
        w = layer.blobs[0].reshape(module.n_index, module.n_output)
        module.weight = jnp.asarray(w)

    def copy_parameters(self):
        from bigdl_tpu import nn
        layers: Dict[str, CaffeLayer] = {}
        def_names = set()
        if self.def_path:
            defs = parse_prototxt_layers(self.def_path)
            def_names = {l.name for l in defs}
            layers.update((l.name, l) for l in defs)
        for l in parse_caffemodel(self.model_path):
            if l.blobs or l.name not in layers:
                layers[l.name] = l  # binary blobs win over text definition
        copied, missed = [], []
        weighted = (nn.Linear, nn.SpatialConvolution, nn.SpaceToDepthConv7,
                    nn.SpatialFullConvolution, nn.BatchNormalization,
                    nn.Scale, nn.PReLU, nn.LookupTable)
        for name, module in self.model.named_modules():
            lname = module.get_name()
            layer = layers.get(lname)
            if layer is None:
                if isinstance(module, weighted):
                    missed.append(lname)
                continue
            if not layer.blobs:
                if lname in def_names:
                    # declared in the definition but weightless — reference
                    # keeps initialized parameters (CaffeLoader.scala:150-155)
                    if isinstance(module, weighted):
                        logger.info("%s uses initialized parameters", lname)
                else:
                    # a blobless layer in the binary itself is a missing
                    # weight (truncated/deploy-only caffemodel), not a
                    # benign definition entry
                    if isinstance(module, weighted):
                        missed.append(lname)
                continue
            if isinstance(module, (nn.SpatialConvolution,
                                   nn.SpaceToDepthConv7)):
                self._copy_conv(module, layer)
            elif isinstance(module, nn.SpatialFullConvolution):
                self._copy_deconv(module, layer)
            elif isinstance(module, nn.Linear):
                self._copy_linear(module, layer)
            elif isinstance(module, nn.BatchNormalization):
                self._copy_batchnorm(module, layer)
            elif isinstance(module, nn.Scale):
                self._copy_scale(module, layer)
            elif isinstance(module, nn.PReLU):
                self._copy_prelu(module, layer)
            elif isinstance(module, nn.LookupTable):
                self._copy_embed(module, layer)
            elif any(m._parameters for m in module.modules()):
                # name-matched, blob-carrying, but no mapping: silently
                # keeping random weights would corrupt — refuse. The scan
                # covers SUBMODULE parameters too (composite modules like
                # Scale keep theirs on children).
                raise ValueError(
                    f"caffe layer {lname!r} (type {layer.type!r}, "
                    f"{len(layer.blobs)} blobs) matches parametric module "
                    f"{type(module).__name__} with no weight mapping")
            else:
                continue
            copied.append(lname)
        if missed and self.match_all:
            raise ValueError(
                f"caffemodel is missing weights for layers {missed}; "
                f"pass match_all=False to load a partial match "
                f"(reference CaffeLoader.scala:132 contract)")
        for lname in missed:
            logger.warning("no caffe weights for layer %s", lname)
        logger.info("copied caffe weights for %d layers", len(copied))
        return self.model


def load_caffe(model, *paths: str, match_all: bool = True):
    """Reference ``Module.loadCaffe(defPath, modelPath, matchAll)``
    (``CaffeLoader.scala:154``). Accepts either ``load_caffe(model,
    model_path)`` — names live in the caffemodel, so the definition is
    optional — or the reference's full ``load_caffe(model, def_path,
    model_path)`` form."""
    if len(paths) == 1:
        def_path, model_path = None, paths[0]
    elif len(paths) == 2:
        def_path, model_path = paths
    else:
        raise TypeError("load_caffe(model, [def_path,] model_path)")
    return CaffeLoader(model, model_path, match_all,
                       def_path=def_path).copy_parameters()


def load_mean_file(path: str) -> np.ndarray:
    """Read a caffe ``.binaryproto`` mean image (a bare serialized BlobProto,
    reference ``example/loadmodel/DatasetUtil.scala`` AlexNetPreprocessor).
    Returns (H, W, C) float32 in caffe's BGR channel order."""
    with open(path, "rb") as f:
        arr = _parse_blob(memoryview(f.read()))
    if arr.ndim == 4:  # legacy (1, C, H, W)
        arr = arr[0]
    if arr.ndim != 3:
        raise ValueError(f"mean file {path} has shape {arr.shape}; "
                         f"expected a (C, H, W) image blob")
    return np.transpose(arr, (1, 2, 0))  # CHW -> HWC
