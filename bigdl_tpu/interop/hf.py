"""HuggingFace-layout checkpoint import for GPT-2- and Llama-family LMs.

The reference's defining interop move is loading a FOREIGN framework's
pretrained weights into its own modules by structural mapping
(``utils/CaffeLoader.scala:132`` ``copyParameters`` name-matches caffemodel
blobs; ``utils/TorchFile.scala:67`` maps ~30 Lua ``nn.*`` classes). This
module replays that move for the LM era: the checkpoints a migrating user
actually holds today are HF ``transformers`` state_dicts, and the two
layouts that cover most of them are GPT-2's (fused Conv1D ``c_attn``,
learned ``wpe`` positions, tied head) and Llama's (split q/k/v with GQA,
RoPE, RMSNorm, gated SwiGLU MLP, no biases).

Both importers are NAME + LAYOUT maps onto ``models.transformer.build_lm``:

GPT-2 (``GPT2LMHeadModel``): HF stores every projection as ``Conv1D`` —
weight (in, out), the TRANSPOSE of torch/our Linear (out, in) — so each
``c_attn``/``c_proj``/``c_fc`` weight transposes on the way in; the fused
``c_attn`` columns are already q;k;v-stacked, which after transposition is
exactly our ``in_proj_weight`` row stacking.

Llama (``LlamaForCausalLM``): separate ``q_proj``/``k_proj``/``v_proj``
Linears concatenate row-wise into our GQA ``in_proj_weight``
((E + 2*E_kv, E) — the k/v blocks are the GROUPED size, so grouped-query
checkpoints load without expansion); ``gate_proj`` (inside silu) is our
``linear1``, ``up_proj`` our ``linear_gate``, ``down_proj`` our
``linear2``; RoPE pairing is the same rotate-half convention, so q/k need
no permutation (``nn/attention.py:rope_rotate``).

Token ids stay 1-based on our side: the tables are copied verbatim, so our
id ``k`` denotes the same token as HF id ``k-1`` (shift ids by +1 on the
way in, -1 on the way out — ``to_framework_ids``/``to_hf_ids``).

Model output is LOG-probabilities (the framework's LM tail convention),
= ``log_softmax`` of HF logits; perplexity and greedy/beam sampling are
therefore directly comparable (verified to 1e-4 by
``tests/test_hf_interop.py`` against live ``transformers`` torch models).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from bigdl_tpu.interop.state_dict import import_lm_state_dict
from bigdl_tpu.nn.module import Module


def to_framework_ids(ids):
    """HF 0-based token ids -> this framework's 1-based ids."""
    return np.asarray(ids) + 1


def to_hf_ids(ids):
    """This framework's 1-based token ids -> HF 0-based ids."""
    return np.asarray(ids) - 1


def _np(v) -> np.ndarray:
    """Materialise a state_dict value (torch tensor / jax / numpy) as fp32
    numpy without importing torch here."""
    if hasattr(v, "detach"):  # torch.Tensor
        v = v.detach().cpu()
        if hasattr(v, "float"):
            v = v.float()
        v = v.numpy()
    return np.asarray(v, np.float32)


# --------------------------------------------------------------------- GPT-2

def gpt2_lm_kwargs(config: Dict[str, Any]) -> Dict[str, Any]:
    """``build_lm`` kwargs for an HF GPT-2 ``config.json`` dict."""
    e = int(config["n_embd"])
    n_inner = config.get("n_inner") or 4 * e
    act = config.get("activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh", "gelu"):
        raise ValueError(f"unsupported GPT-2 activation {act!r}")
    # math-changing attention variants: refuse, don't corrupt (same policy
    # as the Llama rope_scaling/sliding_window guards below)
    if config.get("scale_attn_by_inverse_layer_idx", False):
        raise ValueError("scale_attn_by_inverse_layer_idx=True divides "
                         "attention scores per layer; not mapped")
    if not config.get("scale_attn_weights", True):
        raise ValueError("scale_attn_weights=False (unscaled attention) "
                         "is not mapped")
    # "gelu" is the exact erf form; gelu_new/gelu_pytorch_tanh the tanh
    # approximation (~1e-3 apart) — map each to its own kernel instead of
    # silently substituting
    return dict(
        vocab_size=int(config["vocab_size"]),
        embed_dim=e,
        num_heads=int(config["n_head"]),
        ffn_dim=int(n_inner),
        num_layers=int(config["n_layer"]),
        max_len=int(config.get("n_positions", 1024)),
        pos="learned",
        tie_embeddings=True,
        activation="gelu_exact" if act == "gelu" else "gelu",
        norm="layer",
        norm_eps=float(config.get("layer_norm_epsilon", 1e-5)),
    )


def gpt2_state_dict_to_lm(hf_sd: Dict[str, Any],
                          num_layers: int) -> Dict[str, np.ndarray]:
    """HF GPT-2 state_dict -> our torch-convention LM state_dict.

    Accepts ``GPT2LMHeadModel`` keys (``transformer.``-prefixed) or bare
    ``GPT2Model`` keys. Ignores the non-weight buffers HF carries
    (``attn.bias`` causal mask, ``attn.masked_bias``) and the tied
    ``lm_head.weight`` duplicate.
    """
    sd = {}
    for k, v in hf_sd.items():
        if k.startswith("transformer."):
            k = k[len("transformer."):]
        sd[k] = v
    out: Dict[str, np.ndarray] = {
        "embedding.weight": _np(sd["wte.weight"]),
        "pos_embedding.weight": _np(sd["wpe.weight"]),
        "encoder.norm.weight": _np(sd["ln_f.weight"]),
        "encoder.norm.bias": _np(sd["ln_f.bias"]),
    }
    for i in range(num_layers):
        src, dst = f"h.{i}", f"encoder.layers.{i}"
        out[f"{dst}.norm1.weight"] = _np(sd[f"{src}.ln_1.weight"])
        out[f"{dst}.norm1.bias"] = _np(sd[f"{src}.ln_1.bias"])
        out[f"{dst}.norm2.weight"] = _np(sd[f"{src}.ln_2.weight"])
        out[f"{dst}.norm2.bias"] = _np(sd[f"{src}.ln_2.bias"])
        # Conv1D (in, out) -> Linear (out, in): transpose
        out[f"{dst}.self_attn.in_proj_weight"] = \
            _np(sd[f"{src}.attn.c_attn.weight"]).T.copy()
        out[f"{dst}.self_attn.in_proj_bias"] = \
            _np(sd[f"{src}.attn.c_attn.bias"])
        out[f"{dst}.self_attn.out_proj.weight"] = \
            _np(sd[f"{src}.attn.c_proj.weight"]).T.copy()
        out[f"{dst}.self_attn.out_proj.bias"] = \
            _np(sd[f"{src}.attn.c_proj.bias"])
        out[f"{dst}.linear1.weight"] = _np(sd[f"{src}.mlp.c_fc.weight"]).T.copy()
        out[f"{dst}.linear1.bias"] = _np(sd[f"{src}.mlp.c_fc.bias"])
        out[f"{dst}.linear2.weight"] = _np(sd[f"{src}.mlp.c_proj.weight"]).T.copy()
        out[f"{dst}.linear2.bias"] = _np(sd[f"{src}.mlp.c_proj.bias"])
    return out


def load_gpt2(config: Dict[str, Any], state_dict: Dict[str, Any]) -> Module:
    """Build a ``build_lm`` model from an HF GPT-2 config + state_dict."""
    from bigdl_tpu.models.transformer import build_lm
    kwargs = gpt2_lm_kwargs(config)
    model = build_lm(**kwargs)
    ours = gpt2_state_dict_to_lm(state_dict, kwargs["num_layers"])
    return import_lm_state_dict(model, ours, strict=True)


# --------------------------------------------------------------------- Llama

def llama_lm_kwargs(config: Dict[str, Any]) -> Dict[str, Any]:
    """``build_lm`` kwargs for an HF Llama-family ``config.json`` dict."""
    if config.get("attention_bias", False) or config.get("mlp_bias", False):
        raise ValueError("biased Llama variants are not mapped (set "
                         "attention_bias/mlp_bias False)")
    act = config.get("hidden_act", "silu")
    if act != "silu":
        raise ValueError(f"unsupported Llama activation {act!r}")
    scaling = config.get("rope_scaling")
    rope_scaling = None
    if scaling:
        rt = scaling.get("rope_type", scaling.get("type"))
        if rt in ("llama3", "linear", "yarn"):
            # implemented frequency rescalings (nn.attention
            # .scale_rope_freqs, each parity-tested against transformers)
            rope_scaling = dict(scaling)
        elif rt != "default":
            # the rest (dynamic NTK, longrope) would silently change every
            # attention score if ignored — refuse, don't corrupt
            raise ValueError(f"rope_scaling {scaling!r} is not supported "
                             "yet (plain/llama3/linear/yarn frequencies)")
    window = config.get("sliding_window")
    heads = int(config["num_attention_heads"])
    return dict(
        # Mistral-style sliding window maps to banded causal attention
        # (query i sees keys (i - window, i]); None = global
        window=int(window) if window else None,
        rope_scaling=rope_scaling,
        vocab_size=int(config["vocab_size"]),
        embed_dim=int(config["hidden_size"]),
        num_heads=heads,
        num_kv_heads=int(config.get("num_key_value_heads", heads)),
        ffn_dim=int(config["intermediate_size"]),
        num_layers=int(config["num_hidden_layers"]),
        max_len=int(config.get("max_position_embeddings", 2048)),
        rope=True,
        rope_theta=float(config.get("rope_theta", 10000.0)),
        activation="swiglu",
        norm="rms",
        norm_eps=float(config.get("rms_norm_eps", 1e-6)),
        bias=False,
        tie_embeddings=bool(config.get("tie_word_embeddings", False)),
    )


def llama_state_dict_to_lm(hf_sd: Dict[str, Any],
                           num_layers: int) -> Dict[str, np.ndarray]:
    """HF Llama state_dict -> our torch-convention LM state_dict.

    The q/k/v Linears concatenate row-wise into the GQA ``in_proj_weight``
    ((E + 2*E_kv, E)); everything else is a rename (torch Linear layout on
    both sides). ``rotary_emb.inv_freq`` buffers are ignored.
    """
    sd = dict(hf_sd)
    out: Dict[str, np.ndarray] = {
        "embedding.weight": _np(sd["model.embed_tokens.weight"]),
        "encoder.norm.weight": _np(sd["model.norm.weight"]),
    }
    if "lm_head.weight" in sd:
        out["lm_head.weight"] = _np(sd["lm_head.weight"])
    for i in range(num_layers):
        src, dst = f"model.layers.{i}", f"encoder.layers.{i}"
        out[f"{dst}.norm1.weight"] = _np(sd[f"{src}.input_layernorm.weight"])
        out[f"{dst}.norm2.weight"] = \
            _np(sd[f"{src}.post_attention_layernorm.weight"])
        out[f"{dst}.self_attn.in_proj_weight"] = np.concatenate([
            _np(sd[f"{src}.self_attn.q_proj.weight"]),
            _np(sd[f"{src}.self_attn.k_proj.weight"]),
            _np(sd[f"{src}.self_attn.v_proj.weight"])], axis=0)
        if f"{src}.self_attn.q_proj.bias" in sd:  # Qwen2's qkv-bias layout
            out[f"{dst}.self_attn.in_proj_bias"] = np.concatenate([
                _np(sd[f"{src}.self_attn.q_proj.bias"]),
                _np(sd[f"{src}.self_attn.k_proj.bias"]),
                _np(sd[f"{src}.self_attn.v_proj.bias"])], axis=0)
        out[f"{dst}.self_attn.out_proj.weight"] = \
            _np(sd[f"{src}.self_attn.o_proj.weight"])
        out[f"{dst}.linear1.weight"] = _np(sd[f"{src}.mlp.gate_proj.weight"])
        out[f"{dst}.linear_gate.weight"] = _np(sd[f"{src}.mlp.up_proj.weight"])
        out[f"{dst}.linear2.weight"] = _np(sd[f"{src}.mlp.down_proj.weight"])
    return out


def load_llama(config: Dict[str, Any], state_dict: Dict[str, Any]) -> Module:
    """Build a ``build_lm`` model from an HF Llama config + state_dict."""
    from bigdl_tpu.models.transformer import build_lm
    kwargs = llama_lm_kwargs(config)
    model = build_lm(**kwargs)
    ours = llama_state_dict_to_lm(state_dict, kwargs["num_layers"])
    # tied checkpoints carry no lm_head.weight; untied must have it
    strict = not kwargs["tie_embeddings"]
    return import_lm_state_dict(model, ours, strict=strict)


# -------------------------------------------------------------------- Qwen2

def qwen2_lm_kwargs(config: Dict[str, Any]) -> Dict[str, Any]:
    """``build_lm`` kwargs for an HF Qwen2 ``config.json`` dict — the
    Llama block with biased q/k/v projections (and only those):
    ``qkv_bias=True`` on our side restores exactly that layout."""
    act = config.get("hidden_act", "silu")
    if act != "silu":
        raise ValueError(f"unsupported Qwen2 activation {act!r}")
    # Qwen2's sliding_window key is inert unless use_sliding_window; when
    # active, transformers applies it only to layers with index >=
    # max_window_layers (so max_window_layers == num_hidden_layers — the
    # shape real Qwen2 configs ship — means NO layer slides). We build
    # homogeneous stacks: all-sliding (0) and none-sliding (== n_layers)
    # map cleanly; a genuine mix is refused rather than corrupted.
    window = None
    if config.get("use_sliding_window", False):
        n_layers = int(config["num_hidden_layers"])
        mwl = int(config.get("max_window_layers", 0))
        if mwl == 0:
            window = int(config["sliding_window"])
        elif mwl >= n_layers:
            window = None  # sliding enabled but applies to no layer
        else:
            raise ValueError("Qwen2 mixed sliding-window layers "
                             "(0 < max_window_layers < num_hidden_layers) "
                             "are not mapped")
    base = dict(config)
    base.pop("sliding_window", None)  # handled above (llama semantics differ)
    kwargs = llama_lm_kwargs(base)
    kwargs["window"] = window
    kwargs["qkv_bias"] = True
    return kwargs


def load_qwen2(config: Dict[str, Any], state_dict: Dict[str, Any]) -> Module:
    """Build a ``build_lm`` model from an HF Qwen2 config + state_dict
    (same tensor names as Llama plus q/k/v biases)."""
    from bigdl_tpu.models.transformer import build_lm
    kwargs = qwen2_lm_kwargs(config)
    model = build_lm(**kwargs)
    ours = llama_state_dict_to_lm(state_dict, kwargs["num_layers"])
    strict = not kwargs["tie_embeddings"]
    return import_lm_state_dict(model, ours, strict=strict)


# ------------------------------------------------------------------- export

def export_gpt2_state_dict(model: Module) -> Dict[str, np.ndarray]:
    """Inverse of ``gpt2_state_dict_to_lm``: a GPT-2-shaped ``build_lm``
    model (pos="learned", tied embeddings, LayerNorm, biased) exported as
    an HF ``GPT2LMHeadModel`` state_dict (``transformer.``-prefixed
    Conv1D layout) — so models trained here load straight into
    ``transformers``. The reference's interop is likewise bidirectional
    (``utils/TorchFile.scala:67`` saves as well as loads)."""
    from bigdl_tpu.interop.state_dict import export_lm_state_dict
    ours = export_lm_state_dict(model)
    if "pos_embedding.weight" not in ours:
        raise ValueError("GPT-2 export needs build_lm(pos='learned') "
                         "(a trained wpe table)")
    if "lm_head.weight" in ours:
        raise ValueError("GPT-2 export needs tie_embeddings=True "
                         "(GPT-2 checkpoints carry no separate head)")
    out: Dict[str, np.ndarray] = {
        "transformer.wte.weight": ours["embedding.weight"],
        "transformer.wpe.weight": ours["pos_embedding.weight"],
        "transformer.ln_f.weight": ours["encoder.norm.weight"],
        "transformer.ln_f.bias": ours["encoder.norm.bias"],
        "lm_head.weight": ours["embedding.weight"],  # tied duplicate
    }
    n_layers = 1 + max(int(k.split(".")[2]) for k in ours
                       if k.startswith("encoder.layers."))
    for i in range(n_layers):
        src, dst = f"encoder.layers.{i}", f"transformer.h.{i}"
        out[f"{dst}.ln_1.weight"] = ours[f"{src}.norm1.weight"]
        out[f"{dst}.ln_1.bias"] = ours[f"{src}.norm1.bias"]
        out[f"{dst}.ln_2.weight"] = ours[f"{src}.norm2.weight"]
        out[f"{dst}.ln_2.bias"] = ours[f"{src}.norm2.bias"]
        out[f"{dst}.attn.c_attn.weight"] = \
            ours[f"{src}.self_attn.in_proj_weight"].T.copy()
        out[f"{dst}.attn.c_attn.bias"] = ours[f"{src}.self_attn.in_proj_bias"]
        out[f"{dst}.attn.c_proj.weight"] = \
            ours[f"{src}.self_attn.out_proj.weight"].T.copy()
        out[f"{dst}.attn.c_proj.bias"] = ours[f"{src}.self_attn.out_proj.bias"]
        out[f"{dst}.mlp.c_fc.weight"] = ours[f"{src}.linear1.weight"].T.copy()
        out[f"{dst}.mlp.c_fc.bias"] = ours[f"{src}.linear1.bias"]
        out[f"{dst}.mlp.c_proj.weight"] = ours[f"{src}.linear2.weight"].T.copy()
        out[f"{dst}.mlp.c_proj.bias"] = ours[f"{src}.linear2.bias"]
    return out


def export_llama_state_dict(model: Module) -> Dict[str, np.ndarray]:
    """Inverse of ``llama_state_dict_to_lm``: a Llama-shaped ``build_lm``
    model (rope, rms, swiglu, bias-free) exported under HF
    ``LlamaForCausalLM`` names (q/k/v split back out of the GQA
    in_proj stack)."""
    from bigdl_tpu.interop.state_dict import export_lm_state_dict
    from bigdl_tpu.nn.attention import MultiHeadAttention
    ours = export_lm_state_dict(model)
    mhas = [m for m in model.modules()
            if isinstance(m, MultiHeadAttention)]
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": ours["embedding.weight"],
        "model.norm.weight": ours["encoder.norm.weight"],
    }
    if "lm_head.weight" in ours:
        out["lm_head.weight"] = ours["lm_head.weight"]
    n_layers = 1 + max(int(k.split(".")[2]) for k in ours
                       if k.startswith("encoder.layers."))
    for i in range(n_layers):
        src, dst = f"encoder.layers.{i}", f"model.layers.{i}"
        attn = mhas[i]
        e, ekv = attn.embed_dim, attn._e_kv
        w = ours[f"{src}.self_attn.in_proj_weight"]
        out[f"{dst}.self_attn.q_proj.weight"] = w[:e]
        out[f"{dst}.self_attn.k_proj.weight"] = w[e:e + ekv]
        out[f"{dst}.self_attn.v_proj.weight"] = w[e + ekv:]
        out[f"{dst}.self_attn.o_proj.weight"] = \
            ours[f"{src}.self_attn.out_proj.weight"]
        out[f"{dst}.input_layernorm.weight"] = ours[f"{src}.norm1.weight"]
        out[f"{dst}.post_attention_layernorm.weight"] = \
            ours[f"{src}.norm2.weight"]
        out[f"{dst}.mlp.gate_proj.weight"] = ours[f"{src}.linear1.weight"]
        out[f"{dst}.mlp.up_proj.weight"] = ours[f"{src}.linear_gate.weight"]
        out[f"{dst}.mlp.down_proj.weight"] = ours[f"{src}.linear2.weight"]
    return out


def _lm_geometry(model: Module):
    """(embed, encoder, first MHA, head) of a build_lm-shaped model."""
    from bigdl_tpu.interop.state_dict import _lm_parts
    from bigdl_tpu.nn.attention import MultiHeadAttention
    emb, enc, head = _lm_parts(model)
    mha = enc._modules["layer0"].self_attn
    assert isinstance(mha, MultiHeadAttention)
    return emb, enc, mha, head


def save_hf_checkpoint(model: Module, path: str) -> str:
    """Write ``config.json`` + ``model.safetensors`` so ``transformers``
    loads the directory with ``from_pretrained`` — the full inverse of
    ``load_hf_checkpoint``. The flavour is inferred from the model:
    RoPE + RMSNorm + SwiGLU exports as a Llama config, a learned-position
    LayerNorm/gelu stack as GPT-2. Returns the directory path."""
    from safetensors.numpy import save_file
    emb, enc, mha, head = _lm_geometry(model)
    layer0 = enc._modules["layer0"]
    is_llama = getattr(mha, "rope", False)
    act = getattr(layer0, "activation", None)
    # refuse, don't corrupt (the import-side policy, both directions):
    # the exported config hardcodes the family activation
    if is_llama and act != "swiglu":
        raise ValueError(f"Llama-family export needs activation='swiglu' "
                         f"(model has {act!r})")
    if not is_llama and act != "gelu":
        raise ValueError(f"GPT-2 export needs activation='gelu' "
                         f"(= HF gelu_new; model has {act!r})")
    if is_llama and getattr(mha, "qkv_bias", False):
        # Qwen2-shaped model: the llama export has no home for the q/k/v
        # biases and a llama config would silently drop them
        raise ValueError("Qwen2-family export (qkv_bias=True) is not "
                         "implemented; a Llama config cannot carry the "
                         "q/k/v projection biases")
    os.makedirs(path, exist_ok=True)
    if is_llama:
        sd = export_llama_state_dict(model)
        from bigdl_tpu.nn.linear import TiedLMHead
        window = getattr(mha, "window", None)
        config = {
            # a sliding window makes it a Mistral-shaped checkpoint
            "model_type": "mistral" if window else "llama",
            "architectures": ["MistralForCausalLM" if window
                              else "LlamaForCausalLM"],
            **({"sliding_window": int(window)} if window else {}),
            **({"rope_scaling": dict(mha.rope_scaling)}
               if getattr(mha, "rope_scaling", None) else {}),
            "vocab_size": int(emb.n_index),
            "hidden_size": int(mha.embed_dim),
            "intermediate_size": int(layer0.linear1.output_size),
            "num_hidden_layers": int(enc.num_layers),
            "num_attention_heads": int(mha.num_heads),
            "num_key_value_heads": int(mha.num_kv_heads),
            "max_position_embeddings": int(getattr(model, "lm_max_len",
                                                   2048)),
            "rms_norm_eps": float(layer0.norm1.eps),
            "rope_theta": float(getattr(mha, "rope_theta", 10000.0)),
            "hidden_act": "silu",
            "attention_bias": False,
            "mlp_bias": False,
            "tie_word_embeddings": isinstance(head, TiedLMHead),
            "torch_dtype": "float32",
        }
    else:
        sd = export_gpt2_state_dict(model)
        wpe = sd["transformer.wpe.weight"]
        config = {
            "model_type": "gpt2",
            "architectures": ["GPT2LMHeadModel"],
            "vocab_size": int(emb.n_index),
            "n_positions": int(wpe.shape[0]),
            "n_embd": int(mha.embed_dim),
            "n_layer": int(enc.num_layers),
            "n_head": int(mha.num_heads),
            "n_inner": int(layer0.linear1.output_size),
            "activation_function": "gelu_new",
            "layer_norm_epsilon": float(layer0.norm1.eps),
            "tie_word_embeddings": True,
            "torch_dtype": "float32",
        }
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(config, f, indent=2)
    save_file({k: np.ascontiguousarray(v, np.float32)
               for k, v in sd.items()},
              os.path.join(path, "model.safetensors"))
    return path


# ------------------------------------------------------------- directory I/O

def _read_safetensors(fname: str) -> Dict[str, np.ndarray]:
    """One safetensors file -> numpy dict. ``safetensors.numpy`` cannot
    represent bfloat16 — the dominant dtype of real Llama/Mistral
    checkpoints — so files containing non-numpy dtypes route through
    ``safetensors.torch`` (``.float()``) with an ``ml_dtypes`` raw-buffer
    fallback when torch is unavailable."""
    import json as _json
    import struct

    with open(fname, "rb") as f:
        (hdr_len,) = struct.unpack("<Q", f.read(8))
        header = _json.loads(f.read(hdr_len))
    numpy_ok = {"F64", "F32", "F16", "I64", "I32", "I16", "I8", "U8", "BOOL"}
    dtypes = {m.get("dtype") for k, m in header.items()
              if k != "__metadata__"}
    if dtypes <= numpy_ok:
        from safetensors.numpy import load_file
        return dict(load_file(fname))
    # wide-dtype path: parse the (trivial) wire format directly — header
    # gives per-tensor dtype/shape/data_offsets into one contiguous buffer
    import ml_dtypes
    wide = {"BF16": ml_dtypes.bfloat16, "F8_E4M3": ml_dtypes.float8_e4m3fn,
            "F8_E5M2": ml_dtypes.float8_e5m2}
    np_map = {"F64": np.float64, "F32": np.float32, "F16": np.float16,
              "I64": np.int64, "I32": np.int32, "I16": np.int16,
              "I8": np.int8, "U8": np.uint8, "BOOL": np.bool_}
    out = {}
    with open(fname, "rb") as f:
        base = 8 + hdr_len
        for k, meta in header.items():
            if k == "__metadata__":
                continue
            dt = meta["dtype"]
            if dt in wide:
                dtype, cast = wide[dt], np.float32
            elif dt in np_map:
                dtype, cast = np_map[dt], None
            else:
                raise ValueError(f"unsupported safetensors dtype {dt!r}")
            start, stop = meta["data_offsets"]
            f.seek(base + start)
            arr = np.frombuffer(f.read(stop - start), dtype=dtype) \
                .reshape(meta["shape"])
            out[k] = arr.astype(cast) if cast is not None else arr
    return out


def _read_hf_weights(path: str) -> Dict[str, np.ndarray]:
    """Read an HF checkpoint directory's weights (safetensors preferred,
    single- or multi-shard; falls back to ``pytorch_model.bin``)."""
    st = [f for f in sorted(os.listdir(path)) if f.endswith(".safetensors")]
    if st:
        out: Dict[str, np.ndarray] = {}
        for f in st:
            out.update(_read_safetensors(os.path.join(path, f)))
        return out
    bins = [f for f in sorted(os.listdir(path)) if f.endswith(".bin")
            and f.startswith("pytorch_model")]
    if bins:
        import torch
        out = {}
        for f in bins:
            out.update(torch.load(os.path.join(path, f),
                                  map_location="cpu", weights_only=True))
        return out
    raise FileNotFoundError(f"no .safetensors or pytorch_model*.bin in {path}")


def load_hf_checkpoint(path: str) -> Module:
    """Load an HF checkpoint DIRECTORY (config.json + weights) into a
    ``build_lm`` model. Dispatches on ``config.json``'s ``model_type``:
    ``gpt2`` or the Llama family (``llama``/``mistral``-shaped configs
    that satisfy ``llama_lm_kwargs``)."""
    with open(os.path.join(path, "config.json")) as f:
        config = json.load(f)
    sd = _read_hf_weights(path)
    mt = config.get("model_type", "")
    if mt == "gpt2":
        return load_gpt2(config, sd)
    if mt in ("llama", "mistral"):
        return load_llama(config, sd)
    if mt == "qwen2":
        return load_qwen2(config, sd)
    raise ValueError(
        f"unsupported model_type {mt!r} (gpt2/llama/mistral/qwen2)")
