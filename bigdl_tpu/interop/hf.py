"""HuggingFace-layout checkpoint import for GPT-2- and Llama-family LMs.

The reference's defining interop move is loading a FOREIGN framework's
pretrained weights into its own modules by structural mapping
(``utils/CaffeLoader.scala:132`` ``copyParameters`` name-matches caffemodel
blobs; ``utils/TorchFile.scala:67`` maps ~30 Lua ``nn.*`` classes). This
module replays that move for the LM era: the checkpoints a migrating user
actually holds today are HF ``transformers`` state_dicts, and the two
layouts that cover most of them are GPT-2's (fused Conv1D ``c_attn``,
learned ``wpe`` positions, tied head) and Llama's (split q/k/v with GQA,
RoPE, RMSNorm, gated SwiGLU MLP, no biases).

Both importers are NAME + LAYOUT maps onto ``models.transformer.build_lm``:

GPT-2 (``GPT2LMHeadModel``): HF stores every projection as ``Conv1D`` —
weight (in, out), the TRANSPOSE of torch/our Linear (out, in) — so each
``c_attn``/``c_proj``/``c_fc`` weight transposes on the way in; the fused
``c_attn`` columns are already q;k;v-stacked, which after transposition is
exactly our ``in_proj_weight`` row stacking.

Llama (``LlamaForCausalLM``): separate ``q_proj``/``k_proj``/``v_proj``
Linears concatenate row-wise into our GQA ``in_proj_weight``
((E + 2*E_kv, E) — the k/v blocks are the GROUPED size, so grouped-query
checkpoints load without expansion); ``gate_proj`` (inside silu) is our
``linear1``, ``up_proj`` our ``linear_gate``, ``down_proj`` our
``linear2``; RoPE pairing is the same rotate-half convention, so q/k need
no permutation (``nn/attention.py:rope_rotate``).

Token ids stay 1-based on our side: the tables are copied verbatim, so our
id ``k`` denotes the same token as HF id ``k-1`` (shift ids by +1 on the
way in, -1 on the way out — ``to_framework_ids``/``to_hf_ids``).

Model output is LOG-probabilities (the framework's LM tail convention),
= ``log_softmax`` of HF logits; perplexity and greedy/beam sampling are
therefore directly comparable (verified to 1e-4 by
``tests/test_hf_interop.py`` against live ``transformers`` torch models).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from bigdl_tpu.interop.state_dict import import_lm_state_dict
from bigdl_tpu.nn.module import Module


def to_framework_ids(ids):
    """HF 0-based token ids -> this framework's 1-based ids."""
    return np.asarray(ids) + 1


def to_hf_ids(ids):
    """This framework's 1-based token ids -> HF 0-based ids."""
    return np.asarray(ids) - 1


def _np(v) -> np.ndarray:
    """Materialise a state_dict value (torch tensor / jax / numpy) as fp32
    numpy without importing torch here."""
    if hasattr(v, "detach"):  # torch.Tensor
        v = v.detach().cpu()
        if hasattr(v, "float"):
            v = v.float()
        v = v.numpy()
    return np.asarray(v, np.float32)


# --------------------------------------------------------------------- GPT-2

def gpt2_lm_kwargs(config: Dict[str, Any]) -> Dict[str, Any]:
    """``build_lm`` kwargs for an HF GPT-2 ``config.json`` dict."""
    e = int(config["n_embd"])
    n_inner = config.get("n_inner") or 4 * e
    act = config.get("activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh", "gelu"):
        raise ValueError(f"unsupported GPT-2 activation {act!r}")
    # math-changing attention variants: refuse, don't corrupt (same policy
    # as the Llama rope_scaling/sliding_window guards below)
    if config.get("scale_attn_by_inverse_layer_idx", False):
        raise ValueError("scale_attn_by_inverse_layer_idx=True divides "
                         "attention scores per layer; not mapped")
    if not config.get("scale_attn_weights", True):
        raise ValueError("scale_attn_weights=False (unscaled attention) "
                         "is not mapped")
    # "gelu" (exact erf) differs from our tanh-approx at ~1e-3; GPT-2
    # proper is gelu_new, so accept and document rather than refuse
    return dict(
        vocab_size=int(config["vocab_size"]),
        embed_dim=e,
        num_heads=int(config["n_head"]),
        ffn_dim=int(n_inner),
        num_layers=int(config["n_layer"]),
        max_len=int(config.get("n_positions", 1024)),
        pos="learned",
        tie_embeddings=True,
        activation="gelu",
        norm="layer",
        norm_eps=float(config.get("layer_norm_epsilon", 1e-5)),
    )


def gpt2_state_dict_to_lm(hf_sd: Dict[str, Any],
                          num_layers: int) -> Dict[str, np.ndarray]:
    """HF GPT-2 state_dict -> our torch-convention LM state_dict.

    Accepts ``GPT2LMHeadModel`` keys (``transformer.``-prefixed) or bare
    ``GPT2Model`` keys. Ignores the non-weight buffers HF carries
    (``attn.bias`` causal mask, ``attn.masked_bias``) and the tied
    ``lm_head.weight`` duplicate.
    """
    sd = {}
    for k, v in hf_sd.items():
        if k.startswith("transformer."):
            k = k[len("transformer."):]
        sd[k] = v
    out: Dict[str, np.ndarray] = {
        "embedding.weight": _np(sd["wte.weight"]),
        "pos_embedding.weight": _np(sd["wpe.weight"]),
        "encoder.norm.weight": _np(sd["ln_f.weight"]),
        "encoder.norm.bias": _np(sd["ln_f.bias"]),
    }
    for i in range(num_layers):
        src, dst = f"h.{i}", f"encoder.layers.{i}"
        out[f"{dst}.norm1.weight"] = _np(sd[f"{src}.ln_1.weight"])
        out[f"{dst}.norm1.bias"] = _np(sd[f"{src}.ln_1.bias"])
        out[f"{dst}.norm2.weight"] = _np(sd[f"{src}.ln_2.weight"])
        out[f"{dst}.norm2.bias"] = _np(sd[f"{src}.ln_2.bias"])
        # Conv1D (in, out) -> Linear (out, in): transpose
        out[f"{dst}.self_attn.in_proj_weight"] = \
            _np(sd[f"{src}.attn.c_attn.weight"]).T.copy()
        out[f"{dst}.self_attn.in_proj_bias"] = \
            _np(sd[f"{src}.attn.c_attn.bias"])
        out[f"{dst}.self_attn.out_proj.weight"] = \
            _np(sd[f"{src}.attn.c_proj.weight"]).T.copy()
        out[f"{dst}.self_attn.out_proj.bias"] = \
            _np(sd[f"{src}.attn.c_proj.bias"])
        out[f"{dst}.linear1.weight"] = _np(sd[f"{src}.mlp.c_fc.weight"]).T.copy()
        out[f"{dst}.linear1.bias"] = _np(sd[f"{src}.mlp.c_fc.bias"])
        out[f"{dst}.linear2.weight"] = _np(sd[f"{src}.mlp.c_proj.weight"]).T.copy()
        out[f"{dst}.linear2.bias"] = _np(sd[f"{src}.mlp.c_proj.bias"])
    return out


def load_gpt2(config: Dict[str, Any], state_dict: Dict[str, Any]) -> Module:
    """Build a ``build_lm`` model from an HF GPT-2 config + state_dict."""
    from bigdl_tpu.models.transformer import build_lm
    kwargs = gpt2_lm_kwargs(config)
    model = build_lm(**kwargs)
    ours = gpt2_state_dict_to_lm(state_dict, kwargs["num_layers"])
    return import_lm_state_dict(model, ours, strict=True)


# --------------------------------------------------------------------- Llama

def llama_lm_kwargs(config: Dict[str, Any]) -> Dict[str, Any]:
    """``build_lm`` kwargs for an HF Llama-family ``config.json`` dict."""
    if config.get("attention_bias", False) or config.get("mlp_bias", False):
        raise ValueError("biased Llama variants are not mapped (set "
                         "attention_bias/mlp_bias False)")
    act = config.get("hidden_act", "silu")
    if act != "silu":
        raise ValueError(f"unsupported Llama activation {act!r}")
    scaling = config.get("rope_scaling")
    if scaling and scaling.get("rope_type", scaling.get("type")) != "default":
        # Llama-3.1+ NTK/llama3 frequency scaling would silently change
        # every attention score if ignored — refuse, don't corrupt
        raise ValueError(f"rope_scaling {scaling!r} is not supported yet "
                         "(plain rope_theta frequencies only)")
    if config.get("sliding_window"):
        raise ValueError("sliding-window attention (Mistral v0.1-style) is "
                         "not mapped: imported models attend globally and "
                         "would diverge beyond the window")
    heads = int(config["num_attention_heads"])
    return dict(
        vocab_size=int(config["vocab_size"]),
        embed_dim=int(config["hidden_size"]),
        num_heads=heads,
        num_kv_heads=int(config.get("num_key_value_heads", heads)),
        ffn_dim=int(config["intermediate_size"]),
        num_layers=int(config["num_hidden_layers"]),
        max_len=int(config.get("max_position_embeddings", 2048)),
        rope=True,
        rope_theta=float(config.get("rope_theta", 10000.0)),
        activation="swiglu",
        norm="rms",
        norm_eps=float(config.get("rms_norm_eps", 1e-6)),
        bias=False,
        tie_embeddings=bool(config.get("tie_word_embeddings", False)),
    )


def llama_state_dict_to_lm(hf_sd: Dict[str, Any],
                           num_layers: int) -> Dict[str, np.ndarray]:
    """HF Llama state_dict -> our torch-convention LM state_dict.

    The q/k/v Linears concatenate row-wise into the GQA ``in_proj_weight``
    ((E + 2*E_kv, E)); everything else is a rename (torch Linear layout on
    both sides). ``rotary_emb.inv_freq`` buffers are ignored.
    """
    sd = dict(hf_sd)
    out: Dict[str, np.ndarray] = {
        "embedding.weight": _np(sd["model.embed_tokens.weight"]),
        "encoder.norm.weight": _np(sd["model.norm.weight"]),
    }
    if "lm_head.weight" in sd:
        out["lm_head.weight"] = _np(sd["lm_head.weight"])
    for i in range(num_layers):
        src, dst = f"model.layers.{i}", f"encoder.layers.{i}"
        out[f"{dst}.norm1.weight"] = _np(sd[f"{src}.input_layernorm.weight"])
        out[f"{dst}.norm2.weight"] = \
            _np(sd[f"{src}.post_attention_layernorm.weight"])
        out[f"{dst}.self_attn.in_proj_weight"] = np.concatenate([
            _np(sd[f"{src}.self_attn.q_proj.weight"]),
            _np(sd[f"{src}.self_attn.k_proj.weight"]),
            _np(sd[f"{src}.self_attn.v_proj.weight"])], axis=0)
        out[f"{dst}.self_attn.out_proj.weight"] = \
            _np(sd[f"{src}.self_attn.o_proj.weight"])
        out[f"{dst}.linear1.weight"] = _np(sd[f"{src}.mlp.gate_proj.weight"])
        out[f"{dst}.linear_gate.weight"] = _np(sd[f"{src}.mlp.up_proj.weight"])
        out[f"{dst}.linear2.weight"] = _np(sd[f"{src}.mlp.down_proj.weight"])
    return out


def load_llama(config: Dict[str, Any], state_dict: Dict[str, Any]) -> Module:
    """Build a ``build_lm`` model from an HF Llama config + state_dict."""
    from bigdl_tpu.models.transformer import build_lm
    kwargs = llama_lm_kwargs(config)
    model = build_lm(**kwargs)
    ours = llama_state_dict_to_lm(state_dict, kwargs["num_layers"])
    # tied checkpoints carry no lm_head.weight; untied must have it
    strict = not kwargs["tie_embeddings"]
    return import_lm_state_dict(model, ours, strict=strict)


# ------------------------------------------------------------- directory I/O

def _read_hf_weights(path: str) -> Dict[str, np.ndarray]:
    """Read an HF checkpoint directory's weights (safetensors preferred,
    single- or multi-shard; falls back to ``pytorch_model.bin``)."""
    st = [f for f in sorted(os.listdir(path)) if f.endswith(".safetensors")]
    if st:
        from safetensors.numpy import load_file
        out: Dict[str, np.ndarray] = {}
        for f in st:
            out.update(load_file(os.path.join(path, f)))
        return out
    bins = [f for f in sorted(os.listdir(path)) if f.endswith(".bin")
            and f.startswith("pytorch_model")]
    if bins:
        import torch
        out = {}
        for f in bins:
            out.update(torch.load(os.path.join(path, f),
                                  map_location="cpu", weights_only=True))
        return out
    raise FileNotFoundError(f"no .safetensors or pytorch_model*.bin in {path}")


def load_hf_checkpoint(path: str) -> Module:
    """Load an HF checkpoint DIRECTORY (config.json + weights) into a
    ``build_lm`` model. Dispatches on ``config.json``'s ``model_type``:
    ``gpt2`` or the Llama family (``llama``/``mistral``-shaped configs
    that satisfy ``llama_lm_kwargs``)."""
    with open(os.path.join(path, "config.json")) as f:
        config = json.load(f)
    sd = _read_hf_weights(path)
    mt = config.get("model_type", "")
    if mt == "gpt2":
        return load_gpt2(config, sd)
    if mt in ("llama", "mistral"):
        return load_llama(config, sd)
    raise ValueError(f"unsupported model_type {mt!r} (gpt2/llama/mistral)")
