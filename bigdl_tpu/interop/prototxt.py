"""Protobuf text-format (``.prototxt``) parser (reference
``utils/CaffeLoader.scala:63-66`` reads the model definition with
``com.google.protobuf.TextFormat.merge``).

The reference leans on 96 kLoC of generated protobuf Java for this; the text
format itself is a tiny grammar — schemaless here, since the loader only
needs field *names* and values:

    message  := (field (';')?)*
    field    := ident ':' scalar
              | ident ('{' message '}' | '<' message '>')
              | ident ':' '[' scalar (',' scalar)* ']'
    scalar   := string+ | number | true/false | enum-ident
    comments := '#' to end of line

Parsing yields ``{field_name: [value, ...]}`` — every field is a list (the
text format expresses repeated fields by repetition); nested messages are
dicts. Adjacent string literals concatenate, matching protobuf text format.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple, Union

Message = Dict[str, List[Any]]

_TOKEN_RE = re.compile(r"""
    \s+ | \#[^\n]*                         # whitespace / comment (skipped)
  | (?P<str>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<punct>[:{}<>\[\],;])
  | (?P<atom>[^\s:{}<>\[\],;#"']+)
""", re.VERBOSE)

_NUM_RE = re.compile(
    r"[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|\.\d+(?:[eE][-+]?\d+)?"
    r"|0[xX][0-9a-fA-F]+|inf|nan)$")


class PrototxtError(ValueError):
    pass


def _tokenize(text: str) -> List[Tuple[str, str]]:
    tokens, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise PrototxtError(f"bad character at offset {pos}: "
                                f"{text[pos:pos + 20]!r}")
        pos = m.end()
        if m.lastgroup is not None:
            tokens.append((m.lastgroup, m.group(m.lastgroup)))
    return tokens


def _unquote(tok: str) -> str:
    body = tok[1:-1]
    return re.sub(r"\\(.)", lambda m: {"n": "\n", "t": "\t", "r": "\r"}
                  .get(m.group(1), m.group(1)), body)


def _coerce(kind: str, tok: str) -> Any:
    if kind == "str":
        return _unquote(tok)
    if tok in ("true", "True"):
        return True
    if tok in ("false", "False"):
        return False
    if _NUM_RE.match(tok):
        try:
            return int(tok, 0)
        except ValueError:
            return float(tok)
    return tok  # enum identifier


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.tokens = tokens
        self.pos = 0

    def _peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def _next(self):
        tok = self._peek()
        if tok is None:
            raise PrototxtError("unexpected end of input")
        self.pos += 1
        return tok

    def message(self, closing: str = "") -> Message:
        out: Message = {}
        while True:
            tok = self._peek()
            if tok is None:
                if closing:
                    raise PrototxtError(f"missing closing {closing!r}")
                return out
            if tok == ("punct", closing):
                self._next()
                return out
            if tok == ("punct", ";"):
                self._next()
                continue
            kind, name = self._next()
            if kind != "atom":
                raise PrototxtError(f"expected field name, got {name!r}")
            out.setdefault(name, []).extend(self._field_value())

    def _field_value(self) -> List[Any]:
        tok = self._peek()
        if tok == ("punct", "{"):
            self._next()
            return [self.message("}")]
        if tok == ("punct", "<"):
            self._next()
            return [self.message(">")]
        if tok != ("punct", ":"):
            raise PrototxtError(f"expected ':' or '{{' after field name, "
                                f"got {tok and tok[1]!r}")
        self._next()
        tok = self._peek()
        if tok == ("punct", "{"):   # "name: { ... }" is legal text format
            self._next()
            return [self.message("}")]
        if tok == ("punct", "<"):
            self._next()
            return [self.message(">")]
        if tok == ("punct", "["):   # short repeated form: name: [v, v, ...]
            self._next()
            vals: List[Any] = []
            while True:
                t = self._peek()
                if t == ("punct", "]"):
                    self._next()
                    return vals
                if t == ("punct", ","):
                    self._next()
                    continue
                vals.append(self._scalar())
        return [self._scalar()]

    def _scalar(self) -> Any:
        kind, tok = self._next()
        if kind == "punct":
            raise PrototxtError(f"expected value, got {tok!r}")
        if kind == "str":
            # adjacent string literals concatenate ("ab" "cd" == "abcd")
            parts = [_unquote(tok)]
            while self._peek() and self._peek()[0] == "str":
                parts.append(_unquote(self._next()[1]))
            return "".join(parts)
        return _coerce(kind, tok)


def parse(text: str) -> Message:
    """Parse prototxt text into ``{field: [values...]}``."""
    return _Parser(_tokenize(text)).message()


def parse_file(path: str) -> Message:
    with open(path, encoding="utf-8") as f:
        return parse(f.read())


def first(msg: Message, name: str, default: Any = None) -> Any:
    """The first value of a field, or ``default``."""
    vals = msg.get(name)
    return vals[0] if vals else default
