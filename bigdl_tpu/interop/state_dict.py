"""Torch-convention state_dict interop for the causal LM.

The reference-era interop (``torch_file.py`` .t7, ``caffe.py``) predates
transformers; a migrating LM user's checkpoint today is a torch
``state_dict``. This module maps ``models.transformer.build_lm`` models to
the standard torch naming so weights move in either direction:

    embedding.weight                                 LookupTable (V, E)
    encoder.layers.{i}.self_attn.in_proj_weight      (3E, E) q;k;v stacked
                                                     (GQA: (E + 2*E_kv, E))
    encoder.layers.{i}.self_attn.in_proj_bias        matches in_proj rows
    encoder.layers.{i}.self_attn.out_proj.weight     (E, E)
    encoder.layers.{i}.self_attn.out_proj.bias       (E,)
    encoder.layers.{i}.linear1.{weight,bias}         FFN up
    encoder.layers.{i}.linear_gate.{weight,bias}     swiglu gate (if present)
    encoder.layers.{i}.linear2.{weight,bias}         FFN down
    encoder.layers.{i}.norm1.{weight[,bias]}         bias only for LayerNorm
    encoder.layers.{i}.norm2.{weight[,bias]}         (RMSNorm: gain only)
    encoder.norm.{weight[,bias]}                     final pre-norm norm
    lm_head.{weight,bias}                            (V, E); ABSENT when
                                                     tie_embeddings

Layouts already match torch's (``nn.MultiheadAttention`` in_proj stacking,
``Linear`` (out, in)) — the module zoo keeps torch conventions precisely so
oracle tests and weight interchange line up — so this is a NAME mapping with
shape checks, no transposes. Token ids stay 1-based on our side; the
embedding TABLE is identical (id k reads row k-1, as torch's id k-1 does).

Both LM tails (``TimeDistributed(Linear)+LogSoftMax`` and the fused
``LMHead``) serialise to the same ``lm_head.*`` keys, so checkpoints
interchange between them through this module.

Activation note: the FFN gelu is the TANH-APPROXIMATE form (jax.nn.gelu
default, = torch ``F.gelu(approximate="tanh")`` / HF "gelu_new"); a torch
module built with the exact-erf ``"gelu"`` string differs at ~1e-2.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from bigdl_tpu.nn.attention import (LearnedPositionalEncoding,
                                    MultiHeadAttention, TransformerEncoder)
from bigdl_tpu.nn.linear import (LMHead, Linear, LookupTable,
                                 TiedLMHead)
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.recurrent import TimeDistributed


def _lm_parts(model: Module):
    """(embedding, encoder, head Linear-like) of a build_lm-shaped model."""
    lookups = [m for m in model.modules() if isinstance(m, LookupTable)]
    encoders = [m for m in model.modules()
                if isinstance(m, TransformerEncoder)]
    heads = [m for m in model.modules()
             if isinstance(m, (LMHead, TiedLMHead))]
    if not heads:
        heads = [td.inner for td in model.modules()
                 if isinstance(td, TimeDistributed)
                 and isinstance(getattr(td, "inner", None), Linear)]
    if not (len(lookups) == 1 and len(encoders) == 1 and len(heads) == 1):
        raise ValueError(
            "expected a build_lm-shaped model (one LookupTable, one "
            f"TransformerEncoder, one LM head); found {len(lookups)}/"
            f"{len(encoders)}/{len(heads)}")
    return lookups[0], encoders[0], heads[0]


def _named_params(model: Module) -> List[Tuple[str, Module, str]]:
    """[(torch_name, module, param_name)] in deterministic order."""
    emb, enc, head = _lm_parts(model)
    out: List[Tuple[str, Module, str]] = [
        ("embedding.weight", emb, "weight")]
    # GPT-2-style learned position table (build_lm(pos="learned")); the
    # sinusoidal PositionalEncoding is a constant and serialises nothing
    wpes = [m for m in model.modules()
            if isinstance(m, LearnedPositionalEncoding)]
    if wpes:
        out.append(("pos_embedding.weight", wpes[0], "weight"))
    for i in range(enc.num_layers):
        layer = enc._modules[f"layer{i}"]
        if getattr(layer, "moe_experts", 0):
            raise ValueError("MoE layers have no torch-convention mapping")
        p = f"encoder.layers.{i}"
        attn: MultiHeadAttention = layer.self_attn
        out.append((f"{p}.self_attn.in_proj_weight", attn, "in_proj_weight"))
        if attn.with_bias or getattr(attn, "qkv_bias", False):
            out.append((f"{p}.self_attn.in_proj_bias", attn, "in_proj_bias"))
        out.append((f"{p}.self_attn.out_proj.weight", attn,
                    "out_proj_weight"))
        if attn.with_bias:
            out.append((f"{p}.self_attn.out_proj.bias", attn,
                        "out_proj_bias"))
        lin_names = ["linear1", "linear2"]
        if "linear_gate" in layer._modules:  # swiglu gate (our naming —
            lin_names.append("linear_gate")  # no torch-module analogue)
        for lin_name in lin_names:
            lin = layer._modules[lin_name]
            out.append((f"{p}.{lin_name}.weight", lin, "weight"))
            if lin.with_bias:
                out.append((f"{p}.{lin_name}.bias", lin, "bias"))
        for norm_name in ("norm1", "norm2"):
            ln = layer._modules[norm_name]
            out.append((f"{p}.{norm_name}.weight", ln, "weight"))
            if "bias" in ln._parameters:  # RMSNorm has gain only
                out.append((f"{p}.{norm_name}.bias", ln, "bias"))
    if enc.final_norm is not None:
        out.append(("encoder.norm.weight", enc.final_norm, "weight"))
        if "bias" in enc.final_norm._parameters:
            out.append(("encoder.norm.bias", enc.final_norm, "bias"))
    if isinstance(head, TiedLMHead):
        # GPT-2 convention: tied checkpoints carry NO lm_head.* keys — the
        # head IS embedding.weight (already emitted above)
        return out
    out.append(("lm_head.weight", head, "weight"))
    if head.with_bias:
        out.append(("lm_head.bias", head, "bias"))
    return out


def export_lm_state_dict(model: Module) -> Dict[str, np.ndarray]:
    """Torch-convention ``{name: fp32 numpy array}`` of a build_lm model."""
    return {name: np.asarray(mod._parameters[pname], np.float32)
            for name, mod, pname in _named_params(model)}


def import_lm_state_dict(model: Module, state_dict: Dict[str, Any],
                         strict: bool = True) -> Module:
    """Load torch-convention weights into a build_lm model IN PLACE.

    Accepts numpy arrays, jax arrays, or anything ``np.asarray`` handles
    (torch tensors: pass ``t.detach().numpy()`` — torch is not imported
    here). ``strict=True`` (torch semantics) rejects both missing and
    unexpected keys; ``strict=False`` loads the intersection — e.g. a
    GPT-style checkpoint with tied embeddings that omits ``lm_head.weight``
    loads everything else and keeps the model's current head. All shapes
    are validated BEFORE any assignment, so a rejected state_dict never
    leaves the model half-loaded.
    """
    import jax.numpy as jnp
    entries = _named_params(model)
    if strict:
        missing = [n for n, _, _ in entries if n not in state_dict]
        if missing:
            raise KeyError(f"state_dict is missing {missing[:4]}"
                           f"{'...' if len(missing) > 4 else ''} "
                           "(strict=False to load the intersection)")
        known = {n for n, _, _ in entries}
        extra = sorted(set(state_dict) - known)
        if extra:
            raise KeyError(f"unexpected keys {extra[:4]}"
                           f"{'...' if len(extra) > 4 else ''} "
                           "(strict=False to ignore)")
    staged = []
    for name, mod, pname in entries:
        if name not in state_dict:
            continue  # strict=False: keep the model's current value
        val = np.asarray(state_dict[name], np.float32)
        want = tuple(np.shape(mod._parameters[pname]))
        if tuple(val.shape) != want:
            raise ValueError(f"{name}: shape {val.shape} != expected {want}")
        staged.append((mod, pname, val))
    for mod, pname, val in staged:
        mod._parameters[pname] = jnp.asarray(val)
    return model
