"""Core data-pipeline types (reference ``dataset/DataSet.scala:46,110,164``,
``Transformer.scala:41``, ``Sample.scala:32``, ``Types.scala:73``).

The reference's pipeline is iterator→iterator Transformer stages over Spark
RDD partitions; ours is the same composable-iterator model over host numpy,
feeding device arrays at the last step. TPU-specific duties of the last stage
(``SampleToBatch``): produce *static-shaped* batches (drop or pad the
remainder — XLA recompiles per shape, so ragged final batches are the enemy)
and stack into contiguous numpy ready for a single host→device transfer.

Composition uses ``>>`` where Scala used ``->``:
    pipeline = BytesToGreyImg() >> GreyImgNormalizer(mean, std) >> GreyImgToBatch(128)
"""

from __future__ import annotations

import math
from typing import Any, Callable, Generic, Iterator, List, Optional, Sequence, TypeVar

import numpy as np

from bigdl_tpu.utils.rng import RandomGenerator

A = TypeVar("A")
B = TypeVar("B")
C = TypeVar("C")


class Sample:
    """One (feature, label) record (reference ``dataset/Sample.scala:32``)."""

    __slots__ = ("feature", "label")

    def __init__(self, feature, label):
        self.feature = np.asarray(feature)
        self.label = np.asarray(label)

    def __repr__(self):
        return f"Sample(feature={self.feature.shape}, label={self.label.shape})"


class MiniBatch:
    """One batch pair (reference ``dataset/Types.scala:73``)."""

    __slots__ = ("data", "labels")

    def __init__(self, data, labels):
        self.data = data
        self.labels = labels

    def size(self) -> int:
        return int(self.data.shape[0])

    def __iter__(self):
        yield self.data
        yield self.labels


class ByteRecord:
    """Raw bytes + label (reference ``dataset/Types.scala:79``)."""

    __slots__ = ("data", "label")

    def __init__(self, data: bytes, label: float):
        self.data = data
        self.label = label


class Transformer(Generic[A, B]):
    """Iterator→iterator stage (reference ``dataset/Transformer.scala:41``)."""

    #: marks per-record randomness (random crop/flip/jitter): such stages
    #: must not sit below a DeviceCachedDataSet (they would be frozen at
    #: materialization — the cache scans for this flag)
    stochastic = False

    #: True for stages whose output depends on MORE than one input record
    #: (batching/collation). Such stages cannot be fanned out per-record by
    #: MTTransformer.
    aggregating = False

    def __call__(self, prev: Iterator[A]) -> Iterator[B]:
        raise NotImplementedError

    def __rshift__(self, other: "Transformer[B, C]") -> "ChainedTransformer":
        return ChainedTransformer(self, other)

    def clone_transformer(self) -> "Transformer":
        import copy
        return copy.deepcopy(self)


class ChainedTransformer(Transformer[A, C]):
    """reference ``ChainedTransformer`` (the ``->`` combinator)."""

    def __init__(self, first: Transformer, second: Transformer):
        self.first, self.second = first, second

    def __call__(self, prev: Iterator) -> Iterator:
        return self.second(self.first(prev))


def _flatten_chain(t: Transformer) -> List[Transformer]:
    if isinstance(t, ChainedTransformer):
        return _flatten_chain(t.first) + _flatten_chain(t.second)
    return [t]


class Identity(Transformer[A, A]):
    """reference ``dataset/Transformer.scala`` Identity."""

    def __call__(self, prev: Iterator[A]) -> Iterator[A]:
        return prev


class SampleToBatch(Transformer[Sample, MiniBatch]):
    """Collate Samples into static-shape MiniBatches
    (reference ``dataset/Transformer.scala:129``).

    ``feature_padding``/``label_padding`` + ``fixed_length`` reproduce the
    reference's variable-length text handling (pad every sample to a fixed
    sequence length so XLA sees one shape). ``drop_remainder`` keeps batch
    shape static; the evaluator pads the tail batch instead.
    """

    aggregating = True

    def __init__(self, batch_size: int,
                 feature_padding: Optional[float] = None,
                 label_padding: Optional[float] = None,
                 fixed_length: Optional[int] = None,
                 drop_remainder: bool = True):
        self.batch_size = batch_size
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.fixed_length = fixed_length
        self.drop_remainder = drop_remainder

    def _pad_to(self, arr: np.ndarray, length: int, value: float) -> np.ndarray:
        if arr.shape[0] >= length:
            return arr[:length]
        pad = [(0, length - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
        return np.pad(arr, pad, constant_values=value)

    def __call__(self, prev: Iterator[Sample]) -> Iterator[MiniBatch]:
        buf: List[Sample] = []
        for s in prev:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._collate(buf)
                buf = []
        if buf and not self.drop_remainder:
            yield self._collate(buf)

    def _collate(self, samples: List[Sample],
                 fixed_length: Optional[int] = None) -> MiniBatch:
        fixed_length = fixed_length if fixed_length is not None \
            else self.fixed_length
        if self.feature_padding is not None or fixed_length is not None:
            length = fixed_length or max(s.feature.shape[0] for s in samples)
            feats = np.stack([self._pad_to(s.feature, length,
                                           self.feature_padding or 0.0)
                              for s in samples])
            labs = np.stack([self._pad_to(np.atleast_1d(s.label), length,
                                          self.label_padding)
                             if self.label_padding is not None
                             else np.atleast_1d(s.label)
                             for s in samples])
        else:
            feats = np.stack([s.feature for s in samples])
            labs = np.stack([s.label for s in samples])
        if labs.ndim == 2 and labs.shape[1] == 1:
            labs = labs[:, 0]
        return MiniBatch(feats, labs)


class BucketBatch(SampleToBatch):
    """Length-bucketed collation for variable-length samples.

    The reference sorts samples by length so batches group similar lengths
    (``DataSet.sortRDD``, ``DataSet.scala:373-401``) and pads per batch; jit
    needs STATIC shapes, so here each sample routes to the smallest bucket
    boundary >= its length and every emitted batch is padded exactly to its
    bucket — the compiled-program count is bounded by ``len(boundaries)``
    instead of one program per observed batch-max length.
    """

    def __init__(self, batch_size: int, boundaries: Sequence[int],
                 feature_padding: float = 0.0,
                 label_padding: Optional[float] = None,
                 drop_remainder: bool = True):
        super().__init__(batch_size, feature_padding=feature_padding,
                         label_padding=label_padding,
                         drop_remainder=drop_remainder)
        self.boundaries = sorted(int(b) for b in boundaries)
        if not self.boundaries:
            raise ValueError("BucketBatch needs at least one boundary")

    def _bucket_of(self, length: int) -> int:
        for b in self.boundaries:
            if length <= b:
                return b
        raise ValueError(
            f"sample length {length} exceeds the largest bucket boundary "
            f"{self.boundaries[-1]}")

    def __call__(self, prev: Iterator[Sample]) -> Iterator[MiniBatch]:
        buffers: dict = {b: [] for b in self.boundaries}
        for s in prev:
            if s.feature.ndim == 0:
                raise ValueError("BucketBatch needs samples with a leading "
                                 "(length) dimension; got a scalar feature")
            b = self._bucket_of(int(s.feature.shape[0]))
            buffers[b].append(s)
            if len(buffers[b]) == self.batch_size:
                yield self._collate(buffers[b], fixed_length=b)
                buffers[b] = []
        if not self.drop_remainder:
            for b, buf in buffers.items():
                if buf:
                    yield self._collate(buf, fixed_length=b)


class Prefetch(Transformer[A, A]):
    """Stage up to ``depth`` upstream items in a background thread so host
    decode/augment/collate overlaps device compute.

    The reference overlaps ingest with compute via thread pools
    (``MTLabeledBGRImgToBatch``'s worker threads, ``Engine.default`` IO
    tasks); the TPU-native form is a bounded producer queue in front of the
    jitted step — typically placed last, after batching:
    ``... >> GreyImgToBatch(256) >> Prefetch(2)``.
    """

    aggregating = True  # reorders time, not records; still not per-record

    def __init__(self, depth: int = 2):
        if depth < 1:
            raise ValueError("Prefetch depth must be >= 1")
        self.depth = depth

    def __call__(self, prev: Iterator[A]) -> Iterator[A]:
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        _END, _ERR = object(), object()

        def put_or_stop(item) -> bool:
            """Blocking put that aborts when the consumer walked away —
            EVERY producer put (items and sentinels alike) must go through
            this, or the thread can park forever on a full queue."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in prev:
                    if not put_or_stop(item):
                        return
                put_or_stop(_END)
            except BaseException as e:  # propagate to the consumer
                put_or_stop((_ERR, e))

        t = threading.Thread(target=produce, daemon=True,
                             name="bigdl-tpu-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] is _ERR:
                    raise item[1]
                yield item
        finally:
            stop.set()  # consumer abandoned/finished: unblock the producer


class MTTransformer(Transformer[A, B]):
    """Apply an inner transformer across ``workers`` threads, preserving
    order (reference ``MTLabeledBGRImgToBatch``: multithreaded per-record
    transform; numpy decode/augment releases the GIL, so threads give real
    parallelism).

    Each worker thread gets its own ``clone_transformer()`` of the inner
    stage (matching the reference's per-thread cached transformer clones,
    ``DataSet.scala:166-196``), so stateful stages don't race; random-augment
    streams therefore differ from the single-threaded order. The inner stage
    is applied per record — 1:0/1:1/1:n stages all compose (outputs are
    flattened in input order).
    """

    def __init__(self, inner: Transformer[A, B], workers: int = 4,
                 window: Optional[int] = None):
        # a chained inner (e.g. crop >> flip >> normalize) is stochastic if
        # ANY stage is — the flat inner attribute alone would hide it from
        # DeviceCachedDataSet's freeze guard
        self.stochastic = any(getattr(s, "stochastic", False)
                              for s in _flatten_chain(inner))
        for stage in _flatten_chain(inner):
            if stage.aggregating:
                raise ValueError(
                    f"MTTransformer cannot fan out {type(stage).__name__}: "
                    "it aggregates across records (per-record invocation "
                    "would silently produce wrong/empty output). Put "
                    "MTTransformer around the per-record stages and chain "
                    "the batching stage after it: mt_stage >> SampleToBatch")
        self.inner = inner
        self.workers = max(1, int(workers))
        self.window = window or self.workers * 2

    def __call__(self, prev: Iterator[A]) -> Iterator[B]:
        if self.workers == 1:
            return self.inner(prev)
        return self._parallel(prev)

    def _parallel(self, prev: Iterator[A]) -> Iterator[B]:
        import collections
        import concurrent.futures as cf
        import threading

        local = threading.local()

        def apply_one(item):
            t = getattr(local, "t", None)
            if t is None:
                t = local.t = self.inner.clone_transformer()
            return list(t(iter([item])))

        with cf.ThreadPoolExecutor(self.workers,
                                   thread_name_prefix="bigdl-tpu-mt") as ex:
            pending: "collections.deque" = collections.deque()
            for item in prev:
                pending.append(ex.submit(apply_one, item))
                if len(pending) >= self.window:
                    yield from pending.popleft().result()
            while pending:
                yield from pending.popleft().result()


# --------------------------------------------------------------------------
# DataSets
# --------------------------------------------------------------------------

class AbstractDataSet(Generic[A]):
    """reference ``dataset/DataSet.scala:46``."""

    def data(self, train: bool) -> Iterator[A]:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        raise NotImplementedError

    def is_distributed(self) -> bool:
        return False

    def transform(self, transformer: Transformer) -> "AbstractDataSet":
        return TransformedDataSet(self, transformer)

    # Scala's `->`
    def __rshift__(self, transformer: Transformer) -> "AbstractDataSet":
        return self.transform(transformer)


class LocalDataSet(AbstractDataSet[A]):
    """In-memory dataset (reference ``LocalArrayDataSet``,
    ``DataSet.scala:128``). ``data(train=True)`` iterates one shuffled epoch;
    the optimizer loops epochs (explicit epochs replace the reference's
    endless iterator + epoch arithmetic)."""

    def __init__(self, data: Sequence[A]):
        self._data = list(data)
        self._order = np.arange(len(self._data))

    def data(self, train: bool) -> Iterator[A]:
        if train:
            for i in self._order:
                yield self._data[i]
        else:
            yield from self._data

    def size(self) -> int:
        return len(self._data)

    def shuffle(self) -> None:
        RandomGenerator.RNG().shuffle(self._order)


class TransformedDataSet(AbstractDataSet[B]):
    """DataSet with a transformer chain applied lazily per epoch."""

    def __init__(self, base: AbstractDataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def data(self, train: bool) -> Iterator[B]:
        return self.transformer(self.base.data(train))

    def size(self) -> int:
        return self.base.size()

    def shuffle(self) -> None:
        self.base.shuffle()

    def is_distributed(self) -> bool:
        return self.base.is_distributed()

    def transform(self, transformer: Transformer) -> "AbstractDataSet":
        return TransformedDataSet(self.base, self.transformer >> transformer)


class DistributedDataSet(LocalDataSet[A]):
    """Dataset destined for the multi-chip training path
    (reference ``DistributedDataSet``, ``DataSet.scala:164``).

    The reference pins cached partitions to executors
    (``CachedDistriDataSet``); on TPU the analogue is two-level:

    - **single-host**: the pipeline produces one global batch per step and
      ``DistriOptimizer`` shards it over the mesh's data axis (device
      placement replaces partition locality);
    - **multi-host**: each process keeps only its ``1/process_count``
      round-robin slice of the records (``shard_by_process``, the analogue
      of executor partition pinning) and its pipeline emits *process-local*
      batches; ``DistriOptimizer._place_batch`` assembles them into a global
      array via ``jax.make_array_from_process_local_data``. Batch sizes fed
      to the batching transformer are therefore **per-host**.
    """

    def __init__(self, data: Sequence[A], shard_by_process: bool = True):
        if shard_by_process:
            from bigdl_tpu.utils.engine import Engine
            p, n = Engine.process_index(), Engine.process_count()
            if n > 1:
                data = list(data)[p::n]
        super().__init__(data)

    def is_distributed(self) -> bool:
        return True

    def to_distributed(self) -> "DistributedDataSet":
        return self


class DataSet:
    """Factory namespace (reference ``DataSet`` object, ``DataSet.scala:319``).

    Examples::

        >>> import numpy as np
        >>> samples = [Sample(np.zeros((4,), np.float32), float(i % 2 + 1))
        ...            for i in range(10)]
        >>> ds = DataSet.array(samples) >> SampleToBatch(4)
        >>> [b.size() for b in ds.data(train=False)]
        [4, 4]
        >>> ds2 = DataSet.array(samples) >> SampleToBatch(4,
        ...                                               drop_remainder=False)
        >>> [b.size() for b in ds2.data(train=False)]
        [4, 4, 2]
    """

    @staticmethod
    def array(data: Sequence, distributed: bool = False):
        return DistributedDataSet(data) if distributed else LocalDataSet(data)

    @staticmethod
    def sort(data: Sequence[Sample], key=lambda s: s.feature.shape[0],
             distributed: bool = False):
        """Length-bucketing for variable-length samples
        (reference ``DataSet.sortRDD``, ``DataSet.scala:373-401``)."""
        ordered = sorted(data, key=key)
        return DataSet.array(ordered, distributed)
