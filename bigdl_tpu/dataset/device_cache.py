"""Device-resident dataset cache — the TPU-native ``CachedDistriDataSet``.

The reference caches each partition's samples in executor memory once and
re-shuffles only an index array per epoch (``dataset/DataSet.scala:240,
292-299``: "shuffle = reshuffle indexes only"); batches are then collated
from the cached samples. The TPU-native descendant goes one step further:
the whole (deterministically transformed) dataset lives ON DEVICE as one
stacked feature/label array pair, each epoch draws a fresh SAMPLE-level
permutation (same composition semantics as the reference — batch membership
changes every epoch), and batches are produced by on-device gathers.

Why it exists (PERF.md round 3): the real training loop was host-transfer
bound — every iteration re-stacked ~154 MB on the host and pushed it
through a ~68 MB/s tunneled H2D path (2.2 s/batch for a 0.1 s step). With
the cache, the transfer happens once and an epoch costs one (N,)-int
permutation upload plus device gathers.

Limits, by design:
- the wrapped dataset must be finite and fit device memory next to the
  model (a (N, 224, 224, 3) f32 cache is N x 602 KB);
- RANDOM host augmentations (random crop/flip/jitter) must NOT sit below
  the cache — they would be frozen at materialization. Enforced: stages
  marked ``stochastic`` in the wrapped chain raise at materialization.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.base import AbstractDataSet, MiniBatch, Sample
from bigdl_tpu.utils.rng import RandomGenerator


class CachedSliceBatch:
    """Lazy MiniBatch: indices into the device cache, gathered on access.

    ``data``/``labels`` are properties so the single-dispatch path is
    transparent (``jnp.asarray(batch.data)`` triggers the gather), while the
    K-fused dispatch path (``set_steps_per_dispatch``) reads ``.idx`` and
    performs the gathers INSIDE the jitted multi-step — one dispatch per
    window instead of one per gather (each device dispatch costs ~15 ms RPC
    on the tunneled backend; PERF.md round 3)."""

    __slots__ = ("source", "idx")

    def __init__(self, source: "DeviceCachedDataSet", idx):
        self.source = source
        self.idx = idx

    @property
    def data(self):
        return self.source._x[self.idx]

    @property
    def labels(self):
        return self.source._y[self.idx]

    def size(self) -> int:
        return int(self.idx.shape[0])

    def __iter__(self):
        yield self.data
        yield self.labels


class DeviceCachedDataSet(AbstractDataSet[MiniBatch]):
    """Materialize a Sample-level dataset on device once; serve shuffled
    MiniBatches via on-device gathers.

    >>> import numpy as np
    >>> from bigdl_tpu.dataset.base import DataSet, Sample
    >>> ds = DeviceCachedDataSet(DataSet.array(
    ...     [Sample(np.full((2,), i, np.float32), float(i % 2 + 1))
    ...      for i in range(8)]), batch_size=4)
    >>> batches = list(ds.data(train=False))
    >>> [int(b.size()) for b in batches]
    [4, 4]
    """

    def __init__(self, base: AbstractDataSet[Sample], batch_size: int,
                 cast_dtype: Optional[str] = None):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.base = base
        self.batch_size = batch_size
        # transfer dtype for features (e.g. "bfloat16" halves H2D bytes AND
        # cache footprint when the compute policy is bf16 anyway)
        self.cast_dtype = cast_dtype
        self._x = None
        self._y = None
        self._perm = None
        self._mesh = None
        self._data_axis = None
        self._gather_fn = None

    def set_mesh(self, mesh, data_axis: str = "data") -> None:
        """Shard the cache over the mesh's data axis (the reference's
        per-partition `CachedDistriDataSet`, taken SPMD). Called by
        DistriOptimizer before materialization; shuffling then permutes
        WITHIN each shard (reference semantics: each partition reshuffles
        its own indexes) and batches are per-shard ``shard_map`` gathers —
        local by construction, no cross-device data motion."""
        if self._x is not None and self._mesh is not mesh:
            raise RuntimeError("DeviceCachedDataSet already materialized; "
                               "set_mesh must precede the first epoch")
        if data_axis in mesh.shape and mesh.shape[data_axis] > 1:
            self._mesh = mesh
            self._data_axis = data_axis
        # a 1-wide (or absent) data axis degenerates to the local cache

    # ------------------------------------------------------------------ cache
    def _scan_for_stochastic_stages(self) -> None:
        """Refuse to freeze random augmentation: a stochastic stage (random
        crop/flip/jitter) below the cache would be drawn ONCE and re-served
        every epoch — silent model-quality damage, so it is an error."""
        from bigdl_tpu.dataset.base import (TransformedDataSet,
                                            _flatten_chain)
        ds = self.base
        while isinstance(ds, TransformedDataSet):
            for stage in _flatten_chain(ds.transformer):
                if getattr(stage, "stochastic", False):
                    raise ValueError(
                        f"DeviceCachedDataSet cannot cache below the "
                        f"stochastic stage {type(stage).__name__}: its "
                        "random draw would be frozen at materialization. "
                        "Keep random augmentation out of the cached chain "
                        "(or use the host collate path).")
            ds = ds.base

    def _materialize(self) -> None:
        if self._x is not None:
            return
        import time as _time
        t_fill = _time.perf_counter()
        try:
            self._materialize_inner()
        finally:
            # cold-start attribution (docs/OBSERVABILITY.md): the first
            # step blocks on this whole-cache build — charge it to the
            # ingest stall ledger so "why was step 1 slow" has an answer
            # instead of vanishing into data-wait noise
            from bigdl_tpu.telemetry import get_registry, instruments
            instruments(get_registry()).ingest_stall_seconds_total.labels(
                stage="materialize").inc(_time.perf_counter() - t_fill)

    def _materialize_inner(self) -> None:
        from bigdl_tpu.telemetry import span
        self._scan_for_stochastic_stages()
        import jax.numpy as jnp
        feats, labels = [], []
        with span("ingest.materialize", batch_size=self.batch_size):
            for s in self.base.data(train=False):
                # Sample has .feature; the image types (LabeledImage) carry
                # the array as .data with the same (feature, label) meaning
                feats.append(s.feature if hasattr(s, "feature") else s.data)
                labels.append(s.label)
        if not feats:
            raise ValueError("DeviceCachedDataSet: wrapped dataset is empty")
        if self._mesh is None and len(feats) < self.batch_size:
            # batch_size is GLOBAL; under a multi-process mesh the local
            # record count is a per-process slice — the sharded branch
            # checks the global total itself
            raise ValueError(
                f"DeviceCachedDataSet: {len(feats)} samples cannot fill one "
                f"batch of {self.batch_size}")
        x = np.stack(feats)
        if self.cast_dtype:
            import ml_dtypes  # noqa: F401 - registers bfloat16 with numpy
            x = x.astype(self.cast_dtype)
        y = np.stack([np.asarray(l) for l in labels])
        if y.ndim == 2 and y.shape[1] == 1:
            y = y[:, 0]  # SampleToBatch's (N,1)->(N,) label squeeze parity
        if self._mesh is None:
            self._x = jnp.asarray(x)
            self._y = jnp.asarray(y)
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        d = self._mesh.shape[self._data_axis]
        if self.batch_size % d != 0:
            raise ValueError(
                f"batch_size {self.batch_size} must divide by the data-axis "
                f"size {d} for the sharded cache")
        # equal shards: x holds this PROCESS's records (the wrapped
        # DistributedDataSet yields the per-process slice), covering
        # d / process_count local shards
        if d % jax.process_count() != 0:
            raise ValueError(
                f"sharded cache needs the data-axis size ({d}) to divide by "
                f"the process count ({jax.process_count()}); lay the data "
                "axis out across processes evenly or skip the cache")
        d_local = d // jax.process_count()
        n_local = (x.shape[0] // d_local) * d_local
        x, y = x[:n_local], y[:n_local]
        if n_local * jax.process_count() < self.batch_size:
            raise ValueError(
                f"{n_local * jax.process_count()} samples cannot fill one "
                f"sharded batch of {self.batch_size} over {d} shards")
        sharding = NamedSharding(self._mesh, P(self._data_axis))
        if jax.process_count() > 1:
            self._x = jax.make_array_from_process_local_data(sharding, x)
            self._y = jax.make_array_from_process_local_data(sharding, y)
        else:
            self._x = jax.device_put(jnp.asarray(x), sharding)
            self._y = jax.device_put(jnp.asarray(y), sharding)

    def _sharded_gather(self):
        """Jitted per-shard gather: local indices pick local rows — no
        cross-device data motion, and the output lands exactly in the
        data-parallel batch sharding."""
        if self._gather_fn is None:
            import jax
            from bigdl_tpu.utils.jax_compat import shard_map
            from jax.sharding import PartitionSpec as P
            ax = self._data_axis

            def gather(xs, ys, il):
                # local shapes: xs (S, ...), il (1, Bs) -> (Bs, ...)
                return xs[il[0]], ys[il[0]]

            self._gather_fn = jax.jit(shard_map(
                gather, mesh=self._mesh,
                in_specs=(P(ax), P(ax), P(ax, None)),
                out_specs=(P(ax), P(ax))))
        return self._gather_fn

    # --------------------------------------------------------------- protocol
    def data(self, train: bool) -> Iterator[MiniBatch]:
        self._materialize()
        import jax.numpy as jnp
        n = int(self._x.shape[0])
        n_batches = n // self.batch_size  # static shapes: drop remainder
        if self._mesh is not None:
            d = self._mesh.shape[self._data_axis]
            bs = self.batch_size // d
            s = n // d
            if train:
                if self._perm is None:
                    self.shuffle()
                lperm, self._perm = self._perm, None  # (d, S) local indices
            else:
                # eval: fixed per-shard round-robin (every record exactly
                # once; global order interleaves shards, unlike the host
                # path — evaluators aggregate, so order is immaterial)
                lperm = np.broadcast_to(np.arange(s, dtype=np.int32),
                                        (d, s))
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            ish = NamedSharding(self._mesh, P(self._data_axis, None))
            if jax.process_count() > 1:
                # each process contributes its own shards' rows (its local
                # RNG generated them; remote rows in lperm are ignored)
                d_local = d // jax.process_count()
                lo = jax.process_index() * d_local
                idx_dev = jax.make_array_from_process_local_data(
                    ish, np.ascontiguousarray(lperm[lo:lo + d_local]))
            else:
                idx_dev = jax.device_put(
                    jnp.asarray(np.ascontiguousarray(lperm)), ish)
            gather = self._sharded_gather()
            for b in range(n // self.batch_size):
                il = idx_dev[:, b * bs:(b + 1) * bs]
                xb, yb = gather(self._x, self._y, il)
                yield MiniBatch(xb, yb)
            return
        if train:
            if self._perm is None:
                self.shuffle()
            perm = self._perm
            self._perm = None  # one permutation per epoch
            idx_dev = jnp.asarray(perm)  # one tiny (N,) int32 upload/epoch
            for b in range(n_batches):
                sl = idx_dev[b * self.batch_size:(b + 1) * self.batch_size]
                yield CachedSliceBatch(self, sl)
        else:
            for b in range(n_batches):
                lo, hi = b * self.batch_size, (b + 1) * self.batch_size
                yield MiniBatch(self._x[lo:hi], self._y[lo:hi])

    def size(self) -> int:
        if self._x is not None:
            return int(self._x.shape[0])
        return self.base.size()

    def shuffle(self) -> None:
        # materialize first: the wrapped chain may change record cardinality
        # (1:0/1:n stages), and a permutation sized from base.size() would
        # silently clamp or truncate gathers
        self._materialize()
        n = int(self._x.shape[0])
        rng = RandomGenerator.RNG()
        if self._mesh is not None:
            # per-shard local permutations (reference semantics: each
            # cached partition reshuffles its OWN indexes,
            # DataSet.scala:292-299); randperm is 1-based -> -1
            d = self._mesh.shape[self._data_axis]
            s = n // d
            self._perm = np.stack(
                [np.asarray(rng.randperm(s) - 1, np.int32)
                 for _ in range(d)])
            return
        # randperm is 1-based (Torch semantics); indices here are 0-based
        self._perm = np.asarray(rng.randperm(n) - 1, np.int32)

    def is_distributed(self) -> bool:
        # routes the Optimizer factory: a cache over a distributed base (or
        # an injected mesh) trains through DistriOptimizer
        return self._mesh is not None or self.base.is_distributed()

    def transform(self, transformer):
        raise TypeError(
            "DeviceCachedDataSet is terminal: apply transformers to the "
            "wrapped dataset BEFORE caching (random host augmentations "
            "would be frozen at materialization)")
