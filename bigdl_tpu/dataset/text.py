"""Text pipeline (reference ``$B/dataset/text/``: ``Dictionary.scala:225``,
``SentenceSplitter``/``SentenceTokenizer`` (OpenNLP-backed), ``SentenceBiPadding``,
``TextToLabeledSentence``, ``LabeledSentenceToSample``).

Tokenization here is regex-based (no OpenNLP on TPU hosts); everything else
keeps the reference's semantics: sentence-boundary padding tokens, vocabulary
with UNK, index (1-based) or one-hot sample encodings.
"""

from __future__ import annotations

import json
import logging
import os
import re
from collections import Counter
from typing import Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.base import Sample, Transformer

SENTENCE_START = "SENTENCE_START"
SENTENCE_END = "SENTENCE_END"
_TOKEN_RE = re.compile(r"[A-Za-z0-9']+|[.,!?;]")


class LabeledSentence:
    """Token-index sequence + per-position (or scalar) labels
    (reference ``text/LabeledSentence.scala``)."""

    __slots__ = ("data", "label")

    def __init__(self, data: Sequence[float], label: Sequence[float]):
        self.data = np.asarray(data, np.float32)
        self.label = np.asarray(label, np.float32)

    def length(self) -> int:
        return int(self.data.shape[0])


class Dictionary:
    """Vocabulary with save/load and UNK handling
    (reference ``text/Dictionary.scala:225``)."""

    def __init__(self, sentences: Optional[Iterator[List[str]]] = None,
                 vocab_size: Optional[int] = None):
        self._word2index = {}
        self._index2word = {}
        self._vocab_size = 0
        if sentences is not None:
            counts = Counter()
            for tokens in sentences:
                counts.update(tokens)
            most = counts.most_common(vocab_size)
            for i, (w, _) in enumerate(most):
                self._word2index[w] = i
                self._index2word[i] = w
            self._vocab_size = len(self._word2index)

    def get_index(self, word: str) -> int:
        """0-based index; unknown words map to vocab_size (the UNK slot)."""
        return self._word2index.get(word, self._vocab_size)

    def get_word(self, index: int) -> str:
        return self._index2word.get(int(index), "<unk>")

    def vocab_size(self) -> int:
        return self._vocab_size

    def word2index(self):
        return dict(self._word2index)

    def save(self, folder: str) -> None:
        os.makedirs(folder, exist_ok=True)
        with open(os.path.join(folder, "dictionary.json"), "w") as f:
            json.dump(self._word2index, f)

    @staticmethod
    def load(folder: str) -> "Dictionary":
        d = Dictionary()
        with open(os.path.join(folder, "dictionary.json")) as f:
            d._word2index = json.load(f)
        d._index2word = {v: k for k, v in d._word2index.items()}
        d._vocab_size = len(d._word2index)
        return d


class SentenceSplitter(Transformer[str, List[str]]):
    """Paragraph → sentences (reference ``SentenceSplitter``, which loads
    a trained OpenNLP sentence model —
    ``dataset/text/SentenceSplitter.scala``).

    Rule-based here, with the standard model-free heuristics rather than
    a bare ``[.!?]\\s`` split: a candidate boundary is REJECTED when the
    period belongs to (a) a known never-sentence-final abbreviation
    (titles, latinisms, months), (b) a single-letter initial ("J. K.
    Rowling"), (c) a numeric reference ("No. 7", "sec. 3" — only when a digit
    follows, so "The answer is no." still ends a sentence), or when
    the following token starts lowercase (mid-sentence ellipsis or
    abbreviation not in the list). Trailing quotes/brackets travel with
    the closing sentence. Not OpenNLP-grade on adversarial prose, but
    covers the failure modes a trained model is usually bought for."""

    # Abbreviations that (almost) never END a sentence: a following
    # capitalized word is still the same sentence ("Dr. Smith", "Jan. 5",
    # "fig. 3"). Sentence-final-CAPABLE abbreviations (p.m., etc., Inc.)
    # are deliberately NOT listed: for those the next-word-lowercase rule
    # alone decides ("at 3 p.m. on" joins, "at 3 p.m. It" splits).
    # ... and NOT ordinary English words (no/sat/sun/art/sec/gen/...):
    # "He sat. The dog barked." must split, so an entry earns its place
    # only when the bare word is rare as a sentence ender.
    _ABBREV = {
        "mr", "mrs", "ms", "dr", "prof", "rev", "sen",
        "st", "e.g", "i.e", "cf", "vs", "dept", "fig",
        "nos", "pp", "vol", "ch",
        "jan", "feb", "apr", "jun", "jul", "aug", "sep",
        "sept", "oct", "nov", "dec",
    }
    # Numeric-reference abbreviations: common English words that only act
    # as abbreviations when a NUMBER follows ("No. 7", "sec. 3", "op. 9")
    # — guarded by the next-char-is-digit check, so "The answer is no.
    # We move on." still splits.
    _NUM_REF = {"no", "p", "sec", "art", "op", "para", "pt"}
    _CAND = re.compile(r"([.!?]+)([\"'”’)\]]*)\s+(?=\S)")

    def _split_one(self, para: str) -> List[str]:
        out, start = [], 0
        for m in self._CAND.finditer(para):
            end = m.end(2)
            nxt = para[m.end():m.end() + 1]
            if nxt.islower() and nxt.isalpha():
                continue  # quote attribution / mid-sentence continuation
            if m.group(1).endswith("."):
                before = para[start:m.start(1)]
                word = re.split(r"\s", before)[-1] if before else ""
                token = word.rstrip(".").lstrip("(\"'“‘[").lower()
                if (token in self._ABBREV
                        or (token in self._NUM_REF and nxt.isdigit())
                        or (len(token) == 1 and token.isalpha()
                            and token not in ("i", "a"))):
                    # abbreviation, numeric reference ("No. 7"), or
                    # single-letter initial — but the words "I"/"a" end
                    # sentences ("So did I.")
                    continue
            out.append(para[start:end].strip())
            start = m.end()
        tail = para[start:].strip()
        if tail:
            out.append(tail)
        return [s for s in out if s]

    def __call__(self, prev: Iterator[str]) -> Iterator[List[str]]:
        for para in prev:
            yield self._split_one(para.strip())


class SentenceTokenizer(Transformer[str, List[str]]):
    """Sentence → tokens (reference ``SentenceTokenizer``)."""

    def __call__(self, prev: Iterator[str]) -> Iterator[List[str]]:
        for sent in prev:
            yield _TOKEN_RE.findall(sent.lower())


class SentenceBiPadding(Transformer[List[str], List[str]]):
    """Wrap with SENTENCE_START/END tokens (reference ``SentenceBiPadding``)."""

    def __call__(self, prev: Iterator[List[str]]) -> Iterator[List[str]]:
        for tokens in prev:
            yield [SENTENCE_START] + list(tokens) + [SENTENCE_END]


class TextToLabeledSentence(Transformer[List[str], LabeledSentence]):
    """Language-model pairs: data = tokens[:-1], label = tokens[1:]
    (reference ``TextToLabeledSentence``). Indices stay 0-based here;
    ``LabeledSentenceToSample`` shifts to the framework's 1-based convention.
    """

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def __call__(self, prev: Iterator[List[str]]) -> Iterator[LabeledSentence]:
        for tokens in prev:
            idx = [self.dictionary.get_index(t) for t in tokens]
            if len(idx) < 2:
                continue
            yield LabeledSentence(idx[:-1], idx[1:])


class LabeledSentenceToSample(Transformer[LabeledSentence, Sample]):
    """Encode a LabeledSentence as a Sample
    (reference ``LabeledSentenceToSample``): one-hot features (vocab+1 wide,
    UNK included) or raw 1-based indices; labels always 1-based indices.
    """

    def __init__(self, vocab_length: int,
                 fixed_length: Optional[int] = None,
                 one_hot: bool = True):
        self.vocab_length = vocab_length
        self.fixed_length = fixed_length
        self.one_hot = one_hot

    def __call__(self, prev: Iterator[LabeledSentence]) -> Iterator[Sample]:
        for s in prev:
            n = s.length() if self.fixed_length is None else self.fixed_length
            data_idx = s.data[:n].astype(np.int64)
            label = s.label[:n].astype(np.float32) + 1.0
            if len(data_idx) < n:
                pad = n - len(data_idx)
                data_idx = np.concatenate([data_idx, np.zeros(pad, np.int64)])
                label = np.concatenate([label, np.ones(pad, np.float32)])
            if self.one_hot:
                feat = np.zeros((n, self.vocab_length), np.float32)
                feat[np.arange(n), np.minimum(data_idx, self.vocab_length - 1)] = 1.0
            else:
                feat = (data_idx + 1).astype(np.float32)
            yield Sample(feat, label)


def load_glove_vectors(path: str, word2index,
                       embedding_dim: int) -> np.ndarray:
    """Read GloVe word vectors for a known vocabulary into an embedding
    matrix (reference ``example/utils/TextClassifier.scala:56-70``
    ``buildWord2Vec``: only vocabulary words are kept).

    Returns ``(len(word2index) + 1, embedding_dim)`` float32 — row 0 is the
    all-zero padding/UNK vector, row ``i+1`` the vector of the word with
    index ``i``; words missing from the file stay zero.
    """
    mat = np.zeros((len(word2index) + 1, embedding_dim), np.float32)
    found = 0
    # official glove.6B files are UTF-8 (the reference reads ISO-8859-1,
    # which garbles accented words into never-matching tokens)
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            values = line.rstrip().split(" ")
            word = values[0]
            idx = word2index.get(word)
            if idx is None or len(values) != embedding_dim + 1:
                continue
            mat[idx + 1] = np.asarray(values[1:], np.float32)
            found += 1
    logging.getLogger(__name__).info("Found %d word vectors.", found)
    return mat


def load_category_folder(base_dir: str):
    """Read a 20-newsgroup-style tree — one subdirectory per category, one
    text file per document (reference ``TextClassifier.scala:96-121``
    ``loadRawData``). Returns ``(texts, labels, class_num)`` with 1-based
    labels assigned by sorted category name."""
    from bigdl_tpu.dataset.image import image_folder_paths
    texts, labels = [], []
    for path, label in image_folder_paths(base_dir, extensions=None):
        with open(path, encoding="latin-1") as f:
            texts.append(f.read())
        labels.append(label)
    # max, not len(set(...)): an empty category dir still consumed a label
    # slot, and the model's output width must cover every assigned label
    return texts, labels, int(max(labels)) if labels else 0


class TokensToIndexedSample(Transformer[tuple, Sample]):
    """(tokens, label) -> Sample((seq_len,) 1-based indices, label):
    out-of-vocabulary tokens are dropped (reference filters tokens without a
    word2Meta entry, ``TextClassifier.scala:140-169``), the rest truncated /
    zero-padded to ``seq_len``. Index 0 is the padding row."""

    def __init__(self, word2index, seq_len: int):
        self.word2index = word2index
        self.seq_len = seq_len

    def __call__(self, prev: Iterator[tuple]) -> Iterator[Sample]:
        for tokens, label in prev:
            feat = np.zeros((self.seq_len,), np.float32)
            t = 0
            for tok in tokens:
                if t == self.seq_len:
                    break
                idx = self.word2index.get(tok)
                if idx is None:
                    continue
                feat[t] = idx + 1
                t += 1
            yield Sample(feat, np.float32(label))


class IndexedToEmbeddedSample(Transformer[Sample, Sample]):
    """Sample((T,) indices) -> Sample((T, embedding_dim)) by embedding-matrix
    row lookup, applied lazily per iteration so the dataset stores ~4-byte
    indices, not dense vectors (the reference pre-embeds the whole corpus up
    front; at 20-newsgroup scale that is gigabytes of host RAM)."""

    def __init__(self, embeddings: np.ndarray):
        self.embeddings = np.asarray(embeddings, np.float32)

    def __call__(self, prev: Iterator[Sample]) -> Iterator[Sample]:
        for s in prev:
            idx = np.asarray(s.feature, np.int64)
            yield Sample(self.embeddings[idx], s.label)
