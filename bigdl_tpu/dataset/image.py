"""Image types and transformers (reference ``$B/dataset/image/``: 23 files).

Images are numpy (H, W, C) float32 channels-last throughout — the TPU layout —
labelled by a 1-based float class (Torch convention), mirroring the
reference's ``LabeledBGRImage``/``LabeledGreyImage`` (``dataset/image/Types.scala``).
Decode (JPEG etc.) is handled by ``LocalImgReader`` via Pillow when available;
the tensor-side transformers below are pure numpy and are the ones on the
training hot path.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from bigdl_tpu.dataset.base import ByteRecord, MiniBatch, Sample, Transformer
from bigdl_tpu.utils.rng import RandomGenerator


class LabeledImage:
    """(H, W, C) float image + 1-based label (reference ``Types.scala``)."""

    __slots__ = ("data", "label")

    def __init__(self, data: np.ndarray, label: float):
        self.data = np.asarray(data, np.float32)
        self.label = float(label)

    @property
    def shape(self):
        return self.data.shape


LabeledGreyImage = LabeledImage
LabeledBGRImage = LabeledImage


class BytesToGreyImg(Transformer[ByteRecord, LabeledImage]):
    """Decode row-major grey bytes (reference ``BytesToGreyImg``)."""

    def __init__(self, row: int, col: int):
        self.row, self.col = row, col

    def __call__(self, prev: Iterator[ByteRecord]) -> Iterator[LabeledImage]:
        for rec in prev:
            img = np.frombuffer(rec.data, np.uint8).astype(np.float32)
            yield LabeledImage(img.reshape(self.row, self.col, 1), rec.label)


class BytesToBGRImg(Transformer[ByteRecord, LabeledImage]):
    """Decode interleaved BGR bytes (reference ``BytesToBGRImg``)."""

    def __init__(self, row: int, col: int):
        self.row, self.col = row, col

    def __call__(self, prev: Iterator[ByteRecord]) -> Iterator[LabeledImage]:
        for rec in prev:
            img = np.frombuffer(rec.data, np.uint8).astype(np.float32)
            yield LabeledImage(img.reshape(self.row, self.col, 3), rec.label)


class GreyImgNormalizer(Transformer[LabeledImage, LabeledImage]):
    """(x - mean) / std with dataset-level stats
    (reference ``GreyImgNormalizer``)."""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = mean, std

    @staticmethod
    def from_dataset(dataset) -> "GreyImgNormalizer":
        total, sq, n = 0.0, 0.0, 0
        for img in dataset.data(train=False):
            total += float(img.data.sum())
            sq += float((img.data ** 2).sum())
            n += img.data.size
        mean = total / n
        std = float(np.sqrt(sq / n - mean * mean))
        return GreyImgNormalizer(mean, std)

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in prev:
            yield LabeledImage((img.data - self.mean) / self.std, img.label)


class BGRImgNormalizer(Transformer[LabeledImage, LabeledImage]):
    """Per-channel normalization (reference ``BGRImgNormalizer``)."""

    def __init__(self, mean: Tuple[float, float, float],
                 std: Tuple[float, float, float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in prev:
            yield LabeledImage((img.data - self.mean) / self.std, img.label)


class BGRImgCropper(Transformer[LabeledImage, LabeledImage]):
    """Center/random crop (reference ``BGRImgCropper``)."""

    def __init__(self, crop_width: int, crop_height: int, random: bool = True):
        self.cw, self.ch, self.random = crop_width, crop_height, random
        self.stochastic = random

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        rng = RandomGenerator.RNG()
        for img in prev:
            h, w = img.data.shape[:2]
            if self.random:
                y = int(rng.uniform(0, max(1, h - self.ch + 1)))
                x = int(rng.uniform(0, max(1, w - self.cw + 1)))
            else:
                y, x = (h - self.ch) // 2, (w - self.cw) // 2
            yield LabeledImage(img.data[y:y + self.ch, x:x + self.cw], img.label)


class BGRImgRdmCropper(BGRImgCropper):
    """Random crop with zero padding (reference ``BGRImgRdmCropper``)."""

    def __init__(self, crop_width: int, crop_height: int, padding: int = 0):
        super().__init__(crop_width, crop_height, random=True)
        self.padding = padding

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        def padded():
            for img in prev:
                if self.padding:
                    d = np.pad(img.data, ((self.padding, self.padding),
                                          (self.padding, self.padding), (0, 0)))
                    yield LabeledImage(d, img.label)
                else:
                    yield img

        return super().__call__(padded())


class HFlip(Transformer[LabeledImage, LabeledImage]):
    """Random horizontal flip (reference ``HFlip``)."""

    stochastic = True

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        rng = RandomGenerator.RNG()
        for img in prev:
            if rng.uniform() < self.threshold:
                yield LabeledImage(img.data[:, ::-1], img.label)
            else:
                yield img


class ColorJitter(Transformer[LabeledImage, LabeledImage]):
    """Random brightness/contrast/saturation (reference ``ColorJitter``)."""

    stochastic = True

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4):
        self.brightness, self.contrast, self.saturation = brightness, contrast, saturation

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        rng = RandomGenerator.RNG()
        for img in prev:
            d = img.data
            order = [0, 1, 2]
            rng.shuffle(order)
            for op in order:
                if op == 0 and self.brightness:
                    alpha = 1.0 + float(rng.uniform(-self.brightness, self.brightness))
                    d = d * alpha
                elif op == 1 and self.contrast:
                    alpha = 1.0 + float(rng.uniform(-self.contrast, self.contrast))
                    grey_mean = d.mean()
                    d = d * alpha + grey_mean * (1 - alpha)
                elif op == 2 and self.saturation:
                    alpha = 1.0 + float(rng.uniform(-self.saturation, self.saturation))
                    grey = d.mean(axis=2, keepdims=True)
                    d = d * alpha + grey * (1 - alpha)
            yield LabeledImage(d, img.label)


class Lighting(Transformer[LabeledImage, LabeledImage]):
    """AlexNet PCA-noise lighting (reference ``Lighting``)."""

    stochastic = True

    EIGVAL = np.asarray([0.2175, 0.0188, 0.0045], np.float32)
    EIGVEC = np.asarray([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alphastd: float = 0.1):
        self.alphastd = alphastd

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        rng = RandomGenerator.RNG()
        for img in prev:
            alpha = rng.normal(0.0, self.alphastd, (3,)).astype(np.float32)
            delta = (self.EIGVEC * alpha * self.EIGVAL).sum(axis=1)
            yield LabeledImage(img.data + delta, img.label)


class _ImgToBatch(Transformer[LabeledImage, MiniBatch]):
    aggregating = True

    def __init__(self, batch_size: int, drop_remainder: bool = True):
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[MiniBatch]:
        feats, labels = [], []
        for img in prev:
            feats.append(img.data)
            labels.append(img.label)
            if len(feats) == self.batch_size:
                yield MiniBatch(np.stack(feats), np.asarray(labels, np.float32))
                feats, labels = [], []
        if feats and not self.drop_remainder:
            yield MiniBatch(np.stack(feats), np.asarray(labels, np.float32))


class GreyImgToBatch(_ImgToBatch):
    """reference ``GreyImgToBatch``."""


class BGRImgToBatch(_ImgToBatch):
    """reference ``BGRImgToBatch`` (also covering the multithreaded
    ``MTLabeledBGRImgToBatch`` — host threading lives in Engine.io_pool-based
    prefetch, not in the transformer)."""


class GreyImgToSample(Transformer[LabeledImage, Sample]):
    """reference ``GreyImgToSample``."""

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[Sample]:
        for img in prev:
            yield Sample(img.data, np.float32(img.label))


class BGRImgToSample(GreyImgToSample):
    """reference ``BGRImgToSample``."""


def _decode_scaled_bgr(source, scale_to: int, who: str) -> np.ndarray:
    """Shared PIL decode: RGB convert, short side to ``scale_to``, RGB->BGR
    float32 (the reference's BGR convention)."""
    try:
        from PIL import Image as PILImage
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(f"{who} requires Pillow") from e
    with PILImage.open(source) as im:
        im = im.convert("RGB")
        w, h = im.size
        if min(w, h) != scale_to:
            if w < h:
                im = im.resize((scale_to, int(h * scale_to / w)))
            else:
                im = im.resize((int(w * scale_to / h), scale_to))
        return np.asarray(im, np.float32)[:, :, ::-1]


class EncodedBytesToBGRImg(Transformer[ByteRecord, LabeledImage]):
    """Decode encoded (JPEG/PNG/...) bytes to a scaled BGR image — the
    shard-ingest decode stage (reference seq-file path:
    ``LocalSeqFileToBytes`` -> decode; scaling rule as ``LocalImgReader``:
    short side to ``scale_to``). Requires Pillow."""

    def __init__(self, scale_to: int = 256):
        self.scale_to = scale_to

    def __call__(self, prev: Iterator[ByteRecord]) -> Iterator[LabeledImage]:
        import io
        for rec in prev:
            arr = _decode_scaled_bgr(io.BytesIO(rec.data), self.scale_to,
                                     type(self).__name__)
            yield LabeledImage(arr, rec.label)


class LocalImgReader(Transformer[Tuple[str, float], LabeledImage]):
    """Read + scale image files from disk (reference ``LocalImgReader``).
    Items are (path, label). Requires Pillow; raises cleanly otherwise."""

    def __init__(self, scale_to: int = 256):
        self.scale_to = scale_to

    def __call__(self, prev: Iterator[Tuple[str, float]]) -> Iterator[LabeledImage]:
        for path, label in prev:
            yield LabeledImage(
                _decode_scaled_bgr(path, self.scale_to, type(self).__name__),
                label)


IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp",
                    ".ppm", ".tif", ".tiff")


# Channel-agnostic crop: the reference's Grey variant is the same operation
# on 1-channel data (``dataset/image/GreyImgCropper.scala``).
GreyImgCropper = BGRImgCropper


class BGRImgPixelNormalizer(Transformer[LabeledImage, LabeledImage]):
    """Subtract a per-pixel mean image (reference
    ``BGRImgPixelNormalizer.scala``: ImageNet mean file); the mean must match
    the image shape."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in prev:
            if img.data.shape != self.means.shape:
                raise ValueError(f"mean image shape {self.means.shape} != "
                                 f"image shape {img.data.shape}")
            yield LabeledImage(img.data - self.means, img.label)


class MTLabeledBGRImgToBatch(Transformer[LabeledImage, "MiniBatch"]):
    """Multithreaded transform + collate (reference
    ``MTLabeledBGRImgToBatch.scala``: worker threads each run their own
    transformer clone, then batches are assembled). Composed from the
    generic pieces: ``MTTransformer(transformer)`` >> ``BGRImgToBatch``."""

    aggregating = True

    def __init__(self, width: int, height: int, batch_size: int,
                 transformer: Transformer, workers: int = 4):
        from bigdl_tpu.dataset.base import MTTransformer
        self.width, self.height = width, height
        self._chain = (MTTransformer(transformer, workers=workers)
                       >> BGRImgToBatch(batch_size))

    def __call__(self, prev: Iterator[LabeledImage]):
        for batch in self._chain(prev):
            h, w = batch.data.shape[1:3]
            if (h, w) != (self.height, self.width):
                raise ValueError(
                    f"transformed images are {h}x{w}, expected "
                    f"{self.height}x{self.width} (the declared batch "
                    "geometry — add a cropper/resizer to the transformer)")
            yield batch


class NativeBGRBatchDecoder(Transformer[ByteRecord, MiniBatch]):
    """ByteRecord -> MiniBatch in ONE native call per batch: threaded
    u8->f32 decode with the per-channel ``(x - mean) / std`` fused in
    (``native/src/decode.cc`` ``bt_decode_normalize``; numpy whole-batch
    fallback when the toolchain is absent).

    The round-4 gap this closes: the per-record Python path
    (``BytesToBGRImg >> BGRImgNormalizer``) costs ~1 ms/record of
    interpreter + three array passes — 6.7x under the chip's ResNet-50
    demand (PERF.md). The reference's answer was a threaded decode
    pipeline (``dataset/image/MTLabeledBGRImgToBatch.scala``); this is
    its native-batch form.
    """

    aggregating = True

    def __init__(self, row: int, col: int, batch_size: int,
                 mean: Tuple[float, float, float],
                 std: Tuple[float, float, float],
                 workers: int = 4, channels: int = 3,
                 drop_remainder: bool = True,
                 device_normalize: bool = False):
        self.row, self.col, self.channels = row, col, channels
        self.batch_size = batch_size
        self.workers = workers
        self.drop_remainder = drop_remainder
        # device_normalize: emit RAW uint8 batches (4x fewer host->device
        # bytes) and let ``nn.InputNormalize`` cast+normalize ON DEVICE —
        # the TPU-first split when the host->chip link is the ingest
        # bottleneck (tunneled/PCIe feeds). The native kernel then has
        # nothing to do; the host path reduces to framing + collation.
        self.device_normalize = device_normalize
        n = 1 if channels == 1 else channels
        self.mean = np.ascontiguousarray(
            np.broadcast_to(np.asarray(mean, np.float32), (n,)))
        self.rstd = np.ascontiguousarray(
            1.0 / np.broadcast_to(np.asarray(std, np.float32), (n,)))

    def _decode(self, raw: np.ndarray, labels) -> MiniBatch:
        import ctypes

        from bigdl_tpu import native
        n = raw.shape[0]
        rec_len = raw.shape[1]
        if self.device_normalize:
            shape = ((n, self.row, self.col, self.channels)
                     if self.channels > 1 else (n, self.row, self.col))
            return MiniBatch(raw.reshape(shape).copy(),
                             np.asarray(labels, np.float32))
        lib = native.load()
        if lib is not None:
            out = np.empty((n, rec_len), np.float32)
            fp = ctypes.POINTER(ctypes.c_float)
            lib.bt_decode_normalize(
                raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.c_int64(n), ctypes.c_int64(rec_len),
                self.mean.ctypes.data_as(fp), self.rstd.ctypes.data_as(fp),
                ctypes.c_int(self.channels), out.ctypes.data_as(fp),
                ctypes.c_int(self.workers))
        else:  # vectorized fallback: still whole-batch, no per-record Python
            out = raw.astype(np.float32).reshape(n, -1, self.channels)
            out = ((out - self.mean) * self.rstd).reshape(n, rec_len)
        shape = ((n, self.row, self.col, self.channels) if self.channels > 1
                 else (n, self.row, self.col))
        return MiniBatch(out.reshape(shape),
                         np.asarray(labels, np.float32))

    def __call__(self, prev: Iterator[ByteRecord]) -> Iterator[MiniBatch]:
        rec_len = self.row * self.col * self.channels
        raw = np.empty((self.batch_size, rec_len), np.uint8)
        labels: list = []
        for rec in prev:
            data = np.frombuffer(rec.data, np.uint8)
            if data.size != rec_len:
                raise ValueError(f"record has {data.size} bytes, expected "
                                 f"{rec_len} ({self.row}x{self.col}x"
                                 f"{self.channels})")
            raw[len(labels)] = data
            labels.append(rec.label)
            if len(labels) == self.batch_size:
                yield self._decode(raw, labels)
                labels = []
        if labels and not self.drop_remainder:
            yield self._decode(raw[:len(labels)], labels)


class BGRImgToImageVector(Transformer[LabeledImage, Sample]):
    """Flatten images to plain feature vectors for the sklearn-protocol
    classifier (reference ``BGRImgToImageVector.scala`` feeds Spark-ML
    DenseVectors to DLClassifier)."""

    def __call__(self, prev: Iterator[LabeledImage]) -> Iterator[Sample]:
        for img in prev:
            yield Sample(np.asarray(img.data, np.float32).ravel(), img.label)


class LocalImgReaderWithName(LocalImgReader):
    """Like LocalImgReader but yields (path, LabeledImage) so predictions
    can be joined back to files (reference
    ``LocalImgReaderWithName.scala``)."""

    def __call__(self, prev: Iterator[Tuple[str, float]]):
        for path, label in prev:
            yield path, LabeledImage(
                _decode_scaled_bgr(path, self.scale_to, type(self).__name__),
                label)


def image_folder_paths(folder: str, extensions=IMAGE_EXTENSIONS):
    """(path, 1-based label) pairs from a labeled image tree — one
    subdirectory per class, labels assigned by sorted class name (reference
    ``DataSet.ImageFolder.paths``, ``dataset/DataSet.scala:319-558``).
    ``extensions=None`` keeps every regular file (generic labeled-tree
    walker, reused by the text pipeline's category loader)."""
    import os
    pairs = []
    classes = sorted(d for d in os.listdir(folder)
                     if os.path.isdir(os.path.join(folder, d)))
    for label, cls in enumerate(classes, start=1):
        cls_dir = os.path.join(folder, cls)
        for name in sorted(os.listdir(cls_dir)):
            p = os.path.join(cls_dir, name)
            if not os.path.isfile(p):
                continue
            if extensions and not name.lower().endswith(extensions):
                continue
            pairs.append((p, float(label)))
    return pairs
