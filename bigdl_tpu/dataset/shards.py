"""Packed record shards — the ImageNet-scale ingest path (reference
``DataSet.SeqFileFolder.files`` + ``ImageNetSeqFileGenerator.scala``: bulk
image bytes packed into Hadoop SequenceFiles so training never stats millions
of small files).

TPU-native form: plain local shard files with TFRecord-style framing (length +
masked CRC32C + payload, via ``visualization.tensorboard.RecordWriter``) —
one record per (label, bytes) pair. No Hadoop dependency; per-host shard
assignment replaces HDFS locality (each host of a multi-host pod reads its
own shard subset, ≙ ``CachedDistriDataSet`` partition pinning)."""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional, Sequence

from bigdl_tpu.dataset.base import (AbstractDataSet, ByteRecord, DataSet,
                                    LocalDataSet)
from bigdl_tpu.visualization.tensorboard import FileReader, RecordWriter

_SUFFIX = ".bigdl-shard"


class ShardWriter:
    """Write (label, payload) records into fixed-size shard files
    (reference ``BGRImgToLocalSeqFile``)."""

    def __init__(self, path_prefix: str, records_per_shard: int = 1024):
        self.path_prefix = path_prefix
        self.records_per_shard = records_per_shard
        self._shard_idx = 0
        self._in_shard = 0
        self._file = None
        self._writer: Optional[RecordWriter] = None
        self.written_paths: List[str] = []
        os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)

    def _roll(self) -> None:
        if self._file is not None:
            self._file.close()
        path = f"{self.path_prefix}-{self._shard_idx:05d}{_SUFFIX}"
        self._file = open(path, "wb")
        self._writer = RecordWriter(self._file)
        self.written_paths.append(path)
        self._shard_idx += 1
        self._in_shard = 0

    def write(self, label: float, payload: bytes) -> None:
        if self._writer is None or self._in_shard >= self.records_per_shard:
            self._roll()
        self._writer.write(struct.pack("<f", float(label)) + payload)
        self._in_shard += 1

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        self._writer = None  # a later write() rolls a fresh shard

    def __enter__(self) -> "ShardWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def list_shards(folder: str) -> List[str]:
    return sorted(os.path.join(folder, f) for f in os.listdir(folder)
                  if f.endswith(_SUFFIX))


def _native_scan(path: str):
    """Index + CRC-verify a whole shard in one native pass (``bt_shard_scan``:
    C++ framing walk with multithreaded payload-CRC check); returns
    ``(buf, [(offset, length), ...])`` over the payloads, or None when the
    native library is unavailable."""
    from bigdl_tpu import native
    dll = native.load()
    if dll is None:
        return None
    import ctypes
    with open(path, "rb") as f:
        buf = f.read()
    # Size the index for KB-scale records first; the absolute worst case
    # (16-byte framing around empty payloads) only on the -3 capacity retry —
    # worst-case-first would zero-alloc ~file-size of index per shard.
    worst = len(buf) // 16 + 1
    cap = max(1024, min(len(buf) // 4096 + 1, worst))
    while True:
        offs = (ctypes.c_uint64 * cap)()
        lens = (ctypes.c_uint64 * cap)()
        n = dll.bt_shard_scan(buf, len(buf), offs, lens, cap, 1)
        if n != -3:
            break
        cap = worst
    if n == -1:
        raise IOError(f"corrupt record header in {path}")
    if n == -2:
        raise IOError(f"corrupt record payload in {path}")
    if n < 0:
        raise IOError(f"shard scan failed ({n}) in {path}")
    return buf, [(offs[i], lens[i]) for i in range(n)]


def _count_records(path: str) -> int:
    """Record count via a seek-based framing walk: reads 12 bytes per record
    and seeks over payloads — O(records) IO, near-zero resident memory (a
    full-file read just to count frames would double-buffer multi-GB
    shards)."""
    total = os.path.getsize(path)
    count = 0
    with open(path, "rb") as f:
        pos = 0
        while total - pos >= 12:
            header = f.read(12)
            if len(header) < 12:
                break
            (length,) = struct.unpack_from("<Q", header)
            body = pos + 12
            if length > total - body or total - body - length < 4:
                break  # truncated tail, same semantics as read_shard
            count += 1
            pos = body + length + 4
            f.seek(pos)
    return count


def read_shard(path: str) -> Iterator[ByteRecord]:
    scanned = _native_scan(path)
    if scanned is not None:
        buf, index = scanned
        for off, length in index:
            if length < 4:
                raise IOError(
                    f"record shorter than its 4-byte label ({length}B) in "
                    f"{path}")
            (label,) = struct.unpack_from("<f", buf, off)
            yield ByteRecord(buf[off + 4:off + length], label)
        return
    for record in FileReader.read_records(path):
        if len(record) < 4:
            raise IOError(
                f"record shorter than its 4-byte label ({len(record)}B) in "
                f"{path}")
        (label,) = struct.unpack("<f", record[:4])
        yield ByteRecord(record[4:], label)


class ShardFolder:
    """reference ``SeqFileFolder.files``: a DataSet over shard files."""

    @staticmethod
    def paths(folder: str, host_index: Optional[int] = None,
              host_count: Optional[int] = None) -> List[str]:
        """Shards for this host — round-robin split across hosts (the
        multi-host ingest layout: each host feeds its local chips only).
        Defaults to this process's rank in the jax.distributed topology."""
        if host_index is None or host_count is None:
            from bigdl_tpu.utils.engine import Engine
            host_index = Engine.process_index()
            host_count = Engine.process_count()
        shards = list_shards(folder)
        return shards[host_index::host_count]

    @staticmethod
    def files(folder: str, host_index: Optional[int] = None,
              host_count: Optional[int] = None) -> LocalDataSet:
        """Eagerly materialized dataset — fine for fixture-scale folders;
        use :meth:`stream` for ImageNet-scale data."""
        records: List[ByteRecord] = []
        for path in ShardFolder.paths(folder, host_index, host_count):
            records.extend(read_shard(path))
        # records are already host-sliced by shard assignment: mark the
        # dataset distributed WITHOUT re-slicing per process
        from bigdl_tpu.dataset.base import DistributedDataSet
        return DistributedDataSet(records, shard_by_process=False)

    @staticmethod
    def stream(folder: str, host_index: Optional[int] = None,
               host_count: Optional[int] = None) -> "StreamingShardDataSet":
        """Streaming dataset: one shard resident at a time (the reference
        reads SequenceFiles partition-by-partition; whole-corpus RAM
        residency is not an option at ImageNet scale)."""
        return StreamingShardDataSet(
            ShardFolder.paths(folder, host_index, host_count))


class StreamingShardDataSet(AbstractDataSet):
    """DataSet over shard files that re-reads from disk each epoch.

    Shuffle granularity (reference ``CachedDistriDataSet`` shuffles a cached
    index; here disk order is the index): shard ORDER is permuted per epoch
    and records shuffle WITHIN the resident shard — one shard's records in
    RAM at a time bounds memory at max-shard-size.
    """

    def __init__(self, paths: Sequence[str]):
        # an empty host slice (fewer shards than hosts) is valid: that
        # process streams nothing, mirroring files()'s empty DataSet
        self._paths = list(paths)
        self._order = list(range(len(self._paths)))
        self._size: Optional[int] = None
        self._shuffled = False

    def data(self, train: bool) -> Iterator[ByteRecord]:
        from bigdl_tpu.utils.rng import RandomGenerator
        # eval iteration stays in deterministic disk order regardless of
        # shuffle() calls (LocalDataSet contract: predictions must match
        # back to record order)
        order = self._order if train else range(len(self._paths))
        for i in order:
            records = list(read_shard(self._paths[i]))
            if train and self._shuffled:
                RandomGenerator.RNG().shuffle(records)
            yield from records

    def size(self) -> int:
        if self._size is None:
            # frame-count only: skip payload CRC + decode (a full
            # read_shard pre-pass would stream the whole corpus once just
            # for the epoch-size log line)
            self._size = sum(_count_records(p) for p in self._paths)
        return self._size

    def shuffle(self) -> None:
        from bigdl_tpu.utils.rng import RandomGenerator
        RandomGenerator.RNG().shuffle(self._order)
        self._shuffled = True

    def is_distributed(self) -> bool:
        # paths are already host-sliced (ShardFolder.paths): same contract
        # as files()'s DistributedDataSet(shard_by_process=False)
        return True


class BGRImgToLocalSeqFile:
    """Pack LabeledImages into local shard files, yielding the paths it
    wrote (reference ``BGRImgToLocalSeqFile.scala`` writes Hadoop
    SequenceFiles). Wire format: interleaved uint8 pixels (what
    ``BytesToBGRImg`` decodes) — pack BEFORE normalization; out-of-range
    pixel values error rather than silently wrapping modulo 256."""

    def __init__(self, path_prefix: str, block_size: int = 1024):
        self.path_prefix = path_prefix
        self.block_size = block_size

    def __call__(self, prev):
        import numpy as np
        with ShardWriter(self.path_prefix,
                         records_per_shard=self.block_size) as writer:
            for img in prev:
                data = np.asarray(img.data)
                if data.min() < 0 or data.max() > 255:
                    raise ValueError(
                        "image pixels outside [0, 255] cannot be packed as "
                        "uint8 — write raw images, normalize on the read "
                        f"side (got range [{data.min()}, {data.max()}])")
                writer.write(img.label, data.astype(np.uint8).tobytes())
        yield from writer.written_paths


class LocalSeqFileToBytes:
    """Read shard files back to ByteRecords (reference
    ``LocalSeqFileToBytes.scala``); input items are shard paths."""

    def __call__(self, prev) -> Iterator[ByteRecord]:
        for path in prev:
            yield from read_shard(path)
