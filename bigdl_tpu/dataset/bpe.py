"""Byte-level BPE tokenizer — the LM-era companion to ``dataset/text.py``.

The reference's text pipeline stops at a word-level ``Dictionary``
(``dataset/DataSet.scala`` + the news20 example): fixed vocab, OOV bucket,
no subwords. A causal LM needs open-vocabulary tokenization, so this module
provides classic byte-level BPE (Sennrich-style merges over UTF-8 bytes):

- the BASE vocabulary is all 256 bytes, so ANY text encodes losslessly
  (no OOV, exact decode roundtrip);
- training greedily merges the most frequent adjacent symbol pair until
  ``vocab_size`` is reached (ties break deterministically);
- words are split on the ASCII SPACE byte only, with the space carried
  as a word-prefix byte (GPT-style): merges never cross a space, other
  whitespace (tabs/newlines) stays inside words, and decoding
  reconstructs the exact original string.

Token ids follow the framework's 1-based convention (``LookupTable``):
byte ``b`` is id ``b + 1`` (1..256), merged symbols get 257, 258, ... in
merge order; id ``vocab_size + 1`` is reserved for an optional EOS via
``eos_id``. Train/encode/decode are pure Python (tokenization is host-side
data-pipeline work — it feeds ``DataSet`` exactly like ``text.Tokens``).
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

Pair = Tuple[int, int]


def _to_words(text: str) -> List[bytes]:
    """Split on the ASCII space byte (kept as a word prefix), so
    ``b"".join(words) == text.encode()`` exactly; tabs/newlines remain
    inside words."""
    raw = text.encode("utf-8")
    words: List[bytes] = []
    start = 0
    for i in range(1, len(raw)):
        if raw[i: i + 1] == b" ":
            words.append(raw[start:i])
            start = i
    if start < len(raw) or not raw:
        words.append(raw[start:])
    return [w for w in words if w]


class BPETokenizer:
    """Byte-level BPE: ``train`` -> ``encode``/``decode`` -> 1-based ids."""

    def __init__(self, merges: Optional[Sequence[Pair]] = None):
        # symbol id space (0-based internally): 0..255 bytes, 256+ merges
        self.merges: List[Pair] = list(merges or [])
        self._ranks: Dict[Pair, int] = {p: i for i, p in
                                        enumerate(self.merges)}
        self._bytes: List[bytes] = [bytes([b]) for b in range(256)]
        for a, b in self.merges:
            self._bytes.append(self._bytes[a] + self._bytes[b])
        self._cache: Dict[bytes, Tuple[int, ...]] = {}

    # ------------------------------------------------------------- training
    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int = 1024,
              min_freq: int = 2) -> "BPETokenizer":
        """Learn merges until the vocab reaches ``vocab_size`` (>= 256) or
        no pair occurs at least ``min_freq`` times."""
        if vocab_size < 256:
            raise ValueError("vocab_size must be >= 256 (the byte alphabet)")
        word_freq: Counter = Counter()
        for text in texts:
            word_freq.update(_to_words(text))
        corpus: List[Tuple[List[int], int]] = [
            (list(w), f) for w, f in word_freq.items()]
        # pair -> total freq, plus pair -> set of word indexes containing it
        # (the standard Sennrich incremental bookkeeping: each merge only
        # touches the words that contain the merged pair, not the corpus)
        pairs: Counter = Counter()
        where: Dict[Pair, set] = {}
        for wi, (syms, freq) in enumerate(corpus):
            for i in range(len(syms) - 1):
                pr = (syms[i], syms[i + 1])
                pairs[pr] += freq
                where.setdefault(pr, set()).add(wi)
        merges: List[Pair] = []
        n_symbols = 256
        while n_symbols < vocab_size and pairs:
            best, freq = max(pairs.items(), key=lambda kv: (kv[1], kv[0]))
            if freq < min_freq:
                break
            new_id = n_symbols
            merges.append(best)
            a, b = best
            for wi in sorted(where.get(best, ())):
                syms, wfreq = corpus[wi]
                # retract this word's current pair contributions
                for i in range(len(syms) - 1):
                    pr = (syms[i], syms[i + 1])
                    pairs[pr] -= wfreq
                    if pairs[pr] <= 0:
                        del pairs[pr]
                    w = where.get(pr)
                    if w is not None:
                        w.discard(wi)
                        if not w:
                            del where[pr]
                i = 0
                while i < len(syms) - 1:
                    if syms[i] == a and syms[i + 1] == b:
                        syms[i: i + 2] = [new_id]
                    else:
                        i += 1
                # re-add the merged word's contributions
                for i in range(len(syms) - 1):
                    pr = (syms[i], syms[i + 1])
                    pairs[pr] += wfreq
                    where.setdefault(pr, set()).add(wi)
            n_symbols += 1
        return cls(merges)

    # ------------------------------------------------------------ encoding
    def _bpe_word(self, word: bytes) -> Tuple[int, ...]:
        cached = self._cache.get(word)
        if cached is not None:
            return cached
        syms = list(word)
        while len(syms) > 1:
            ranked = [(self._ranks.get((syms[i], syms[i + 1])), i)
                      for i in range(len(syms) - 1)]
            ranked = [(r, i) for r, i in ranked if r is not None]
            if not ranked:
                break
            rank, i = min(ranked)
            a, b = self.merges[rank]
            # merge EVERY occurrence of this lowest-ranked pair
            j = 0
            while j < len(syms) - 1:
                if syms[j] == a and syms[j + 1] == b:
                    syms[j: j + 2] = [256 + rank]
                else:
                    j += 1
        out = tuple(syms)
        if len(self._cache) < 65536:
            self._cache[word] = out
        return out

    def encode(self, text: str) -> List[int]:
        """UTF-8 text -> 1-based token ids."""
        ids: List[int] = []
        for word in _to_words(text):
            ids.extend(s + 1 for s in self._bpe_word(word))
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        """1-based ids -> text (exact inverse of encode; ids outside the
        vocab — e.g. an ``eos_id`` — are skipped)."""
        n = len(self._bytes)
        data = b"".join(self._bytes[int(i) - 1] for i in ids
                        if 1 <= int(i) <= n)
        return data.decode("utf-8", errors="replace")

    # ------------------------------------------------------------- surface
    @property
    def vocab_size(self) -> int:
        return len(self._bytes)

    @property
    def eos_id(self) -> int:
        """A reserved id one past the learned vocab (give the LM
        ``vocab_size = tokenizer.vocab_size + 1`` to use it)."""
        return len(self._bytes) + 1

    def save(self, path: str) -> None:
        from bigdl_tpu.utils import file_io
        file_io.save({"merges": self.merges}, path)

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        from bigdl_tpu.utils import file_io
        return cls(file_io.load(path)["merges"])

    def __repr__(self):
        return f"BPETokenizer(vocab_size={self.vocab_size})"
