"""Staged, threaded ingest engine — read / decode / device-feed as
overlapping stages connected by bounded queues.

The serial host chain (shard read -> decode -> collate -> device put ->
step; PERF.md round 5: ~415 us/record, 509 img/s against a 2560 img/s
chip demand) runs every stage on the consumer thread, so each stage's
latency adds. Every link in that chain releases the GIL — shard reads
are file IO, the whole-batch decode is a ctypes call into
``bt_decode_normalize``, ``jax.device_put`` is an async transfer — so
plain Python threads already overlap them; what a naive thread pool
loses is *order*, and with it shuffle replay and mid-epoch resume.

The engine keeps both properties:

- **read pool**: N reader threads pull ``(seq, path, seed)`` shard tasks
  off a work queue, read + CRC-verify the shard, apply the per-shard
  record shuffle from the task's seed (drawn by the *constructing*
  thread — ``RandomGenerator`` is thread-local, so worker-side draws
  would be nondeterministic), and land the record list in a
  sequence-numbered :class:`~bigdl_tpu.dataset.ingest.reorder.ReorderBuffer`.
- **collate feeder**: one thread restores shard order, slices the record
  stream into batch-size chunks, and tickets them into the decode pool.
- **decode pool**: M threads each own a ``clone_transformer()`` of the
  decode chain (per-worker native buffers — ``NativeBGRBatchDecoder``
  reuses its raw staging buffer across calls) and run whole chunks
  through it; outputs reorder by chunk sequence.
- **device feed**: one thread pops ordered batches and issues
  ``jax.device_put`` ahead of consumption — batch N+1 transfers while
  the step computes batch N. Each put allocates fresh device buffers, so
  a donating jitted step never aliases an engine-held buffer (donation-
  safe rotation); the bounded output queue is the backpressure that
  stops the engine when the step falls behind.

Memory is bounded end to end: resident shards by a reader semaphore
(released when the collate feeder finishes a shard), in-flight chunks by
an admission-ticket semaphore (released when the device feed pops the
ordered result), handed-off batches by the output queue's
``prefetch_depth``. A stalled consumer therefore freezes the pipeline at
a fixed footprint instead of buffering the epoch.

Every stage is instrumented (``bigdl_ingest_*`` in the telemetry
catalogue) and span-traced (``ingest.read_shard`` / ``ingest.decode`` /
``ingest.device_put``), so ``BIGDL_TPU_TRACE`` shows the stages as
concurrent lanes and ``bigdl_ingest_stall_seconds_total{stage}`` names
the starved stage: a stage's input wait counts as a stall only when the
pipeline had admission room (otherwise the wait is backpressure from
below, charged to nobody).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.base import Transformer, _flatten_chain
from bigdl_tpu.dataset.ingest.reorder import ReorderBuffer
from bigdl_tpu.telemetry import get_registry, instruments, span

__all__ = ["IngestConfig", "IngestEngine", "validate_chain"]

_END = object()

_WAIT_SLICE_S = 0.05


class IngestConfig:
    """Knobs of the staged engine (defaults suit a few-core host)."""

    __slots__ = ("workers", "decode_workers", "prefetch_depth",
                 "resident_shards", "inflight_chunks", "device_put",
                 "chunk_records")

    def __init__(self, workers: int = 2,
                 decode_workers: Optional[int] = None,
                 prefetch_depth: int = 2,
                 resident_shards: Optional[int] = None,
                 inflight_chunks: Optional[int] = None,
                 device_put: bool = True,
                 chunk_records: int = 256):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        if prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {prefetch_depth}")
        self.workers = int(workers)
        self.decode_workers = int(decode_workers if decode_workers
                                  else workers)
        self.prefetch_depth = int(prefetch_depth)
        # one shard resident per reader plus one being collated mirrors
        # StreamingShardDataSet's max-shard-size memory bound, scaled by
        # the worker count
        self.resident_shards = int(resident_shards if resident_shards
                                   else self.workers + 1)
        self.inflight_chunks = int(
            inflight_chunks if inflight_chunks
            else self.decode_workers + self.prefetch_depth + 1)
        self.device_put = bool(device_put)
        # chunk size when no batching stage dictates one (records pass
        # through unbatched, e.g. the determinism tests)
        self.chunk_records = int(chunk_records)


def validate_chain(chain: Optional[Transformer]) -> Tuple[
        List[Transformer], Optional[Transformer]]:
    """Split a decode chain into (per-record stages, trailing batcher).

    The engine fans whole chunks out to decode workers, so the chain must
    be order-deterministic and chunk-alignable: no ``stochastic`` stages
    (their thread-local RNG draws would depend on worker scheduling —
    keep random augmentation above the engine), per-record stages must be
    1:1, and at most one ``aggregating`` stage, in trailing position,
    carrying an integer ``batch_size`` (chunks are cut to exactly that
    size, so per-chunk collation equals whole-stream collation).
    """
    if chain is None:
        return [], None
    stages = _flatten_chain(chain)
    for s in stages:
        if getattr(s, "stochastic", False):
            raise ValueError(
                f"ingest engine cannot pipeline the stochastic stage "
                f"{type(s).__name__}: worker-thread RNG draws are "
                "schedule-dependent, which breaks the bit-exact ordering "
                "contract. Apply random augmentation above the engine.")
    for s in stages[:-1]:
        if getattr(s, "aggregating", False):
            raise ValueError(
                f"ingest engine needs the aggregating stage "
                f"{type(s).__name__} in trailing position (chunks are "
                "cut to its batch_size; a mid-chain aggregator would "
                "see chunk boundaries).")
    batcher = None
    if stages and getattr(stages[-1], "aggregating", False):
        batcher = stages[-1]
        if not isinstance(getattr(batcher, "batch_size", None), int):
            raise ValueError(
                f"trailing aggregating stage {type(batcher).__name__} "
                "must expose an integer .batch_size so the engine can "
                "align chunks to batch boundaries")
        stages = stages[:-1]
    return stages, batcher


def _rechain(stages: Sequence[Transformer],
             batcher: Optional[Transformer]) -> Optional[Transformer]:
    out: Optional[Transformer] = None
    for s in list(stages) + ([batcher] if batcher is not None else []):
        out = s if out is None else (out >> s)
    return out


class IngestEngine:
    """One epoch of pipelined ingest over an ordered shard task list.

    ``tasks`` is ``[(path, seed), ...]`` in epoch order (seed ``None``
    for disk order); ``read_fn(path)`` yields the shard's records.
    Iterate the engine to consume ordered batches; ``close()`` (also
    called automatically at end of stream and by ``__exit__``) drains and
    joins every worker thread — no leaks on exception paths.
    """

    def __init__(self, tasks: Sequence[Tuple[str, Optional[int]]],
                 read_fn, chain: Optional[Transformer] = None,
                 config: Optional[IngestConfig] = None):
        self.config = cfg = config or IngestConfig()
        self._read_fn = read_fn
        stages, batcher = validate_chain(chain)
        self._stages = stages
        self._batcher = batcher
        self._chunk_size = (batcher.batch_size if batcher is not None
                            else cfg.chunk_records)
        self._tasks = list(tasks)
        ins = instruments(get_registry())
        self._m_depth = ins.ingest_queue_depth
        self._m_stage = ins.ingest_stage_seconds
        self._m_records = ins.ingest_records_total
        self._m_bytes = ins.ingest_bytes_total
        self._m_batches = ins.ingest_batches_total
        self._m_stall = ins.ingest_stall_seconds_total

        self._stop = threading.Event()
        # guards _error, _closed, _inflight_chunks (written by worker
        # threads AND the consumer thread)
        self._lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._closed = False
        self._inflight_chunks = 0

        self._task_q: "queue.Queue" = queue.Queue()
        for seq, (path, seed) in enumerate(self._tasks):
            self._task_q.put((seq, path, seed))
        for _ in range(cfg.workers):
            self._task_q.put(_END)
        self._shard_sem = threading.Semaphore(cfg.resident_shards)
        self._shard_ro = ReorderBuffer()
        self._chunk_q: "queue.Queue" = queue.Queue()
        self._chunk_sem = threading.Semaphore(cfg.inflight_chunks)
        self._batch_ro = ReorderBuffer()
        self._out_q: "queue.Queue" = queue.Queue(maxsize=cfg.prefetch_depth)

        self._threads: List[threading.Thread] = []
        for i in range(cfg.workers):
            self._spawn(self._read_loop, f"bigdl-ingest-read-{i}")
        self._spawn(self._collate_loop, "bigdl-ingest-collate")
        for i in range(cfg.decode_workers):
            self._spawn(self._decode_loop, f"bigdl-ingest-decode-{i}",
                        (i,))
        self._spawn(self._feed_loop, "bigdl-ingest-feed")

    # ------------------------------------------------------------ lifecycle
    def _spawn(self, target, name: str, args: tuple = ()) -> None:
        t = threading.Thread(target=target, name=name, args=args,
                             daemon=True)
        self._threads.append(t)
        t.start()

    def _fail(self, exc: BaseException) -> None:
        with self._lock:
            if self._error is None:
                self._error = exc
        self._stop.set()

    def close(self) -> None:
        """Drain + join every stage thread. Idempotent; safe to call from
        ``finally`` blocks, the consumer, or the preemption drain path."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        # poke ticket waiters so blocked stages re-check the stop event
        self._shard_sem.release()
        self._chunk_sem.release()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = [t for t in self._threads if t.is_alive()]
        # closed means CLOSED: drop already-buffered output so a drained
        # iterator ends at once instead of replaying stale prefetch (the
        # preemption path must not hand batches past the snapshot cursor)
        while True:
            try:
                self._out_q.get_nowait()
            except queue.Empty:
                break

    def __enter__(self) -> "IngestEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def inflight_chunks(self) -> int:
        """Chunks admitted but not yet released to the output queue (the
        quantity the admission tickets bound; backpressure tests poll
        it)."""
        with self._lock:
            return self._inflight_chunks

    # ----------------------------------------------------- blocking helpers
    def _acquire(self, sem: threading.Semaphore) -> bool:
        while not self._stop.is_set():
            if sem.acquire(timeout=_WAIT_SLICE_S):
                return True
        return False

    def _get(self, q: "queue.Queue", stage: str,
             count_stall: bool = True):
        """Stop-aware ``q.get`` charging the wait to the stage's stall
        counter (only while admission room exists — a full pipeline means
        the wait is downstream backpressure, not upstream starvation)."""
        t0 = time.perf_counter()
        while not self._stop.is_set():
            try:
                item = q.get(timeout=_WAIT_SLICE_S)
            except queue.Empty:
                continue
            waited = time.perf_counter() - t0
            if count_stall and waited > 0 and item is not _END:
                with self._lock:
                    starved = (self._inflight_chunks
                               < self.config.inflight_chunks)
                if starved:
                    self._m_stall.labels(stage=stage).inc(waited)
            return item
        return _END

    def _put(self, q: "queue.Queue", item: Any) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=_WAIT_SLICE_S)
                return True
            except queue.Full:
                continue
        return False

    # --------------------------------------------------------------- stages
    def _read_loop(self) -> None:
        try:
            while True:
                item = self._get(self._task_q, "read", count_stall=False)
                if item is _END:
                    return
                seq, path, seed = item
                if not self._acquire(self._shard_sem):
                    return
                t0 = time.perf_counter()
                with span("ingest.read_shard", seq=seq):
                    records = list(self._read_fn(path))
                    if seed is not None:
                        # seeded worker-local generator: the draw depends
                        # only on (epoch seed, shard seq), never on which
                        # worker or in what order shards complete
                        np.random.default_rng(seed).shuffle(records)
                self._m_stage.labels(stage="read").observe(
                    time.perf_counter() - t0)
                self._m_bytes.inc(sum(len(r.data) for r in records
                                      if hasattr(r, "data")
                                      and isinstance(r.data, bytes)))
                if not self._shard_ro.put(seq, records, self._stop):
                    return
                self._m_depth.labels(stage="shards").set(
                    self._shard_ro.pending())
        except BaseException as e:
            self._fail(e)

    def _collate_loop(self) -> None:
        try:
            chunk_seq = 0
            buf: List[Any] = []
            for _ in range(len(self._tasks)):
                t0 = time.perf_counter()
                records = self._shard_ro.pop(self._stop)
                waited = time.perf_counter() - t0
                if records is None:
                    return  # stopped mid-epoch
                if waited > 0:
                    with self._lock:
                        starved = (self._inflight_chunks
                                   < self.config.inflight_chunks)
                    if starved:
                        self._m_stall.labels(stage="collate").inc(waited)
                buf.extend(records)
                self._shard_sem.release()
                while len(buf) >= self._chunk_size:
                    chunk, buf = (buf[:self._chunk_size],
                                  buf[self._chunk_size:])
                    if not self._submit_chunk(chunk_seq, chunk):
                        return
                    chunk_seq += 1
            if buf:
                if not self._submit_chunk(chunk_seq, buf):
                    return
                chunk_seq += 1
            self._batch_ro.close(chunk_seq)
            for _ in range(self.config.decode_workers):
                self._put(self._chunk_q, _END)
        except BaseException as e:
            self._fail(e)

    def _submit_chunk(self, seq: int, chunk: List[Any]) -> bool:
        if not self._acquire(self._chunk_sem):
            return False
        with self._lock:
            self._inflight_chunks += 1
        ok = self._put(self._chunk_q, (seq, chunk))
        self._m_depth.labels(stage="chunks").set(self._chunk_q.qsize())
        return ok

    def _decode_loop(self, worker: int) -> None:
        try:
            import copy
            chain = _rechain([s.clone_transformer() for s in self._stages],
                             copy.deepcopy(self._batcher)
                             if self._batcher is not None else None)
            while True:
                item = self._get(self._chunk_q, "decode")
                if item is _END:
                    return
                seq, chunk = item
                t0 = time.perf_counter()
                with span("ingest.decode", seq=seq, worker=worker,
                          records=len(chunk)):
                    outs = (list(chain(iter(chunk))) if chain is not None
                            else [chunk])
                self._m_stage.labels(stage="decode").observe(
                    time.perf_counter() - t0)
                if not self._batch_ro.put(seq, outs, self._stop):
                    return
                self._m_depth.labels(stage="batches").set(
                    self._batch_ro.pending())
        except BaseException as e:
            self._fail(e)

    def _feed_loop(self) -> None:
        try:
            while True:
                t0 = time.perf_counter()
                outs = self._batch_ro.pop(self._stop)
                waited = time.perf_counter() - t0
                if outs is None:
                    if self._stop.is_set():
                        return
                    self._put(self._out_q, _END)
                    return
                if waited > 0:
                    # the feed has no downstream admission stage: an input
                    # wait here is always upstream starvation
                    self._m_stall.labels(stage="device_put").inc(waited)
                for b in outs:
                    placed = self._place(b)
                    if not self._put(self._out_q, placed):
                        return
                    self._m_depth.labels(stage="out").set(
                        self._out_q.qsize())
                with self._lock:
                    self._inflight_chunks -= 1
                self._chunk_sem.release()
        except BaseException as e:
            self._fail(e)

    def _place(self, batch):
        """Async host->device transfer of one batch: by the time the
        consumer pops it, the bytes are on (or in flight to) the device.
        ``device_put`` allocates fresh buffers every call, so a jitted
        step donating its inputs never invalidates anything the engine
        still holds."""
        if not self.config.device_put:
            return batch
        data = getattr(batch, "data", None)
        labels = getattr(batch, "labels", None)
        if not isinstance(data, np.ndarray) or labels is None:
            return batch
        import jax
        t0 = time.perf_counter()
        with span("ingest.device_put", bytes=int(data.nbytes)):
            placed = type(batch)(jax.device_put(data),
                                 jax.device_put(labels))
        self._m_stage.labels(stage="device_put").observe(
            time.perf_counter() - t0)
        return placed

    # ------------------------------------------------------------- consumer
    def __iter__(self) -> "IngestEngine":
        return self

    def __next__(self):
        t0 = time.perf_counter()
        while True:
            with self._lock:
                err = self._error
            if err is not None:
                self.close()
                raise err
            try:
                item = self._out_q.get(timeout=_WAIT_SLICE_S)
                break
            except queue.Empty:
                with self._lock:
                    dead = self._closed
                if dead:
                    raise StopIteration
                continue
        waited = time.perf_counter() - t0
        if item is _END:
            self.close()
            raise StopIteration
        if waited > 0:
            # the training loop's data wait, attributed: ingest could not
            # keep the step fed
            self._m_stall.labels(stage="step").inc(waited)
        self._m_batches.inc()
        size = getattr(item, "size", None)
        if callable(size):
            try:
                self._m_records.inc(int(size()))
            except TypeError:
                self._m_records.inc(len(item))
        elif isinstance(item, list):
            self._m_records.inc(len(item))
        return item
