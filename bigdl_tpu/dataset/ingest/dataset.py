"""``PrefetchingDataSet`` — the ingest engine behind the standard
``AbstractDataSet`` protocol.

Drop-in for ``ShardFolder.stream(folder) >> decoder``: the optimizer,
``DistriOptimizer``, the evaluator, and ``apps/ingest_bench.py`` consume
it through the same ``data()/size()/shuffle()`` surface with no call-site
rewrites, but ``data(train=True)`` runs the staged threaded engine
(``bigdl_tpu/dataset/ingest/engine.py``) instead of the serial chain.

Ordering contract (what makes resume and replay bit-exact):

- ``shuffle()`` draws the per-epoch shard-order permutation AND one
  epoch record-shuffle seed from the process RNG — the SAME replayable
  call sequence the resilience resume path re-executes
  (``for _ in range(epoch-1): dataset.shuffle()``).
- ``data()`` consumes NO host RNG: per-shard shuffles derive from
  ``(epoch_seed, shard_seq)`` alone, so serial and pipelined execution,
  and an interrupted vs uninterrupted run, all see bit-identical record
  order. (``StreamingShardDataSet`` draws inside iteration instead,
  which a worker pool cannot reproduce — thread-local RNGs would make
  the draw order schedule-dependent.)

Per-host sharding matches ``ShardFolder.stream``: construct via
:meth:`from_folder` and each process gets its round-robin shard slice.
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional, Sequence

from bigdl_tpu.dataset.base import AbstractDataSet, Transformer
from bigdl_tpu.dataset.ingest.engine import (IngestConfig, IngestEngine,
                                             validate_chain)
from bigdl_tpu.utils.rng import RandomGenerator

__all__ = ["PrefetchingDataSet"]


def _shard_seed(epoch_seed: int, seq: int) -> List[int]:
    """Per-shard shuffle seed: a pure function of (epoch seed, shard
    sequence number) — any worker, in any completion order, derives the
    same record permutation (fed to ``np.random.default_rng`` as
    SeedSequence entropy)."""
    return [int(epoch_seed), int(seq)]


class PrefetchingDataSet(AbstractDataSet):
    """Pipelined shard ingest fronting the ``AbstractDataSet`` protocol.

    ``transformer`` is the decode/collate chain the engine's decode pool
    clones per worker (validated: deterministic per-record stages plus at
    most one trailing batcher). ``config.workers == 0`` selects the
    serial engine: identical ordering rule, no threads — the A/B
    baseline ``apps/ingest_bench.py --engine serial`` measures.
    """

    def __init__(self, paths: Sequence[str],
                 transformer: Optional[Transformer] = None,
                 config: Optional[IngestConfig] = None,
                 serial: bool = False):
        validate_chain(transformer)  # fail at construction, not in a pool
        self._paths = list(paths)
        self._transformer = transformer
        self.config = config or IngestConfig()
        self.serial = bool(serial)
        self._order = list(range(len(self._paths)))
        self._epoch_seed: Optional[int] = None
        self._shuffled = False
        self._size: Optional[int] = None
        # engines spawned by live epoch iterators, so drain() can stop
        # them from the preemption path (worker threads never touch this;
        # the lock serializes consumer-thread vs signal-path access)
        self._live_lock = threading.Lock()
        self._live: List[IngestEngine] = []

    @classmethod
    def from_folder(cls, folder: str,
                    transformer: Optional[Transformer] = None,
                    config: Optional[IngestConfig] = None,
                    host_index: Optional[int] = None,
                    host_count: Optional[int] = None,
                    serial: bool = False) -> "PrefetchingDataSet":
        """Engine over this host's round-robin shard slice (the
        multi-process mesh layout of ``ShardFolder.paths``)."""
        from bigdl_tpu.dataset.shards import ShardFolder
        return cls(ShardFolder.paths(folder, host_index, host_count),
                   transformer, config, serial=serial)

    # ------------------------------------------------------------- protocol
    def _tasks(self, train: bool):
        order = self._order if train else range(len(self._paths))
        shuffle = train and self._shuffled
        return [(self._paths[i],
                 _shard_seed(self._epoch_seed, seq) if shuffle else None)
                for seq, i in enumerate(order)]

    def data(self, train: bool) -> Iterator:
        tasks = self._tasks(train)
        if self.serial or self.config.workers == 0:
            return self._serial_iter(tasks)
        return self._engine_iter(tasks)

    def _serial_iter(self, tasks) -> Iterator:
        """Same ordering rule as the pipeline, executed inline."""
        import numpy as np
        from bigdl_tpu.dataset.shards import read_shard

        def records():
            for path, seed in tasks:
                recs = list(read_shard(path))
                if seed is not None:
                    np.random.default_rng(seed).shuffle(recs)
                yield from recs

        if self._transformer is None:
            return records()
        return self._transformer(records())

    def _engine_iter(self, tasks) -> Iterator:
        from bigdl_tpu.dataset.shards import read_shard
        engine = IngestEngine(tasks, read_shard, self._transformer,
                              self.config)
        with self._live_lock:
            self._live.append(engine)
        try:
            for item in engine:
                if self._transformer is None and isinstance(item, list):
                    # unbatched chunks flatten to records; re-check the
                    # engine between records so drain() cuts the stream
                    # even when a chunk is already in this generator
                    for rec in item:
                        if engine.closed:
                            return
                        yield rec
                else:
                    if engine.closed:
                        return
                    yield item
        finally:
            engine.close()
            with self._live_lock:
                if engine in self._live:
                    self._live.remove(engine)

    def size(self) -> int:
        if self._size is None:
            from bigdl_tpu.dataset.shards import _count_records
            self._size = sum(_count_records(p) for p in self._paths)
        return self._size

    def shuffle(self) -> None:
        rng = RandomGenerator.RNG()
        rng.shuffle(self._order)
        # ONE draw per epoch; data() derives every per-shard shuffle from
        # it, so iteration itself is RNG-pure (resume replays shuffle()
        # calls only — see module docstring)
        self._epoch_seed = int(rng.uniform(0.0, float(2 ** 31 - 1)))
        self._shuffled = True

    def is_distributed(self) -> bool:
        # paths are host-sliced at construction (from_folder), same
        # contract as StreamingShardDataSet
        return True

    # ---------------------------------------------------------------- drain
    def drain(self) -> None:
        """Stop and join every live epoch engine — the preemption path
        (``PreemptionHandler`` drain hooks) calls this before the final
        snapshot so no reader/decoder thread races shard files or device
        transfers against checkpoint IO."""
        with self._live_lock:
            live = list(self._live)
            self._live.clear()
        for engine in live:
            engine.close()
