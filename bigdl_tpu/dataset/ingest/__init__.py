"""bigdl_tpu.dataset.ingest — staged, threaded ingest engine.

Turns the serial shard-read -> decode -> collate -> device-put chain
into overlapping stages behind bounded queues, while keeping epoch
order bit-exact (sequence-numbered reorder buffers + replayable RNG
draws). Entry points:

- :class:`PrefetchingDataSet` — ``AbstractDataSet`` drop-in over a shard
  folder (``from_folder``) or explicit path list.
- :class:`IngestEngine` / :class:`IngestConfig` — the raw staged engine
  for one epoch's ordered task list.
"""

from bigdl_tpu.dataset.ingest.dataset import PrefetchingDataSet
from bigdl_tpu.dataset.ingest.engine import (IngestConfig, IngestEngine,
                                             validate_chain)
from bigdl_tpu.dataset.ingest.reorder import ReorderBuffer

__all__ = ["PrefetchingDataSet", "IngestConfig", "IngestEngine",
           "ReorderBuffer", "validate_chain"]
