"""Sequence-numbered reorder buffer — the determinism hinge of the
pipelined ingest engine.

Worker pools finish out of order (shard 3's read may land before shard
1's); the consumer must see items in exact sequence order or shuffle
replay and mid-epoch resume (``bigdl_tpu/resilience``) stop being
bit-exact. The buffer accepts ``(seq, item)`` pairs in any order and
releases them strictly ascending from 0.

Memory is NOT bounded here — the engine bounds it upstream with
admission tickets (a semaphore acquired before work is submitted,
released when the ordered consumer pops), so a producer holding the
*next* sequence number can never be blocked by the buffer itself: that
shape deadlocks, a ticket bound cannot.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

__all__ = ["ReorderBuffer"]

_WAIT_SLICE_S = 0.05  # poll quantum for stop-aware blocking waits


class ReorderBuffer:
    """Release out-of-order ``(seq, item)`` arrivals in ascending order.

    ``close(total)`` declares how many sequence numbers exist; ``pop``
    returns ``None`` once every one of them has been released. All waits
    are stop-aware: when ``stop`` is set mid-wait, ``put`` drops the item
    and ``pop`` returns ``None`` so pool threads can unwind.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        # all three guarded by _cond's lock (worker threads write _items
        # and _total; the consumer thread writes _next)
        self._items: Dict[int, Any] = {}
        self._next = 0
        self._total: Optional[int] = None

    def put(self, seq: int, item: Any, stop: threading.Event) -> bool:
        with self._cond:
            if stop.is_set():
                return False
            self._items[seq] = item
            self._cond.notify_all()
            return True

    def close(self, total: int) -> None:
        """Declare the final sequence count (producer side, once known)."""
        with self._cond:
            self._total = int(total)
            self._cond.notify_all()

    def pop(self, stop: threading.Event):
        """Next in-order item, blocking until it arrives; ``None`` at end
        of stream or when ``stop`` is set."""
        with self._cond:
            while True:
                if self._next in self._items:
                    item = self._items.pop(self._next)
                    self._next += 1
                    return item
                if self._total is not None and self._next >= self._total:
                    return None
                if stop.is_set():
                    return None
                self._cond.wait(_WAIT_SLICE_S)

    def pending(self) -> int:
        """Completed-but-unreleased items (queue-depth telemetry)."""
        with self._cond:
            return len(self._items)
