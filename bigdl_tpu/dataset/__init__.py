"""bigdl_tpu.dataset — data pipeline (reference ``$B/dataset/``)."""

from bigdl_tpu.dataset.base import (
    Sample, MiniBatch, ByteRecord, Transformer, ChainedTransformer,
    Identity as IdentityTransformer, SampleToBatch, BucketBatch, Prefetch,
    MTTransformer,
    AbstractDataSet, LocalDataSet, DistributedDataSet, DataSet,
)
from bigdl_tpu.dataset.device_cache import DeviceCachedDataSet
from bigdl_tpu.dataset.ingest import (IngestConfig, IngestEngine,
                                      PrefetchingDataSet)
from bigdl_tpu.dataset import image
from bigdl_tpu.dataset import text
from bigdl_tpu.dataset import mnist
from bigdl_tpu.dataset import cifar
from bigdl_tpu.dataset.bpe import BPETokenizer
