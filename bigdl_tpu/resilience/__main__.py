"""CLI: ``python -m bigdl_tpu.resilience`` (``scripts/bigdl-tpu.sh chaos``).

Subcommands (all filesystem-only — no device/backend touch):

- ``validate <checkpoint_dir>``: list every snapshot pair with its
  complete/partial verdict and marker summary; exit 0 iff a resume point
  exists.
- ``latest <checkpoint_dir>``: print the newest complete (model, state)
  pair, one path per line (for shell scripting).
- ``chaos corrupt <snapshot_dir> [--shard N] [--mode flip|truncate|
  delete] [--seed S]``: deterministically damage a shard file (drills
  the partial-snapshot rejection path).
- ``chaos selftest``: exercise the injectors deterministically.
- ``chaos drill [--replicas N] [--disaggregate P:D] [--requests R]``:
  the kill-one-replica serving drill — build a tiny in-process fleet
  behind ``LMRouter``, kill one decode replica mid-stream via
  ``kill-replica@K``, and assert ZERO accepted requests lost with
  greedy outputs bit-identical to an unkilled reference. The only
  subcommand that touches jax; prints a JSON report, exit 0 iff the
  drill holds.
"""

from __future__ import annotations

import argparse
import sys

from bigdl_tpu.resilience import chaos as chaos_mod
from bigdl_tpu.resilience import coordinator


def _cmd_validate(args) -> int:
    import os
    pairs = coordinator.snapshot_pairs(args.checkpoint_dir)
    if not pairs:
        print(f"no snapshot pairs under {args.checkpoint_dir}")
        return 1
    any_ok = False
    for neval, _, model_name, state_name in reversed(pairs):
        model = os.path.join(args.checkpoint_dir, model_name)
        state = os.path.join(args.checkpoint_dir, state_name)
        ok = coordinator.validate_pair(model, state)
        any_ok = any_ok or ok
        marker = coordinator.read_marker(state) if ok else None
        tag = "complete" if ok else "PARTIAL "
        extra = ""
        if marker:
            mesh = marker.get("mesh") or {}
            extra = (f"  marker: step {marker.get('step')} epoch "
                     f"{marker.get('epoch')} procs "
                     f"{mesh.get('process_count')}")
        print(f"{tag}  {model_name} / {state_name}"
              f" (neval {neval}){extra}")
    return 0 if any_ok else 1


def _cmd_latest(args) -> int:
    point = coordinator.latest_resume_point(args.checkpoint_dir)
    if point is None:
        print("no complete snapshot", file=sys.stderr)
        return 1
    print(point.model_path)
    print(point.state_path)
    return 0


def _cmd_chaos_corrupt(args) -> int:
    info = chaos_mod.corrupt_snapshot(args.snapshot_dir, shard=args.shard,
                                      mode=args.mode, seed=args.seed)
    print(f"corrupted {info['file']} ({info['mode']})")
    return 0


def _cmd_chaos_selftest(args) -> int:
    del args
    fired = []
    k = chaos_mod.KillAtStep(3, sig=0, _kill=lambda pid, sig: fired.append(3))
    for step in range(1, 6):
        k.on_step(step)
    assert fired == [3], fired
    slept = []
    d = chaos_mod.DelayAtStep(2, 0.25, _sleep=slept.append)
    for step in range(1, 6):
        d.on_step(step)
    assert slept == [0.25], slept
    specs = [chaos_mod.parse_spec(s) for s in
             ("kill@5", "kill@7:SIGINT", "delay@3:0.5")]
    assert [type(s).__name__ for s in specs] == ["KillAtStep", "KillAtStep",
                                                 "DelayAtStep"]

    # serving-plane injectors against stub server/router objects
    class _Stub:
        requests_admitted = 0
        decode_blocks = 0
    stub = _Stub()
    kr = chaos_mod.KillReplicaAfterRequests(2)
    kr.on_decode_block(stub)          # 0 admitted: no fire
    stub.requests_admitted = 2
    try:
        kr.on_decode_block(stub)
        raise AssertionError("KillReplicaAfterRequests did not fire")
    except chaos_mod.ChaosReplicaKill:
        pass
    kr.on_decode_block(stub)          # fires once only
    slept2 = []
    dd = chaos_mod.DelayDecodeStep(3, 0.125, _sleep=slept2.append)
    for block in range(1, 6):
        stub.decode_blocks = block
        dd.on_decode_block(stub)
    assert slept2 == [0.125], slept2
    dh = chaos_mod.DropHandoff(2)
    drops = [dh.on_handoff(None) for _ in range(4)]
    assert drops == [False, True, False, False], drops
    sspecs = [chaos_mod.parse_spec(s) for s in
              ("kill-replica@2", "delay-decode@3:0.25", "drop-handoff@1")]
    assert [type(s).__name__ for s in sspecs] == [
        "KillReplicaAfterRequests", "DelayDecodeStep", "DropHandoff"]
    print("chaos selftest: kill-at-step fired once at 3; delay slept 0.25s "
          "at 2; kill-replica raised once at 2 admissions; delay-decode "
          "slept 0.125s at block 3; drop-handoff dropped exactly the 2nd; "
          "spec parsing ok")
    return 0


def _cmd_chaos_drill(args) -> int:
    """Kill-one-replica fleet drill (see tests/test_serving_fleet.py for
    the pinned version). Heavy: imports jax and compiles tiny models."""
    import json

    from bigdl_tpu.resilience.serving_drill import run_kill_drill

    report = run_kill_drill(replicas=args.replicas,
                            disaggregate=args.disaggregate,
                            requests=args.requests,
                            kill_after=args.kill_after)
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.resilience",
        description="snapshot validation + fault-injection tooling "
                    "(docs/RESILIENCE.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("validate", help="audit a checkpoint directory")
    p.add_argument("checkpoint_dir")
    p.set_defaults(fn=_cmd_validate)

    p = sub.add_parser("latest", help="print the newest complete pair")
    p.add_argument("checkpoint_dir")
    p.set_defaults(fn=_cmd_latest)

    p = sub.add_parser("chaos", help="fault injection")
    csub = p.add_subparsers(dest="chaos_cmd", required=True)
    c = csub.add_parser("corrupt", help="damage one shard file")
    c.add_argument("snapshot_dir")
    c.add_argument("--shard", type=int, default=0)
    c.add_argument("--mode", default="flip",
                   choices=["flip", "truncate", "delete"])
    c.add_argument("--seed", type=int, default=0)
    c.set_defaults(fn=_cmd_chaos_corrupt)
    c = csub.add_parser("selftest", help="deterministic injector check")
    c.set_defaults(fn=_cmd_chaos_selftest)
    c = csub.add_parser("drill",
                        help="kill-one-replica zero-loss serving drill")
    c.add_argument("--replicas", type=int, default=2)
    c.add_argument("--disaggregate", default=None, metavar="P:D",
                   help="prefill:decode split, e.g. 1:2")
    c.add_argument("--requests", type=int, default=6)
    c.add_argument("--kill-after", type=int, default=2,
                   help="kill replica 0 after it admits this many requests")
    c.set_defaults(fn=_cmd_chaos_drill)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
