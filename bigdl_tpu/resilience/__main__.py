"""CLI: ``python -m bigdl_tpu.resilience`` (``scripts/bigdl-tpu.sh chaos``).

Subcommands (all filesystem-only — no device/backend touch):

- ``validate <checkpoint_dir>``: list every snapshot pair with its
  complete/partial verdict and marker summary; exit 0 iff a resume point
  exists.
- ``latest <checkpoint_dir>``: print the newest complete (model, state)
  pair, one path per line (for shell scripting).
- ``chaos corrupt <snapshot_dir> [--shard N] [--mode flip|truncate|
  delete] [--seed S]``: deterministically damage a shard file (drills
  the partial-snapshot rejection path).
- ``chaos selftest``: exercise the injectors deterministically.
"""

from __future__ import annotations

import argparse
import sys

from bigdl_tpu.resilience import chaos as chaos_mod
from bigdl_tpu.resilience import coordinator


def _cmd_validate(args) -> int:
    import os
    pairs = coordinator.snapshot_pairs(args.checkpoint_dir)
    if not pairs:
        print(f"no snapshot pairs under {args.checkpoint_dir}")
        return 1
    any_ok = False
    for neval, _, model_name, state_name in reversed(pairs):
        model = os.path.join(args.checkpoint_dir, model_name)
        state = os.path.join(args.checkpoint_dir, state_name)
        ok = coordinator.validate_pair(model, state)
        any_ok = any_ok or ok
        marker = coordinator.read_marker(state) if ok else None
        tag = "complete" if ok else "PARTIAL "
        extra = ""
        if marker:
            mesh = marker.get("mesh") or {}
            extra = (f"  marker: step {marker.get('step')} epoch "
                     f"{marker.get('epoch')} procs "
                     f"{mesh.get('process_count')}")
        print(f"{tag}  {model_name} / {state_name}"
              f" (neval {neval}){extra}")
    return 0 if any_ok else 1


def _cmd_latest(args) -> int:
    point = coordinator.latest_resume_point(args.checkpoint_dir)
    if point is None:
        print("no complete snapshot", file=sys.stderr)
        return 1
    print(point.model_path)
    print(point.state_path)
    return 0


def _cmd_chaos_corrupt(args) -> int:
    info = chaos_mod.corrupt_snapshot(args.snapshot_dir, shard=args.shard,
                                      mode=args.mode, seed=args.seed)
    print(f"corrupted {info['file']} ({info['mode']})")
    return 0


def _cmd_chaos_selftest(args) -> int:
    del args
    fired = []
    k = chaos_mod.KillAtStep(3, sig=0, _kill=lambda pid, sig: fired.append(3))
    for step in range(1, 6):
        k.on_step(step)
    assert fired == [3], fired
    slept = []
    d = chaos_mod.DelayAtStep(2, 0.25, _sleep=slept.append)
    for step in range(1, 6):
        d.on_step(step)
    assert slept == [0.25], slept
    specs = [chaos_mod.parse_spec(s) for s in
             ("kill@5", "kill@7:SIGINT", "delay@3:0.5")]
    assert [type(s).__name__ for s in specs] == ["KillAtStep", "KillAtStep",
                                                 "DelayAtStep"]
    print("chaos selftest: kill-at-step fired once at 3; delay slept 0.25s "
          "at 2; spec parsing ok")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.resilience",
        description="snapshot validation + fault-injection tooling "
                    "(docs/RESILIENCE.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("validate", help="audit a checkpoint directory")
    p.add_argument("checkpoint_dir")
    p.set_defaults(fn=_cmd_validate)

    p = sub.add_parser("latest", help="print the newest complete pair")
    p.add_argument("checkpoint_dir")
    p.set_defaults(fn=_cmd_latest)

    p = sub.add_parser("chaos", help="fault injection")
    csub = p.add_subparsers(dest="chaos_cmd", required=True)
    c = csub.add_parser("corrupt", help="damage one shard file")
    c.add_argument("snapshot_dir")
    c.add_argument("--shard", type=int, default=0)
    c.add_argument("--mode", default="flip",
                   choices=["flip", "truncate", "delete"])
    c.add_argument("--seed", type=int, default=0)
    c.set_defaults(fn=_cmd_chaos_corrupt)
    c = csub.add_parser("selftest", help="deterministic injector check")
    c.set_defaults(fn=_cmd_chaos_selftest)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
