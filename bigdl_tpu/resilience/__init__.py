"""bigdl_tpu.resilience — preemption-aware, elastically resumable training.

TPU pods preempt; the reference framework only retried after a crash,
losing everything since the last periodic checkpoint and resuming only
on the same cluster shape (PAPER.md §5.3). This subsystem makes training
survive preemption with at-most-one-step loss:

- ``preemption``: SIGTERM/SIGINT hooks + a cooperative
  ``should_snapshot()`` flag the training loop polls at step boundaries;
  on a notice it writes one final snapshot + RESUME marker and raises
  ``TrainingPreempted``.
- ``coordinator``: discovers the newest COMPLETE snapshot (manifest-
  validated; partial writes rejected), reads/writes RESUME markers
  (step, epoch, RNG key state, data cursor, mesh shape) and detects
  elastic restarts — resuming onto a DIFFERENT process count, which the
  resharding restore in ``utils/sharded_checkpoint.py`` makes exact.
- ``chaos``: deterministic kill-at-step / delay / corrupt-shard
  injectors (``scripts/bigdl-tpu.sh chaos``) keeping the recovery paths
  honest.

Wire-up: ``Optimizer.set_preemption_handler().auto_resume()`` (see
``docs/RESILIENCE.md``); metrics ``bigdl_resilience_*`` in the telemetry
catalogue.
"""

from bigdl_tpu.resilience import chaos, coordinator
from bigdl_tpu.resilience.chaos import (DelayAtStep, KillAtStep,
                                        corrupt_snapshot)
from bigdl_tpu.resilience.coordinator import (ResumePoint, is_elastic,
                                              latest_resume_point,
                                              read_marker, validate_pair,
                                              write_marker)
from bigdl_tpu.resilience.preemption import (PreemptionHandler,
                                             TrainingPreempted)

__all__ = [
    "PreemptionHandler", "TrainingPreempted", "ResumePoint",
    "latest_resume_point", "validate_pair", "write_marker", "read_marker",
    "is_elastic", "KillAtStep", "DelayAtStep", "corrupt_snapshot",
    "chaos", "coordinator",
]
