"""The kill-one-replica serving drill (``bigdl-tpu.sh chaos drill``).

An executable statement of the fleet's zero-loss contract: build a tiny
in-process fleet behind ``LMRouter``, attach a ``KillReplicaAfterRequests``
injector to decode replica 0 so it dies mid-stream through the REAL die
path, drive a batch of concurrent greedy requests — and assert that
every request completes with output bit-identical to an unkilled
single-server reference. The pinned (fast, deterministic) version lives
in ``tests/test_serving_fleet.py``; this module is the CLI-sized knob
(``--replicas``, ``--disaggregate P:D``, ``--requests``) for poking the
drill at other fleet shapes.

Heavy: imports jax and compiles the tiny models. Everything else in
``resilience/`` stays jax-free; keep drill-only imports inside here.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["run_kill_drill"]

VOCAB = 24


def _mk_model(seed: int = 4):
    """The test-sized LM every replica shares (identical weights by
    construction — one build's replicas must agree bit-for-bit)."""
    from bigdl_tpu.models import transformer
    from bigdl_tpu.utils.rng import manual_seed

    manual_seed(seed)
    return transformer.build_lm(VOCAB, 16, 2, 32, num_layers=2, max_len=64,
                                rope=True, activation="swiglu", norm="rms",
                                tie_embeddings=True)


def _reference(ids, max_new):
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.models.generation import generate

    out = np.asarray(generate(_mk_model(), jnp.asarray(
        np.asarray(ids, np.float32)[None]), max_new, greedy=True))
    return out[0, len(ids):].astype(int).tolist()


def parse_split(spec: Optional[str]):
    """``'P:D'`` -> (prefill, decode) counts, or None for aggregated."""
    if not spec:
        return None
    p_s, sep, d_s = spec.partition(":")
    try:
        p, d = int(p_s), int(d_s)
    except ValueError:
        raise ValueError(f"bad disaggregate spec {spec!r}: expected P:D "
                         f"(e.g. 1:2)") from None
    if not sep or p < 1 or d < 1:
        raise ValueError(f"bad disaggregate spec {spec!r}: expected P:D "
                         f"with both counts >= 1")
    return p, d


def run_kill_drill(replicas: int = 2, disaggregate: Optional[str] = None,
                   requests: int = 6, kill_after: int = 2,
                   max_new: int = 6, timeout: float = 120.0) -> dict:
    """Run the drill; return a JSON-able report with ``ok`` verdict."""
    import threading

    from bigdl_tpu.models.router import LMRouter
    from bigdl_tpu.models.serving import ContinuousLMServer
    from bigdl_tpu.resilience.chaos import KillReplicaAfterRequests
    from bigdl_tpu.telemetry import MetricsRegistry, instruments

    split = parse_split(disaggregate)
    n_decode = split[1] if split else int(replicas)
    n_prefill = split[0] if split else 0
    if n_decode < 2:
        raise ValueError("the kill drill needs >= 2 decode replicas "
                         "(killing the only one proves nothing)")

    registry = MetricsRegistry()
    kill = KillReplicaAfterRequests(kill_after)
    decode = [ContinuousLMServer(_mk_model(), slots=2, max_len=48,
                                 greedy=True, decode_block=2,
                                 registry=registry,
                                 chaos=[kill] if i == 0 else None)
              for i in range(n_decode)]
    prefill = [ContinuousLMServer(_mk_model(), slots=1, max_len=48,
                                  greedy=True, registry=registry)
               for _ in range(n_prefill)]
    router = LMRouter(decode, prefill_replicas=prefill, registry=registry)

    prompts = [[(3 * i + j) % (VOCAB - 1) + 1 for j in range(2 + i % 3)]
               for i in range(int(requests))]
    results = [None] * len(prompts)
    errors = [None] * len(prompts)

    def worker(i):
        try:
            results[i] = router.submit(prompts[i], max_new, timeout=timeout)
        except Exception as e:  # the drill REPORTS losses, not crashes
            errors[i] = f"{type(e).__name__}: {e}"

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)
        mismatches = [i for i, ids in enumerate(prompts)
                      if errors[i] is None
                      and results[i] != _reference(ids, max_new)]
        lost = [i for i in range(len(prompts)) if errors[i] is not None]
        tm = instruments(registry)
        report = {
            "ok": not lost and not mismatches and kill.fired,
            "requests": len(prompts),
            "lost": [{"i": i, "error": errors[i]} for i in lost],
            "mismatched": mismatches,
            "kill_fired": kill.fired,
            "kill_after": kill_after,
            "decode_replicas": n_decode,
            "prefill_replicas": n_prefill,
            "replica_states": [r["state"] for r in
                               router.health_extra["replicas"]],
            "requeues": int(tm.router_requeues_total.value),
            "retries": int(tm.router_retries_total.value),
        }
        return report
    finally:
        router.close()
