"""Preemption notices -> cooperative end-of-step snapshots.

TPU pods are preempted with a SIGTERM and a grace window (tens of
seconds); the reference framework's only answer was the crash-then-retry
loop (``DistriOptimizer.scala:728-796``), which loses everything since
the last periodic checkpoint. This module turns the signal into a
COOPERATIVE flag: the training loop polls ``should_snapshot()`` at step
boundaries, writes one final sharded snapshot + RESUME marker
(``coordinator.write_marker``) and raises ``TrainingPreempted`` — at most
one step of work is lost, and the snapshot resumes onto a different
process count (``docs/RESILIENCE.md``).

Signal-handler discipline: the handler body only flips plain attributes
and a ``threading.Event`` — no locks shared with the metrics registry
(a registry-lock acquire inside a signal handler could deadlock against
the interrupted main thread). The ``resilience_preemptions_total``
counter is incremented by the CONSUMER (``drain_notices`` from the
training loop), not by the handler.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Optional, Sequence

logger = logging.getLogger("bigdl_tpu.resilience")

#: default grace window a platform allows between notice and kill; purely
#: advisory here (``remaining_grace`` lets snapshot code log overrun risk)
DEFAULT_GRACE_SECONDS = 30.0


class TrainingPreempted(Exception):
    """Training stopped on a preemption notice AFTER writing a resumable
    snapshot. Deliberately not retried by the optimizer's
    crash-retry loop: the host is going away — relaunch and
    ``auto_resume()`` instead."""

    def __init__(self, reason: str, snapshot: Optional[str] = None):
        super().__init__(
            f"training preempted ({reason})"
            + (f"; snapshot at {snapshot}" if snapshot else
               "; no checkpoint path configured — nothing was saved"))
        self.reason = reason
        self.snapshot = snapshot


def _parse_signals(spec: str) -> tuple:
    out = []
    for name in spec.split(","):
        name = name.strip()
        if not name:
            continue
        if not name.startswith("SIG"):
            name = "SIG" + name
        out.append(getattr(signal, name))
    return tuple(out)


class PreemptionHandler:
    """Installable SIGTERM (by default) hook with a cooperative flag.

    - ``install()``/``uninstall()``: register/restore the OS handlers
      (main thread only; off the main thread installation degrades to
      cooperative-``trigger()``-only with a warning).
    - ``should_snapshot()``: polled by the training loop at step
      boundaries.
    - ``trigger(reason)``: cooperative path — chaos injectors and tests
      preempt without involving the OS.
    - second notice while one is pending: the previous disposition is
      restored and the signal re-delivered, so an impatient platform
      still gets a prompt exit.

    Env knobs: ``BIGDL_PREEMPT_SIGNALS`` (comma list, default
    ``SIGTERM``), ``BIGDL_PREEMPT_GRACE_SECONDS`` (advisory budget for
    the final snapshot, default 30).
    """

    def __init__(self, signals: Optional[Sequence[int]] = None,
                 grace_seconds: Optional[float] = None):
        if signals is None:
            signals = _parse_signals(
                os.environ.get("BIGDL_PREEMPT_SIGNALS", "SIGTERM"))
        self.signals = tuple(signals)
        if grace_seconds is None:
            grace_seconds = float(
                os.environ.get("BIGDL_PREEMPT_GRACE_SECONDS",
                               str(DEFAULT_GRACE_SECONDS)))
        self.grace_seconds = float(grace_seconds)
        self._flag = threading.Event()
        self._reason: Optional[str] = None
        self._t_notice: Optional[float] = None
        self._notices = 0          # set by handler/trigger, read by drain
        self._drained = 0          # consumer-side counter (metrics)
        self._prev: dict = {}
        self.installed = False
        self._drain_hooks: list = []  # consumer-thread only, never the handler

    # ------------------------------------------------------------- lifecycle
    def install(self) -> "PreemptionHandler":
        if self.installed:
            return self
        try:
            for sig in self.signals:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            self.installed = True
        except ValueError:
            # signal.signal outside the main thread: cooperative-only mode
            self._prev.clear()
            logger.warning(
                "[Preemption] cannot install signal handlers off the main "
                "thread; only cooperative trigger() preemption is active")
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # non-main thread / exotic prev
                pass
        self._prev.clear()
        self.installed = False

    # ------------------------------------------------------------- the flag
    def _on_signal(self, signum, frame) -> None:
        if self._flag.is_set():
            # second notice: restore previous disposition and re-deliver —
            # the platform is out of patience, exit promptly
            self.uninstall()
            os.kill(os.getpid(), signum)
            return
        self._reason = f"signal {signal.Signals(signum).name}"
        self._t_notice = time.monotonic()
        self._notices += 1
        self._flag.set()

    def trigger(self, reason: str = "cooperative trigger") -> None:
        """Preempt without a signal (chaos injectors, tests)."""
        if not self._flag.is_set():
            self._reason = reason
            self._t_notice = time.monotonic()
            self._notices += 1
            self._flag.set()

    def should_snapshot(self) -> bool:
        return self._flag.is_set()

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    def remaining_grace(self) -> float:
        """Seconds left of the advisory grace window (inf before any
        notice) — snapshot code can log when it is about to overrun."""
        if self._t_notice is None:
            return float("inf")
        return self.grace_seconds - (time.monotonic() - self._t_notice)

    # ---------------------------------------------------------- drain hooks
    def add_drain_hook(self, fn) -> None:
        """Register a callable the SNAPSHOT PATH runs before writing the
        final snapshot (normal thread context, never the signal handler).
        The optimizer registers its dataset's ingest ``drain()`` here so
        reader/decoder threads are stopped and joined before checkpoint
        IO starts — a live ingest pipeline would race shard reads and
        device transfers against the snapshot inside the grace window."""
        if fn not in self._drain_hooks:
            self._drain_hooks.append(fn)

    def run_drain_hooks(self) -> None:
        """Run (and clear) the registered drain hooks; hook failures are
        logged, not raised — a drain error must not cost the snapshot."""
        hooks, self._drain_hooks = self._drain_hooks, []
        for fn in hooks:
            try:
                fn()
            except Exception:
                logger.exception("[Preemption] drain hook %r failed "
                                 "(continuing to snapshot)", fn)

    def drain_notices(self) -> int:
        """Notices received since the last drain — called from the
        training loop (normal thread context) to account
        ``resilience_preemptions_total`` outside the signal handler."""
        seen = self._notices
        fresh = seen - self._drained
        self._drained = seen
        return fresh
