"""Deterministic fault injection for resilience tests and drills.

Retry loops and resume coordinators rot unless something exercises them;
these injectors make the failure REPRODUCIBLE — kill exactly at step N,
stall exactly at step N, corrupt exactly the same bytes of a snapshot —
so a recovery test failing once fails every time:

- ``KillAtStep`` / ``DelayAtStep``: step-boundary injectors the
  optimizer polls (``set_chaos([...])`` or env ``BIGDL_CHAOS``,
  e.g. ``BIGDL_CHAOS="kill@5"`` or ``"delay@3:0.25,kill@7:SIGINT"``);
  a kill delivers a REAL signal to this process, driving the installed
  ``PreemptionHandler`` through the same path a platform preemption
  takes.
- ``corrupt_snapshot``: deterministic shard-file corruption (flip bytes
  seeded, truncate, or delete) against a sharded snapshot dir — what the
  partial-snapshot-rejection tests and ``scripts/bigdl-tpu.sh chaos
  corrupt`` feed the coordinator.
- SERVING-PLANE injectors (the fleet drill, ``bigdl-tpu.sh chaos
  drill``): ``KillReplicaAfterRequests`` (``kill-replica@N`` — the
  attached continuous server dies at the first decode-block boundary
  after admitting N requests, driving the REAL die path mid-stream),
  ``DelayDecodeStep`` (``delay-decode@B:S`` — stall decode block B for
  S seconds, the straggler-replica simulation), and ``DropHandoff``
  (``drop-handoff@N`` — the router's Nth shipped prefill partition
  evaporates in transit, exercising the re-ship fallback). Servers poll
  anything with an ``on_decode_block(server)`` hook; the router polls
  ``on_handoff(router)``.

jax-free; importable by the CLI on a bare host.
"""

from __future__ import annotations

import os
import signal
import time
from typing import List, Optional

__all__ = ["KillAtStep", "DelayAtStep", "KillReplicaAfterRequests",
           "DelayDecodeStep", "DropHandoff", "ChaosReplicaKill",
           "corrupt_snapshot", "parse_spec", "from_env"]


class ChaosReplicaKill(RuntimeError):
    """Raised inside a serving worker's decode dispatch by
    ``KillReplicaAfterRequests`` — lands in the server's die path
    exactly like a real decode failure (donated buffers gone, requests
    failed WITH their handoff cursors)."""


class KillAtStep:
    """Deliver ``sig`` to this process the FIRST time the training loop
    completes step ``step`` — a deterministic stand-in for the platform's
    preemption notice. ``_kill`` is injectable for selftests."""

    def __init__(self, step: int, sig: int = signal.SIGTERM, _kill=os.kill):
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        self.step = int(step)
        self.sig = int(sig)
        self.fired = False
        self._kill = _kill

    def on_step(self, neval: int) -> None:
        if not self.fired and neval >= self.step:
            self.fired = True
            self._kill(os.getpid(), self.sig)

    def __repr__(self):
        return f"KillAtStep(step={self.step}, sig={self.sig})"


class DelayAtStep:
    """Stall the host for ``seconds`` the first time step ``step``
    completes (straggler / slow-host simulation)."""

    def __init__(self, step: int, seconds: float, _sleep=time.sleep):
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        self.step = int(step)
        self.seconds = float(seconds)
        self.fired = False
        self._sleep = _sleep

    def on_step(self, neval: int) -> None:
        if not self.fired and neval >= self.step:
            self.fired = True
            self._sleep(self.seconds)

    def __repr__(self):
        return f"DelayAtStep(step={self.step}, seconds={self.seconds})"


class KillReplicaAfterRequests:
    """Kill the attached serving replica at the first decode-block
    boundary after it has admitted ``n`` requests: raises
    ``ChaosReplicaKill`` inside the worker's decode dispatch, driving
    the REAL die path (donated buffers lost, in-flight requests failed
    with their handoff cursors). The kill-one-replica drill's trigger."""

    def __init__(self, n: int):
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        self.n = int(n)
        self.fired = False

    def on_decode_block(self, server) -> None:
        if not self.fired and server.requests_admitted >= self.n:
            self.fired = True
            raise ChaosReplicaKill(
                f"chaos: replica killed after {self.n} admissions")

    def __repr__(self):
        return f"KillReplicaAfterRequests(n={self.n})"


class DelayDecodeStep:
    """Stall one decode block for ``seconds`` (straggler-replica
    simulation): sleeps inside the worker loop the first time block
    ``block`` starts, delaying every stream on the replica by exactly
    one injected pause."""

    def __init__(self, block: int, seconds: float, _sleep=time.sleep):
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        self.block = int(block)
        self.seconds = float(seconds)
        self.fired = False
        self._sleep = _sleep

    def on_decode_block(self, server) -> None:
        if not self.fired and server.decode_blocks >= self.block:
            self.fired = True
            self._sleep(self.seconds)

    def __repr__(self):
        return f"DelayDecodeStep(block={self.block}, seconds={self.seconds})"


class DropHandoff:
    """Evaporate the router's ``n``-th shipped prefill partition in
    transit (``on_handoff`` returns True exactly once): exercises the
    router's re-ship / local-prefill fallback in the disaggregated
    topology."""

    def __init__(self, n: int = 1):
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        self.n = int(n)
        self.seen = 0
        self.fired = False

    def on_handoff(self, router) -> bool:
        self.seen += 1
        if not self.fired and self.seen >= self.n:
            self.fired = True
            return True
        return False

    def __repr__(self):
        return f"DropHandoff(n={self.n})"


def corrupt_snapshot(path: str, shard: int = 0, mode: str = "flip",
                     seed: int = 0, nbytes: int = 64) -> dict:
    """Deterministically damage one shard file of a sharded snapshot dir.

    ``mode='flip'``: XOR ``nbytes`` bytes at positions drawn from
    ``default_rng(seed)`` (same seed -> same bytes, every time);
    ``'truncate'``: drop the file's second half; ``'delete'``: remove it.
    Returns a description dict (file, mode, positions) for logging."""
    import numpy as np  # heavier import kept out of module load

    from bigdl_tpu.utils.sharded_checkpoint import read_manifest

    leaves_, shards = read_manifest(path)
    del leaves_
    if shards is None:
        shards = sorted(f for f in os.listdir(path)
                        if f.startswith("shard-") and f.endswith(".npz"))
    if not 0 <= shard < len(shards):
        raise ValueError(f"shard {shard} out of range; snapshot has "
                         f"{len(shards)} shard files")
    target = os.path.join(path, shards[shard])
    info = {"file": target, "mode": mode}
    if mode == "delete":
        os.unlink(target)
        return info
    size = os.path.getsize(target)
    if mode == "truncate":
        with open(target, "r+b") as f:
            f.truncate(size // 2)
        info["truncated_to"] = size // 2
        return info
    if mode != "flip":
        raise ValueError(f"unknown mode {mode!r}; use flip|truncate|delete")
    rng = np.random.default_rng(seed)
    positions = sorted(int(p) for p in
                       rng.integers(0, max(1, size), size=min(nbytes, size)))
    with open(target, "r+b") as f:
        for pos in positions:
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0xFF]))
    info["positions"] = positions
    return info


def parse_spec(spec: str):
    """One injector from ``kind@step[:arg]``. Training-plane:
    ``kill@5``, ``kill@7:SIGINT``, ``delay@3:0.25``. Serving-plane:
    ``kill-replica@2``, ``delay-decode@3:0.25``, ``drop-handoff@1``."""
    kind, _, rest = spec.strip().partition("@")
    step_s, _, arg = rest.partition(":")
    try:
        step = int(step_s)
    except ValueError:
        raise ValueError(f"bad chaos spec {spec!r}: expected kind@step"
                         f"[:arg]") from None
    if kind == "kill":
        sig = signal.SIGTERM
        if arg:
            name = arg if arg.startswith("SIG") else "SIG" + arg
            sig = getattr(signal, name)
        return KillAtStep(step, sig)
    if kind == "delay":
        return DelayAtStep(step, float(arg or "1.0"))
    if kind == "kill-replica":
        return KillReplicaAfterRequests(step)
    if kind == "delay-decode":
        return DelayDecodeStep(step, float(arg or "1.0"))
    if kind == "drop-handoff":
        return DropHandoff(step)
    raise ValueError(f"unknown chaos injector {kind!r} in {spec!r}")


def from_env(var: str = "BIGDL_CHAOS") -> List["KillAtStep"]:
    """Injectors from a comma-separated env spec (empty -> none) — lets
    launcher-level drills inject faults without touching code."""
    spec = os.environ.get(var, "").strip()
    if not spec:
        return []
    return [parse_spec(s) for s in spec.split(",") if s.strip()]
