"""Resume coordination: discover the newest COMPLETE snapshot, reject
partial writes, and restart — possibly on a different process count.

The optimizer's crash-retry loop used to trust the newest
``model.N``/``state.N`` pair by name; a writer killed mid-save (the exact
failure preemption produces) would leave a half-written snapshot that the
retry then crashed on. This module is the validating replacement:

- ``latest_resume_point(path)`` walks snapshot pairs newest-first
  (numeric ``neval`` tag first, mtime as tie-break — the reference's
  ``getLatestFile`` order) and returns the first COMPLETE one as a
  ``ResumePoint``; partial snapshots are skipped, not fatal.
- completeness for a sharded snapshot = ``manifest.json`` present AND
  every shard file the manifest names present (manifest format 2,
  ``utils/sharded_checkpoint.py``; both the model and state dirs must
  pass, plus ``driver.json``). Shards and manifest are written via
  tmp+rename, so presence == fully written. Plain (single-file)
  snapshots: both files exist and are non-empty.
- the RESUME marker (``resume.json`` beside the state snapshot) records
  step/epoch, the loop's exact PRNG key state, the data-iterator cursor
  and the saving run's mesh shape — what ``_run_training`` needs for a
  bit-exact mid-epoch restart, and what elastic detection compares
  against the CURRENT topology (``is_elastic``). Markers are optional:
  a pair without one still resumes, epoch-granular, like before.

Filesystem-only (no jax at import): the CLI (``python -m
bigdl_tpu.resilience validate``) runs on a bare host in milliseconds.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

MARKER_NAME = "resume.json"
MARKER_FORMAT = 1


# --------------------------------------------------------------- the marker
def _marker_path(state_path: str) -> str:
    if os.path.isdir(state_path):
        return os.path.join(state_path, MARKER_NAME)
    return state_path + "." + MARKER_NAME


def write_marker(state_path: str, *, step: int, epoch: int,
                 rng_key_data: Optional[List[int]], rng_seed: int,
                 epoch_batches: int, epoch_records: int,
                 mesh: Dict[str, Any],
                 cursor_epoch: Optional[int] = None) -> str:
    """Atomically write the RESUME marker beside ``state_path`` (inside a
    sharded state dir, or as ``<file>.resume.json`` for a plain one).
    Call from process 0 only; written LAST, after the snapshot itself, so
    a marker's presence implies the saver got that far. ``cursor_epoch``
    is the epoch the batch counts refer to — at an epoch-boundary save it
    is the FINISHED epoch while ``epoch`` already names the next one, and
    the resuming loop only skips batches when they match."""
    marker = {
        "format": MARKER_FORMAT,
        "step": int(step),
        "epoch": int(epoch),
        "rng": {"key_data": rng_key_data, "seed": int(rng_seed)},
        "cursor": {"epoch": int(epoch if cursor_epoch is None
                                else cursor_epoch),
                   "epoch_batches": int(epoch_batches),
                   "epoch_records": int(epoch_records)},
        "mesh": mesh,
        "complete": True,
    }
    path = _marker_path(state_path)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(marker, f)
    os.replace(tmp, path)
    return path


def read_marker(state_path: str) -> Optional[Dict[str, Any]]:
    """The RESUME marker for a state snapshot, or None (absent marker is
    legal — pre-resilience snapshots resume epoch-granular; an unreadable
    or incomplete one reads as absent too)."""
    path = _marker_path(state_path)
    try:
        with open(path) as f:
            marker = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(marker, dict) or not marker.get("complete"):
        return None
    return marker


# ------------------------------------------------------------- completeness
def sharded_snapshot_complete(path: str) -> bool:
    """Manifest present and every shard file it names present (format 2).
    Format-1 manifests (no shard list) are complete when at least one
    shard file exists — the strongest check that format allows."""
    manifest_path = os.path.join(path, "manifest.json")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return False
    shards = (manifest.get("shards")
              if isinstance(manifest, dict) and "leaves" in manifest
              else None)
    if shards is None:
        return any(f.startswith("shard-") and f.endswith(".npz")
                   for f in os.listdir(path))
    return all(os.path.exists(os.path.join(path, s)) for s in shards)


def validate_pair(model_path: str, state_path: str) -> bool:
    """Is (model, state) a complete, restartable snapshot?"""
    if "://" in model_path:
        # scheme'd (utils/file_io) plain snapshots: existence is the
        # strongest check the handler contract offers
        from bigdl_tpu.utils import file_io
        try:
            return file_io.exists(model_path) and file_io.exists(state_path)
        except NotImplementedError:
            return True  # no exists hook — keep the legacy trust-by-name
    if os.path.isdir(model_path):
        return (sharded_snapshot_complete(model_path)
                and os.path.isdir(state_path)
                and sharded_snapshot_complete(state_path)
                and os.path.exists(os.path.join(state_path, "driver.json")))
    try:
        return (os.path.getsize(model_path) > 0
                and os.path.getsize(state_path) > 0)
    except OSError:
        return False


# ---------------------------------------------------------------- discovery
@dataclass
class ResumePoint:
    """One validated restart point under a checkpoint directory."""

    model_path: str
    state_path: str
    neval: int                                  # numeric tag; -1 = untagged
    sharded: bool
    marker: Optional[Dict[str, Any]] = field(default=None, repr=False)

    def saved_mesh(self) -> Optional[Dict[str, Any]]:
        return (self.marker or {}).get("mesh")


def _listdir(path: str) -> List[str]:
    # scheme'd checkpoint paths (utils/file_io registry) keep working for
    # PLAIN snapshot discovery; local paths stay stdlib-only so the CLI
    # does not pull the jax-backed IO layer
    if "://" in path:
        from bigdl_tpu.utils import file_io
        return file_io.listdir(path)
    return os.listdir(path)


def _mtime(path: str) -> float:
    if "://" in path:
        from bigdl_tpu.utils import file_io
        return file_io.getmtime(path)
    return os.path.getmtime(path)


def _join(base: str, name: str) -> str:
    if "://" in base:
        return base.rstrip("/") + "/" + name
    return os.path.join(base, name)


def snapshot_pairs(checkpoint_path: str) -> List[Tuple[int, float, str, str]]:
    """All (neval, mtime, model_name, state_name) snapshot pairs under
    ``checkpoint_path``, best-last (numeric tag order, mtime tie-break —
    the reference ``getLatestFile`` order, ``DistriOptimizer.scala:808``)."""
    try:
        names = set(_listdir(checkpoint_path))
    except (OSError, NotImplementedError):
        return []
    pairs = []
    for name in names:
        if name != "model" and not name.startswith("model."):
            continue
        state_name = "state" + name[len("model"):]
        if state_name not in names:
            continue
        try:
            neval = int(name[len("model."):])
        except ValueError:
            neval = -1
        try:
            mtime = _mtime(_join(checkpoint_path, name))
        except OSError:
            continue
        pairs.append((neval, mtime, name, state_name))
    pairs.sort()
    return pairs


def latest_resume_point(checkpoint_path: Optional[str]) -> Optional[ResumePoint]:
    """The newest COMPLETE snapshot pair, or None. Partial pairs (a save
    killed mid-write) are skipped in favour of the previous complete one —
    the retry/auto-resume contract that makes preemption survivable."""
    if not checkpoint_path:
        return None
    for neval, _, model_name, state_name in reversed(
            snapshot_pairs(checkpoint_path)):
        model_path = _join(checkpoint_path, model_name)
        state_path = _join(checkpoint_path, state_name)
        if not validate_pair(model_path, state_path):
            continue
        return ResumePoint(model_path=model_path, state_path=state_path,
                           neval=neval, sharded=os.path.isdir(model_path),
                           marker=read_marker(state_path))
    return None


# ------------------------------------------------------------------ elastic
def current_mesh_descriptor() -> Dict[str, Any]:
    """The CURRENT topology in marker ``mesh`` form (imports jax lazily)."""
    import jax
    return {"process_count": int(jax.process_count()),
            "device_count": int(jax.device_count()),
            "mesh_shape": None, "sync_mode": None}


def is_elastic(marker: Optional[Dict[str, Any]]) -> Optional[bool]:
    """Did the topology change between save and resume? None when the
    marker is absent or carries no mesh record (unknowable)."""
    mesh = (marker or {}).get("mesh") or {}
    if "process_count" not in mesh:
        return None
    import jax
    return (int(mesh["process_count"]) != int(jax.process_count())
            or int(mesh.get("device_count", jax.device_count()))
            != int(jax.device_count()))


# ------------------------------------------------- host-side snapshot loads
def manifest_leaf_keys(path: str) -> List[str]:
    """Leaf key paths stored in a sharded snapshot (format 1 or 2)."""
    from bigdl_tpu.utils.sharded_checkpoint import read_manifest
    leaves, _ = read_manifest(path)
    return list(leaves)


def load_snapshot_host(model_path: str, state_path: str,
                       params_template: Any, state_template: Any):
    """(params, opt_state, driver) restored to HOST values from either a
    plain or a sharded snapshot pair — the path for custom training loops
    (``apps/transformer.py --contextParallel``) that do not go through
    ``Optimizer.resume``. Templates supply the pytree structures a
    sharded restore needs."""
    import jax

    from bigdl_tpu.utils import file_io
    from bigdl_tpu.utils import sharded_checkpoint as sckpt

    if sckpt.is_sharded_checkpoint(model_path):
        none_of = lambda t: jax.tree_util.tree_map(lambda _: None, t)
        snap = sckpt.load_sharded(
            model_path, {"params": none_of(params_template),
                         "buffers": {}})
        st = sckpt.load_sharded(state_path,
                                {"optim": none_of(state_template)})
        with open(os.path.join(state_path, "driver.json")) as f:
            driver = json.load(f)
        return snap["params"], st["optim"], driver
    snap = file_io.load(model_path)
    if not isinstance(snap, dict):      # a saved Module (model_final style)
        snap = {"params": snap.parameter_tree()}
    st = file_io.load(state_path)
    return snap["params"], st["optim"], dict(st.get("driver", {}))
