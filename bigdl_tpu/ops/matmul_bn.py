"""Fused matmul + batch-norm statistics: the conv(1x1)+BN epilogue fusion.

PERF.md's single-chip analysis: the train-mode ResNet step is
bandwidth-bound, and after the fused-BN rewrite the biggest remaining
avoidable traffic is re-READING each conv output to compute BN statistics
(~5.6 GB of bf16 activations per forward at b=256). A 1x1 convolution is
exactly a matmul over (N*H*W, Cin) x (Cin, Cout) — and ~half of ResNet-50's
convs are 1x1 — so this kernel computes

    y = x @ w,   col_sum[j] = sum_m y[m, j],   col_sumsq[j] = sum_m y[m, j]^2

in ONE pass: per-column partial sums accumulate in VMEM scratch while each
output tile is still register/VMEM-resident, eliminating the separate
stats-reduction read of y. XLA cannot express this fusion (reductions don't
fuse into conv epilogues on this toolchain); Pallas can.

Grid layout: (n_blocks, m_blocks) — the LAST grid dimension iterates
fastest on TPU, so for a fixed column block j the kernel sweeps all row
blocks i, accumulating into a persistent (1, block_n) scratch that is
zeroed at i == 0 and flushed to the sums outputs at the final i.

Correctness is interpret-mode tested on CPU (tests/test_matmul_bn.py);
wiring it into the ResNet bottleneck path is gated on an on-chip A/B
(see PERF.md) — the kernel must beat XLA's native matmul by more than the
stats read it saves.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, w_ref, y_ref, sum_ref, sq_ref, acc_sum, acc_sq):
    i = pl.program_id(1)  # row block — innermost

    y = jnp.dot(x_ref[...], w_ref[...], preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _zero():
        acc_sum[...] = jnp.zeros_like(acc_sum)
        acc_sq[...] = jnp.zeros_like(acc_sq)

    acc_sum[...] += jnp.sum(y, axis=0, keepdims=True)
    acc_sq[...] += jnp.sum(y * y, axis=0, keepdims=True)
    y_ref[...] = y.astype(y_ref.dtype)

    @pl.when(i == pl.num_programs(1) - 1)
    def _flush():
        sum_ref[...] = acc_sum[...]
        sq_ref[...] = acc_sq[...]


def _pad_to(x, m: int, axis: int):
    short = m - x.shape[axis] % m
    if short == m:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, short)
    return jnp.pad(x, widths)


@functools.partial(jax.jit,
                   static_argnames=("block_m", "block_n", "interpret"))
def matmul_with_stats(x, w, block_m: int = 256, block_n: int = 256,
                      interpret: Optional[bool] = None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """``(y, col_sum, col_sumsq)`` for ``y = x @ w`` in one pass.

    x: (M, K); w: (K, N). Sums accumulate in fp32 regardless of input dtype
    (same policy as ``ops.batch_norm``). Zero-padded rows contribute zeros
    to both sums, so no masking is needed for ragged M.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    xp = _pad_to(x, block_m, 0)
    wp = _pad_to(w, block_n, 1)
    mp, np_ = xp.shape[0], wp.shape[1]

    y, s, sq = pl.pallas_call(
        _kernel,
        grid=(np_ // block_n, mp // block_m),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda j, i: (i, 0)),
            pl.BlockSpec((k, block_n), lambda j, i: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((block_m, block_n), lambda j, i: (i, j)),
            pl.BlockSpec((1, block_n), lambda j, i: (0, j)),
            pl.BlockSpec((1, block_n), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, np_), x.dtype),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, block_n), jnp.float32),
            pltpu.VMEM((1, block_n), jnp.float32),
        ],
        interpret=interpret,
    )(xp, wp)
    return y[:m, :n], s[0, :n], sq[0, :n]
