"""Fused matmul + batch-norm statistics: the conv(1x1)+BN epilogue fusion.

PERF.md's single-chip analysis: the train-mode ResNet step is
bandwidth-bound, and after the fused-BN rewrite the biggest remaining
avoidable traffic is re-READING each conv output to compute BN statistics
(~5.6 GB of bf16 activations per forward at b=256). A 1x1 convolution is
exactly a matmul over (N*H*W, Cin) x (Cin, Cout) — and ~half of ResNet-50's
convs are 1x1 — so this kernel computes

    y = x @ w,   col_sum[j] = sum_m y[m, j],   col_sumsq[j] = sum_m y[m, j]^2

in ONE pass: per-column partial sums accumulate in VMEM scratch while each
output tile is still register/VMEM-resident, eliminating the separate
stats-reduction read of y. XLA cannot express this fusion (reductions don't
fuse into conv epilogues on this toolchain); Pallas can.

Grid layout: one axis over row blocks. The whole weight matrix stays
VMEM-resident (every ResNet 1x1 weight is <= 2 MB bf16, far under VMEM),
so x streams through exactly once, y is written exactly once, and the sums
accumulate directly into their (1, N) output blocks — which Pallas keeps
resident across the sweep because their index map is constant. Any other
grid order re-streams x or w per block and the re-read can exceed the
stats read this kernel exists to save.

Correctness is interpret-mode tested on CPU (tests/test_matmul_bn.py);
wiring it into the ResNet bottleneck path is gated on an on-chip A/B
(see PERF.md) — the kernel must beat XLA's native matmul by more than the
stats read it saves.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, w_ref, y_ref, sum_ref, sq_ref):
    i = pl.program_id(0)  # row block

    y = jnp.dot(x_ref[...], w_ref[...], preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _zero():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    sum_ref[...] += jnp.sum(y, axis=0, keepdims=True)
    sq_ref[...] += jnp.sum(y * y, axis=0, keepdims=True)
    y_ref[...] = y.astype(y_ref.dtype)


def _pad_to(x, m: int, axis: int):
    short = m - x.shape[axis] % m
    if short == m:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, short)
    return jnp.pad(x, widths)


@functools.partial(jax.jit,
                   static_argnames=("block_m", "block_n", "interpret"))
def matmul_with_stats(x, w, block_m: int = 256, block_n: int = 128,
                      interpret: Optional[bool] = None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """``(y, col_sum, col_sumsq)`` for ``y = x @ w`` in one pass.

    x: (M, K); w: (K, N). Sums accumulate in fp32 regardless of input dtype
    (same policy as ``ops.batch_norm``). Zero-padded rows/cols contribute
    zeros to both sums, so no masking is needed for ragged shapes.
    ``block_n`` only pads N up to lane alignment — the full width stays
    resident per row block.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    xp = _pad_to(x, block_m, 0)
    wp = _pad_to(w, block_n, 1)
    mp, np_ = xp.shape[0], wp.shape[1]
    # Per-step VMEM: resident w + one x tile + one (block_m, N) y tile
    # (fp32 in-kernel) + fp32 accumulators. Every ResNet 1x1 fits easily.
    vmem = (k * np_ * wp.dtype.itemsize          # w, resident
            + block_m * k * xp.dtype.itemsize    # x tile
            + block_m * np_ * 4                  # y tile (fp32 compute)
            + 2 * np_ * 4)                       # sum/sumsq accumulators
    if vmem > 12 * 2 ** 20:
        raise ValueError(
            f"per-step VMEM footprint ~{vmem >> 20} MB for ({m}x{k})@"
            f"({k}x{n}) with block_m={block_m} exceeds the 12 MB budget "
            "this kernel assumes; shrink block_m or tile N upstream")

    y, s, sq = pl.pallas_call(
        _kernel,
        grid=(mp // block_m,),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda i: (i, 0)),
            pl.BlockSpec((k, np_), lambda i: (0, 0)),  # w fully resident
        ],
        out_specs=[
            pl.BlockSpec((block_m, np_), lambda i: (i, 0)),
            pl.BlockSpec((1, np_), lambda i: (0, 0)),  # resident accumulator
            pl.BlockSpec((1, np_), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, np_), x.dtype),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
        ],
        interpret=interpret,
    )(xp, wp)
    return y[:m, :n], s[0, :n], sq[0, :n]
