"""Fused 3x3-conv (stride 1, SAME) + batch-norm statistics — the Pallas
kernel that extends the conv+BN epilogue fusion past 1x1 convs.

PERF.md's bandwidth analysis: after the 1x1 fusion the remaining avoidable
HBM traffic is the BN-stats re-read of every 3x3 conv output — and the 3x3
convs carry the majority of ResNet-50's FLOPs. This kernel computes, in ONE
pass over the input,

    y = conv3x3(x, w)         (stride 1, SAME padding)
    col_sum[c]   = sum_{n,h,w} y[n,h,w,c]
    col_sumsq[c] = sum_{n,h,w} y[n,h,w,c]^2

Convolution as 9 shifted matmuls: for each tap (dy, dx), a
(H*W, Cin) @ (Cin, Cout) matmul on the MXU accumulating into the f32 output
tile — the TPU-native descendant of the reference's im2col
(``nn/NNPrimitive.scala:24``), except the "column" matrix is never
materialised: taps are VMEM slices of a zero-padded scratch copy of the
image. SAME padding happens IN VMEM (a scratch buffer per grid step), so no
padded copy of x ever hits HBM — padding in XLA would cost a full
read+write of x and erase the fusion's bandwidth win.

Grid = (N,): one image per step, weights and the (1, Cout) stat
accumulators resident across the sweep (their index maps are constant), the
next image's DMA overlapping the current matmuls. Every ResNet-50 3x3
layer's per-step footprint fits VMEM (largest: 56x56x64 at ~2.6 MB f32).

Correctness is interpret-mode tested on CPU (tests/test_conv3x3_bn.py);
dispatch is gated like the 1x1 fusion (``BIGDL_TPU_FUSED_3X3``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, w_ref, y_ref, sum_ref, sq_ref, scratch, *,
            h: int, w: int, cout: int):
    n = pl.program_id(0)
    # SAME padding in VMEM: zero the halo, copy the image into the interior.
    scratch[...] = jnp.zeros_like(scratch)
    scratch[1:h + 1, 1:w + 1, :] = x_ref[0]

    acc = jnp.zeros((h * w, cout), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            xs = scratch[dy:dy + h, dx:dx + w, :].reshape(h * w, -1)
            acc = acc + jnp.dot(xs, w_ref[dy * 3 + dx],
                                preferred_element_type=jnp.float32)

    @pl.when(n == 0)
    def _zero():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    sum_ref[...] += jnp.sum(acc, axis=0, keepdims=True)
    sq_ref[...] += jnp.sum(acc * acc, axis=0, keepdims=True)
    y_ref[0] = acc.reshape(h, w, cout).astype(y_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def conv3x3_with_stats(x, w, interpret: Optional[bool] = None
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """``(y, col_sum, col_sumsq)`` for ``y = conv3x3_same(x, w)`` in one pass.

    x: (N, H, W, Cin); w: (3, 3, Cin, Cout) HWIO. Stats accumulate in fp32
    over all N*H*W positions per output channel (the exact reductions
    train-mode BN needs).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, h, ww, cin = x.shape
    assert w.shape[:3] == (3, 3, cin), (x.shape, w.shape)
    cout = w.shape[-1]
    wk = w.reshape(9, cin, cout)

    vmem = ((h + 2) * (ww + 2) * cin * (x.dtype.itemsize + 1)  # x blk+scratch
            + 9 * cin * cout * w.dtype.itemsize                # taps, resident
            + h * ww * cout * (4 + x.dtype.itemsize)           # acc + y tile
            + 2 * cout * 4)                                    # stat residents
    if vmem > 12 * 2 ** 20:
        raise ValueError(
            f"per-step VMEM footprint ~{vmem >> 20} MB for 3x3 fusion on "
            f"({n},{h},{ww},{cin})->{cout} exceeds the 12 MB budget; "
            "use the unfused conv+BN path for this layer")

    y, s, sq = pl.pallas_call(
        functools.partial(_kernel, h=h, w=ww, cout=cout),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9, cin, cout), lambda i: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, ww, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((h + 2, ww + 2, cin), x.dtype)],
        interpret=interpret,
    )(x, wk)
    return y, s[0], sq[0]


# --------------------------------------------------- fused train-mode BN op

_DN = ("NHWC", "HWIO", "NHWC")


def _conv3x3(x, w):
    return lax.conv_general_dilated(x, w, (1, 1), ((1, 1), (1, 1)),
                                    dimension_numbers=_DN)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def conv3x3_bn_train(x, w, gamma, beta, eps, interpret=None):
    """conv3x3(SAME) + train-mode BN over (N, H, W); the forward runs the
    one-pass Pallas kernel. Returns ``(out, mean, var)`` (stats fp32,
    biased var — the ``ops.batch_norm.batch_norm_train`` contract)."""
    out, mean, var, *_ = _forward(x, w, gamma, beta, eps, interpret)
    return out, mean, var


def _forward(x, w, gamma, beta, eps, interpret):
    m = x.shape[0] * x.shape[1] * x.shape[2]
    y, s, sq = conv3x3_with_stats(x, w, interpret=interpret)
    mean = s / m
    var = jnp.maximum(sq / m - mean * mean, 0.0)
    inv = lax.rsqrt(var + eps)
    xhat = (y.astype(jnp.float32) - mean) * inv
    out = (xhat * gamma.astype(jnp.float32)
           + beta.astype(jnp.float32)).astype(x.dtype)
    return out, mean, var, y, inv


def _fwd(x, w, gamma, beta, eps, interpret):
    out, mean, var, y, inv = _forward(x, w, gamma, beta, eps, interpret)
    return (out, mean, var), (x, w, gamma, y, mean, inv)


def _bwd(eps, interpret, res, cts):
    dout, _dmean, _dvar = cts  # stats feed running buffers: non-diff
    x, w, gamma, y, mean, inv = res
    m = x.shape[0] * x.shape[1] * x.shape[2]
    dy = dout.astype(jnp.float32)
    xhat = (y.astype(jnp.float32) - mean) * inv
    dbeta = jnp.sum(dy, axis=(0, 1, 2))
    dgamma = jnp.sum(dy * xhat, axis=(0, 1, 2))
    g32 = gamma.astype(jnp.float32)
    # closed-form BN input gradient (see ops/batch_norm.py)
    dyconv = (g32 * inv / m) * (m * dy - dbeta - xhat * dgamma)
    dyconv = dyconv.astype(x.dtype)
    # conv input grad: correlate with the spatially-flipped, io-swapped taps
    w_flip = w[::-1, ::-1].swapaxes(2, 3).astype(x.dtype)
    dx = _conv3x3(dyconv, w_flip)
    # conv weight grad: batch becomes the contraction — (Cin,H,W,N) conv
    # (H,W,N,Cout) with SAME padding yields the (Cin,3,3,Cout) taps
    dw = lax.conv_general_dilated(
        x.transpose(3, 1, 2, 0), dyconv.transpose(1, 2, 0, 3),
        (1, 1), ((1, 1), (1, 1)), dimension_numbers=_DN)
    dw = dw.transpose(1, 2, 0, 3)
    return (dx, dw.astype(w.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


conv3x3_bn_train.defvjp(_fwd, _bwd)
