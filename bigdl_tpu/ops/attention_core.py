"""Functional attention cores.

New TPU-native capability — the reference has **no attention of any kind**
(SURVEY §5.7: its longest-sequence machinery is the ``Recurrent`` time-loop,
``nn/Recurrent.scala:66-135``). Attention is introduced here because
long-context support is first-class in the TPU build: this module provides
the single-device mathematical core; ``bigdl_tpu/parallel/context.py`` shards
the same computation over a mesh ``seq`` axis (ring attention / Ulysses), and
``bigdl_tpu/ops/flash_attention.py`` provides the Pallas TPU kernel.

Two formulations of softmax(QK^T/sqrt(d))V are provided:

- ``dot_product_attention`` — the plain XLA formulation. For moderate
  sequence lengths XLA already fuses this well on TPU (two MXU matmuls with
  a fused softmax between).
- ``blockwise_attention`` — the online-softmax (flash) formulation over key
  blocks via ``lax.scan``. O(S) memory in sequence length instead of O(S^2),
  and the exact recurrence ring attention distributes over devices.

Shapes follow the (batch, seq, heads, head_dim) = BSND convention; the head
axis stays adjacent to head_dim so head-parallel (tensor) sharding splits a
single array axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _mask_bias(mask: Optional[jax.Array], dtype) -> Optional[jax.Array]:
    """Boolean mask (True = attend) -> additive bias."""
    if mask is None:
        return None
    return jnp.where(mask, jnp.asarray(0.0, dtype),
                     jnp.asarray(jnp.finfo(dtype).min, dtype))


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          mask: Optional[jax.Array] = None,
                          bias: Optional[jax.Array] = None,
                          causal: bool = False,
                          scale: Optional[float] = None,
                          dropout_p: float = 0.0,
                          dropout_key: Optional[jax.Array] = None) -> jax.Array:
    """softmax(q k^T * scale + bias) v, shapes (B, S, N, D).

    ``mask``: broadcastable to (B, N, Sq, Sk), True where attention is
    allowed. ``causal`` adds the lower-triangular mask. ``dropout_p`` with
    a ``dropout_key`` applies inverted-scale dropout to the NORMALISED
    attention probabilities (torch ``nn.MultiheadAttention`` semantics —
    unbiased: E[output] equals the no-dropout output). Only this core
    takes it: the blockwise/flash paths never see normalised probabilities
    (online softmax normalises at the end), so the dispatch gate excludes
    them under attention dropout.
    """
    *_, sq, n, d = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / float(d) ** 0.5
    logits = jnp.einsum("bqnd,bknd->bnqk", q, k) * scale
    logits = logits.astype(jnp.float32)  # softmax in f32: bf16 exp loses range
    if bias is not None:
        logits = logits + bias
    mb = _mask_bias(mask, logits.dtype)
    if mb is not None:
        logits = logits + mb
    if causal:
        # Top-left alignment (query i sees keys <= i), matching the blockwise
        # core, the Pallas kernel, and torch SDPA ``is_causal``.
        cm = jnp.tril(jnp.ones((sq, sk), bool))
        logits = jnp.where(cm, logits, jnp.finfo(logits.dtype).min)
    # Fully-masked rows: softmax of all -inf would give a uniform average of
    # values; zero them instead (batch-padding masks hit this).
    dead = jnp.max(logits, axis=-1, keepdims=True) <= jnp.finfo(logits.dtype).min / 2
    weights = jax.nn.softmax(logits, axis=-1)
    weights = jnp.where(dead, 0.0, weights)
    if dropout_p > 0.0:
        if dropout_key is None:
            raise ValueError("dropout_p > 0 needs a dropout_key")
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p,
                                    weights.shape)
        weights = jnp.where(keep, weights, 0.0) / (1.0 - dropout_p)
    return jnp.einsum("bnqk,bknd->bqnd", weights.astype(q.dtype), v)


def _block_scan(q, k, v, mask_bias, causal, scale, q_offset, block_size):
    """Online-softmax scan over key blocks for one query block.

    q: (B, Sq, N, D); k/v: (B, Sk, N, D); mask_bias broadcastable
    (B, N, Sq, Sk) additive. Returns (B, Sq, N, D).

    The recurrence carries (acc, row_sum, row_max) per query position —
    identical to the flash-attention forward and to what each ring step
    folds in (parallel/context.py reuses ``online_softmax_combine``).
    """
    b, sq, n, d = q.shape
    sk = k.shape[1]
    nblocks = -(-sk // block_size)
    pad = nblocks * block_size - sk
    if pad:
        kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    else:
        kp, vp = k, v
    kb = kp.reshape(b, nblocks, block_size, n, d).transpose(1, 0, 2, 3, 4)
    vb = vp.reshape(b, nblocks, block_size, n, d).transpose(1, 0, 2, 3, 4)

    neg = jnp.finfo(jnp.float32).min
    q_pos = q_offset + jnp.arange(sq)

    def step(carry, xs):
        acc, rsum, rmax = carry
        kblk, vblk, blk_idx = xs
        k_pos = blk_idx * block_size + jnp.arange(block_size)
        logits = jnp.einsum("bqnd,bknd->bnqk", q, kblk) * scale
        logits = logits.astype(jnp.float32)
        if mask_bias is not None:
            start = blk_idx * block_size
            mb = lax.dynamic_slice_in_dim(mask_bias, start, block_size, axis=3)
            logits = logits + mb
        valid = k_pos < sk
        if causal:
            valid = valid[None, :] & (k_pos[None, :] <= q_pos[:, None])
            logits = jnp.where(valid[None, None], logits, neg)
        else:
            logits = jnp.where(valid[None, None, None, :], logits, neg)
        blk_max = jnp.max(logits, axis=-1)                    # (B,N,Sq)
        new_max = jnp.maximum(rmax, blk_max)
        p = jnp.exp(logits - new_max[..., None])              # (B,N,Sq,K)
        # Rows with every key masked so far: p would be e^0 = 1 everywhere
        # (uniform garbage); keep them empty until a live key appears.
        dead = new_max <= neg / 2
        p = jnp.where(dead[..., None], 0.0, p)
        correction = jnp.where(dead, 1.0, jnp.exp(rmax - new_max))
        blk_sum = jnp.sum(p, axis=-1)
        new_sum = rsum * correction + blk_sum
        pv = jnp.einsum("bnqk,bknd->bqnd", p, vblk.astype(jnp.float32))
        new_acc = acc * correction.transpose(0, 2, 1)[..., None] + pv
        return (new_acc, new_sum, new_max), None

    if pad and mask_bias is not None:
        mask_bias = jnp.pad(mask_bias, ((0, 0),) * 3 + ((0, pad),),
                            constant_values=neg)
    # Derive the zero carries from q so they carry q's device-varying type
    # when traced inside shard_map (vma typing rejects unvarying inits whose
    # loop outputs vary over a mesh axis).
    acc0 = jnp.zeros_like(q, dtype=jnp.float32)
    zero_bnq = jnp.sum(q * 0.0, axis=-1, dtype=jnp.float32).transpose(0, 2, 1)
    sum0 = zero_bnq
    max0 = zero_bnq + neg
    (acc, rsum, rmax), _ = lax.scan(
        step, (acc0, sum0, max0),
        (kb, vb, jnp.arange(nblocks)))
    rsum = jnp.maximum(rsum, 1e-37)  # fully-masked rows -> 0 output, not NaN
    out = acc / rsum.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mask: Optional[jax.Array] = None,
                        causal: bool = False,
                        scale: Optional[float] = None,
                        block_size: int = 512) -> jax.Array:
    """Flash-style exact attention with O(S) memory (BSND shapes)."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / float(d) ** 0.5
    mb = _mask_bias(mask, jnp.float32)
    if mb is not None:
        mb = jnp.broadcast_to(
            mb, (q.shape[0], q.shape[2], q.shape[1], k.shape[1]))
    return _block_scan(q, k, v, mb, causal, scale, 0,
                       min(block_size, k.shape[1]))


def online_softmax_combine(acc_a, sum_a, max_a, acc_b, sum_b, max_b):
    """Merge two partial attention results over disjoint key sets.

    Each partial is (acc = sum_j e^{l_j - max} v_j, row_sum, row_max) with
    acc shaped (B, Sq, N, D) and sums/maxes (B, N, Sq). Associative and
    commutative — ring attention folds per-device partials with this.
    """
    new_max = jnp.maximum(max_a, max_b)
    ca = jnp.exp(max_a - new_max)
    cb = jnp.exp(max_b - new_max)
    new_sum = sum_a * ca + sum_b * cb
    new_acc = (acc_a * ca.transpose(0, 2, 1)[..., None]
               + acc_b * cb.transpose(0, 2, 1)[..., None])
    return new_acc, new_sum, new_max


def attention_partial(q, k, v, scale, k_offset, q_offset, causal,
                      kv_valid_len=None, q_pos=None, k_pos=None):
    """Unnormalised attention of q against one key/value chunk.

    Returns (acc, row_sum, row_max) suitable for ``online_softmax_combine``.
    ``k_offset``/``q_offset`` are the global positions of the chunks'
    first elements (needed for causal masking across devices). For
    non-contiguous layouts (zigzag ring shards) pass explicit ``q_pos``/
    ``k_pos`` global-position vectors instead — they override the offsets.
    """
    b, sq, n, d = q.shape
    sk = k.shape[1]
    logits = jnp.einsum("bqnd,bknd->bnqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    neg = jnp.finfo(jnp.float32).min
    if kv_valid_len is not None:
        valid = jnp.arange(sk) < kv_valid_len
        logits = jnp.where(valid[None, None, None, :], logits, neg)
    if causal:
        if q_pos is None:
            q_pos = q_offset + jnp.arange(sq)
        if k_pos is None:
            k_pos = k_offset + jnp.arange(sk)
        cm = k_pos[None, :] <= q_pos[:, None]
        logits = jnp.where(cm[None, None], logits, neg)
    rmax = jnp.max(logits, axis=-1)                      # (B,N,Sq)
    p = jnp.exp(logits - rmax[..., None])
    # A fully-masked chunk has rmax == -inf -> p == e^0 == 1 rows; zero them.
    dead = rmax <= neg / 2
    p = jnp.where(dead[..., None], 0.0, p)
    rmax = jnp.where(dead, neg, rmax)
    rsum = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bnqk,bknd->bqnd", p, v.astype(jnp.float32))
    return acc, rsum, rmax


def finalize_partial(acc, rsum):
    rsum = jnp.maximum(rsum, 1e-37)
    return acc / rsum.transpose(0, 2, 1)[..., None]
