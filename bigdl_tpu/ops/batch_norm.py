"""Fused training batch-norm with a hand-written VJP.

Why this exists: profiling the ResNet-50 train step on a v5e chip showed
~46% of TensorCore time in ``multiply_reduce``/``convert_reduce`` fusions —
the reductions autodiff emits for batch-norm statistics and their chain
through ``mean``/``var`` (separate dependent passes over the activation for
mean, then var, then the backward's d-mean/d-var reductions). The classic
fused form cuts this to the information-theoretic minimum:

- forward: ONE pass over x computing sum(x) and sum(x*x) together
  (independent reductions fuse; ``jnp.var``'s (x - mean)**2 depends on the
  mean and forces a second pass), then one elementwise normalize pass;
- backward: ONE pass computing sum(dy) and sum(dy * xhat) together, then one
  elementwise pass for dx via the standard closed form
  ``dx = gamma * inv / N * (N*dy - sum(dy) - xhat * sum(dy*xhat))``.

Statistics accumulate in fp32 regardless of compute dtype (bf16's 8 mantissa
bits make E[x^2] - E[x]^2 useless otherwise); outputs return in the input
dtype. The ``mean``/``var`` outputs exist to feed running-stat buffers and
are non-differentiable by construction (their cotangents are ignored —
nothing in the training loss differentiates through running statistics).

Reference counterpart: ``nn/BatchNormalization.scala:50`` hand-writes the
same two-reduction backward (``backward`` sums gradOutput and
gradOutput*(x-mean) per channel) — this is its XLA-native form.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def batch_norm_train(x, gamma, beta, eps):
    """Normalize ``x`` over all axes but the last; returns
    ``(out, mean, var)`` with biased ``var`` (both fp32)."""
    out, mean, var, _, _ = _forward(x, gamma, beta, eps)
    return out, mean, var


def _forward(x, gamma, beta, eps):
    x32 = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    n = x.size // x.shape[-1]
    # sum(x) and sum(x*x) are independent -> one fused pass over x
    mean = jnp.mean(x32, axis=axes)
    meansq = jnp.mean(x32 * x32, axis=axes)
    var = jnp.maximum(meansq - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + eps)
    # Tagged so name-based remat policies can SAVE the per-channel stats
    # (tiny) while recomputing the normalize pass: recomputing the stats
    # themselves would cost a full re-read of x in the backward.
    mean = checkpoint_name(mean, "bn_stats")
    inv = checkpoint_name(inv, "bn_stats")
    xhat = (x32 - mean) * inv
    out = (xhat * gamma.astype(jnp.float32)
           + beta.astype(jnp.float32)).astype(x.dtype)
    return out, mean, var, inv, n


def _fwd(x, gamma, beta, eps):
    out, mean, var, inv, n = _forward(x, gamma, beta, eps)
    return (out, mean, var), (x, gamma, mean, inv, n)


def _bwd(eps, res, cts):
    dout, _dmean, _dvar = cts  # running-stat outputs: non-differentiable
    x, gamma, mean, inv, n = res
    dy = dout.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean) * inv
    axes = tuple(range(x.ndim - 1))
    # sum(dy) and sum(dy*xhat) are independent -> one fused pass
    dbeta = jnp.sum(dy, axis=axes)
    dgamma = jnp.sum(dy * xhat, axis=axes)
    g32 = gamma.astype(jnp.float32)
    dx = (g32 * inv / n) * (n * dy - dbeta - xhat * dgamma)
    return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


batch_norm_train.defvjp(_fwd, _bwd)
