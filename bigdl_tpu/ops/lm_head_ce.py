"""Fused LM-head cross-entropy: logits are never materialised.

The standard causal-LM tail — ``TimeDistributed(Linear(E, V)) -> LogSoftMax
-> ClassNLL`` — materialises the (B*S, V) logits (plus the normalised
log-probs and their cotangent) in HBM. At B*S = 16K, V = 32K that is ~1 GB
per array per pass, and an on-chip probe measured the head at **54% of the
whole training step** (PERF.md round 3). The reference has no analogue (its
``nn/LogSoftMax.scala`` + ``ClassNLLCriterion.scala`` pair materialises the
full activation just the same — at reference scale V is tiny).

This op computes ``mean(logsumexp(h @ W^T + b) - logit[target])`` by a
``lax.scan`` over VOCAB CHUNKS with an online (flash-style) logsumexp:

- forward: per chunk, one (N, C) matmul + running (max, sumexp, target-logit)
  — only the (N, C) chunk is ever live;
- backward (custom VJP): recompute each chunk's logits from the saved
  row logsumexp, form ``softmax - onehot`` in place, and accumulate
  ``dh`` and the per-chunk rows of ``dW``/``db``.

Matmuls run in the inputs' compute dtype (bf16 under the mixed policy);
softmax statistics and accumulations are fp32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_NEG = -1e30  # effective -inf that survives exp without NaNs


def _pad_vocab(w: jax.Array, b: jax.Array, chunk: int):
    v = w.shape[0]
    n_chunks = -(-v // chunk)
    pad = n_chunks * chunk - v
    if pad:
        w = jnp.pad(w, ((0, pad), (0, 0)))
        # padded rows get bias -inf so exp() contributes 0 mass
        b = jnp.pad(b, (0, pad), constant_values=_NEG)
    return w, b, n_chunks


def _chunk_logits(h, w, b, c, chunk):
    """(N, C) logits of chunk c in compute dtype, fp32 out."""
    w_c = lax.dynamic_slice_in_dim(w, c * chunk, chunk, axis=0)
    b_c = lax.dynamic_slice_in_dim(b, c * chunk, chunk, axis=0)
    logits = jnp.matmul(h, w_c.T.astype(h.dtype))
    return logits.astype(jnp.float32) + b_c.astype(jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _lm_head_ce(h, w, b, valid, tgt0, chunk):
    """Per-row CE over valid rows; returns (loss_sum, n_valid, lse)."""
    out, _ = _lm_head_ce_fwd(h, w, b, valid, tgt0, chunk)
    return out


def _lm_head_ce_fwd(h, w, b, valid, tgt0, chunk):
    n = h.shape[0]
    wp, bp, n_chunks = _pad_vocab(w, b, chunk)

    def body(carry, c):
        m, s, zt = carry
        logits = _chunk_logits(h, wp, bp, c, chunk)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        idx = tgt0 - c * chunk
        in_c = (idx >= 0) & (idx < chunk)
        z = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, chunk - 1)[:, None], axis=1)[:, 0]
        zt = jnp.where(in_c, z, zt)
        return (m_new, s, zt), None

    init = (jnp.full((n,), _NEG, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.full((n,), _NEG, jnp.float32))
    (m, s, zt), _ = lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(jnp.maximum(s, 1e-37))
    per_row = jnp.where(valid, lse - zt, 0.0)
    loss_sum = jnp.sum(per_row)
    n_valid = jnp.sum(valid.astype(jnp.float32))
    return (loss_sum, n_valid, lse), (h, w, b, valid, tgt0, lse)


def _lm_head_ce_bwd(chunk, res, cts):
    h, w, b, valid, tgt0, lse = res
    g_sum, _, g_lse = cts  # cotangents for (loss_sum, n_valid, lse)
    wp, bp, n_chunks = _pad_vocab(w, b, chunk)
    n, e = h.shape
    vmask = valid.astype(jnp.float32)
    # d loss_sum / d logits_c = (softmax - onehot) * valid; plus the lse
    # cotangent's softmax term (lse is also an output — g_lse is zero in
    # the criterion path but keeps the op a correct VJP in general).
    row_g = g_sum * vmask + g_lse

    def body(dh, c):
        logits = _chunk_logits(h, wp, bp, c, chunk)
        p = jnp.exp(logits - lse[:, None])
        idx = tgt0 - c * chunk
        onehot = ((jnp.arange(chunk)[None, :] == idx[:, None])
                  .astype(jnp.float32))
        g_logits = p * row_g[:, None] - onehot * (g_sum * vmask)[:, None]
        w_c = lax.dynamic_slice_in_dim(wp, c * chunk, chunk, axis=0)
        gl = g_logits.astype(h.dtype)
        dh = dh + jnp.matmul(gl, w_c.astype(h.dtype)).astype(jnp.float32)
        dw_c = jnp.matmul(gl.T, h).astype(jnp.float32)
        return dh, (dw_c, jnp.sum(g_logits, axis=0))

    dh, (dw_chunks, db_chunks) = lax.scan(
        body, jnp.zeros((n, e), jnp.float32), jnp.arange(n_chunks))
    v = w.shape[0]
    dw = dw_chunks.reshape(n_chunks * chunk, e)[:v]
    db = db_chunks.reshape(n_chunks * chunk)[:v]
    return (dh.astype(h.dtype), dw.astype(w.dtype), db.astype(b.dtype),
            np.zeros(valid.shape, dtype=jax.dtypes.float0),
            np.zeros(tgt0.shape, dtype=jax.dtypes.float0))


_lm_head_ce.defvjp(_lm_head_ce_fwd, _lm_head_ce_bwd)


def fused_lm_head_ce(hidden: jax.Array, weight: jax.Array,
                     bias: Optional[jax.Array], targets: jax.Array, *,
                     chunk: int = 16384, size_average: bool = True,
                     ignore_index: Optional[int] = None) -> jax.Array:
    """Cross-entropy of ``hidden @ weight.T + bias`` against 1-based targets.

    ``hidden``: (..., E); ``weight``: (V, E); ``targets``: hidden's leading
    shape, values in 1..V (any numeric dtype). Rows whose target equals
    ``ignore_index`` contribute nothing (and don't count toward the mean).
    Numerically equal to ``ClassNLL(LogSoftMax(logits), targets)`` without
    ever materialising (N, V) logits.
    """
    e = hidden.shape[-1]
    h2 = hidden.reshape(-1, e)
    tgt = targets.reshape(-1)
    tgt0 = tgt.astype(jnp.int32) - 1
    if ignore_index is not None:
        valid = (tgt.astype(jnp.int32) != int(ignore_index))
    else:
        valid = jnp.ones(tgt0.shape, bool)
    if bias is None:
        bias = jnp.zeros((weight.shape[0],), weight.dtype)
    chunk = min(int(chunk), weight.shape[0])
    loss_sum, n_valid, _ = _lm_head_ce(h2, weight, bias, valid, tgt0, chunk)
    if size_average:
        return loss_sum / jnp.maximum(n_valid, 1.0)
    return loss_sum
