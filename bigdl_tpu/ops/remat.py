"""Named remat policy for bandwidth-bound conv/BN models.

The tags live at the producer sites — ``nn/conv.py`` wraps conv outputs in
``checkpoint_name(out, "conv_out")`` and ``ops/batch_norm.py`` tags the BN
statistics ``"bn_stats"`` — and this is the ONE place the save-list is
spelled, so a tag rename cannot silently diverge from the policy (a stale
name in ``save_only_these_names`` saves nothing and degenerates to full
remat with no error). Consumed by ``Optimizer.set_remat("conv")`` and
bench.py's ``BIGDL_TPU_BENCH_REMAT=conv`` lever.

Measured on a real v5e (PERF.md round 3): for ResNet-50 this policy LOSES
~7% vs no remat — XLA's backward fusions already recompute the elementwise
tail — so it is an explicit memory/HBM knob, not a default.
"""

from __future__ import annotations

import jax

REMAT_SAVED_NAMES = ("conv_out", "bn_stats")


def conv_remat_policy():
    """Save conv outputs + BN statistics; recompute the elementwise tail."""
    return jax.checkpoint_policies.save_only_these_names(*REMAT_SAVED_NAMES)
