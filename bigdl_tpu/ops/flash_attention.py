"""Flash attention as Pallas TPU kernels — forward AND backward.

New capability (no reference analogue — the reference's hottest hand-written
loops are im2col/col2im, ``nn/NNPrimitive.scala``; this is the TPU build's
equivalent "hand kernel" for its hottest new op). Three kernels:

- forward: online-softmax attention tiled for VMEM. grid = (batch*heads,
  query blocks); each program holds one query tile resident and streams
  key/value tiles for its (batch, head) row; running (acc, row_sum,
  row_max) carried in f32 on the VPU, the two matmuls per tile hit the
  MXU; causal masking skips fully-masked key tiles. Emits the row
  logsumexp (LSE) alongside the output — the residual the backward needs,
  and the statistic ring attention folds across devices.
- backward dQ: grid over query tiles; recomputes p = exp(logits - lse)
  per key tile (no O(S^2) materialisation) and accumulates
  dq += (p * (dO v^T - delta)) k * scale.
- backward dK/dV: grid over key tiles; streams query tiles, accumulating
  dv += p^T dO and dk += (p * (dO v^T - delta))^T q * scale. Causal runs
  start at the diagonal query tile.

The LSE output is a first-class differentiable output: its cotangent folds
into the delta term (d lse_i / d logits_ij = p_ij, so delta_i becomes
rowsum(dO_i * O_i) - g_lse_i). Ring attention exploits exactly this to
backprop through cross-device online-softmax combines.

On CPU the kernels run in Pallas interpret mode (tests); dispatch via
``use_flash`` selects the kernel on real TPU backends.
``BIGDL_TPU_FLASH_XLA_BWD=1`` falls back to the recompute-via-XLA backward
(A/B lever; it was the only backward before round 3).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG = float(jnp.finfo(jnp.float32).min)


# ------------------------------------------------------------------ forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, *, block_k: int, sk: int,
                causal: bool, scale: float, block_q: int):
    # q_ref: (1, BQ, D); k_ref/v_ref: (1, Sk_pad, D); o_ref: (1, BQ, D);
    # l_ref: (1, 1, BQ) row logsumexp of the scaled, masked logits. The
    # LSE rides a (BH, 1, S) array so its block's penultimate dim equals
    # the array dim — the real TPU lowering rejects (1, BQ) blocks over a
    # (BH, S) array (last-two-dims divisibility rule; interpret mode does
    # not enforce it, which is how this shipped unverified in round 2).
    j = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale                # (BQ, D)
    bq, d = q.shape
    nkb = k_ref.shape[1] // block_k

    q_pos = j * block_q + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(kb, carry):
        acc, rsum, rmax = carry
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        logits = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32)
        k_pos = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        valid = k_pos < sk
        if causal:
            valid = valid & (k_pos <= q_pos)
        logits = jnp.where(valid, logits, _NEG)
        blk_max = jnp.max(logits, axis=-1)
        new_max = jnp.maximum(rmax, blk_max)
        p = jnp.exp(logits - new_max[:, None])
        dead = new_max <= _NEG / 2                      # all-masked row so far
        p = jnp.where(dead[:, None], 0.0, p)
        corr = jnp.where(dead, 1.0, jnp.exp(rmax - new_max))
        new_sum = rsum * corr + jnp.sum(p, axis=-1)
        pv = jnp.dot(p, vblk, preferred_element_type=jnp.float32)
        new_acc = acc * corr[:, None] + pv
        return new_acc, new_sum, new_max

    if causal:
        # Key tiles strictly above the diagonal contribute nothing: the last
        # key position this query tile can see is its own last row.
        last_q = j * block_q + bq - 1
        nkb_eff = lax.min(nkb, lax.div(last_q, block_k) + 1)
    else:
        nkb_eff = nkb
    acc0 = jnp.zeros((bq, d), jnp.float32)
    sum0 = jnp.zeros((bq,), jnp.float32)
    max0 = jnp.full((bq,), _NEG, jnp.float32)
    acc, rsum, rmax = lax.fori_loop(0, nkb_eff, body, (acc0, sum0, max0))
    dead = rmax <= _NEG / 2
    rsum_safe = jnp.maximum(rsum, 1e-37)
    o_ref[0] = (acc / rsum_safe[:, None]).astype(o_ref.dtype)
    # Dead rows keep the finite _NEG sentinel (NOT -inf): downstream
    # logaddexp-style combines stay NaN-free on all-masked rows.
    l_ref[0, 0] = jnp.where(dead, _NEG, rmax + jnp.log(rsum_safe))


def _flash_fwd_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    """Returns (o (B,Sq,N,D), lse (B,N,Sq) f32)."""
    b, sq, n, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # BSND -> (B*N, S, D): one grid row per (batch, head).
    qt = q.transpose(0, 2, 1, 3).reshape(b * n, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * n, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * n, sk, d)
    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, pad_k), (0, 0)))
    sq_p, sk_p = qt.shape[1], kt.shape[1]

    grid = (b * n, sq_p // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, sk=sk,
                          causal=causal, scale=scale, block_q=block_q),
        out_shape=(jax.ShapeDtypeStruct((b * n, sq_p, d), q.dtype),
                   jax.ShapeDtypeStruct((b * n, 1, sq_p), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk_p, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk_p, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
                   pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j))),
        interpret=interpret,
    )(qt, kt, vt)
    out = out[:, :sq].reshape(b, n, sq, d).transpose(0, 2, 1, 3)
    lse = lse[:, 0, :sq].reshape(b, n, sq)
    return out, lse


# ----------------------------------------------------------------- backward

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, d_ref, dq_ref, *,
                   block_k: int, sk: int, causal: bool, scale: float,
                   block_q: int):
    # Per query tile: stream key tiles, recompute p from the saved LSE.
    j = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                        # (BQ, D)
    do = do_ref[0].astype(jnp.float32)                      # (BQ, D)
    lse = l_ref[0, 0]                                       # (BQ,)
    delta = d_ref[0, 0]                                     # (BQ,)
    bq, d = q.shape
    nkb = k_ref.shape[1] // block_k
    q_pos = j * block_q + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(kb, dq):
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        logits = jnp.dot(q, kblk.T,
                         preferred_element_type=jnp.float32) * scale
        k_pos = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        valid = k_pos < sk
        if causal:
            valid = valid & (k_pos <= q_pos)
        # guard the exponent BEFORE exp (dead rows carry the _NEG sentinel;
        # the raw exponent would overflow), then mask
        expo = jnp.where(valid, logits - lse[:, None], 0.0)
        p = jnp.where(valid, jnp.exp(expo), 0.0)
        dp = jnp.dot(do, vblk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jnp.dot(ds, kblk, preferred_element_type=jnp.float32)

    if causal:
        last_q = j * block_q + bq - 1
        nkb_eff = lax.min(nkb, lax.div(last_q, block_k) + 1)
    else:
        nkb_eff = nkb
    dq = lax.fori_loop(0, nkb_eff, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, d_ref,
                    dk_ref, dv_ref, *, block_q: int, sk: int, sq: int,
                    causal: bool, scale: float, block_k: int):
    # Per key tile: stream query tiles. Padded query rows are masked out
    # explicitly (q_pos < sq): they carry the _NEG LSE sentinel, and
    # exp(logits - _NEG) = inf would otherwise poison dk/dv with inf*0=NaN
    # whenever seq is not a block_q multiple.
    jkb = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                        # (BK, D)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    nqb = q_ref.shape[1] // block_q
    k_pos = jkb * block_k + lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def body(qb, carry):
        dk, dv = carry
        qblk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        doblk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lblk = l_ref[0, 0, pl.ds(qb * block_q, block_q)]    # (BQ,)
        dblk = d_ref[0, 0, pl.ds(qb * block_q, block_q)]    # (BQ,)
        logits = jnp.dot(qblk, k.T,
                         preferred_element_type=jnp.float32) * scale
        q_pos = qb * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        valid = (k_pos < sk) & (q_pos < sq)
        if causal:
            valid = valid & (k_pos <= q_pos)
        # guard the exponent BEFORE exp: a padded/dead row's _NEG sentinel
        # would overflow to inf and inf*0 -> NaN survives jnp.where
        expo = jnp.where(valid, logits - lblk[:, None], 0.0)
        p = jnp.where(valid, jnp.exp(expo), 0.0)            # (BQ, BK)
        dv = dv + jnp.dot(p.T, doblk, preferred_element_type=jnp.float32)
        dp = jnp.dot(doblk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dblk[:, None]) * scale
        dk = dk + jnp.dot(ds.T, qblk, preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # Query tiles strictly before this key tile's first row see none of
        # its keys.
        first_qb = lax.div(jkb * block_k, block_q)
    else:
        first_qb = 0
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = lax.fori_loop(first_qb, nqb, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g_o, g_l, causal, scale, block_q, block_k,
               interpret):
    b, sq, n, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    qt = q.transpose(0, 2, 1, 3).reshape(b * n, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * n, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * n, sk, d)
    dot = g_o.transpose(0, 2, 1, 3).reshape(b * n, sq, d)
    ot = o.transpose(0, 2, 1, 3).reshape(b * n, sq, d)
    # lse/delta ride (BH, 1, S) arrays (see _fwd_kernel: the TPU lowering
    # rejects (1, BQ) blocks over a (BH, S) array).
    lt = lse.reshape(b * n, 1, sq)
    # delta_i = rowsum(dO_i * O_i) - g_lse_i (the LSE cotangent enters the
    # softmax jacobian exactly where the diagonal correction sits).
    delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1)[:, None, :]
    if g_l is not None:
        delta = delta - g_l.reshape(b * n, 1, sq).astype(jnp.float32)

    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, pad_q), (0, 0)))
        dot = jnp.pad(dot, ((0, 0), (0, pad_q), (0, 0)))
        # pad value is irrelevant (padded query rows are masked by
        # q_pos < sq in both kernels); 0 keeps the exponent finite
        lt = jnp.pad(lt, ((0, 0), (0, 0), (0, pad_q)))
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, pad_q)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, pad_k), (0, 0)))
    sq_p, sk_p = qt.shape[1], kt.shape[1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, sk=sk,
                          causal=causal, scale=scale, block_q=block_q),
        out_shape=jax.ShapeDtypeStruct((b * n, sq_p, d), q.dtype),
        grid=(b * n, sq_p // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk_p, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk_p, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qt, kt, vt, dot, lt, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, sk=sk, sq=sq,
                          causal=causal, scale=scale, block_k=block_k),
        out_shape=(jax.ShapeDtypeStruct((b * n, sk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b * n, sk_p, d), v.dtype)),
        grid=(b * n, sk_p // block_k),
        in_specs=[
            pl.BlockSpec((1, sq_p, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sq_p, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, sq_p), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, sq_p), lambda i, j: (i, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
                   pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0))),
        interpret=interpret,
    )(qt, kt, vt, dot, lt, delta)

    dq = dq[:, :sq].reshape(b, n, sq, d).transpose(0, 2, 1, 3)
    dk = dk[:, :sk].reshape(b, n, sk, d).transpose(0, 2, 1, 3)
    dv = dv[:, :sk].reshape(b, n, sk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


# ------------------------------------------------------ differentiable core

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd_lse(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _flash_lse_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret)
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    g_o, g_l = g
    q, k, v, o, lse = res
    if os.environ.get("BIGDL_TPU_FLASH_XLA_BWD"):
        # Pre-round-3 recompute path (A/B lever). Has no LSE cotangent
        # plumbing — valid only when nothing consumes lse downstream.
        from bigdl_tpu.ops.attention_core import blockwise_attention
        f = lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, causal=causal, scale=scale, block_size=block_k)
        _, vjp = jax.vjp(jax.checkpoint(f), q, k, v)
        return vjp(g_o)
    return _flash_bwd(q, k, v, o, lse, g_o, g_l, causal, scale,
                      block_q, block_k, interpret)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


# ------------------------------------------------------------- public entry

# In-model on-chip default (PERF.md round-3 crossover table): 512/512 beat
# 256/256 and 128/128 at every measured LM config, op-level AND in-model.
_DEFAULT_BLOCK = 512


def _env_block(name: str, default: int) -> int:
    """On-chip block-size tuning without code edits
    (``BIGDL_TPU_FLASH_BLOCK_Q`` / ``BIGDL_TPU_FLASH_BLOCK_K``)."""
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention, shapes (B, S, N, D); differentiable (Pallas fwd+bwd)."""
    if scale is None:
        scale = 1.0 / float(q.shape[-1]) ** 0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None:
        block_q = _env_block("BIGDL_TPU_FLASH_BLOCK_Q", _DEFAULT_BLOCK)
    if block_k is None:
        block_k = _env_block("BIGDL_TPU_FLASH_BLOCK_K", _DEFAULT_BLOCK)
    o, _ = _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret)
    return o


def flash_attention_with_lse(
        q, k, v, causal: bool = False, scale: Optional[float] = None,
        block_q: Optional[int] = None, block_k: Optional[int] = None,
        interpret: Optional[bool] = None) -> Tuple[jax.Array, jax.Array]:
    """Flash attention returning ``(o (B,S,N,D), lse (B,N,S) f32)``.

    The LSE is differentiable (its cotangent folds into the softmax
    jacobian), which is what lets ring attention run this kernel per hop
    and still train: the cross-device combine consumes both outputs.
    All-masked rows carry the finite ``float32.min`` sentinel, not -inf.
    """
    if scale is None:
        scale = 1.0 / float(q.shape[-1]) ** 0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None:
        block_q = _env_block("BIGDL_TPU_FLASH_BLOCK_Q", _DEFAULT_BLOCK)
    if block_k is None:
        block_k = _env_block("BIGDL_TPU_FLASH_BLOCK_K", _DEFAULT_BLOCK)
    return _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret)


def use_flash(q, mask) -> bool:
    """Dispatch policy for MultiHeadAttention: Pallas kernel on real TPU for
    unmasked sequences (masked paths use the XLA cores which take an
    arbitrary additive bias).

    Gate encodes the measured in-model crossover (PERF.md round-3 table,
    real v5e): at seq 512 XLA's fused attention wins (the opaque
    pallas_call costs more in lost fusion + layout copies around it than
    online softmax saves there); from seq 1024 the kernel wins in-model —
    +22% tokens/s at 1024, +50% at 2048, +87% at 4096 (blocks 512/512).
    Op-level microbenchmarks showed flash ahead even at 512 — gate on
    IN-MODEL data, not op-level.
    """
    if os.environ.get("BIGDL_TPU_DISABLE_FLASH"):
        return False
    if mask is not None:
        return False
    if jax.default_backend() != "tpu":
        return False
    seq, d = q.shape[1], q.shape[-1]
    return seq >= 1024 and d % 64 == 0
