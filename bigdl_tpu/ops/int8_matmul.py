"""Fused int8-weight matmul Pallas kernel (round 5, VERDICT #5).

Round 4 measured int8 weight-only decode at only 1.19x fp32 while the
plain bf16 cast reached 1.69x: XLA lowers ``(q.astype(bf16) * scale) @ x``
as a dequantize kernel that WRITES the bf16 weight to HBM and a matmul
that reads it back — the int8 byte saving is spent twice. This kernel
keeps the weight int8 all the way into VMEM:

- grid (out_tiles, k_tiles), K innermost: the f32 output tile lives in
  VMEM across the K sweep (one revisit chain), int8 weight tiles stream
  HBM->VMEM at 1 byte/element;
- the tile dequantizes IN REGISTERS (int8 -> bf16 is exact for |q|<=127),
  feeds the MXU with bf16, accumulates f32;
- the per-output-channel scale multiplies ONCE after the K sweep
  (``(x @ q.T) * s == x @ (q*s).T`` exactly, since s is constant per
  output row) — so the kernel is also numerically tighter than
  dequantize-then-matmul.

Decode (B=1) at real model sizes is weight-READ-bound (PERF.md round-4
decode cost model), so halving resident bytes vs bf16 should approach 2x
— the ``bench_int8`` harness in ``scripts/int8_decode_bench.py`` records
the measured number.

Round 10 made the tiling FULL-COVERAGE: the grid rounds up and Pallas
masks the partial final output tile, so any (O, K%128==0) shape takes the
kernel at the largest tile under the waste bound — V=32000 moves from
125x 256-row tiles to 32x 1024-row tiles (2.4% tail padding), and
off-quantum vocabs like Qwen2's V=151936 keep the kernel (149 tiles,
0.4% padding) instead of losing it entirely.

``int8_matmul`` falls back to the XLA dequant path off-TPU, for big-M
prefill calls, or when K is off the 128-lane quantum; used by
``nn/quantized.py``'s Linear / LMHead / MultiHeadAttention twins.
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional, Set, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Output-tile candidates, largest first: fewer grid steps = less per-step
# overhead (measured: at 368M the 256-row tiling paid ~1200 grid steps per
# decoded token and ran at half the weight-read roof). The weight block is
# (TO, K) int8 and must stay well under VMEM with double buffering.
_TO_CANDIDATES = (1024, 512, 256, 128)
_TILE_BYTES_CAP = 4 * 1024 * 1024
_M_PAD = 16  # bf16 sublane quantum

# Padded rows in the final partial tile are wasted weight-read bytes; cap
# them at 1/8 of the real output so an awkward O drops to a smaller tile
# instead of paying a mostly-empty large one (O=1100: a 1024-tile would
# read 86% garbage, the 128-tile reads 4.7%).
_WASTE_NUM, _WASTE_DEN = 1, 8


def _pick_to(out_dim: int, kdim: int) -> int:
    """Largest output tile whose int8 (TO, K) block fits the VMEM cap and
    whose final-partial-tile padding stays under the waste bound. O no
    longer has to divide the tile: the grid rounds up and Pallas masks
    the tail (OOB block reads are padded, OOB writes dropped — same
    semantics on Mosaic and in interpret mode). Returns 0 only when even
    the smallest tile would blow the VMEM cap (K > 32768)."""
    viable = [to for to in _TO_CANDIDATES if to * kdim <= _TILE_BYTES_CAP]
    if not viable:
        return 0
    for to in viable:
        waste = -out_dim % to
        if waste * _WASTE_DEN <= out_dim * _WASTE_NUM:
            return to
    # tiny / awkward O: every candidate over-pads, take the least-padded
    # (smallest) tile — still cheaper than the XLA dequant re-read
    return viable[-1]


def _kernel(x_ref, w_ref, s_ref, o_ref):
    # whole-K block per output tile: one dot, no output revisits (a
    # revisit-accumulate grid variant triggered a Mosaic compiler abort
    # when embedded in large decode programs on this toolchain)
    wt = w_ref[...].astype(jnp.bfloat16)            # int8 -> bf16 in-register
    acc = jax.lax.dot_general(
        x_ref[...], wt, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (M, TO) f32 on the MXU
    o_ref[...] = acc * s_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _int8_matmul_pallas(x2, w_q, scale_row, interpret=False):
    m, kdim = x2.shape
    out_dim = w_q.shape[0]
    to = _pick_to(out_dim, kdim)
    # ceil grid: the final output tile may be partial — Pallas pads OOB
    # reads of the weight/scale blocks and drops OOB writes of the
    # output block, so no in-kernel mask is needed
    no = (out_dim + to - 1) // to
    mp = max(_M_PAD, ((m + _M_PAD - 1) // _M_PAD) * _M_PAD)
    xp = jnp.zeros((mp, kdim), jnp.bfloat16).at[:m].set(
        x2.astype(jnp.bfloat16))
    call = pl.pallas_call(
        _kernel,
        grid=(no,),
        in_specs=[
            pl.BlockSpec((mp, kdim), lambda i: (0, 0)),
            pl.BlockSpec((to, kdim), lambda i: (i, 0)),
            pl.BlockSpec((1, to), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((mp, to), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((mp, out_dim), jnp.float32),
        interpret=interpret,
    )
    out = call(xp, w_q, scale_row.reshape(1, out_dim).astype(jnp.float32))
    return out[:m]


# shapes already warned about: the fallback is per-call (traffic), the
# warning is once per distinct (K, O) — loud, not spammy
_FALLBACK_WARNED: Set[Tuple[int, int]] = set()


def _note_lost_kernel(kdim: int, out_dim: int) -> None:
    """A decode-shaped matmul whose REDUCTION dim is off the 128-lane
    quantum silently loses the fused kernel (the output dim no longer
    matters: the ceil grid covers any O — V=32000 runs 1024-row tiles,
    Qwen2's V=151936 keeps the kernel at 0.4% tail padding). Count the
    event (``bigdl_int8_fallbacks_total`` — once per eager call, once
    per TRACE under jit: the branch runs at trace time, so the counter
    counts shapes/compilations that lost the kernel, not per-step
    dispatches) and warn ONCE per shape, naming the shape and the
    quantum so the fix (pad K) is obvious from the log line."""
    from bigdl_tpu.telemetry import get_registry, instruments
    instruments(get_registry()).int8_fallbacks_total.inc()
    key = (kdim, out_dim)
    if key in _FALLBACK_WARNED:
        return
    _FALLBACK_WARNED.add(key)
    warnings.warn(
        f"int8_matmul: K={kdim} (out_dim={out_dim}) is off the 128-lane "
        f"quantum, so the fused int8 kernel is DISABLED for this shape "
        f"and the XLA dequantize path runs instead (weight bytes re-read "
        f"at bf16, ~2x the int8 floor). Pad the reduction dimension to a "
        f"multiple of 128 (e.g. pad the embed dim) to recover the "
        f"kernel.", RuntimeWarning, stacklevel=3)


def kernel_applicable(m: int, kdim: int, out_dim: int) -> bool:
    """Tiling gate: K must sit on the 128-lane quantum and the whole-K
    int8 weight block must fit VMEM at the smallest tile (K <= 32768).
    ANY output dim qualifies — the ceil grid masks the partial final
    tile. M is capped — for big-M prefill/batch the weight read
    amortizes and XLA's path is fine, while the kernel's fixed (M_pad, K)
    x-tile residency would bloat."""
    return (kdim % 128 == 0 and m <= 256
            and _pick_to(out_dim, kdim) > 0)


def int8_matmul(x: jax.Array, w_q: jax.Array, scale: jax.Array,
                bias: Optional[jax.Array] = None,
                compute_dtype=jnp.bfloat16) -> jax.Array:
    """``y = x @ (w_q * scale).T (+ bias)`` with w_q int8 (O, K) and a
    per-output-channel ``scale`` broadcastable to (O, 1). Dispatches to
    the fused Pallas kernel on TPU when the tiling divides; XLA
    dequant-then-matmul otherwise. Output in ``compute_dtype``."""
    lead = x.shape[:-1]
    kdim = x.shape[-1]
    out_dim = w_q.shape[0]
    x2 = x.reshape(-1, kdim)
    m = x2.shape[0]
    scale_row = jnp.asarray(scale).reshape(out_dim)
    interpret = jax.default_backend() != "tpu"
    if kernel_applicable(m, kdim, out_dim) and (not interpret or m <= 32):
        # off-TPU the interpreter is slow — only worth it at test sizes
        y = _int8_matmul_pallas(x2, w_q, scale_row, interpret=interpret)
        y = y.astype(compute_dtype)
    else:
        if m <= 256 and kdim % 128 != 0:
            # decode-shaped call that lost the kernel BECAUSE K is off
            # the lane quantum (a VMEM-capped K > 32768 is a deliberate
            # exclusion padding can't fix, and big-M calls amortize the
            # weight read anyway): loud once, counted per trace
            _note_lost_kernel(kdim, out_dim)
        w = w_q.astype(compute_dtype) * scale_row[:, None].astype(
            compute_dtype)
        y = jnp.matmul(x2.astype(compute_dtype), w.T)
    if bias is not None:
        # bias stays in ITS dtype (fp32 buffer): the add promotes the
        # output to fp32, matching the unfused twins' numerics — logits
        # argmax is sensitive to a bf16 downcast here
        y = y + bias
    return y.reshape(*lead, out_dim)
