"""Fused 1x1-conv + train-mode batch-norm: forward runs the Pallas
``matmul_with_stats`` kernel (one pass produces the conv output AND its BN
statistics — no separate stats read of the activation), normalize is one
elementwise pass, and the backward reuses the closed-form BN gradient
(``ops/batch_norm.py``) followed by plain matmul grads.

This is the composition PERF.md identifies as the next single-chip lever;
the ResNet builder adopts it behind ``BIGDL_TPU_FUSED_1X1=1``
(``models/resnet.py``) pending an on-chip A/B.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.matmul_bn import matmul_with_stats


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def conv1x1_bn_train(x2d, w, gamma, beta, eps, interpret=None):
    """``x2d`` (M, K) @ ``w`` (K, N), batch-normalized over M with batch
    statistics; returns ``(out, mean, var)`` (stats fp32, biased var —
    the same contract as ``ops.batch_norm.batch_norm_train``)."""
    out, mean, var, *_ = _forward(x2d, w, gamma, beta, eps, interpret)
    return out, mean, var


def _forward(x2d, w, gamma, beta, eps, interpret):
    m = x2d.shape[0]
    y, s, sq = matmul_with_stats(x2d, w, interpret=interpret)
    mean = s / m
    var = jnp.maximum(sq / m - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (y.astype(jnp.float32) - mean) * inv
    out = (xhat * gamma.astype(jnp.float32)
           + beta.astype(jnp.float32)).astype(x2d.dtype)
    return out, mean, var, y, inv


def _fwd(x2d, w, gamma, beta, eps, interpret):
    out, mean, var, y, inv = _forward(x2d, w, gamma, beta, eps, interpret)
    return (out, mean, var), (x2d, w, gamma, y, mean, inv)


def _bwd(eps, interpret, res, cts):
    dout, _dmean, _dvar = cts  # stats feed running buffers: non-diff
    x2d, w, gamma, y, mean, inv = res
    m = x2d.shape[0]
    dy = dout.astype(jnp.float32)
    xhat = (y.astype(jnp.float32) - mean) * inv
    dbeta = jnp.sum(dy, axis=0)
    dgamma = jnp.sum(dy * xhat, axis=0)
    g32 = gamma.astype(jnp.float32)
    # closed-form BN input gradient (see ops/batch_norm.py), then the
    # matmul transposes
    dyconv = (g32 * inv / m) * (m * dy - dbeta - xhat * dgamma)
    dyconv = dyconv.astype(x2d.dtype)
    dx = dyconv @ w.T
    dw = x2d.T @ dyconv
    return (dx, dw.astype(w.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


conv1x1_bn_train.defvjp(_fwd, _bwd)
