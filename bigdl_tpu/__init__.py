"""bigdl_tpu — a TPU-native deep-learning framework with BigDL's capabilities.

A ground-up rebuild of BigDL's feature surface (Torch-style layer zoo,
DataSet/Transformer pipeline, Optimizer facade with triggers/validation,
distributed synchronous SGD, TensorBoard summaries, checkpoint/resume,
Torch/Caffe import) designed TPU-first:

- compute is JAX/XLA: every training/inference step is a traced, jit-compiled
  SPMD program (vs. the reference's interpreted per-layer JVM execution,
  reference ``optim/DistriOptimizer.scala``),
- distribution is a `jax.sharding.Mesh` + XLA collectives over ICI/DCN
  (vs. the reference's Spark BlockManager all-reduce,
  reference ``parameters/AllReduceParameter.scala``),
- hot ops lower to the MXU via XLA or Pallas kernels (vs. MKL JNI,
  reference ``tensor/TensorNumeric.scala``).

Public surface mirrors the reference's (``com.intel.analytics.bigdl``):

    import bigdl_tpu as bt
    model = bt.nn.Sequential()(...)
    opt = bt.optim.Optimizer(model, dataset, bt.nn.ClassNLLCriterion())
    opt.set_end_when(bt.optim.Trigger.max_epoch(10)).optimize()
"""

from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.tensor import Tensor
from bigdl_tpu import nn
from bigdl_tpu import optim
from bigdl_tpu import dataset
from bigdl_tpu import parallel
from bigdl_tpu import utils
from bigdl_tpu import visualization
from bigdl_tpu import interop
from bigdl_tpu import ml
from bigdl_tpu import telemetry

__version__ = "0.1.0"

__all__ = [
    "Engine", "Table", "T", "Tensor",
    "nn", "optim", "dataset", "parallel", "utils", "visualization", "interop",
    "ml", "telemetry",
    "__version__",
]
