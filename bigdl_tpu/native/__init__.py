"""Native C++ runtime components (reference §2.9: the BigDL-core MKL JNI
library, ``com.intel.analytics.bigdl.mkl.MKL``).

On TPU the math the reference routed to MKL (gemm/gemv/VML) lowers to the MXU
via XLA/Pallas, so the native layer's job shifts to the *runtime around* the
compute path — exactly the pieces the reference kept native or
native-adjacent:

- ``bt_crc32c``     — CRC32C for TFRecord framing (``java/netty/Crc32c.java``)
- ``bt_fp32_to_bf16`` / ``bt_bf16_to_fp32`` / ``bt_bf16_add`` /
  ``bt_bf16_accumulate`` — the bf16 compression codec
  (``parameters/FP16CompressedTensor.scala``: fp32 truncated to its top
  16 bits, multithreaded compress/decompress/add)
- ``bt_kth_largest`` — quickselect (``utils/Util.scala:20``)
- ``bt_set_num_threads`` — thread control (``MKL.setNumThreads``)
- ``bt_shard_scan`` — packed-shard index + multithreaded CRC verify, the
  bulk-ingest fast path (reference: Hadoop SequenceFile reading +
  ``MTLabeledBGRImgToBatch``'s multithreaded decode)
- ``bt_decode_normalize`` — threaded whole-batch u8->f32 decode with fused
  per-channel normalize (the decode half of
  ``MTLabeledBGRImgToBatch.scala``; used by
  ``dataset.image.NativeBGRBatchDecoder``)

Bound via ctypes (no pybind11). The shared library is compiled lazily from
``src/*.cc`` with g++ on first import and cached next to the sources; if no
toolchain is available, ``lib`` is None and every caller falls back to a pure
Python/numpy path — the framework never hard-requires the native build.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

logger = logging.getLogger("bigdl_tpu.native")

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_NAME = "libbigdl_tpu_native.so"
_lock = threading.Lock()
_build_attempted = False

lib: Optional[ctypes.CDLL] = None


def _candidate_paths():
    yield os.path.join(os.path.dirname(__file__), _LIB_NAME)
    cache = os.environ.get("BIGDL_TPU_NATIVE_CACHE",
                           os.path.join(tempfile.gettempdir(),
                                        "bigdl_tpu_native"))
    yield os.path.join(cache, _LIB_NAME)


def _sources():
    return sorted(
        os.path.join(_SRC_DIR, f) for f in os.listdir(_SRC_DIR)
        if f.endswith(".cc"))


def _stale(path: str) -> bool:
    try:
        built = os.path.getmtime(path)
    except OSError:
        return True
    return any(os.path.getmtime(s) > built for s in _sources())


def _compile(unique: bool = False) -> Optional[str]:
    """Build the shared library; ``unique=True`` writes to a fresh filename
    (dlopen caches by pathname — rebuilding over a path this process already
    loaded would hand back the stale mapping)."""
    cxx = os.environ.get("CXX", "g++")
    for out_path in _candidate_paths():
        out_dir = os.path.dirname(out_path)
        if unique:
            out_path = os.path.join(
                out_dir, f"libbigdl_tpu_native-{os.getpid()}.so")
        try:
            os.makedirs(out_dir, exist_ok=True)
            cmd = [cxx, "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
                   "-o", out_path] + _sources()
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            return out_path
        except (OSError, subprocess.SubprocessError) as e:
            logger.debug("native build failed at %s: %s", out_path, e)
    return None


def _bind(path: str) -> ctypes.CDLL:
    dll = ctypes.CDLL(path)
    dll.bt_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    dll.bt_crc32c.restype = ctypes.c_uint32
    fp = ctypes.POINTER(ctypes.c_float)
    u16 = ctypes.POINTER(ctypes.c_uint16)
    dll.bt_fp32_to_bf16.argtypes = [fp, u16, ctypes.c_size_t]
    dll.bt_bf16_to_fp32.argtypes = [u16, fp, ctypes.c_size_t]
    dll.bt_bf16_add.argtypes = [u16, u16, ctypes.c_size_t]
    dll.bt_bf16_accumulate.argtypes = [fp, u16, ctypes.c_size_t]
    dll.bt_set_num_threads.argtypes = [ctypes.c_int]
    dll.bt_kth_largest.argtypes = [ctypes.POINTER(ctypes.c_double),
                                   ctypes.c_size_t, ctypes.c_size_t]
    dll.bt_kth_largest.restype = ctypes.c_double
    u64 = ctypes.POINTER(ctypes.c_uint64)
    dll.bt_shard_scan.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                  u64, u64, ctypes.c_size_t, ctypes.c_int]
    dll.bt_shard_scan.restype = ctypes.c_int64
    u8 = ctypes.POINTER(ctypes.c_uint8)
    dll.bt_decode_normalize.argtypes = [
        u8, ctypes.c_int64, ctypes.c_int64, fp, fp, ctypes.c_int, fp,
        ctypes.c_int]
    return dll


def load(force_rebuild: bool = False) -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global lib, _build_attempted
    with _lock:
        if lib is not None and not force_rebuild:
            return lib
        if _build_attempted and not force_rebuild:
            return lib
        _build_attempted = True
        if os.environ.get("BIGDL_TPU_DISABLE_NATIVE"):
            return None
        path = None
        if not force_rebuild:
            for cand in _candidate_paths():
                if os.path.exists(cand) and not _stale(cand):
                    path = cand
                    break
        compiled_fresh = False
        if path is None:
            path = _compile()
            compiled_fresh = True
        if path is not None:
            try:
                lib = _bind(path)
                logger.info("native library loaded from %s", path)
            except (OSError, AttributeError) as e:
                # AttributeError = a cached .so that predates a newly added
                # symbol but passed the mtime staleness check; rebuild once
                # rather than crashing every native caller.
                lib = None
                if not compiled_fresh:
                    # unique filename: dlopen already cached the stale
                    # mapping under the original path for this process
                    logger.info("native library at %s is stale/unloadable "
                                "(%s); rebuilding", path, e)
                    stale_path = path
                    path = _compile(unique=True)
                    if path is not None:
                        try:
                            lib = _bind(path)
                        except (OSError, AttributeError) as e2:
                            logger.warning("native rebuild failed: %s", e2)
                        else:
                            # Replace the stale base .so so later processes
                            # load the fixed library directly instead of each
                            # repeating the AttributeError + full rebuild.
                            try:
                                os.replace(path, stale_path)
                            except OSError:
                                pass
                else:
                    logger.warning("native library load failed: %s", e)
        return lib


def is_loaded() -> bool:
    """Reference ``MKL.isMKLLoaded`` equivalent."""
    return load() is not None


def set_num_threads(n: int) -> None:
    """Reference ``MKL.setNumThreads`` equivalent."""
    dll = load()
    if dll is not None:
        dll.bt_set_num_threads(int(n))


# NOTE: no eager load() here — the first actual native use (crc32c, codec,
# Engine.init) triggers the build, keeping `import bigdl_tpu` free of
# subprocess compiles.
