// Packed-shard scanner — native ingest for dataset/shards.py (the TPU
// build's answer to the reference's Hadoop SequenceFile reader +
// MTLabeledBGRImgToBatch multithreaded decode: BigDL keeps bulk-record IO
// off the interpreter; here a single C++ pass indexes and CRC-verifies a
// whole shard instead of a Python loop framing record-by-record).
//
// Framing (visualization/tensorboard.py RecordWriter, TFRecord-compatible):
//   uint64 length (LE) | uint32 masked_crc32c(length bytes)
//   payload            | uint32 masked_crc32c(payload)
// masked_crc = rotr15(crc32c(x)) + 0xa282ead8 (mod 2^32).
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" uint32_t bt_crc32c(const uint8_t* data, size_t n);  // crc32c.cc

namespace {

constexpr uint32_t kMaskDelta = 0xa282ead8u;

inline uint32_t masked(uint32_t crc) {
  return ((crc >> 15) | (crc << 17)) + kMaskDelta;
}

inline uint32_t load_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t load_u64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

extern "C" {

// Scan a whole in-memory shard, writing each payload's (offset, length)
// into the caller-provided arrays.  Returns the record count, or
//   -1  corrupt record header (masked length-CRC mismatch)
//   -2  corrupt record payload (masked payload-CRC mismatch)
//   -3  more than max_records records
// A truncated tail (crashed writer) terminates the scan cleanly, matching
// FileReader.read_records.  Header CRCs are checked inline (12 bytes each);
// payload CRCs are verified across records with std::thread when
// validate != 0.
int64_t bt_shard_scan(const uint8_t* buf, size_t n, uint64_t* offsets,
                      uint64_t* lengths, size_t max_records, int validate) {
  size_t pos = 0, count = 0;
  while (n - pos >= 12) {
    uint64_t len = load_u64(buf + pos);
    if (validate && masked(bt_crc32c(buf + pos, 8)) != load_u32(buf + pos + 8))
      return -1;
    size_t body = pos + 12;
    if (len > n - body || n - body - len < 4) break;  // truncated tail
    if (count >= max_records) return -3;
    offsets[count] = body;
    lengths[count] = len;
    ++count;
    pos = body + len + 4;
  }
  if (validate && count) {
    unsigned hw = std::thread::hardware_concurrency();
    size_t t = hw ? hw : 1;
    if (t > count) t = count;
    if (t > 16) t = 16;
    std::vector<int> bad(t, 0);
    std::vector<std::thread> workers;
    size_t chunk = (count + t - 1) / t;
    for (size_t i = 0; i < t; ++i) {
      size_t lo = i * chunk, hi = lo + chunk < count ? lo + chunk : count;
      if (lo >= hi) break;
      workers.emplace_back([&, lo, hi, i] {
        for (size_t r = lo; r < hi; ++r) {
          const uint8_t* p = buf + offsets[r];
          if (masked(bt_crc32c(p, lengths[r])) != load_u32(p + lengths[r]))
            bad[i] = 1;
        }
      });
    }
    for (auto& w : workers) w.join();
    for (size_t i = 0; i < t; ++i)
      if (bad[i]) return -2;
  }
  return static_cast<int64_t>(count);
}

}  // extern "C"
