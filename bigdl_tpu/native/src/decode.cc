// Threaded image-record decode/normalize (round 5, VERDICT #2).
//
// The reference's ingest answer is a threaded JVM pipeline
// (dataset/image/MTLabeledBGRImgToBatch.scala: worker threads each decode
// + normalize records, batches assemble downstream). Round 4 measured the
// Python per-record path at ~1 ms/record — 6.7x under the chip's demand.
// This kernel moves the whole batch's decode into one native call:
//
//   u8 interleaved BGR -> f32, fused (x - mean[c]) * (1/std[c]),
//   written straight into the caller's (N, H*W*3) batch buffer,
//   std::thread-parallel over records, inner loop written for the
//   compiler's auto-vectorizer (contiguous, no branches).

#include <cstdint>
#include <cstddef>
#include <thread>
#include <vector>
#include <algorithm>

extern "C" {

// One record: len bytes of interleaved C-channel data.
static void decode_one(const uint8_t* in, float* out, int64_t len,
                       const float* mean, const float* rstd, int channels) {
  if (channels == 3) {
    const float m0 = mean[0], m1 = mean[1], m2 = mean[2];
    const float r0 = rstd[0], r1 = rstd[1], r2 = rstd[2];
    int64_t px = len / 3;
    for (int64_t p = 0; p < px; ++p) {
      out[3 * p + 0] = (static_cast<float>(in[3 * p + 0]) - m0) * r0;
      out[3 * p + 1] = (static_cast<float>(in[3 * p + 1]) - m1) * r1;
      out[3 * p + 2] = (static_cast<float>(in[3 * p + 2]) - m2) * r2;
    }
  } else {
    const float m = mean[0], r = rstd[0];
    for (int64_t i = 0; i < len; ++i)
      out[i] = (static_cast<float>(in[i]) - m) * r;
  }
}

// in: n contiguous records of rec_len bytes; out: n * rec_len floats.
// mean/rstd: per-channel mean and RECIPROCAL std (channels entries).
void bt_decode_normalize(const uint8_t* in, int64_t n, int64_t rec_len,
                         const float* mean, const float* rstd, int channels,
                         float* out, int threads) {
  if (n <= 0 || rec_len <= 0) return;
  int nt = std::max(1, threads);
  nt = static_cast<int>(std::min<int64_t>(nt, n));
  if (nt == 1) {
    for (int64_t i = 0; i < n; ++i)
      decode_one(in + i * rec_len, out + i * rec_len, rec_len, mean, rstd,
                 channels);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(nt);
  for (int t = 0; t < nt; ++t) {
    pool.emplace_back([=]() {
      for (int64_t i = t; i < n; i += nt)
        decode_one(in + i * rec_len, out + i * rec_len, rec_len, mean, rstd,
                   channels);
    });
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
