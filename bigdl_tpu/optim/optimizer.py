"""Optimizer facade + single-chip training loop
(reference ``optim/Optimizer.scala:42`` factory at ``:278-333``,
``optim/LocalOptimizer.scala:39``).

Where the reference's LocalOptimizer clones one model replica per core and
hand-reduces their gradients (``LocalOptimizer.scala:52-141``), the TPU loop
is **one jitted step**: forward + backward (autodiff) + optimizer update fused
into a single XLA program, donated buffers, no host round-trips except the
scalar loss. Intra-chip parallelism is XLA's job, not a thread pool's.

The facade keeps the reference's builder surface: ``set_validation``,
``set_checkpoint``, ``set_train_summary``, ``set_state``, ``set_optim_method``,
``set_end_when``, ``optimize()``.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.base import (AbstractDataSet, DistributedDataSet,
                                    MiniBatch, SampleToBatch)
from bigdl_tpu.nn.criterion import Criterion
from bigdl_tpu.nn.module import Module, functional_apply
from bigdl_tpu.optim.methods import OptimMethod, SGD
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.triggers import Trigger
from bigdl_tpu.optim.validation import ValidationMethod
from bigdl_tpu.resilience.preemption import (PreemptionHandler,
                                             TrainingPreempted)
from bigdl_tpu.telemetry import get_registry, instruments, span
from bigdl_tpu.telemetry import profiling
from bigdl_tpu.telemetry.profiling import sample_device_memory, tracked_jit
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.rng import RandomGenerator
from bigdl_tpu.utils.table import Table, T

logger = logging.getLogger("bigdl_tpu.optim")


def _regularizer_pairs(model: Module):
    """[(path_tuple, Regularizer)] for params with an attached regularizer."""
    import jax.tree_util as jtu
    reg_leaves, reg_treedef = jtu.tree_flatten(
        model.regularizer_tree(), is_leaf=lambda x: x is None or hasattr(x, "loss"))
    param_paths = [p for p, _ in jtu.tree_flatten_with_path(model.parameter_tree())[0]]
    out = []
    for path, reg in zip(param_paths, reg_leaves):
        if reg is not None:
            out.append((path, reg))
    return out


def _reg_loss(params, reg_pairs):
    import jax.tree_util as jtu
    if not reg_pairs:
        return 0.0
    by_path = {tuple(str(k) for k in p): r for p, r in reg_pairs}
    total = 0.0
    for path, leaf in jtu.tree_flatten_with_path(params)[0]:
        key = tuple(str(k) for k in path)
        if key in by_path:
            total = total + by_path[key].loss(leaf)
    return total


def make_grad_clipper(clip):
    """Gradient-clip transform from an Optimizer's ``_grad_clip`` setting —
    a dict with optional ``"constant": (lo, hi)`` (elementwise clamp) and
    ``"l2": max_norm`` (global-L2 rescale) entries. Both may be active at
    once (reference: independent parameter processors); the clamp applies
    FIRST, then the norm bound, so the L2 guarantee always holds on the
    final gradient. ``None``/empty: identity. For the ZeRO-1 sharded
    plane, pass ``axis_name`` so the squared norm reduces across the
    slice shards (each device holds 1/P of the flat gradient)."""
    if not clip:
        return lambda g, axis_name=None, valid_mask=None: g
    const = clip.get("constant")
    max_norm = clip.get("l2")

    def apply(g, axis_name=None, valid_mask=None):
        if const is not None:
            lo, hi = const
            g = jax.tree_util.tree_map(lambda x: jnp.clip(x, lo, hi), g)
        if valid_mask is not None:
            # flat-vector padding lanes (ZeRO-1): a clamp range excluding 0
            # would lift the pad zeros and pollute the global norm below
            g = jax.tree_util.tree_map(lambda x: x * valid_mask, g)
        if max_norm is not None:
            leaves = jax.tree_util.tree_leaves(g)
            gn_sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in leaves)
            if axis_name is not None:
                gn_sq = jax.lax.psum(gn_sq, axis_name)
            scale = jnp.minimum(1.0, max_norm
                                * jax.lax.rsqrt(jnp.maximum(gn_sq, 1e-24)))
            g = jax.tree_util.tree_map(
                lambda x: (x * scale).astype(x.dtype), g)
        return g

    return apply


def make_training_loss_fn(model, criterion, policy, reg_pairs, remat,
                          buffers, rng, data, labels):
    """The ONE training loss closure shared by every step builder (local,
    distributed allreduce, ZeRO-1 sharded): precision cast -> functional
    forward (optionally rematerialized via ``jax.checkpoint``) -> criterion
    + regularizer, returning ``(loss, (new_buffers, raw_loss))``."""
    def forward(p, data):
        from bigdl_tpu.ops.precision import cast_tree
        p_c = policy.cast_params_for_compute(p)
        out, new_buf = functional_apply(model, p_c, buffers, data,
                                        training=True, rng=rng)
        return out, cast_tree(new_buf, jnp.float32)

    if remat == "conv":
        from bigdl_tpu.ops.remat import conv_remat_policy
        fwd = jax.checkpoint(forward, policy=conv_remat_policy())
    elif remat:
        fwd = jax.checkpoint(forward)
    else:
        fwd = forward

    def loss_fn(p):
        out, new_buf = fwd(p, data)
        loss = criterion.apply(out, labels).astype(jnp.float32)
        return loss + _reg_loss(p, reg_pairs), (new_buf, loss)

    return loss_fn


class Optimizer:
    """Facade/factory (reference ``Optimizer.scala:278-333``): constructing
    ``Optimizer(model, dataset, criterion)`` yields a LocalOptimizer or — for
    a DistributedDataSet — a DistriOptimizer.

    Examples::

        >>> import numpy as np
        >>> from bigdl_tpu import nn
        >>> from bigdl_tpu.dataset.base import DataSet, Sample, SampleToBatch
        >>> from bigdl_tpu.optim import SGD, Trigger
        >>> rng = np.random.RandomState(0)
        >>> ds = (DataSet.array([Sample(rng.randn(4).astype(np.float32),
        ...                             float(i % 2 + 1))
        ...                      for i in range(32)]) >> SampleToBatch(16))
        >>> model = (nn.Sequential().add(nn.Linear(4, 2))
        ...          .add(nn.LogSoftMax()))
        >>> opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
        ...        .set_optim_method(SGD(learningrate=0.1))
        ...        .set_end_when(Trigger.max_iteration(2)))
        >>> type(opt).__name__
        'LocalOptimizer'
        >>> trained = opt.optimize()
        >>> trained is model
        True
    """

    def __new__(cls, model: Module = None, dataset: AbstractDataSet = None,
                criterion: Criterion = None, **kwargs):
        if (cls is Optimizer and dataset is not None
                and dataset.is_distributed()):
            from bigdl_tpu.parallel.distri_optimizer import DistriOptimizer
            return super().__new__(DistriOptimizer)
        if cls is Optimizer:
            return super().__new__(LocalOptimizer)
        return super().__new__(cls)

    def __init__(self, model: Module, dataset: AbstractDataSet,
                 criterion: Criterion, **kwargs):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger = Trigger.max_epoch(10)
        self.validation_trigger: Optional[Trigger] = None
        self.validation_dataset: Optional[AbstractDataSet] = None
        self.validation_methods: Optional[List[ValidationMethod]] = None
        self.checkpoint_path: Optional[str] = None
        self.checkpoint_trigger: Optional[Trigger] = None
        self.is_overwrite = False
        self.train_summary = None
        self.validation_summary = None
        self.state: Table = T()
        self.metrics = Metrics()
        self._resume_from: Optional[Tuple[str, str]] = None
        self._profile: Optional[Tuple[str, int, int]] = None
        self._remat = False
        self._grad_clip = {}
        self._steps_per_dispatch = 1
        self._eval_cache = {}  # validation scorer jit, traced once
        # resilience (bigdl_tpu/resilience, docs/RESILIENCE.md)
        self._preemption: Optional[PreemptionHandler] = None
        self._auto_resume = False
        self._chaos: List = []
        self._loop_cursor: Optional[Dict] = None  # data-iterator position
        self._loop_rng = None                     # the loop's key stream
        from bigdl_tpu.ops.precision import DtypePolicy
        self.precision = DtypePolicy.fp32()

    # ---------------------------------------------------------------- builder
    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       v_methods: Sequence[ValidationMethod]) -> "Optimizer":
        self.validation_trigger = trigger
        self.validation_dataset = dataset
        self.validation_methods = list(v_methods)
        return self

    def set_checkpoint(self, path: str, trigger: Trigger,
                       sharded: bool = False) -> "Optimizer":
        """``sharded=True``: per-process shard files, no driver gather
        (``utils/sharded_checkpoint.py``) — replaces the reference's
        reassemble-on-driver snapshot (``DistriOptimizer.scala:378-400``)
        for multi-host/FSDP states; restore reshards onto the resuming
        run's mesh. Local filesystem paths only."""
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        self._ckpt_sharded = sharded
        return self

    def overwrite_checkpoint(self) -> "Optimizer":
        self.is_overwrite = True
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_validation_summary(self, summary) -> "Optimizer":
        self.validation_summary = summary
        return self

    def set_state(self, state: Table) -> "Optimizer":
        self.state = state
        return self

    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        if not getattr(method, "supports_minibatch", True):
            # Fail at configuration time, not at step time (reference LBFGS
            # is likewise a full-batch optimize(feval, x) driver,
            # ``optim/LBFGS.scala:38``).
            raise ValueError(
                f"{type(method).__name__} is a full-batch method and cannot "
                "drive the minibatch training loop; call "
                "method.optimize(feval, x) directly instead")
        self.optim_method = method
        return self

    def set_end_when(self, end_when: Trigger) -> "Optimizer":
        self.end_when = end_when
        return self

    def set_remat(self, enabled=True) -> "Optimizer":
        """Rematerialize the forward in the backward pass (``jax.checkpoint``).

        ``True``: full remat — activation memory drops to O(1) forwards at
        ~1.3x FLOPs, the standard TPU recipe when a model does not fit HBM.

        ``"conv"``: name-based policy for bandwidth-bound conv/BN models —
        SAVE conv outputs and BN statistics (tagged via ``checkpoint_name``
        in ``nn/conv.py`` / ``ops/batch_norm.py``), recompute the cheap
        elementwise tail (BN normalize, ReLU) in the backward instead of
        materializing those activation copies to HBM.

        ``"block"``: per-transformer-block checkpointing — every
        ``TransformerEncoder`` in the model recomputes inside each block
        during the backward, keeping only block-boundary activations. THE
        policy for billion-param LMs (full remat saves nothing there: one
        outer checkpoint re-materialises all intermediates in its replay).

        Off by default (compute-bound models should keep activations)."""
        from bigdl_tpu.nn.attention import TransformerEncoder
        encs = [m for m in self.model.modules()
                if isinstance(m, TransformerEncoder)]
        for enc in encs:  # reset; "block" re-enables below
            enc.remat_blocks = False
        if isinstance(enabled, str):
            if enabled == "full":  # alias for True (matches the bench lever)
                self._remat = True
            elif enabled == "conv":
                self._remat = enabled
            elif enabled == "block":
                if not encs:
                    raise ValueError("remat='block' needs a model with "
                                     "TransformerEncoder blocks")
                for enc in encs:
                    enc.remat_blocks = True
                self._remat = False  # per-block checkpoints, no outer wrap
            else:
                raise ValueError(f"unknown remat policy {enabled!r}; "
                                 "expected True/False, 'full', 'conv' or "
                                 "'block'")
        else:
            self._remat = bool(enabled)
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float) -> "Optimizer":
        """Scale gradients so their GLOBAL L2 norm (over the whole parameter
        tree, and across data shards under DistriOptimizer) never exceeds
        ``clip_norm`` (reference ``Optimizer.setGradientClippingByl2Norm``).
        Applied inside the jitted step, between autodiff and the update."""
        if clip_norm <= 0:
            raise ValueError(f"clip_norm must be > 0, got {clip_norm}")
        self._grad_clip = {**self._grad_clip, "l2": float(clip_norm)}
        return self

    def set_constant_gradient_clipping(self, min_value: float,
                                       max_value: float) -> "Optimizer":
        """Clamp every gradient element into [min_value, max_value]
        (reference ``Optimizer.setConstantGradientClipping``)."""
        if not min_value < max_value:
            raise ValueError(f"need min_value < max_value, got "
                             f"[{min_value}, {max_value}]")
        self._grad_clip = {**self._grad_clip,
                           "constant": (float(min_value), float(max_value))}
        return self

    def disable_gradient_clipping(self) -> "Optimizer":
        """reference ``Optimizer.disableGradientClipping`` (clears both)."""
        self._grad_clip = {}
        return self

    def set_steps_per_dispatch(self, k: int) -> "Optimizer":
        """Fuse up to ``k`` training iterations into ONE jitted dispatch
        (``lax.scan`` over stacked batches) — amortizes per-dispatch host
        overhead (~15 ms RPC on a tunneled backend; PERF.md round 3) the
        way the bench harness's K-step fusion does, while keeping
        per-iteration logs exact (the k losses come back as an array).

        Windows never cross a trigger firing: before extending a window
        past iteration m, the validation/checkpoint/summary/end triggers
        are probed at ``neval = m+1`` and a firing bounds the window, so
        hooks always run against the params of the iteration they follow.
        Built-in trigger factories are pure under this probing (windows
        never span epoch boundaries); loss-based triggers force k=1.
        Local (single-program) training only — DistriOptimizer ignores it."""
        if int(k) < 1:
            raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
        self._steps_per_dispatch = int(k)
        return self

    def set_precision(self, policy) -> "Optimizer":
        """'bf16' / 'fp32' or a DtypePolicy: bf16 compute with fp32 master
        params (the MXU-native recipe; see ``ops/precision.py``)."""
        from bigdl_tpu.ops.precision import DtypePolicy
        if isinstance(policy, str):
            try:
                policy = {"bf16": DtypePolicy.bf16,
                          "fp32": DtypePolicy.fp32}[policy]()
            except KeyError:
                raise ValueError(
                    f"unknown precision {policy!r}; use 'bf16', 'fp32', "
                    f"or a DtypePolicy") from None
        self.precision = policy
        return self

    def resume(self, model_path: str, state_path: str) -> "Optimizer":
        """Continue from snapshot files (reference examples' --model/--state)."""
        self._resume_from = (model_path, state_path)
        return self

    def auto_resume(self, enabled: bool = True) -> "Optimizer":
        """On ``optimize()``, discover the newest COMPLETE snapshot under
        ``checkpoint_path`` (partial writes rejected) and continue from it
        — the relaunch half of preemption survival. A RESUME marker
        (written by every checkpoint save) restores the data-iterator
        cursor and the exact per-step key stream, so a mid-epoch restart
        is bit-exact; the snapshot reshards onto THIS run's mesh even if
        the process count changed (elastic resume, docs/RESILIENCE.md)."""
        self._auto_resume = bool(enabled)
        return self

    def set_preemption_handler(self,
                               handler: Optional[PreemptionHandler] = None
                               ) -> "Optimizer":
        """Install SIGTERM (by default) preemption hooks for the duration
        of ``optimize()``: on a notice, the loop finishes the step in
        flight, writes one final snapshot + RESUME marker under
        ``checkpoint_path`` and raises ``TrainingPreempted`` — at most one
        step of work is lost (single-host; multi-host runs agree on the
        snapshot step via a periodic flag all-gather, so loss is bounded
        by the ``BIGDL_PREEMPT_SYNC_EVERY`` cadence, default 10 steps —
        set 1 for strict one-step loss at a per-step collective cost)."""
        self._preemption = handler if handler is not None \
            else PreemptionHandler()
        return self

    def set_chaos(self, injectors: Sequence) -> "Optimizer":
        """Deterministic fault injectors probed at every step boundary
        (``bigdl_tpu.resilience.chaos``); the env spec ``BIGDL_CHAOS``
        (e.g. ``kill@5``) adds to these at ``optimize()`` time."""
        self._chaos = list(injectors)
        return self

    def set_profiling(self, log_dir: str, start_iteration: int = 5,
                      n_iterations: int = 5) -> "Optimizer":
        """Capture a ``jax.profiler`` trace of iterations
        [start_iteration, start_iteration + n_iterations). The TPU-native
        per-module breakdown (reference ``getTimes``,
        ``AbstractModule.scala:134-145``): every module forward runs under
        ``jax.named_scope(module.name)``, so the trace's HLO ops are
        attributed to layers; open the dump with TensorBoard's profile
        plugin or Perfetto."""
        self._profile = (log_dir, int(start_iteration), int(n_iterations))
        return self

    def optimize(self) -> Module:
        raise NotImplementedError

    def _telemetry_mode(self) -> str:
        """Label value for the ``bigdl_train_*`` metric families
        (docs/OBSERVABILITY.md); DistriOptimizer overrides with its mesh
        sync mode so local and distributed step breakdowns stay separate
        series in one scrape."""
        return "local"

    def _mesh_descriptor(self) -> Dict[str, Any]:
        """The topology recorded in RESUME markers — what elastic-resume
        detection compares against the restarting run's; DistriOptimizer
        overrides with its mesh shape + sync mode."""
        return {"process_count": int(jax.process_count()),
                "device_count": int(jax.device_count()),
                "mesh_shape": None, "sync_mode": "local"}

    def _train_instruments(self):
        """The mode-labeled training metric children (step-time breakdown,
        throughput, compile counter) as a namespace; resolved once per
        optimizer and cached (label resolution costs a schema check per
        child — not something _validate should re-pay every trigger)."""
        cached = getattr(self, "_tm_cache", None)
        if cached is not None:
            return cached
        from types import SimpleNamespace
        tm = instruments(get_registry())
        mode = self._telemetry_mode()
        cached = SimpleNamespace(
            step=tm.train_step_seconds.labels(mode=mode),
            data_wait=tm.train_data_wait_seconds.labels(mode=mode),
            dispatch=tm.train_dispatch_seconds.labels(mode=mode),
            sync=tm.train_sync_seconds.labels(mode=mode),
            steps=tm.train_steps_total.labels(mode=mode),
            records=tm.train_records_total.labels(mode=mode),
            rps=tm.train_records_per_second.labels(mode=mode),
            compiles=tm.train_compiles_total.labels(mode=mode),
            mfu=tm.train_mfu.labels(mode=mode),
            validation=tm.train_validation_seconds.labels(mode=mode))
        self._tm_cache = cached
        return cached

    # ------------------------------------------------------------ checkpoint
    def _save_checkpoint(self, params, buffers, opt_state, driver_state) -> None:
        if self.checkpoint_path is None:
            return
        tag = "" if self.is_overwrite else f".{int(driver_state['neval'])}"
        if getattr(self, "_ckpt_sharded", False):
            import json as _json
            from bigdl_tpu.utils import sharded_checkpoint as sckpt
            sckpt.save_sharded(
                file_io.join(self.checkpoint_path, f"model{tag}"),
                {"params": params, "buffers": buffers})
            state_dir = file_io.join(self.checkpoint_path, f"state{tag}")
            sckpt.save_sharded(state_dir, {"optim": opt_state})
            if jax.process_index() == 0:
                driver = {k: (v.item() if hasattr(v, "item") else v)
                          for k, v in dict(driver_state).items()}
                with open(os.path.join(state_dir, "driver.json"), "w") as f:
                    _json.dump(driver, f)
        else:
            file_io.save({"params": params, "buffers": buffers},
                         file_io.join(self.checkpoint_path, f"model{tag}"))
            file_io.save({"optim": opt_state, "driver": dict(driver_state)},
                         file_io.join(self.checkpoint_path, f"state{tag}"))
        self._write_resume_marker(driver_state, tag)
        logger.info("[Checkpoint] saved model%s to %s", tag, self.checkpoint_path)

    def _write_resume_marker(self, driver_state, tag: str) -> None:
        """RESUME marker beside the state snapshot (process 0; written
        LAST): step/epoch, the loop's exact PRNG key state, the data
        cursor and this run's mesh shape — what makes the snapshot
        mid-epoch bit-exact and elastically resumable. No-op outside a
        live training loop (no cursor yet)."""
        if self._loop_cursor is None or self._loop_rng is None:
            return
        if jax.process_index() != 0:
            return
        if "://" in self.checkpoint_path:
            return  # markers are a local-fs refinement; scheme'd snapshots
            # resume epoch-granular exactly as before
        from bigdl_tpu.resilience import coordinator
        coordinator.write_marker(
            file_io.join(self.checkpoint_path, f"state{tag}"),
            step=int(driver_state["neval"]),
            epoch=int(driver_state["epoch"]),
            rng_key_data=self._loop_rng.get_key_state(),
            rng_seed=self._loop_rng.get_seed(),
            epoch_batches=int(self._loop_cursor["epoch_batches"]),
            epoch_records=int(self._loop_cursor["epoch_records"]),
            mesh=self._mesh_descriptor(),
            cursor_epoch=int(self._loop_cursor["epoch"]))

    def _resume_shardings(self, params_tpl, buffers_tpl):
        """Target shardings for a sharded-checkpoint resume: pytrees of
        Sharding (or None = host numpy) matching (params, buffers,
        opt_state). LocalOptimizer restores to host; DistriOptimizer
        overrides to reshard onto its mesh."""
        none_of = lambda tpl: jax.tree_util.tree_map(lambda _: None, tpl)
        state_tpl = jax.eval_shape(self.optim_method.init_state, params_tpl)
        return none_of(params_tpl), none_of(buffers_tpl), none_of(state_tpl)

    def _load_sharded_checkpoint(self, model_path, state_path):
        """(params, buffers, opt_state, driver) from per-shard files,
        resharded onto this run's placement — possibly a different mesh
        shape than the saving run's (``utils/sharded_checkpoint.py``)."""
        import json as _json
        from bigdl_tpu.utils import sharded_checkpoint as sckpt
        params_tpl = self.model.parameter_tree()
        buffers_tpl = self.model.buffer_tree()
        p_sh, b_sh, s_sh = self._resume_shardings(params_tpl, buffers_tpl)
        snap = sckpt.load_sharded(model_path,
                                  {"params": p_sh, "buffers": b_sh})
        st = sckpt.load_sharded(state_path, {"optim": s_sh})
        with open(os.path.join(state_path, "driver.json")) as f:
            driver = _json.load(f)
        return snap["params"], snap["buffers"], st["optim"], driver


class LocalOptimizer(Optimizer):
    """Single-chip training loop (reference ``optim/LocalOptimizer.scala:39``)."""

    #: K-fused dispatch works on the single-program path; DistriOptimizer
    #: overrides to False (stacking sharded batches would break placements)
    supports_multi_dispatch = True

    # Subclass hooks (DistriOptimizer overrides for mesh placement/sharding).
    def _place_batch(self, batch: MiniBatch):
        return jnp.asarray(batch.data), jnp.asarray(batch.labels)

    def _init_opt_state(self, params):
        return self.optim_method.init_state(params)

    def _place_state(self, params, buffers, opt_state):
        """Device-placement hook: DistriOptimizer overrides to commit the
        training state onto the (possibly multi-host) mesh before jit."""
        return params, buffers, opt_state

    def _finalize_params(self, params):
        return params

    def _build_step(self) -> Callable:
        model, criterion, optim = self.model, self.criterion, self.optim_method
        reg_pairs = _regularizer_pairs(model)
        policy = self.precision
        remat = self._remat
        clip = make_grad_clipper(self._grad_clip)

        def step(params, buffers, opt_state, rng, data, labels):
            loss_fn = make_training_loss_fn(
                model, criterion, policy, reg_pairs, remat,
                buffers, rng, data, labels)
            grads, (new_buf, loss) = jax.grad(loss_fn, has_aux=True)(params)
            new_params, new_opt_state = optim.update(clip(grads), opt_state,
                                                     params)
            return new_params, new_buf, new_opt_state, loss

        # compile flight recorder: counts/times every step compilation
        # and yields the program's cost analysis — the FLOPs numerator of
        # the live bigdl_train_mfu gauge (telemetry/profiling.py)
        return tracked_jit(step, site="train.step", donate_argnums=(0, 1, 2))

    def _build_multi_step(self) -> Callable:
        """K fused iterations per dispatch (``set_steps_per_dispatch``):
        ``lax.scan`` over leading-axis-stacked (keys, data, labels); returns
        the K per-iteration losses so logging stays exact."""
        model, criterion, optim = self.model, self.criterion, self.optim_method
        reg_pairs = _regularizer_pairs(model)
        policy = self.precision
        remat = self._remat

        clip = make_grad_clipper(self._grad_clip)

        def multi(params, buffers, opt_state, keys, datas, labels):
            def body(carry, inp):
                p, b, o = carry
                key, x, y = inp
                loss_fn = make_training_loss_fn(
                    model, criterion, policy, reg_pairs, remat, b, key, x, y)
                grads, (nb, loss) = jax.grad(loss_fn, has_aux=True)(p)
                np_, no = optim.update(clip(grads), o, p)
                return (np_, nb, no), loss

            (p, b, o), losses = jax.lax.scan(
                body, (params, buffers, opt_state), (keys, datas, labels))
            return p, b, o, losses

        return tracked_jit(multi, site="train.multi_step",
                           donate_argnums=(0, 1, 2))

    def _build_multi_step_cached(self) -> Callable:
        """K-fused dispatch over a device-resident dataset cache
        (``DeviceCachedDataSet``): the scan body gathers each iteration's
        batch from the cache arrays by index INSIDE the program, so a
        window costs exactly one dispatch (stacking pre-gathered batches
        would re-pay one dispatch per gather)."""
        model, criterion, optim = self.model, self.criterion, self.optim_method
        reg_pairs = _regularizer_pairs(model)
        policy = self.precision
        remat = self._remat
        clip = make_grad_clipper(self._grad_clip)

        def multi(params, buffers, opt_state, keys, x_cache, y_cache, idx):
            def body(carry, inp):
                p, b, o = carry
                key, ix = inp
                loss_fn = make_training_loss_fn(
                    model, criterion, policy, reg_pairs, remat, b, key,
                    x_cache[ix], y_cache[ix])
                grads, (nb, loss) = jax.grad(loss_fn, has_aux=True)(p)
                np_, no = optim.update(clip(grads), o, p)
                return (np_, nb, no), loss

            (p, b, o), losses = jax.lax.scan(
                body, (params, buffers, opt_state), (keys, idx))
            return p, b, o, losses

        return tracked_jit(multi, site="train.multi_step_cached",
                           donate_argnums=(0, 1, 2))

    def _build_forward(self) -> Callable:
        model = self.model

        def fwd(params, buffers, data):
            out, _ = functional_apply(model, params, buffers, data, training=False)
            return out

        return tracked_jit(fwd, site="train.forward")

    def optimize(self) -> Module:
        """Train with retry-from-checkpoint (reference
        ``DistriOptimizer.scala:728-796``): on a non-configuration failure,
        reload the newest COMPLETE snapshot under ``checkpoint_path``
        (partial writes rejected by the resilience coordinator) and retry,
        up to ``BIGDL_FAILURE_RETRY_TIMES`` (default 5) failures inside a
        sliding ``BIGDL_FAILURE_RETRY_INTERVAL``-second window (default
        120). ``TrainingPreempted`` is NOT retried — the host is going
        away; the snapshot it wrote is picked up by ``auto_resume()`` on
        relaunch."""
        from bigdl_tpu.resilience import chaos as chaos_mod
        from bigdl_tpu.resilience import coordinator
        retry_times = int(os.environ.get("BIGDL_FAILURE_RETRY_TIMES", "5"))
        retry_window = float(
            os.environ.get("BIGDL_FAILURE_RETRY_INTERVAL", "120"))
        failures: List[float] = []
        resume = self._resume_from
        if resume is None and self._auto_resume:
            point = coordinator.latest_resume_point(self.checkpoint_path)
            if point is not None:
                resume = point
                logger.info("[AutoResume] discovered snapshot %s",
                            point.model_path)
        self._chaos_live = list(self._chaos) + chaos_mod.from_env()
        handler = self._preemption
        if handler is not None:
            handler.install()
            drain = getattr(self.dataset, "drain", None)
            if callable(drain):
                # ingest-engine datasets: stop + join the reader/decode/
                # device-feed threads before the final snapshot's IO
                handler.add_drain_hook(drain)
        try:
            while True:
                try:
                    return self._run_training(resume)
                except (ValueError, TypeError, KeyboardInterrupt,
                        TrainingPreempted):
                    raise  # config errors ≙ IllegalArgument; preemption ≙
                    # the host is being reclaimed — don't spin on it
                except Exception as e:  # noqa: BLE001 - the retry boundary
                    now = time.time()
                    failures = [t for t in failures if now - t < retry_window]
                    failures.append(now)
                    latest = (coordinator.latest_resume_point(
                        self.checkpoint_path) if self.checkpoint_path
                        else None)
                    if len(failures) > retry_times or latest is None:
                        raise
                    # IN-PROCESS retry: the dataset's in-place shuffle
                    # order and the host RNG have already advanced past
                    # their fresh-process state, so the marker's shuffle
                    # replay + batch-cursor fast-forward would align to
                    # the wrong permutation (training some records twice,
                    # skipping others). Drop the marker — the epoch
                    # restarts from batch 0, the pre-resilience retry
                    # semantics. A fresh-process relaunch (auto_resume)
                    # keeps the marker and resumes bit-exact.
                    import dataclasses
                    resume = dataclasses.replace(latest, marker=None)
                    logger.warning(
                        "[Retry %d/%d] training failed (%s); restarting "
                        "from checkpoint %s", len(failures), retry_times, e,
                        latest.model_path)
        finally:
            self._close_data_iter()
            if handler is not None:
                handler.uninstall()

    def _latest_checkpoint(self) -> Optional[Tuple[str, str]]:
        """Newest COMPLETE (model, state) snapshot pair under
        ``checkpoint_path`` (reference ``getLatestFile``,
        ``DistriOptimizer.scala:808-825``; completeness validation in
        ``bigdl_tpu/resilience/coordinator.py``)."""
        from bigdl_tpu.resilience import coordinator
        point = coordinator.latest_resume_point(self.checkpoint_path)
        if point is None:
            return None
        return (point.model_path, point.state_path)

    def _run_training(self, resume) -> Module:
        model = self.model
        # Private copies: the jitted step donates its param/buffer inputs, and
        # donating the model's own arrays would delete buffers any other
        # reference (a cloned model, user code) still points at.
        driver_state = T(epoch=1, neval=1)
        driver_state.update(self.state)

        from bigdl_tpu.resilience import coordinator
        marker = None
        if resume:
            if isinstance(resume, coordinator.ResumePoint):
                model_path, state_path = resume.model_path, resume.state_path
                marker = resume.marker
            else:
                model_path, state_path = resume
                marker = coordinator.read_marker(state_path)
            from bigdl_tpu.utils import sharded_checkpoint as sckpt
            if sckpt.is_sharded_checkpoint(model_path):
                params, buffers, opt_state, driver = \
                    self._load_sharded_checkpoint(model_path, state_path)
                driver_state.update(driver)
            else:
                snap = file_io.load(model_path)
                params, buffers = snap["params"], snap["buffers"]
                st = file_io.load(state_path)
                opt_state = st["optim"]
                driver_state.update(st["driver"])
            elastic = coordinator.is_elastic(marker)
            instruments(get_registry()).resilience_resumes_total.labels(
                elastic="unknown" if elastic is None
                else ("true" if elastic else "false")).inc()
            if elastic:
                saved = (marker.get("mesh") or {})
                logger.info(
                    "[Resume] ELASTIC: snapshot saved by %s processes / %s "
                    "devices, resharding onto %d processes / %d devices",
                    saved.get("process_count"), saved.get("device_count"),
                    jax.process_count(), jax.device_count())
            logger.info("[Resume] from %s at epoch %s neval %s", model_path,
                        driver_state["epoch"], driver_state["neval"])
        else:
            params = jax.tree_util.tree_map(jnp.array, model.parameter_tree())
            buffers = jax.tree_util.tree_map(jnp.array, model.buffer_tree())
            opt_state = self._init_opt_state(params)
        params, buffers, opt_state = self._place_state(params, buffers,
                                                       opt_state)

        step = self._build_step()
        fwd = self._build_forward()
        uses_loss_any = (getattr(self.end_when, "uses_loss", False)
                         or getattr(self.validation_trigger, "uses_loss",
                                    False)
                         or getattr(self.checkpoint_trigger, "uses_loss",
                                    False))
        # K-fused dispatch (set_steps_per_dispatch): loss-based triggers
        # need per-iteration losses on the host -> windows of 1
        multi_step = (self._build_multi_step()
                      if (self._steps_per_dispatch > 1
                          and self.supports_multi_dispatch
                          and not uses_loss_any) else None)
        multi_step_cached = (self._build_multi_step_cached()
                             if multi_step is not None else None)
        self._profiling_active = False
        rng = RandomGenerator.RNG()
        from bigdl_tpu.utils.engine import Engine
        n_proc = Engine.process_count()
        if n_proc > 1:
            # SPMD contract: replicated jit inputs (dropout keys) must be
            # identical on every process — sync the stream to process 0's.
            from jax.experimental import multihost_utils
            seed = int(multihost_utils.broadcast_one_to_all(
                np.asarray(rng.get_seed(), np.int64)))
            rng = RandomGenerator(seed)
        resume_cursor = None
        if marker is not None:
            # Bit-exact mid-epoch restart (docs/RESILIENCE.md): restore the
            # loop's exact key-stream position, replay the per-epoch
            # shuffles a fresh process has not performed (the composed
            # in-place permutation then matches the uninterrupted run —
            # provided the host RNG is consumed only by these shuffles),
            # and skip the batches the saved epoch already consumed.
            key_data = (marker.get("rng") or {}).get("key_data")
            if key_data:
                rng = RandomGenerator(int(marker["rng"]["seed"]))
                rng.set_key_state(key_data)
            for _ in range(int(driver_state["epoch"]) - 1):
                self.dataset.shuffle()
            resume_cursor = dict(marker.get("cursor") or {})
        self._loop_cursor = None  # set at the first step boundary
        self._loop_rng = rng
        wall_start = time.time()
        handler = self._preemption
        chaos_injectors = getattr(self, "_chaos_live", None)
        if chaos_injectors is None:
            chaos_injectors = list(self._chaos)
        # multi-host preemption must be AGREED: every process snapshots at
        # the same step or the shard files diverge. A small flag
        # all-gather decides — but it is a host-blocking cross-host round
        # trip, so it runs every BIGDL_PREEMPT_SYNC_EVERY steps (default
        # 10), not every step: a notice still resolves well inside the
        # grace window, and the hot loop keeps its async pipeline.
        sync_every = max(1, int(os.environ.get("BIGDL_PREEMPT_SYNC_EVERY",
                                               "10")))

        def preemption_agreed(neval: int) -> bool:
            local = handler is not None and handler.should_snapshot()
            if n_proc <= 1:
                return local
            if handler is None or neval % sync_every != 0:
                return False
            from jax.experimental import multihost_utils
            return bool(multihost_utils.process_allgather(
                np.asarray(1 if local else 0, np.int32)).max())

        # One-deep software pipeline: iteration i's loss is fetched AFTER
        # iteration i+1 is dispatched, so the host-side log/summary work and
        # the device->host sync overlap the device computing the next step
        # (an unpipelined float(loss) per step costs ~15 ms of idle device
        # time on a tunneled backend). Logs stay exact — each line reports
        # its own iteration's true loss, one dispatch later.
        pending = None  # in-flight dispatch awaiting its loss fetch
        last_done = None  # wall time the previous dispatch's losses landed
        tm = self._train_instruments()

        def flush():
            nonlocal pending, last_done
            if pending is None:
                return
            p = pending
            pending = None
            # sync point: blocks until the dispatch is done. A K-fused
            # dispatch (set_steps_per_dispatch) returns (K,) losses — one
            # exact log line per iteration either way.
            t_sync = time.time()
            with span("train.sync", k=len(p["iters"])):
                losses = np.atleast_1d(np.asarray(p["losses"], np.float32))
            tm.sync.observe(time.time() - t_sync)
            # inter-completion interval ~= per-dispatch device time in
            # steady state; measuring to the NEXT dispatch instead would
            # fold hook time and the next batch's data wait into
            # "computing time"
            done = time.time()
            window_time = done - (last_done if last_done is not None
                                  and last_done > p["t0"] else p["t0"])
            last_done = done
            iter_time = window_time / len(p["iters"])
            first_window = p["iters"][0]["neval"] == 1
            if first_window:
                # first step pays tracing+XLA compile (unless cached)
                self.metrics.add("compile and first-step time", window_time)
                tm.compiles.inc()
            # live MFU: the dispatched program's cost-analysis FLOPs (one
            # program ran the whole window, K iterations included) over
            # the window wall-clock and the chip's peak — absent when the
            # backend has no cost analysis or no known roof. The compile-
            # bearing first window is SKIPPED: its wall-clock is mostly
            # XLA, and publishing FLOPs/(compile+step) would trip any
            # dashboard threshold at every (re)start.
            fn = p.get("fn")
            fn = getattr(fn, "tracked", fn)  # ZeRO-1 wraps its TrackedJit
            if not first_window:
                m = profiling.mfu(getattr(fn, "last_flops", None),
                                  window_time)
                if m is not None:
                    tm.mfu.set(m)
            # step-boundary device-memory watermark (no-op on CPU)
            sample_device_memory()
            for meta, loss_f in zip(p["iters"], losses):
                loss_f = float(loss_f)
                throughput = meta["n_records"] / max(iter_time, 1e-9)
                tm.step.observe(iter_time)
                tm.steps.inc()
                tm.records.inc(meta["n_records"])
                tm.rps.set(throughput)
                driver_state["trainingLoss"] = loss_f
                logger.info(
                    "[Epoch %d %d/%d][Iteration %d][Wall %.3fs] Trained %d "
                    "records in %.4fs. Throughput is %.1f records/second. "
                    "Loss is %.5f.",
                    meta["epoch"], meta["epoch_records"], meta["size"],
                    meta["neval"], time.time() - wall_start,
                    meta["n_records"], iter_time, throughput, loss_f)
                self.metrics.add("computing time average", iter_time)
                if self.train_summary is not None:
                    self.train_summary.add_scalar("Loss", loss_f,
                                                  meta["neval"])
                    self.train_summary.add_scalar("Throughput", throughput,
                                                  meta["neval"])
                    if meta["lr"] is not None:
                        self.train_summary.add_scalar(
                            "LearningRate", float(meta["lr"]), meta["neval"])

        stop = False
        while not stop and not self.end_when(driver_state):
            self.dataset.shuffle()
            epoch = int(driver_state["epoch"])
            opt_state["epoch"] = jnp.asarray(epoch, jnp.int32)
            epoch_start = time.time()
            epoch_records = 0
            data_wait = 0.0
            t_data = time.time()
            ptrig = (self.train_summary.get_summary_trigger("Parameters")
                     if (self.train_summary is not None
                         and hasattr(self.train_summary,
                                     "get_summary_trigger")) else None)
            # window bounding PROBES triggers at simulated nevals: a custom
            # stateful predicate (probe_safe=False, the Trigger(fn) default)
            # would be corrupted, so its presence forces windows of 1
            can_window = multi_step is not None and all(
                getattr(t, "probe_safe", False)
                for t in (self.validation_trigger, self.checkpoint_trigger,
                          self.end_when, ptrig) if t is not None)

            def probe(trigger, neval_at):
                """Evaluate a trigger at a simulated neval (same epoch —
                windows never span epoch boundaries, under which the
                built-in factories are pure)."""
                if trigger is None:
                    return False
                st = T()
                st.update(driver_state)
                st["neval"] = neval_at
                return bool(trigger(st))

            def extension_ok(neval0, j):
                """May the window grow to include iteration neval0+j?
                Member neval0+j-1 then loses its per-iteration hook slot,
                so nothing may fire there: no Parameters summary at
                neval=neval0+j-1 (checked pre-increment), no
                validation/checkpoint/end at neval=neval0+j."""
                if probe(ptrig, neval0 + j - 1):
                    return False
                for trig in (self.validation_trigger,
                             self.checkpoint_trigger, self.end_when):
                    if probe(trig, neval0 + j):
                        return False
                return True

            data_iter = iter(self.dataset.data(train=True))
            # tracked for deterministic teardown: an engine-backed iterator
            # owns worker threads; epoch end AND the exception path out of
            # optimize() close it explicitly instead of waiting on GC
            self._live_data_iter = data_iter
            epoch_batches = 0
            if (resume_cursor is not None
                    and int(resume_cursor.get("epoch", -1)) == epoch):
                # fast-forward past the batches the preempted run already
                # trained on: the resumed epoch continues where the
                # snapshot stopped instead of repeating it
                skip = int(resume_cursor.get("epoch_batches", 0))
                for _ in range(skip):
                    if next(data_iter, None) is None:
                        break
                epoch_batches = skip
                epoch_records = int(resume_cursor.get("epoch_records", 0))
            resume_cursor = None  # first resumed epoch only
            while True:
                try:
                    batch = next(data_iter)
                except StopIteration:
                    break
                window = [batch]
                neval0 = int(driver_state["neval"])
                while (can_window
                       and len(window) < self._steps_per_dispatch
                       and extension_ok(neval0, len(window))):
                    try:
                        window.append(next(data_iter))
                    except StopIteration:
                        break
                dw = time.time() - t_data
                data_wait += dw
                tm.data_wait.observe(dw)
                k = len(window)
                last_neval = neval0 + k - 1
                if self._profile is not None:
                    pdir, pstart, pn = self._profile
                    if (neval0 <= pstart <= last_neval
                            and not self._profiling_active):
                        jax.profiler.start_trace(pdir)
                        self._profiling_active = True
                t0 = time.time()
                used_fn = step  # which tracked program served the window
                with span("train.dispatch", k=k):
                    if k == 1:
                        data, labels = self._place_batch(window[0])
                        params, buffers, opt_state, losses = step(
                            params, buffers, opt_state, rng.next_key(),
                            data, labels)
                    else:
                        from bigdl_tpu.dataset.device_cache import \
                            CachedSliceBatch
                        keys = jnp.stack([rng.next_key() for _ in window])
                        if (all(isinstance(b, CachedSliceBatch)
                                for b in window)
                                and len({id(b.source)
                                         for b in window}) == 1):
                            # gathers happen inside the fused program: ONE
                            # dispatch per window
                            src = window[0].source
                            idx = jnp.stack([b.idx for b in window])
                            used_fn = multi_step_cached
                            params, buffers, opt_state, losses = \
                                multi_step_cached(params, buffers,
                                                  opt_state, keys,
                                                  src._x, src._y, idx)
                        else:
                            # host batches: one fused H2D + dispatch per
                            # window
                            xs = jnp.stack([jnp.asarray(b.data)
                                            for b in window])
                            ys = jnp.stack([jnp.asarray(b.labels)
                                            for b in window])
                            used_fn = multi_step
                            params, buffers, opt_state, losses = multi_step(
                                params, buffers, opt_state, keys, xs, ys)
                # host time enqueueing the window (async; device compute
                # lands in the NEXT flush's sync wait)
                tm.dispatch.observe(time.time() - t0)
                flush()  # previous dispatch: fetch losses, log, summarize
                # snapshot the lr as its own small array NOW: opt_state's
                # buffers are donated to the next dispatch and deleted
                # (* 1 forces a fresh buffer if the schedule returns a state
                # array by identity). One snapshot per dispatch: intra-window
                # schedule steps are not observable host-side.
                lr_arr = None
                if (self.train_summary is not None
                        and hasattr(self.optim_method, "current_rate")):
                    lr_arr = self.optim_method.current_rate(opt_state)
                    if not isinstance(lr_arr, (int, float)):
                        lr_arr = lr_arr * 1
                iters = []
                for j, b in enumerate(window):
                    epoch_records += b.size()
                    iters.append({"neval": neval0 + j, "epoch": epoch,
                                  "n_records": b.size(),
                                  "epoch_records": epoch_records,
                                  "size": self.dataset.size(),
                                  "lr": lr_arr})
                pending = {"losses": losses, "iters": iters, "t0": t0,
                           "fn": used_fn}
                if self._profiling_active and last_neval >= pstart + pn - 1:
                    jax.profiler.stop_trace()
                    self._profiling_active = False
                    logger.info("[Profiler] trace for iterations %d-%d "
                                "written to %s", pstart, last_neval, pdir)
                # non-final window members were probed trigger-silent; the
                # final member gets the real per-iteration hook slot
                driver_state["neval"] = last_neval
                if ptrig is not None and ptrig(driver_state):
                    self._summarize_parameters(params, last_neval)
                driver_state["neval"] = last_neval + 1
                epoch_batches += k
                # the data-iterator cursor any checkpoint written at this
                # boundary records in its RESUME marker
                self._loop_cursor = {"epoch": epoch,
                                     "epoch_batches": epoch_batches,
                                     "epoch_records": epoch_records}
                if uses_loss_any:
                    # loss-sensitive stop/hook triggers must see THIS
                    # iteration's loss, not the pipelined previous one
                    flush()
                self._hooks(params, buffers, opt_state, driver_state, fwd,
                            epoch_done=False, flush=flush)
                for inj in chaos_injectors:
                    inj.on_step(last_neval)
                if handler is not None:
                    fresh = handler.drain_notices()
                    if fresh:
                        instruments(get_registry()) \
                            .resilience_preemptions_total.inc(fresh)
                if preemption_agreed(last_neval):
                    flush()
                    self._preempt_snapshot(params, buffers, opt_state,
                                           driver_state)
                if self.end_when(driver_state):  # iteration/loss-based stops
                    stop = True
                    break
                t_data = time.time()
            flush()  # drain the pipeline at epoch end (exact epoch log)
            self._close_data_iter()
            self.metrics.add("data wait time", data_wait)
            logger.info("[Epoch %d] Epoch finished. Wall clock time is %.1f ms (%d records)",
                        epoch, (time.time() - epoch_start) * 1e3, epoch_records)
            driver_state["epoch"] = epoch + 1
            self._hooks(params, buffers, opt_state, driver_state, fwd,
                        epoch_done=True)

        if self._profiling_active:  # window outran training: close the trace
            jax.profiler.stop_trace()
            self._profiling_active = False
        model.load_parameter_tree(self._finalize_params(params))
        model.load_buffer_tree(buffers)
        return model

    def _close_data_iter(self) -> None:
        """Close the tracked epoch iterator (no-op when none is live).
        Generator-backed pipelines run their ``finally`` blocks —
        engine-backed ones drain + join their stage threads — so the
        data-wait accounting and thread census stay exact on every exit
        path, exceptions included."""
        it = getattr(self, "_live_data_iter", None)
        self._live_data_iter = None
        if it is not None:
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def _preempt_snapshot(self, params, buffers, opt_state,
                          driver_state) -> None:
        """End-of-step preemption snapshot: persist (model, state, RESUME
        marker) through the normal checkpoint machinery, leave the latest
        weights on the model object, and stop training via
        ``TrainingPreempted`` (never retried in-process — the host is
        being reclaimed; ``auto_resume()`` picks the snapshot up on
        relaunch, possibly on a different process count)."""
        reason = (self._preemption.reason
                  if self._preemption is not None and self._preemption.reason
                  else "preempted")
        if self._preemption is not None:
            # drain ingest first: a live reader/decode pipeline would race
            # shard reads and H2D transfers against snapshot IO inside the
            # grace window
            self._preemption.run_drain_hooks()
        final = self._finalize_params(params)
        snap_path = None
        if self.checkpoint_path is not None:
            t0 = time.time()
            with span("resilience.snapshot"):
                self._save_checkpoint(final, buffers, opt_state,
                                      driver_state)
            elapsed = time.time() - t0
            instruments(get_registry()).resilience_snapshot_seconds \
                .observe(elapsed)
            tag = ("" if self.is_overwrite
                   else f".{int(driver_state['neval'])}")
            snap_path = file_io.join(self.checkpoint_path, f"model{tag}")
            remaining = (self._preemption.remaining_grace()
                         if self._preemption is not None else float("inf"))
            logger.warning(
                "[Preempted] %s: snapshot %s written in %.2fs (grace "
                "remaining %.1fs); relaunch with auto_resume() to continue",
                reason, snap_path, elapsed, remaining)
        else:
            logger.warning("[Preempted] %s: no checkpoint path configured "
                           "— stopping WITHOUT a snapshot", reason)
        self.model.load_parameter_tree(final)
        self.model.load_buffer_tree(buffers)
        raise TrainingPreempted(reason, snap_path)

    def _summarize_parameters(self, params, neval: int) -> None:
        """Per-parameter histograms (reference ``TrainSummary`` "Parameters"
        trigger, ``DistriOptimizer.scala:410-440``)."""
        import jax.tree_util as jtu
        # sharded DistriOptimizer carries a flat padded vector; unravel it
        # back to the named pytree before logging per-parameter histograms
        flat = jtu.tree_flatten_with_path(self._finalize_params(params))[0]
        for path, leaf in flat:
            tag = "Parameters/" + "/".join(
                str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            self.train_summary.add_histogram(tag, np.asarray(leaf), neval)

    # ------------------------------------------------------------------ hooks
    def _hooks(self, params, buffers, opt_state, driver_state, fwd,
               epoch_done: bool, flush=None) -> None:
        if (self.validation_trigger is not None
                and self.validation_trigger(driver_state)):
            self._validate(params, buffers, fwd, driver_state)
        if (self.checkpoint_trigger is not None
                and self.checkpoint_trigger(driver_state)):
            if flush is not None:
                flush()  # persist an exact driver_state (trainingLoss is
                # otherwise one pipelined iteration stale in the snapshot)
            self._save_checkpoint(self._finalize_params(params), buffers,
                                  opt_state, driver_state)

    def _run_validation(self, params, buffers, fwd):
        """(results, count) over the validation set; DistriOptimizer
        overrides for the multi-host per-process-shard + merge path."""
        from bigdl_tpu.optim.evaluator import evaluate_batches
        return evaluate_batches(
            fwd, params, buffers, self.validation_dataset.data(train=False),
            self.validation_methods, cache=self._eval_cache)

    def _validate(self, params, buffers, fwd, driver_state) -> None:
        if self.validation_dataset is None:
            return
        t0 = time.time()
        with span("train.validate"):
            results, count = self._run_validation(params, buffers, fwd)
        elapsed = time.time() - t0
        self.metrics.add("validation time", elapsed)
        self._train_instruments().validation.observe(elapsed)
        logger.info("[Validation] %d records in %.3fs. Throughput is %.1f records/s",
                    count, elapsed, count / max(elapsed, 1e-9))
        for i, (m, r) in enumerate(zip(self.validation_methods, results)):
            if r is None:
                continue
            logger.info("%s is %s", m.name, r)
            value = r.result()[0]
            if i == 0:
                # 'score' (used by Trigger.max_score) tracks the FIRST method.
                driver_state["score"] = value
            if self.validation_summary is not None:
                self.validation_summary.add_scalar(m.name, value,
                                                   int(driver_state["neval"]) - 1)
