"""Deprecated standalone validation drivers (reference
``optim/Validator.scala:63``: ``Validator(model, dataset)`` factory building
``LocalValidator``/``DistriValidator``; deprecated in 0.2.0 in favor of
``model.evaluate``) and the legacy accuracy helpers
(``optim/EvaluateMethods.scala``). Kept for API parity; both delegate to the
one batch-eval loop in ``optim.evaluator``.
"""

from __future__ import annotations

import logging
import warnings
from typing import List, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.base import AbstractDataSet
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.evaluator import Evaluator
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult

logger = logging.getLogger("bigdl_tpu.optim")


class Validator:
    """reference ``optim/Validator.scala``: abstract test driver with a
    deprecated factory. The Local/Distri split collapses here — one jitted
    forward serves both — but both names stay constructible."""

    def __init__(self, model: Module, dataset: AbstractDataSet):
        self.model = model
        self.dataset = dataset

    def test(self, v_methods: Sequence[ValidationMethod]
             ) -> List[Tuple[ValidationResult, ValidationMethod]]:
        return Evaluator(self.model).test(self.dataset, v_methods)

    def __new__(cls, model, dataset, *a, **k):
        if cls is Validator:
            warnings.warn(
                "Validator(model, dataset) is deprecated. Please use "
                "model.evaluate instead", DeprecationWarning, stacklevel=2)
            logger.warning("Validator(model, dataset) is deprecated. "
                           "Please use model.evaluate instead")
            target = (DistriValidator
                      if isinstance(dataset, AbstractDataSet)
                      and dataset.is_distributed() else LocalValidator)
            return super().__new__(target)
        return super().__new__(cls)


class LocalValidator(Validator):
    """reference ``optim/LocalValidator.scala``."""


class DistriValidator(Validator):
    """reference ``optim/DistriValidator.scala``."""


def calc_accuracy(output, target) -> Tuple[int, int]:
    """(correct, count) top-1 (reference ``EvaluateMethods.calcAccuracy``;
    1-based labels)."""
    out = np.asarray(output)
    tgt = np.asarray(target).ravel()
    if out.ndim == 1:
        out = out[None]
    pred = out.argmax(axis=-1) + 1
    return int((pred == tgt).sum()), int(out.shape[0])


def calc_top5_accuracy(output, target) -> Tuple[int, int]:
    """(correct, count) top-5 (reference ``EvaluateMethods.calcTop5Accuracy``)."""
    out = np.asarray(output)
    tgt = np.asarray(target).ravel()
    if out.ndim == 1:
        out = out[None]
    top5 = np.argsort(-out, axis=-1)[:, :5] + 1
    correct = sum(int(t in row) for t, row in zip(tgt, top5))
    return correct, int(out.shape[0])
