"""Deprecated standalone validation drivers (reference
``optim/Validator.scala:63``: ``Validator(model, dataset)`` factory building
``LocalValidator``/``DistriValidator``; deprecated in 0.2.0 in favor of
``model.evaluate``) and the legacy accuracy helpers
(``optim/EvaluateMethods.scala``). Kept for API parity; both delegate to the
one batch-eval loop in ``optim.evaluator``.
"""

from __future__ import annotations

import warnings
from typing import List, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from bigdl_tpu.dataset.base import AbstractDataSet
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.evaluator import Evaluator
from bigdl_tpu.optim.validation import (ValidationMethod, ValidationResult,
                                        _topk_correct)

class Validator:
    """reference ``optim/Validator.scala``: abstract test driver with a
    deprecated factory. The Local/Distri split collapses here — one jitted
    forward serves both — but both names stay constructible."""

    def __init__(self, model: Module, dataset: AbstractDataSet):
        self.model = model
        self.dataset = dataset

    def test(self, v_methods: Sequence[ValidationMethod]
             ) -> List[Tuple[ValidationResult, ValidationMethod]]:
        return Evaluator(self.model).test(self.dataset, v_methods)

    def __new__(cls, model, dataset, *a, **k):
        if cls is Validator:
            warnings.warn(
                "Validator(model, dataset) is deprecated. Please use "
                "model.evaluate instead", DeprecationWarning, stacklevel=2)
            target = (DistriValidator
                      if isinstance(dataset, AbstractDataSet)
                      and dataset.is_distributed() else LocalValidator)
            return super().__new__(target)
        return super().__new__(cls)


class LocalValidator(Validator):
    """reference ``optim/LocalValidator.scala``."""


class DistriValidator(Validator):
    """reference ``optim/DistriValidator.scala``."""


def _calc_topk(output, target, k: int) -> Tuple[int, int]:
    out = jnp.asarray(np.asarray(output))
    tgt = jnp.asarray(np.asarray(target).ravel())
    n = 1 if out.ndim == 1 else out.shape[0]
    if tgt.shape[0] != n:
        raise ValueError(f"output rows ({n}) != target length "
                         f"({tgt.shape[0]})")
    correct, count = _topk_correct(out, tgt, k)
    return int(correct), int(count)


def calc_accuracy(output, target) -> Tuple[int, int]:
    """(correct, count) top-1 (reference ``EvaluateMethods.calcAccuracy``;
    1-based labels; delegates to the one top-k kernel in
    ``optim.validation``)."""
    return _calc_topk(output, target, 1)


def calc_top5_accuracy(output, target) -> Tuple[int, int]:
    """(correct, count) top-5 (reference ``EvaluateMethods.calcTop5Accuracy``)."""
    return _calc_topk(output, target, 5)
