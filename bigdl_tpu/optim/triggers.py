"""Composable training-loop triggers (reference ``optim/Trigger.scala:26``).

A trigger is a predicate over the driver-side state Table (keys ``epoch``,
``neval``, ``trainingLoss`` ... — same vocabulary as the reference).
"""

from __future__ import annotations

from typing import Callable

from bigdl_tpu.utils.table import Table


class Trigger:
    """Composable training-state predicate (reference ``optim/Trigger.scala:26``).

    Examples::

        >>> from bigdl_tpu.utils.table import T
        >>> Trigger.max_epoch(5)(T(epoch=6, neval=1))
        True
        >>> Trigger.several_iteration(10)(T(neval=20))
        True
        >>> both = Trigger.and_(Trigger.max_epoch(2), Trigger.max_iteration(9))
        >>> both(T(epoch=3, neval=5))
        False
    """

    def __init__(self, fn: Callable[[Table], bool], name: str = "trigger",
                 uses_loss: bool = False, probe_safe: bool = False):
        self._fn = fn
        self.name = name
        # loss-sensitive triggers force the training loop to drain its
        # one-step loss pipeline before each end_when check, so they see
        # the CURRENT iteration's loss, not the previous one
        self.uses_loss = uses_loss
        # probe_safe: the K-fused dispatch loop (set_steps_per_dispatch)
        # may evaluate the trigger at SIMULATED future nevals (same epoch)
        # to bound a window; a trigger whose predicate latches internal
        # state across calls would be corrupted by that, so custom
        # Trigger(fn) defaults to NOT probe-safe (forcing windows of 1).
        # All built-in factories are probe-safe under same-epoch probing.
        self.probe_safe = probe_safe

    def __call__(self, state: Table) -> bool:
        return bool(self._fn(state))

    # -- factories (reference Trigger object methods) -----------------------
    @staticmethod
    def every_epoch() -> "Trigger":
        """Fires at each epoch *boundary* (when the epoch counter advances
        past the first value seen — so never mid-first-epoch)."""
        box = {"last": None}

        def fn(state: Table) -> bool:
            e = int(state["epoch"])
            if box["last"] is None:
                box["last"] = e
                return False
            if e > box["last"]:
                box["last"] = e
                return True
            return False

        # stateful, but only on epoch CHANGE - pure under same-epoch probing
        return Trigger(fn, "everyEpoch", probe_safe=True)

    @staticmethod
    def several_iteration(interval: int) -> "Trigger":
        def fn(state: Table) -> bool:
            return int(state["neval"]) % interval == 0

        return Trigger(fn, f"severalIteration({interval})", probe_safe=True)

    @staticmethod
    def max_epoch(maximum: int) -> "Trigger":
        def fn(state: Table) -> bool:
            return int(state["epoch"]) > maximum

        return Trigger(fn, f"maxEpoch({maximum})", probe_safe=True)

    @staticmethod
    def max_iteration(maximum: int) -> "Trigger":
        def fn(state: Table) -> bool:
            return int(state["neval"]) > maximum

        return Trigger(fn, f"maxIteration({maximum})", probe_safe=True)

    @staticmethod
    def max_score(maximum: float) -> "Trigger":
        def fn(state: Table) -> bool:
            return float(state.get("score", float("-inf"))) > maximum

        return Trigger(fn, f"maxScore({maximum})", probe_safe=True)

    @staticmethod
    def min_loss(minimum: float) -> "Trigger":
        def fn(state: Table) -> bool:
            return float(state.get("trainingLoss", float("inf"))) < minimum

        return Trigger(fn, f"minLoss({minimum})", uses_loss=True, probe_safe=True)

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: all(t(s) for t in triggers), "and",
                       uses_loss=any(t.uses_loss for t in triggers),
                       probe_safe=all(t.probe_safe for t in triggers))

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: any(t(s) for t in triggers), "or",
                       uses_loss=any(t.uses_loss for t in triggers),
                       probe_safe=all(t.probe_safe for t in triggers))
