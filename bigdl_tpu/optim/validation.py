"""Validation metrics (reference ``optim/ValidationMethod.scala:33``:
``Top1Accuracy:116``, ``Top5Accuracy:154``, ``Loss:248`` with mergeable
``ValidationResult``s).

Each method has a pure, jit-friendly core ``batch_result(output, target)``
returning (correct_or_sum, count) so evaluation loops can run entirely on
device and only merge scalars on the host.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.criterion import Criterion, ClassNLLCriterion


class ValidationResult:
    def result(self):
        raise NotImplementedError

    def __add__(self, other):
        raise NotImplementedError

    # (numerator, count) wire form — lets results merge across hosts via a
    # single allgather of floats (multi-host validation).
    def state(self):
        raise NotImplementedError

    @classmethod
    def from_state(cls, numerator, count):
        return cls(numerator, count)


class AccuracyResult(ValidationResult):
    """(correct, count) pair (reference ``AccuracyResult``)."""

    def __init__(self, correct: int, count: int):
        self.correct, self.count = int(correct), int(count)

    def state(self):
        return (float(self.correct), float(self.count))

    def result(self):
        return (self.correct / max(1, self.count), self.count)

    def __add__(self, other: "AccuracyResult"):
        return AccuracyResult(self.correct + other.correct, self.count + other.count)

    def __repr__(self):
        acc, n = self.result()
        return f"Accuracy(correct: {self.correct}, count: {n}, accuracy: {acc:.6f})"

    def __eq__(self, other):
        return (self.correct, self.count) == (other.correct, other.count)


class LossResult(ValidationResult):
    """(sum loss, count) pair (reference ``LossResult``)."""

    def __init__(self, loss: float, count: int):
        self.loss, self.count = float(loss), int(count)

    def state(self):
        return (self.loss, float(self.count))

    def result(self):
        return (self.loss / max(1, self.count), self.count)

    def __add__(self, other: "LossResult"):
        return LossResult(self.loss + other.loss, self.count + other.count)

    def __repr__(self):
        mean, n = self.result()
        return f"Loss(sum: {self.loss:.4f}, count: {n}, mean: {mean:.6f})"


class ValidationMethod:
    """Base metric (reference ``ValidationMethod``)."""

    name = "validation"

    def batch_result(self, output, target):
        """Pure device-side (value, count) for one batch."""
        raise NotImplementedError

    def to_result(self, value, count) -> ValidationResult:
        raise NotImplementedError

    def apply(self, output, target) -> ValidationResult:
        v, c = self.batch_result(output, target)
        return self.to_result(float(v), int(c))

    def __call__(self, output, target) -> ValidationResult:
        return self.apply(output, target)

    def __repr__(self):
        return self.name


def _topk_correct(output, target, k: int):
    # output (N, C) scores; target (N,) 1-based labels.
    if output.ndim == 1:
        output = output[None, :]
        target = jnp.reshape(target, (1,))
    n, c = output.shape
    k = min(k, c)
    # stable sort on negated scores: ties resolve to the LOWEST class index,
    # matching np.argmax / torch.topk (a reversed ascending argsort would
    # invert tie-breaking)
    idx = jnp.argsort(-output, axis=1, stable=True)[:, :k]  # top-k, 0-based
    hits = jnp.any(idx == (target.astype(jnp.int32) - 1)[:, None], axis=1)
    return jnp.sum(hits), n


class Top1Accuracy(ValidationMethod):
    """reference ``ValidationMethod.scala:116``."""

    name = "Top1Accuracy"

    def batch_result(self, output, target):
        return _topk_correct(output, target, 1)

    def to_result(self, value, count):
        return AccuracyResult(value, count)


class Top5Accuracy(ValidationMethod):
    """reference ``ValidationMethod.scala:154``."""

    name = "Top5Accuracy"

    def batch_result(self, output, target):
        return _topk_correct(output, target, 5)

    def to_result(self, value, count):
        return AccuracyResult(value, count)


class Loss(ValidationMethod):
    """Criterion-as-metric (reference ``ValidationMethod.scala:248``)."""

    name = "Loss"

    def __init__(self, criterion: Optional[Criterion] = None):
        self.criterion = criterion or ClassNLLCriterion()

    def batch_result(self, output, target):
        n = output.shape[0] if output.ndim > 1 else 1
        return self.criterion.apply(output, target) * n, n

    def to_result(self, value, count):
        return LossResult(value, count)


class PerplexityResult(ValidationResult):
    """(sum NLL over tokens, token count): result = exp(mean NLL)."""

    def __init__(self, nll: float, count: int):
        self.nll, self.count = float(nll), int(count)

    def state(self):
        return (self.nll, float(self.count))

    def result(self):
        import math
        return (math.exp(self.nll / max(1, self.count)), self.count)

    def __add__(self, other: "PerplexityResult"):
        return PerplexityResult(self.nll + other.nll, self.count + other.count)

    def __repr__(self):
        ppl, n = self.result()
        return f"Perplexity(tokens: {n}, ppl: {ppl:.4f})"


class Perplexity(ValidationMethod):
    """LM perplexity: exp of the mean per-token NLL — the standard LM eval
    metric, paired with the causal-LM workload (no reference analogue; the
    reference predates LMs). ``output`` is (B, S, V) LOG-PROBS (the LM's
    eval-mode output, unfused or ``LMHead``); ``target`` is (B, S) 1-based
    token ids. Tokens equal to ``ignore_index`` (e.g. padding) are skipped.
    """

    name = "Perplexity"

    def __init__(self, ignore_index: Optional[int] = None):
        self.ignore_index = ignore_index

    def batch_result(self, output, target):
        tgt = target.astype(jnp.int32)
        picked = jnp.take_along_axis(output, (tgt - 1)[..., None],
                                     axis=-1)[..., 0]
        if self.ignore_index is not None:
            valid = tgt != int(self.ignore_index)
            return -jnp.sum(jnp.where(valid, picked, 0.0)), jnp.sum(valid)
        return -jnp.sum(picked), picked.size

    def to_result(self, value, count):
        return PerplexityResult(value, count)
