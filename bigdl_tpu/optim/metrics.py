"""Named performance counters (reference ``optim/Metrics.scala:31``),
bridged onto the telemetry registry.

The reference backs these with Spark accumulators (driver-aggregated);
here each ``Metrics`` instance is a view over ``bigdl_tpu.telemetry``
gauge children — ``bigdl_legacy_metric{scope=...,name=...}`` — so the
training loop's counters land in the same ``GET /metrics`` scrape as the
serving SLOs, with no second bookkeeping copy (the registry child IS the
store; this class keeps only the ``parallel`` divisors and its name
set). ``scope`` is a per-instance label: successive optimizer runs in
one process stay distinguishable, fresh instances read zeros like they
always did, and a finalizer removes the instance's children from the
registry when it is collected — repeated Optimizer construction does not
grow the scrape forever.

``summary()`` prints the same per-phase report the reference dumps at
debug level (``DistriOptimizer.scala:283``).
"""

from __future__ import annotations

import itertools
import threading
import weakref
from typing import Tuple

from bigdl_tpu.telemetry import get_registry, instruments

_scope_ids = itertools.count()


def _drop_children(family, scope, names):
    """weakref.finalize callback — must not close over the instance."""
    for name in list(names):
        family.remove(scope=scope, name=name)


class Metrics:
    def __init__(self, registry=None):
        reg = registry if registry is not None else get_registry()
        # the family comes from the catalogue (single source of truth for
        # name/help/labels — docs/API.md renders the same spec)
        self._family = instruments(reg).legacy_metric
        self._scope = f"m{next(_scope_ids)}"
        self._lock = threading.Lock()
        self._parallel = {}     # name -> divisor (config, not a counter)
        weakref.finalize(self, _drop_children, self._family, self._scope,
                         self._parallel)

    def _child(self, name: str):
        return self._family.labels(scope=self._scope, name=name)

    def set(self, name: str, value: float, parallel: int = 1) -> None:
        with self._lock:
            self._parallel[name] = parallel
        self._child(name).set(value)

    def add(self, name: str, value: float) -> None:
        with self._lock:
            self._parallel.setdefault(name, 1)
        self._child(name).inc(value)

    def get(self, name: str) -> Tuple[float, int]:
        with self._lock:
            if name not in self._parallel:
                return (0.0, 1)
            n = self._parallel[name]
        return (self._child(name).value, n)

    def value(self, name: str) -> float:
        v, n = self.get(name)
        return v / max(1, n)

    def summary(self, unit: str = "s", scale: float = 1.0) -> str:
        with self._lock:
            names = sorted(self._parallel)
            divisors = dict(self._parallel)
        lines = ["========== Metrics Summary =========="]
        for name in names:
            v = self._child(name).value
            lines.append(f"{name} : {v / max(1, divisors[name]) / scale} "
                         f"{unit}")
        lines.append("=====================================")
        return "\n".join(lines)
