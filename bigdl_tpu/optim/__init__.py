"""bigdl_tpu.optim — optimization layer (reference ``$B/optim/``)."""

from bigdl_tpu.optim.methods import (
    OptimMethod, SGD, Adagrad, Adam, AdamW, Adamax, Adadelta, RMSprop, LBFGS,
    LearningRateSchedule, Default, Poly, Step, MultiStep, EpochStep,
    EpochDecay, Regime, EpochSchedule, Warmup, CosineDecay,
)
from bigdl_tpu.optim.triggers import Trigger
from bigdl_tpu.optim.validation import (
    ValidationMethod, ValidationResult, AccuracyResult, LossResult,
    PerplexityResult, Top1Accuracy, Top5Accuracy, Loss, Perplexity,
)
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optimizer import Optimizer, LocalOptimizer
from bigdl_tpu.optim.evaluator import Evaluator, Predictor
from bigdl_tpu.optim.validator import (Validator, LocalValidator,
                                       DistriValidator, calc_accuracy,
                                       calc_top5_accuracy)
