"""Optimization methods (reference ``optim/SGD.scala:29``, ``Adam.scala:26``,
``Adagrad.scala:31``, ``Adamax.scala:26``, ``Adadelta.scala:25``,
``RMSprop.scala:25``, ``Ftrl``-absent, ``LBFGS.scala:38``).

Design: each method is a *pure* (init_state, update) pair over parameter
pytrees — the shape jit/grad needs — wrapped in an object that also carries
the reference's Table-style hyper-parameters. The reference's
``optimize(feval, x, config, state)`` imperative entry exists too (used by
the LBFGS path and tests), built on the pure core.

Learning-rate schedules (reference ``SGD.scala:147-295``) are pure functions
of the traced step/epoch counters, so schedule changes never trigger a
recompile.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.utils.table import Table, T


# --------------------------------------------------------------------------
# Learning-rate schedules (reference SGD inner classes)
# --------------------------------------------------------------------------

class LearningRateSchedule:
    def rate(self, base_lr, state: Dict[str, Any]):
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + neval·decay) (reference ``SGD.Default``)."""

    def rate(self, base_lr, state):
        decay = state.get("learningRateDecay", 0.0)
        return base_lr / (1.0 + state["evalCounter"] * decay)


class Poly(LearningRateSchedule):
    """lr·(1 - iter/max)^power (reference ``SGD.Poly``)."""

    def __init__(self, power: float, max_iteration: int):
        self.power, self.max_iteration = power, max_iteration

    def rate(self, base_lr, state):
        it = jnp.minimum(state["evalCounter"], self.max_iteration)
        return base_lr * (1.0 - it / self.max_iteration) ** self.power


class Step(LearningRateSchedule):
    """lr·gamma^(floor(iter/stepSize)) (reference ``SGD.Step``)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size, self.gamma = step_size, gamma

    def rate(self, base_lr, state):
        return base_lr * self.gamma ** jnp.floor(state["evalCounter"] / self.step_size)


class MultiStep(LearningRateSchedule):
    """lr·gamma^(#milestones passed)."""

    def __init__(self, step_sizes, gamma: float):
        self.step_sizes = jnp.asarray(step_sizes)
        self.gamma = gamma

    def rate(self, base_lr, state):
        passed = jnp.sum(state["evalCounter"] >= self.step_sizes)
        return base_lr * self.gamma ** passed


class EpochStep(LearningRateSchedule):
    """lr·gamma^(floor(epoch/stepSize)) (reference ``SGD.EpochStep``)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size, self.gamma = step_size, gamma

    def rate(self, base_lr, state):
        return base_lr * self.gamma ** jnp.floor((state["epoch"] - 1) / self.step_size)


class EpochDecay(LearningRateSchedule):
    """lr·0.1^decay(epoch) with a user decay fn (reference ``SGD.EpochDecay``).
    The decay fn must be jax-traceable (int epoch array -> float)."""

    def __init__(self, decay_fn: Callable):
        self.decay_fn = decay_fn

    def rate(self, base_lr, state):
        return base_lr * 0.1 ** self.decay_fn(state["epoch"])


class Regime:
    """One row of an epoch-range schedule (reference ``SGD.Regime``)."""

    def __init__(self, start_epoch: int, end_epoch: int, config: Table):
        self.start_epoch, self.end_epoch = start_epoch, end_epoch
        self.config = config


class EpochSchedule(LearningRateSchedule):
    """Piecewise-per-epoch hyper config (reference ``SGD.EpochSchedule``)."""

    def __init__(self, regimes):
        self.regimes = list(regimes)

    def rate(self, base_lr, state):
        lr = base_lr
        for r in self.regimes:
            lr_r = r.config.get("learningRate", base_lr)
            in_range = (state["epoch"] >= r.start_epoch) & (state["epoch"] <= r.end_epoch)
            lr = jnp.where(in_range, lr_r, lr)
        return lr

    def weight_decay(self, base_wd, state):
        wd = base_wd
        for r in self.regimes:
            wd_r = r.config.get("weightDecay", base_wd)
            in_range = (state["epoch"] >= r.start_epoch) & (state["epoch"] <= r.end_epoch)
            wd = jnp.where(in_range, wd_r, wd)
        return wd


class CosineDecay(LearningRateSchedule):
    """Cosine annealing from base_lr to ``min_lr`` over ``decay_iterations``
    (the standard transformer-LM schedule; compose with ``Warmup`` for the
    canonical warmup+cosine recipe — no reference equivalent, the
    reference predates it)."""

    def __init__(self, decay_iterations: int, min_lr: float = 0.0):
        if decay_iterations <= 0:
            raise ValueError("decay_iterations must be > 0")
        self.decay_iterations = decay_iterations
        self.min_lr = min_lr

    def rate(self, base_lr, state):
        it = jnp.minimum(state["evalCounter"], self.decay_iterations)
        frac = it.astype(jnp.float32) / self.decay_iterations
        return self.min_lr + 0.5 * (base_lr - self.min_lr) * (
            1.0 + jnp.cos(jnp.pi * frac))


class Warmup(LearningRateSchedule):
    """Linear warmup then delegate (common TPU-scale recipe; no reference
    equivalent — large-batch training needs it)."""

    def __init__(self, warmup_iterations: int, after: LearningRateSchedule):
        self.warmup_iterations = warmup_iterations
        self.after = after

    def rate(self, base_lr, state):
        it = state["evalCounter"]
        warm = base_lr * (it + 1) / self.warmup_iterations
        # the inner schedule starts at 0 AFTER warmup (standard composed
        # semantics: Warmup(N, CosineDecay(T)) anneals over [N, N+T])
        after_state = {**state,
                       "evalCounter": it - self.warmup_iterations}
        return jnp.where(it < self.warmup_iterations, warm,
                         self.after.rate(base_lr, after_state))


# --------------------------------------------------------------------------
# OptimMethod protocol
# --------------------------------------------------------------------------

def _tree_zeros(params):
    return jax.tree_util.tree_map(jnp.zeros_like, params)


class OptimMethod:
    """Base optimizer (reference ``optim/OptimMethod.scala:25``)."""

    #: False for full-batch methods (LBFGS) that drive their own
    #: ``optimize(feval, x)`` loop and cannot run as a per-minibatch
    #: ``update`` inside Optimizer's jitted step.
    supports_minibatch = True

    def __init__(self, learningrate: float = 1e-3, weightdecay: float = 0.0):
        self.learningrate = learningrate
        self.weightdecay = weightdecay

    # pure core ------------------------------------------------------------
    def init_state(self, params) -> Dict[str, Any]:
        return {"evalCounter": jnp.asarray(0, jnp.int32),
                "epoch": jnp.asarray(1, jnp.int32)}

    def update(self, grads, state, params) -> Tuple[Any, Dict[str, Any]]:
        raise NotImplementedError

    def _decayed(self, grads, params):
        if self.weightdecay:
            return jax.tree_util.tree_map(
                lambda g, p: g + self.weightdecay * p, grads, params)
        return grads

    # reference-style imperative entry --------------------------------------
    def optimize(self, feval: Callable, x, state: Optional[Dict] = None):
        """Torch-style: feval(x) -> (loss, grad); returns (new_x, [loss]).

        Used by tests and the LBFGS-style drivers; the training loops use the
        pure ``update`` inside one jitted step instead.
        """
        if state is None:
            state = getattr(self, "_state", None)
            if state is None:
                state = self.init_state(x)
        loss, grad = feval(x)
        new_x, new_state = self.update(grad, state, x)
        self._state = new_state
        return new_x, [loss]

    def get_hyper_parameter(self) -> Table:
        return T(learningRate=self.learningrate, weightDecay=self.weightdecay)


class SGD(OptimMethod):
    """SGD with momentum/nesterov/dampening and pluggable LR schedules
    (reference ``optim/SGD.scala:29``)."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0,
                 weightdecay: float = 0.0,
                 momentum: float = 0.0,
                 dampening: Optional[float] = None,
                 nesterov: bool = False,
                 learningrate_schedule: Optional[LearningRateSchedule] = None):
        super().__init__(learningrate, weightdecay)
        self.learningrate_decay = learningrate_decay
        self.momentum = momentum
        self.dampening = dampening if dampening is not None else momentum
        self.nesterov = nesterov
        if nesterov:
            assert momentum > 0 and self.dampening == 0, \
                "nesterov requires momentum>0, dampening=0"
        self.schedule = learningrate_schedule or Default()

    def init_state(self, params):
        s = super().init_state(params)
        s["learningRateDecay"] = jnp.asarray(self.learningrate_decay)
        if self.momentum > 0:
            s["velocity"] = _tree_zeros(params)
        return s

    def current_rate(self, state):
        return self.schedule.rate(self.learningrate, state)

    def update(self, grads, state, params):
        lr = self.current_rate(state)
        wd = self.weightdecay
        if isinstance(self.schedule, EpochSchedule):
            wd = self.schedule.weight_decay(wd, state)
        grads = jax.tree_util.tree_map(lambda g, p: g + wd * p, grads, params) \
            if (self.weightdecay or isinstance(self.schedule, EpochSchedule)) else grads
        new_state = dict(state)
        if self.momentum > 0:
            mu, damp = self.momentum, self.dampening

            def vel(v, g):
                return mu * v + (1 - damp) * g

            v_new = jax.tree_util.tree_map(vel, state["velocity"], grads)
            if self.nesterov:
                step_dir = jax.tree_util.tree_map(
                    lambda g, v: g + mu * v, grads, v_new)
            else:
                step_dir = v_new
            new_state["velocity"] = v_new
        else:
            step_dir = grads
        new_params = jax.tree_util.tree_map(
            lambda p, d: p - lr * d, params, step_dir)
        new_state["evalCounter"] = state["evalCounter"] + 1
        return new_params, new_state


class Adagrad(OptimMethod):
    """reference ``optim/Adagrad.scala:31``."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0,
                 weightdecay: float = 0.0):
        super().__init__(learningrate, weightdecay)
        self.learningrate_decay = learningrate_decay

    def init_state(self, params):
        s = super().init_state(params)
        s["accum"] = _tree_zeros(params)
        return s

    def update(self, grads, state, params):
        grads = self._decayed(grads, params)
        lr = self.learningrate / (1.0 + state["evalCounter"] * self.learningrate_decay)
        accum = jax.tree_util.tree_map(lambda a, g: a + g * g, state["accum"], grads)
        new_params = jax.tree_util.tree_map(
            lambda p, g, a: p - lr * g / (jnp.sqrt(a) + 1e-10), params, grads, accum)
        return new_params, {**state, "accum": accum,
                            "evalCounter": state["evalCounter"] + 1}


class Adam(OptimMethod):
    """reference ``optim/Adam.scala:26`` (bias-corrected)."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, weightdecay: float = 0.0,
                 state_dtype=None):
        super().__init__(learningrate, weightdecay)
        self.learningrate_decay = learningrate_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        # state_dtype="bfloat16": store the m/v moments at half width — the
        # single biggest HBM lever for billion-param training on one chip
        # (fp32 Adam states are 8 bytes/param, more than the weights
        # themselves). Moment MATH stays fp32: states upcast on read and
        # round on store, so only the storage precision drops. Measured to
        # be what moves the one-chip capacity boundary past 1B params
        # (PERF.md round 4).
        self.state_dtype = state_dtype

    def _zeros_like_state(self, params):
        if self.state_dtype is None:
            return _tree_zeros(params)
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, self.state_dtype), params)

    def init_state(self, params):
        s = super().init_state(params)
        s["m"] = self._zeros_like_state(params)
        s["v"] = self._zeros_like_state(params)
        return s

    def _scheduled_lr(self, state):
        return self.learningrate / (1.0 + state["evalCounter"]
                                    * self.learningrate_decay)

    def update(self, grads, state, params):
        grads = self._decayed(grads, params)
        t = state["evalCounter"] + 1
        lr = self._scheduled_lr(state)
        b1, b2 = self.beta1, self.beta2
        sd = getattr(self, "state_dtype", None)
        up = (lambda x: x.astype(jnp.float32)) if sd else (lambda x: x)
        dn = (lambda x: x.astype(sd)) if sd else (lambda x: x)
        m = jax.tree_util.tree_map(
            lambda m_, g: dn(b1 * up(m_) + (1 - b1) * g), state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v_, g: dn(b2 * up(v_) + (1 - b2) * g * g),
            state["v"], grads)
        bc1 = 1 - b1 ** t.astype(jnp.float32)
        bc2 = 1 - b2 ** t.astype(jnp.float32)
        new_params = jax.tree_util.tree_map(
            lambda p, m_, v_: p - lr * (up(m_) / bc1)
            / (jnp.sqrt(up(v_) / bc2) + self.epsilon),
            params, m, v)
        return new_params, {**state, "m": m, "v": v, "evalCounter": t}


class AdamW(Adam):
    """Adam with DECOUPLED weight decay (Loshchilov & Hutter) — the
    standard transformer-LM optimizer, added beyond the reference (whose
    ``weightDecay`` is L2-coupled: it enters the gradient and hence the
    adaptive moments). Here decay multiplies the parameter directly by
    ``(1 - lr*decay)`` at the update, outside the moment estimates."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, weightdecay: float = 0.01,
                 state_dtype=None):
        super().__init__(learningrate, learningrate_decay, beta1, beta2,
                         epsilon, weightdecay=0.0, state_dtype=state_dtype)
        self.decoupled_decay = weightdecay

    def get_hyper_parameter(self):
        return T(learningRate=self.learningrate,
                 weightDecay=self.decoupled_decay)

    def update(self, grads, state, params):
        lr = self._scheduled_lr(state)
        if self.decoupled_decay:
            params = jax.tree_util.tree_map(
                lambda p: p * (1.0 - lr * self.decoupled_decay), params)
        return super().update(grads, state, params)


class Adamax(OptimMethod):
    """reference ``optim/Adamax.scala:26``."""

    def __init__(self, learningrate: float = 0.002,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-38, weightdecay: float = 0.0):
        super().__init__(learningrate, weightdecay)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        s = super().init_state(params)
        s["m"] = _tree_zeros(params)
        s["u"] = _tree_zeros(params)
        return s

    def update(self, grads, state, params):
        grads = self._decayed(grads, params)
        t = state["evalCounter"] + 1
        b1 = self.beta1
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        u = jax.tree_util.tree_map(
            lambda u_, g: jnp.maximum(self.beta2 * u_, jnp.abs(g) + self.epsilon),
            state["u"], grads)
        bc1 = 1 - b1 ** t.astype(jnp.float32)
        new_params = jax.tree_util.tree_map(
            lambda p, m_, u_: p - (self.learningrate / bc1) * m_ / u_, params, m, u)
        return new_params, {**state, "m": m, "u": u, "evalCounter": t}


class Adadelta(OptimMethod):
    """reference ``optim/Adadelta.scala:25``."""

    def __init__(self, decayrate: float = 0.9, epsilon: float = 1e-10):
        super().__init__(learningrate=1.0)
        self.rho, self.epsilon = decayrate, epsilon

    def init_state(self, params):
        s = super().init_state(params)
        s["accum"] = _tree_zeros(params)
        s["delta_accum"] = _tree_zeros(params)
        return s

    def update(self, grads, state, params):
        rho, eps = self.rho, self.epsilon
        accum = jax.tree_util.tree_map(
            lambda a, g: rho * a + (1 - rho) * g * g, state["accum"], grads)
        delta = jax.tree_util.tree_map(
            lambda d, a, g: jnp.sqrt(d + eps) / jnp.sqrt(a + eps) * g,
            state["delta_accum"], accum, grads)
        delta_accum = jax.tree_util.tree_map(
            lambda d, dl: rho * d + (1 - rho) * dl * dl, state["delta_accum"], delta)
        new_params = jax.tree_util.tree_map(lambda p, d: p - d, params, delta)
        return new_params, {**state, "accum": accum, "delta_accum": delta_accum,
                            "evalCounter": state["evalCounter"] + 1}


class RMSprop(OptimMethod):
    """reference ``optim/RMSprop.scala:25``."""

    def __init__(self, learningrate: float = 1e-2,
                 learningrate_decay: float = 0.0,
                 decayrate: float = 0.99, epsilon: float = 1e-8):
        super().__init__(learningrate)
        self.learningrate_decay = learningrate_decay
        self.rho, self.epsilon = decayrate, epsilon

    def init_state(self, params):
        s = super().init_state(params)
        s["accum"] = _tree_zeros(params)
        return s

    def update(self, grads, state, params):
        lr = self.learningrate / (1.0 + state["evalCounter"] * self.learningrate_decay)
        accum = jax.tree_util.tree_map(
            lambda a, g: self.rho * a + (1 - self.rho) * g * g, state["accum"], grads)
        new_params = jax.tree_util.tree_map(
            lambda p, g, a: p - lr * g / (jnp.sqrt(a) + self.epsilon),
            params, grads, accum)
        return new_params, {**state, "accum": accum,
                            "evalCounter": state["evalCounter"] + 1}


def _wolfe_line_search(feval, x, d, f0, g0, t0, c1: float = 1e-4,
                       c2: float = 0.9, max_iter: int = 25,
                       t_max: float = 1e8):
    """Strong-Wolfe line search along ``d`` (reference ``LineSearch.scala``
    lswolfe): bracket by doubling, then bisect until both the sufficient-
    decrease (Armijo, c1) and curvature (c2) conditions hold.

    Returns (t, f_t, g_t, n_evals); host-side loop around jitted fevals —
    the same CPU-control/TPU-compute split as LBFGS itself."""
    import math
    gtd0 = float(jnp.dot(g0, d))
    lo_t, lo_f, lo_g = 0.0, f0, g0
    hi_t = None
    t = t0
    evals = 0
    for _ in range(max_iter):
        f_t, g_t = feval(x + t * d)
        f_t = float(f_t)
        evals += 1
        gtd = float(jnp.dot(g_t, d))
        if not math.isfinite(f_t):
            hi_t = t  # overflow at this step: shrink, never extend
        elif f_t > f0 + c1 * t * gtd0 or (evals > 1 and f_t >= lo_f):
            hi_t = t  # overshot: minimum bracketed in (lo_t, t)
        elif abs(gtd) <= -c2 * gtd0:
            return t, f_t, g_t, evals  # strong Wolfe satisfied
        elif gtd >= 0:
            hi_t = t  # slope turned positive: bracketed
        else:
            lo_t, lo_f, lo_g = t, f_t, g_t
            if hi_t is None:
                t = min(2.0 * t, t_max)  # still descending: extend
                continue
        t = 0.5 * (lo_t + hi_t)  # bisect the bracket
        if hi_t - lo_t < 1e-12:
            break
    # Wolfe not met within budget: fall back to the best EVALUATED point
    # (t=0 = no step if nothing improved) — returning a re-bisected t whose
    # f/g were never evaluated would corrupt the L-BFGS curvature pairs.
    return lo_t, lo_f, lo_g, evals


class LBFGS(OptimMethod):
    """Limited-memory BFGS with optional line search
    (reference ``optim/LBFGS.scala:38`` + ``LineSearch.scala``).

    Full-batch second-order method; runs as a host-side loop around a jitted
    (loss, grad) evaluation — the natural TPU split, since the two-loop
    recursion is O(m·n) vector work best left to XLA but the control flow is
    data-dependent.
    """

    supports_minibatch = False

    def __init__(self, max_iter: int = 20, max_eval: Optional[float] = None,
                 tolfun: float = 1e-5, tolx: float = 1e-9,
                 ncorrection: int = 100, learningrate: float = 1.0,
                 linesearch: bool = False):
        super().__init__(learningrate)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 1.25
        self.tolfun, self.tolx = tolfun, tolx
        self.ncorrection = ncorrection
        self.linesearch = linesearch

    def optimize(self, feval, x, state=None):
        from jax.flatten_util import ravel_pytree
        x_flat, unravel = ravel_pytree(x)
        loss, g = feval(x)
        g_flat, _ = ravel_pytree(g)
        losses = [float(loss)]
        old_dirs, old_steps = [], []
        H_diag = 1.0
        prev_flat, prev_g = x_flat, g_flat
        n_eval = 1
        for it in range(self.max_iter):
            if jnp.max(jnp.abs(g_flat)) <= self.tolfun:
                break
            if it == 0:
                d = -g_flat
            else:
                y = g_flat - prev_g
                s = x_flat - prev_flat
                ys = jnp.dot(y, s)
                if ys > 1e-10:
                    if len(old_dirs) >= self.ncorrection:
                        old_dirs.pop(0)
                        old_steps.pop(0)
                    old_dirs.append(y)
                    old_steps.append(s)
                    H_diag = ys / jnp.dot(y, y)
                # two-loop recursion
                k = len(old_dirs)
                ro = [1.0 / jnp.dot(old_dirs[i], old_steps[i]) for i in range(k)]
                q = -g_flat
                al = [None] * k
                for i in range(k - 1, -1, -1):
                    al[i] = jnp.dot(old_steps[i], q) * ro[i]
                    q = q - al[i] * old_dirs[i]
                d = q * H_diag
                for i in range(k):
                    be_i = jnp.dot(old_dirs[i], d) * ro[i]
                    d = d + (al[i] - be_i) * old_steps[i]
            prev_flat, prev_g, prev_loss = x_flat, g_flat, loss
            gtd = jnp.dot(g_flat, d)
            if gtd > -self.tolx:
                break
            t = min(1.0, 1.0 / float(jnp.sum(jnp.abs(g_flat)))) \
                if it == 0 else self.learningrate
            if self.linesearch:
                def feval_flat(xf):
                    l, gr = feval(unravel(xf))
                    return l, ravel_pytree(gr)[0]

                t, loss, g_flat, evals = _wolfe_line_search(
                    feval_flat, x_flat, d, float(loss), g_flat, t)
                x_flat = x_flat + t * d
                n_eval += evals
            else:
                x_flat = x_flat + t * d
                loss, g = feval(unravel(x_flat))
                g_flat, _ = ravel_pytree(g)
                n_eval += 1
            losses.append(float(loss))
            if n_eval >= self.max_eval:
                break
            if jnp.abs(loss - prev_loss) < self.tolfun:
                break
            if jnp.max(jnp.abs(t * d)) <= self.tolx:
                break
        return unravel(x_flat), losses

    def update(self, grads, state, params):  # pragma: no cover - not iterative
        raise NotImplementedError("LBFGS uses optimize(feval, x)")
