"""Batch evaluation / prediction drivers (reference ``optim/Evaluator.scala:37``,
``optim/Predictor.scala:34``).

The reference broadcasts the model to executors and mapPartitions over the
RDD; here a single jitted forward is reused across batches (and sharded over
the mesh by ``parallel.distri_optimizer`` when one is active).
``evaluate_batches`` is the one batch-eval/merge loop — Evaluator, Predictor
and in-training validation all delegate to it.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.base import (AbstractDataSet, LocalDataSet, MiniBatch,
                                    Sample, SampleToBatch)
from bigdl_tpu.nn.module import Module, functional_apply
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult
from bigdl_tpu.telemetry import get_registry, instruments, span
from bigdl_tpu.telemetry.profiling import tracked_jit


def _as_minibatch(item) -> MiniBatch:
    if isinstance(item, Sample):
        return MiniBatch(item.feature[None], jnp.atleast_1d(item.label))
    return item


def evaluate_batches(fwd: Callable, params, buffers,
                     batches: Iterable,
                     v_methods: Sequence[ValidationMethod],
                     cache: Optional[dict] = None,
                     ) -> Tuple[List[Optional[ValidationResult]], int]:
    """Run ``fwd(params, buffers, data)`` over batches, merging each method's
    ValidationResults. Returns (results, record_count).

    A tail batch smaller than the first-seen batch is zero-padded up to the
    static shape before ``fwd`` (XLA would otherwise compile a second
    program for the one odd shape) and the padded rows are sliced off the
    output before scoring — every record is evaluated, none double-counted.

    Telemetry: ``bigdl_eval_batches_total`` / ``bigdl_eval_records_total``
    counters + a per-batch host wall-clock histogram land in the global
    registry; the whole call traces as one ``eval.batches`` span.
    """
    with span("eval.batches", methods=len(v_methods)):
        return _evaluate_batches(fwd, params, buffers, batches, v_methods,
                                 cache)


def _evaluate_batches(fwd, params, buffers, batches, v_methods, cache):
    import time
    tm = instruments(get_registry())
    results: List[Optional[ValidationResult]] = [None] * len(v_methods)
    count = 0
    full_bs: Optional[int] = None
    sliceable: Optional[bool] = None  # learned from the first (full) batch
    # Device-side accumulation (steady state): one jitted dispatch per
    # batch carries a donated (M, 2) [value, count] accumulator — the
    # per-batch ``float(v)`` host syncs otherwise dominate eval on
    # dispatch-latency-bound backends (each sync ~a full RPC round trip).
    # Callers that evaluate repeatedly (the training loop's validation
    # trigger) pass a persistent ``cache`` dict so the scorer jit is traced
    # ONCE, not per validation (a per-call retrace costs seconds and undoes
    # the win).
    # The fast path jits each method's pure device core. A custom subclass
    # that overrides only apply() (the old per-batch contract) has no such
    # core — run the whole loop on the compatible eager path for it.
    from bigdl_tpu.optim.validation import ValidationMethod as _VM
    fast_ok = all(type(m).batch_result is not _VM.batch_result
                  for m in v_methods)
    # id()-keyed: exact and collision-safe (the cached closure pins the
    # objects alive). Callers constructing FRESH method instances per call
    # miss the cache and pay a retrace — reuse method objects across
    # evaluations (the training loop's validation path does).
    cache_key = (id(fwd),) + tuple(id(m) for m in v_methods)
    scorer = (cache or {}).get(cache_key)
    scorer_cached = scorer is not None
    if fast_ok and scorer is None:
        # built ONCE before the batch loop (graftlint JG004: a jax.jit
        # call inside the loop — even lazily guarded — is the
        # recompile-churn shape; tracing still happens at first use).
        # The CACHE insert stays lazy (first fast-path batch): evicting a
        # valid entry for a scorer that never runs would cost the next
        # evaluation its cached trace.
        def scorer_fn(p, b, x, y, a):
            out = fwd(p, b, x)
            av, ac = a
            # values accumulate f32 (per-batch sums are f32 device
            # results anyway); counts accumulate int32 — EXACT to
            # 2^31 records where an f32 count goes wrong past 2^24
            pairs = [m.batch_result(out, y) for m in v_methods]
            vs = jnp.stack([jnp.asarray(v).astype(jnp.float32)
                            for v, _ in pairs])
            cs = jnp.stack([jnp.asarray(c).astype(jnp.int32)
                            for _, c in pairs])
            return av + vs, ac + cs

        scorer = tracked_jit(scorer_fn, site="eval.scorer",
                             donate_argnums=(4,))
    acc = None
    n_batches = 0
    for item in batches:
        t_batch = time.perf_counter()
        n_batches += 1
        batch = _as_minibatch(item)
        n = batch.size()
        data = jnp.asarray(batch.data)
        if full_bs is None:
            full_bs = n
        labels = jnp.asarray(batch.labels)
        if fast_ok and sliceable and n == full_bs:
            if cache is not None and not scorer_cached:
                cache.clear()  # fwd/methods changed: old entry is stale
                # graftlint: ignore[JG013] -- one-entry cache: cleared immediately above, so at most one program is ever retained
                cache[cache_key] = scorer
                scorer_cached = True
            if acc is None:
                acc = (jnp.zeros((len(v_methods),), jnp.float32),
                       jnp.zeros((len(v_methods),), jnp.int32))
            acc = scorer(params, buffers, data, labels, acc)
            count += n
            tm.eval_batch_seconds.observe(time.perf_counter() - t_batch)
            continue
        if n < full_bs and sliceable:
            pad = jnp.zeros((full_bs - n, *data.shape[1:]), data.dtype)
            out = fwd(params, buffers, jnp.concatenate([data, pad]))[:n]
        else:  # first batch, or structured output needing the exact shape
            out = fwd(params, buffers, data)
            if sliceable is None:
                sliceable = isinstance(out, jax.Array)
        for i, m in enumerate(v_methods):
            r = m.apply(out, labels)
            results[i] = r if results[i] is None else results[i] + r
        count += n
        tm.eval_batch_seconds.observe(time.perf_counter() - t_batch)
    tm.eval_batches_total.inc(n_batches)
    tm.eval_records_total.inc(count)
    if acc is not None:
        vals = np.asarray(acc[0])  # the ONE device->host sync
        counts = np.asarray(acc[1])
        for i, m in enumerate(v_methods):
            r = m.to_result(float(vals[i]), int(counts[i]))
            results[i] = r if results[i] is None else results[i] + r
    return results, count


class Evaluator:
    """reference ``optim/Evaluator.scala``."""

    def __init__(self, model: Module, batch_size: int = 128):
        self.model = model
        self.batch_size = batch_size
        self._eval_cache = {}  # scorer jit, traced once per (fwd, methods)

    def _as_batches(self, dataset):
        if isinstance(dataset, AbstractDataSet):
            return dataset.data(train=False)
        # raw list of Samples: batch them (reference uses SampleToBatch(4/p))
        ds = LocalDataSet(dataset) >> SampleToBatch(self.batch_size,
                                                    drop_remainder=False)
        return ds.data(train=False)

    def _fwd(self):
        # cached: repeated .test() calls (an eval loop) must not retrace
        if getattr(self, "_fwd_jit", None) is None:
            model = self.model

            def fwd(p, b, x):
                out, _ = functional_apply(model, p, b, x, training=False)
                return out

            self._fwd_jit = tracked_jit(fwd, site="eval.forward")
        return self._fwd_jit

    def test(self, dataset, v_methods: Sequence[ValidationMethod]
             ) -> List[Tuple[ValidationResult, ValidationMethod]]:
        params, buffers = self.model.functional_state()
        results, _ = evaluate_batches(self._fwd(), params, buffers,
                                      self._as_batches(dataset), v_methods,
                                      cache=self._eval_cache)
        return [(r, m) for r, m in zip(results, v_methods)]


class Predictor:
    """reference ``optim/Predictor.scala``."""

    def __init__(self, model: Module, batch_size: int = 128):
        self.model = model
        self.batch_size = batch_size

    def predict(self, dataset) -> List:
        ev = Evaluator(self.model, self.batch_size)
        fwd = ev._fwd()
        params, buffers = self.model.functional_state()
        outs = []
        for item in ev._as_batches(dataset):
            batch = _as_minibatch(item)
            outs.append(fwd(params, buffers, jnp.asarray(batch.data)))
        return outs

    def predict_class(self, dataset) -> List:
        return [jnp.argmax(o, axis=-1) + 1 for o in self.predict(dataset)]
