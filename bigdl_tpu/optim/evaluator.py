"""Batch evaluation / prediction drivers (reference ``optim/Evaluator.scala:37``,
``optim/Predictor.scala:34``).

The reference broadcasts the model to executors and mapPartitions over the
RDD; here a single jitted forward is reused across batches (and sharded over
the mesh by ``parallel.distri_optimizer`` when one is active).
``evaluate_batches`` is the one batch-eval/merge loop — Evaluator, Predictor
and in-training validation all delegate to it.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.dataset.base import (AbstractDataSet, LocalDataSet, MiniBatch,
                                    Sample, SampleToBatch)
from bigdl_tpu.nn.module import Module, functional_apply
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult


def _as_minibatch(item) -> MiniBatch:
    if isinstance(item, Sample):
        return MiniBatch(item.feature[None], jnp.atleast_1d(item.label))
    return item


def evaluate_batches(fwd: Callable, params, buffers,
                     batches: Iterable,
                     v_methods: Sequence[ValidationMethod],
                     ) -> Tuple[List[Optional[ValidationResult]], int]:
    """Run ``fwd(params, buffers, data)`` over batches, merging each method's
    ValidationResults. Returns (results, record_count).

    A tail batch smaller than the first-seen batch is zero-padded up to the
    static shape before ``fwd`` (XLA would otherwise compile a second
    program for the one odd shape) and the padded rows are sliced off the
    output before scoring — every record is evaluated, none double-counted.
    """
    results: List[Optional[ValidationResult]] = [None] * len(v_methods)
    count = 0
    full_bs: Optional[int] = None
    sliceable: Optional[bool] = None  # learned from the first (full) batch
    for item in batches:
        batch = _as_minibatch(item)
        n = batch.size()
        data = jnp.asarray(batch.data)
        if full_bs is None:
            full_bs = n
        if n < full_bs and sliceable:
            pad = jnp.zeros((full_bs - n, *data.shape[1:]), data.dtype)
            out = fwd(params, buffers, jnp.concatenate([data, pad]))[:n]
        else:  # full batch, or structured output needing the exact shape
            out = fwd(params, buffers, data)
            if sliceable is None:
                sliceable = isinstance(out, jax.Array)
        labels = jnp.asarray(batch.labels)
        for i, m in enumerate(v_methods):
            r = m.apply(out, labels)
            results[i] = r if results[i] is None else results[i] + r
        count += n
    return results, count


class Evaluator:
    """reference ``optim/Evaluator.scala``."""

    def __init__(self, model: Module, batch_size: int = 128):
        self.model = model
        self.batch_size = batch_size

    def _as_batches(self, dataset):
        if isinstance(dataset, AbstractDataSet):
            return dataset.data(train=False)
        # raw list of Samples: batch them (reference uses SampleToBatch(4/p))
        ds = LocalDataSet(dataset) >> SampleToBatch(self.batch_size,
                                                    drop_remainder=False)
        return ds.data(train=False)

    def _fwd(self):
        model = self.model

        @jax.jit
        def fwd(p, b, x):
            out, _ = functional_apply(model, p, b, x, training=False)
            return out

        return fwd

    def test(self, dataset, v_methods: Sequence[ValidationMethod]
             ) -> List[Tuple[ValidationResult, ValidationMethod]]:
        params, buffers = self.model.functional_state()
        results, _ = evaluate_batches(self._fwd(), params, buffers,
                                      self._as_batches(dataset), v_methods)
        return [(r, m) for r, m in zip(results, v_methods)]


class Predictor:
    """reference ``optim/Predictor.scala``."""

    def __init__(self, model: Module, batch_size: int = 128):
        self.model = model
        self.batch_size = batch_size

    def predict(self, dataset) -> List:
        ev = Evaluator(self.model, self.batch_size)
        fwd = ev._fwd()
        params, buffers = self.model.functional_state()
        outs = []
        for item in ev._as_batches(dataset):
            batch = _as_minibatch(item)
            outs.append(fwd(params, buffers, jnp.asarray(batch.data)))
        return outs

    def predict_class(self, dataset) -> List:
        return [jnp.argmax(o, axis=-1) + 1 for o in self.predict(dataset)]
