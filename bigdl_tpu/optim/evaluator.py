"""Batch evaluation / prediction drivers (reference ``optim/Evaluator.scala:37``,
``optim/Predictor.scala:34``).

The reference broadcasts the model to executors and mapPartitions over the
RDD; here a single jitted forward is reused across batches (and sharded over
the mesh by ``parallel.distri_optimizer`` when one is active).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.dataset.base import AbstractDataSet, MiniBatch, Sample, SampleToBatch, LocalDataSet
from bigdl_tpu.nn.module import Module, functional_apply
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult


class Evaluator:
    """reference ``optim/Evaluator.scala``."""

    def __init__(self, model: Module, batch_size: int = 128):
        self.model = model
        self.batch_size = batch_size

    def _as_batches(self, dataset):
        if isinstance(dataset, AbstractDataSet):
            it = dataset.data(train=False)
            probe = next(iter([]), None)
            return it
        # list of Samples
        ds = LocalDataSet(dataset) >> SampleToBatch(self.batch_size,
                                                    drop_remainder=False)
        return ds.data(train=False)

    def test(self, dataset, v_methods: Sequence[ValidationMethod]
             ) -> List[Tuple[ValidationResult, ValidationMethod]]:
        model = self.model
        params, buffers = model.parameter_tree(), model.buffer_tree()

        @jax.jit
        def fwd(p, b, x):
            out, _ = functional_apply(model, p, b, x, training=False)
            return out

        results = [None] * len(v_methods)
        for batch in self._as_batches(dataset):
            if isinstance(batch, Sample):  # raw sample stream
                batch = MiniBatch(batch.feature[None], jnp.atleast_1d(batch.label))
            out = fwd(params, buffers, jnp.asarray(batch.data))
            labels = jnp.asarray(batch.labels)
            for i, m in enumerate(v_methods):
                r = m.apply(out, labels)
                results[i] = r if results[i] is None else results[i] + r
        return [(r, m) for r, m in zip(results, v_methods)]


class Predictor:
    """reference ``optim/Predictor.scala``."""

    def __init__(self, model: Module, batch_size: int = 128):
        self.model = model
        self.batch_size = batch_size

    def predict(self, dataset) -> List:
        model = self.model
        params, buffers = model.parameter_tree(), model.buffer_tree()

        @jax.jit
        def fwd(p, b, x):
            out, _ = functional_apply(model, p, b, x, training=False)
            return out

        outs = []
        ev = Evaluator(model, self.batch_size)
        for batch in ev._as_batches(dataset):
            outs.append(fwd(params, buffers, jnp.asarray(batch.data)))
        return outs

    def predict_class(self, dataset) -> List:
        return [jnp.argmax(o, axis=-1) + 1 for o in self.predict(dataset)]
