"""Engine: process-global accelerator topology, the TPU analogue of
``utils/Engine.scala:32``.

The reference Engine parses Spark configs into (nExecutors x coresPerExecutor)
and owns two JVM thread pools that fan work out over cores. On TPU the unit of
parallelism is a *chip on a mesh*, not a core in a thread pool: XLA already
parallelises within a chip (MXU/VPU lanes), so ``Engine.model``-style intra-op
pools are unnecessary. What remains Engine's job:

- device discovery (``jax.devices()``), local vs. global counts (multi-host),
- construction of the default `jax.sharding.Mesh` used by DistriOptimizer,
- a small host-side IO thread pool (data pipeline prefetch — the one place
  host threads still matter, replacing ``Engine.default``),
- environment sanity checks (the analogue of ``Engine.checkSparkContext``).
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
import logging
from typing import List, Optional, Sequence

import numpy as np

logger = logging.getLogger("bigdl_tpu")


class _EngineState:
    def __init__(self) -> None:
        self.initialized = False
        self.dist_checked = False
        self.env_warned: set = set()
        self.node_number = 1
        self.core_number = 1
        self._devices = None
        self._mesh = None
        self._io_pool: Optional[ThreadPoolExecutor] = None
        self._lock = threading.Lock()


_state = _EngineState()


class Engine:
    """Process-global topology singleton (reference ``utils/Engine.scala``)."""

    @staticmethod
    def init(node_number: Optional[int] = None,
             core_number: Optional[int] = None,
             devices: Optional[Sequence] = None) -> None:
        """Initialise topology.

        ``node_number``/``core_number`` retain the reference's names
        (``Engine.init`` at ``utils/Engine.scala:100``) but map to hosts and
        local chips. With no arguments, discovers the JAX runtime topology.
        """
        Engine._maybe_init_distributed()
        import jax

        with _state._lock:
            _state._devices = list(devices) if devices is not None else jax.devices()
            _state.node_number = node_number if node_number is not None else jax.process_count()
            _state.core_number = (core_number if core_number is not None
                                  else max(1, len(_state._devices) // max(1, _state.node_number)))
            _state._mesh = None  # rebuilt lazily against the new device set
            _state.initialized = True
        # pin the native runtime's host threads to the declared core budget
        # (reference ThreadPool.setMKLThread / MKL.setNumThreads)
        try:
            from bigdl_tpu import native
            native.set_num_threads(_state.core_number)
        except Exception:  # pragma: no cover - native layer is optional
            pass
        Engine.check_env()

    @staticmethod
    def check_env(strict: bool = False) -> List[str]:
        """Verify the launch environment the way the reference verifies its
        required spark conf (``Engine.checkSparkContext``,
        ``utils/Engine.scala:269-293`` against ``spark-bigdl.conf:31-43``).

        ``scripts/bigdl-tpu.sh`` sets these; a bare ``python`` invocation
        gets warnings (or, with ``strict=True`` ≙ the reference's
        ``forceCheck``, an error) listing what's off. Returns the list of
        complaint strings. Suppress with ``BIGDL_TPU_DISABLE_ENV_CHECK=1``
        (reference ``bigdl.disableCheckSysEnv``)."""
        problems: List[str] = []
        disable = os.environ.get("BIGDL_TPU_DISABLE_ENV_CHECK", "")
        if disable.strip().lower() in ("1", "true", "yes", "y", "on"):
            return problems
        if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            problems.append(
                "JAX_COMPILATION_CACHE_DIR is unset: every process pays the "
                "full XLA compile (20-40s for large models); run under "
                "scripts/bigdl-tpu.sh or export a cache dir")
        omp = os.environ.get("OMP_NUM_THREADS")
        if omp is not None:
            omp = omp.strip()
        if omp is None or not omp.isdigit() or not 1 <= int(omp) <= 4:
            problems.append(
                f"OMP_NUM_THREADS={omp or '<unset>'}: host BLAS/OpenMP "
                "threads fight the data-pipeline IO pool; the launcher "
                "pins it to 1 (reference spark-bigdl.conf OMP_NUM_THREADS=1)")
        # warn once per process per complaint — library-style users re-init
        # Engine freely and should not see the same nag every time
        for p in problems:
            if p not in _state.env_warned:
                _state.env_warned.add(p)
                logger.warning("[Engine.check_env] %s", p)
        if strict and problems:
            raise RuntimeError("launch environment check failed:\n  "
                               + "\n  ".join(problems))
        return problems

    @staticmethod
    def _maybe_init_distributed() -> None:
        """Multi-host bring-up: ``jax.distributed.initialize`` from env.

        The reference parses its cluster topology out of spark-submit
        properties (``utils/Engine.scala:346-416``); here the launcher
        exports a coordinator endpoint instead:

        - ``BIGDL_COORDINATOR_ADDRESS`` (or ``JAX_COORDINATOR_ADDRESS``) —
          host:port of process 0's coordination service,
        - ``BIGDL_NUM_PROCESSES`` / ``BIGDL_PROCESS_ID`` (or the JAX names).

        On a real TPU pod slice none of these are needed (JAX auto-detects
        via the TPU metadata server) — initialize is then a no-arg call,
        triggered by ``BIGDL_AUTO_DISTRIBUTED=1``. Idempotent.
        """
        if _state.dist_checked:
            return
        coord = (os.environ.get("BIGDL_COORDINATOR_ADDRESS")
                 or os.environ.get("JAX_COORDINATOR_ADDRESS"))
        auto = os.environ.get("BIGDL_AUTO_DISTRIBUTED", "0") == "1"
        if not coord and not auto:
            _state.dist_checked = True
            return
        import jax
        # jax < 0.5 has no jax.distributed.is_initialized; _state.dist_checked
        # already makes this call once-per-process, so absence just means we
        # proceed straight to initialize
        is_init = getattr(jax.distributed, "is_initialized", None)
        if is_init is not None and is_init():
            _state.dist_checked = True
            return
        # CPU multi-process collectives need the gloo implementation
        # selected BEFORE the backend initializes (jax >= 0.4.34 otherwise
        # refuses cross-process computations on CPU); a no-op on TPU pods
        # and on jax versions without the option.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # unknown option on this jax — leave defaults
            pass
        # A genuine connect failure must RAISE: swallowing it would let N
        # hosts silently train independently against one checkpoint path.
        if coord:
            nproc = (os.environ.get("BIGDL_NUM_PROCESSES")
                     or os.environ.get("JAX_NUM_PROCESSES"))
            pid = (os.environ.get("BIGDL_PROCESS_ID")
                   or os.environ.get("JAX_PROCESS_ID"))
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(nproc) if nproc else None,
                process_id=int(pid) if pid else None)
        else:
            jax.distributed.initialize()
        _state.dist_checked = True
        if jax.process_index() != 0:
            # driver-style logging: per-iteration INFO only on process 0
            # (reference logs on the Spark driver only)
            import logging
            logging.getLogger("bigdl_tpu.optim").setLevel(logging.WARNING)

    @staticmethod
    def process_index() -> int:
        """This host's rank (0 = the 'driver' for logging/checkpoint IO)."""
        Engine._maybe_init_distributed()  # before the backend freezes
        import jax
        return jax.process_index()

    @staticmethod
    def process_count() -> int:
        Engine._maybe_init_distributed()
        import jax
        return jax.process_count()

    @staticmethod
    def local_devices():
        import jax
        return jax.local_devices()

    @staticmethod
    def is_initialized() -> bool:
        return _state.initialized

    @staticmethod
    def node_number() -> int:
        Engine._ensure()
        return _state.node_number

    @staticmethod
    def core_number() -> int:
        Engine._ensure()
        return _state.core_number

    @staticmethod
    def devices():
        Engine._ensure()
        return list(_state._devices)

    @staticmethod
    def device_count() -> int:
        return len(Engine.devices())

    @staticmethod
    def default_mesh(axis_name: str = "data"):
        """The 1-D data-parallel mesh over all devices.

        This is the TPU-native stand-in for the reference's implicit
        "one partition per executor" topology (``AllReduceParameter`` slice
        ownership): every chip holds a full replica, gradients are reduced by
        an XLA ``psum`` riding ICI instead of BlockManager fetches.
        """
        from jax.sharding import Mesh

        Engine._ensure()
        if _state._mesh is None or _state._mesh.axis_names != (axis_name,):
            devs = np.array(Engine.devices())
            _state._mesh = Mesh(devs, (axis_name,))
        return _state._mesh

    @staticmethod
    def io_pool() -> ThreadPoolExecutor:
        """Host-side IO/prefetch pool (descendant of ``Engine.default``,
        ``utils/Engine.scala:236-241`` — here only for the data pipeline)."""
        Engine._ensure()
        if _state._io_pool is None:
            n = int(os.environ.get("BIGDL_TPU_IO_THREADS", str(min(16, os.cpu_count() or 4))))
            _state._io_pool = ThreadPoolExecutor(max_workers=n, thread_name_prefix="bigdl-io")
        return _state._io_pool

    @staticmethod
    def check_singleton() -> bool:
        """One training process per host (reference ``Engine.checkSingleton``,
        ``utils/Engine.scala:160`` — there a JVM-wide flag; here an exclusive
        host lock file keyed by $BIGDL_SINGLETON_DIR). Returns True when this
        process holds (or just acquired) the claim; False when another live
        process holds it. Disabled unless BIGDL_CHECK_SINGLETON=1, matching
        the reference's ``bigdl.check.singleton`` property."""
        import os
        if os.environ.get("BIGDL_CHECK_SINGLETON", "0") != "1":
            return True
        import tempfile
        lock_dir = os.environ.get("BIGDL_SINGLETON_DIR",
                                  tempfile.gettempdir())
        path = os.path.join(lock_dir, "bigdl_tpu.singleton.lock")
        pid = os.getpid()

        def try_claim() -> bool:
            # write pid to a private file, then hard-link it into place —
            # link(2) is atomic, so exactly one contender wins and the lock
            # file is never observable with partial/empty contents
            tmp = f"{path}.{pid}"
            try:
                with open(tmp, "w") as f:
                    f.write(str(pid))
                os.link(tmp, path)
                return True
            except FileExistsError:
                return False
            finally:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

        if try_claim():
            return True
        try:
            holder = int(open(path).read().strip() or "0")
        except (OSError, ValueError):
            holder = 0
        if holder == pid:
            return True
        if holder:
            try:
                os.kill(holder, 0)  # probe liveness
                return False  # live holder
            except ProcessLookupError:
                pass  # stale lock from a dead process — take it over
            except PermissionError:
                return False  # live process of another user holds it
        else:
            return False  # unreadable/foreign lock: don't steal
        try:
            os.unlink(path)
        except OSError:
            pass
        return try_claim()  # only one stale-lock contender wins the link

    @staticmethod
    def reset() -> None:
        """Forget topology (test hook, analogue of re-running Engine.init)."""
        with _state._lock:
            if _state._io_pool is not None:
                _state._io_pool.shutdown(wait=False)
            _state.__init__()

    @staticmethod
    def _ensure() -> None:
        if not _state.initialized:
            Engine.init()
