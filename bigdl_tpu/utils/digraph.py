"""General directed-graph utility (reference ``utils/DirectedGraph.scala:33``
and its ``Node`` at ``:120``): Kahn topological sort with cycle check, DFS,
BFS, and the ``>>`` edge builder (Scala's ``->``). ``nn.Graph`` keeps its own
specialized module-graph walk; this is the standalone structure the reference
exposes for user code.
"""

from __future__ import annotations

from typing import Any, Iterator, List


class Node:
    """A graph node holding an ``element`` with directed edges
    (reference ``DirectedGraph.scala:120``)."""

    def __init__(self, element: Any):
        self.element = element
        self.prevs: List["Node"] = []
        self.nexts: List["Node"] = []

    def __rshift__(self, other: "Node") -> "Node":
        """``a >> b`` adds the edge a->b and returns ``b`` for chaining
        (Scala's ``a -> b``)."""
        self.nexts.append(other)
        other.prevs.append(self)
        return other

    add = __rshift__

    def __repr__(self):
        return f"Node({self.element!r})"


class DirectedGraph:
    """Graph rooted at ``source``; ``reverse=True`` walks edges backwards
    (the reference builds its module graph reversed from a dummy output)."""

    def __init__(self, source: Node, reverse: bool = False):
        self.source = source
        self.reverse = reverse

    def _adj(self, node: Node) -> List[Node]:
        return node.prevs if self.reverse else node.nexts

    def size(self) -> int:
        return sum(1 for _ in self.bfs())

    def edges(self) -> int:
        return sum(len(self._adj(n)) for n in self.bfs())

    def bfs(self) -> Iterator[Node]:
        """Breadth-first traversal from the source."""
        from collections import deque
        seen = {id(self.source)}
        q = deque([self.source])
        while q:
            n = q.popleft()
            yield n
            for s in self._adj(n):
                if id(s) not in seen:
                    seen.add(id(s))
                    q.append(s)

    def dfs(self) -> Iterator[Node]:
        """Depth-first traversal from the source."""
        seen = set()
        stack = [self.source]
        while stack:
            n = stack.pop()
            if id(n) in seen:
                continue
            seen.add(id(n))
            yield n
            stack.extend(self._adj(n))

    def topology_sort(self) -> List[Node]:
        """Kahn's algorithm over the reachable subgraph; raises on cycles
        (reference ``DirectedGraph.topologySort``)."""
        nodes = list(self.bfs())
        ids = {id(n) for n in nodes}
        indegree = {id(n): 0 for n in nodes}
        for n in nodes:
            for s in self._adj(n):
                if id(s) in ids:
                    indegree[id(s)] += 1
        ready = [n for n in nodes if indegree[id(n)] == 0]
        order: List[Node] = []
        while ready:
            n = ready.pop()
            order.append(n)
            for s in self._adj(n):
                indegree[id(s)] -= 1
                if indegree[id(s)] == 0:
                    ready.append(s)
        if len(order) != len(nodes):
            raise ValueError("graph contains a cycle")
        return order
