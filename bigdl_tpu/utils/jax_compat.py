"""Version-tolerant jax imports.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace; the toolchains this repo targets span both
sides of the move. Import it from here so every call site works on
either.
"""

try:
    from jax import shard_map  # noqa: F401
except ImportError:  # pre-graduation toolchains (< jax 0.6)
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kwargs):
        # the replication check was renamed check_rep -> check_vma at
        # graduation; call sites use the new spelling
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(f, **kwargs)

try:
    from jax.lax import axis_size  # noqa: F401
except ImportError:  # pre-graduation: axis_frame(name) IS the static size
    from jax import core as _core

    def axis_size(axis_name):
        return _core.axis_frame(axis_name)

try:
    from jax.lax import pcast  # noqa: F401
except ImportError:
    def pcast(x, axes=None, *, to=None):
        # varying/invariant marks exist only under the new vma typing;
        # the old shard_map (check_rep) has nothing to mark
        return x
