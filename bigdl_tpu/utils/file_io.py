"""Checkpoint file IO (reference ``utils/File.scala`` — java serialization
with local/HDFS URIs, ``hdfsPrefix`` ``File.scala:27``).

TPU-native rebuild: pytrees of device arrays are pulled to host numpy and
written with a small self-describing pickle envelope. URI schemes dispatch
to registered handlers the way ``File.scala`` branches on the ``hdfs://``
prefix:

- local paths and ``file://`` — direct filesystem IO;
- ``gs://`` — Google Cloud Storage via ``google.cloud.storage`` (the natural
  remote store for a TPU pod; a clear error tells you to install the client
  if it's absent);
- ``mem://`` — an in-process store, the tested reference implementation of
  the handler protocol;
- anything else — ``register_scheme`` your own.
"""

from __future__ import annotations

import functools
import io
import itertools
import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

_MAGIC = b"BIGDL_TPU_V1"


class SchemeHandler:
    """IO surface a remote scheme provides. ``opener(path, mode) -> file`` is
    mandatory; ``lister(path) -> [name]`` and ``mtime(path) -> float`` make
    checkpoint-resume discovery (``Optimizer._latest_checkpoint``) work on
    the scheme; ``exists(path) -> bool`` guards ``save(overwrite=False)``."""

    def __init__(self, opener: Callable[[str, str], Any],
                 lister: Optional[Callable[[str], List[str]]] = None,
                 mtime: Optional[Callable[[str], float]] = None,
                 exists: Optional[Callable[[str], bool]] = None):
        self.opener = opener
        self.lister = lister
        self.mtime = mtime
        self.exists = exists


_SCHEME_HANDLERS: Dict[str, SchemeHandler] = {}


def register_scheme(scheme: str, opener: Callable[[str, str], Any],
                    lister=None, mtime=None, exists=None) -> None:
    """Register an ``opener(path, mode) -> file`` (plus optional ``lister``/
    ``mtime``/``exists``) for a URI scheme."""
    _SCHEME_HANDLERS[scheme] = SchemeHandler(opener, lister, mtime, exists)


def _split(path: str) -> Tuple[Optional[str], str]:
    if "://" in path:
        scheme, rest = path.split("://", 1)
        if scheme == "file":
            return None, rest
        return scheme, rest
    return None, path


def _handler(scheme: str) -> SchemeHandler:
    h = _SCHEME_HANDLERS.get(scheme)
    if h is None:
        raise ValueError(f"no handler registered for scheme {scheme!r}; "
                         f"use file_io.register_scheme")
    return h


def _open(path: str, mode: str):
    scheme, rest = _split(path)
    if scheme is not None:
        return _handler(scheme).opener(rest, mode)
    if "w" in mode:
        parent = os.path.dirname(os.path.abspath(rest))
        os.makedirs(parent, exist_ok=True)
    return open(rest, mode)


def exists(path: str) -> bool:
    scheme, rest = _split(path)
    if scheme is None:
        return os.path.exists(rest)
    h = _handler(scheme)
    if h.exists is None:
        raise NotImplementedError(
            f"scheme {scheme!r} has no exists hook; "
            f"register_scheme(..., exists=...) to enable existence checks")
    return h.exists(rest)


def listdir(path: str) -> List[str]:
    """Names under a directory/prefix, for checkpoint discovery."""
    scheme, rest = _split(path)
    if scheme is None:
        return os.listdir(rest)
    h = _handler(scheme)
    if h.lister is None:
        raise NotImplementedError(
            f"scheme {scheme!r} has no lister; checkpoint discovery "
            f"needs one (register_scheme(..., lister=...))")
    return h.lister(rest)


def getmtime(path: str) -> float:
    scheme, rest = _split(path)
    if scheme is None:
        return os.path.getmtime(rest)
    h = _handler(scheme)
    if h.mtime is None:
        return 0.0
    return h.mtime(rest)


def join(base: str, *names: str) -> str:
    """URI-safe path join (``os.path.join`` mangles nothing here, but be
    explicit about the contract)."""
    return "/".join([base.rstrip("/")] + [n.strip("/") for n in names])


def _to_host(obj: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, (jax.Array, np.ndarray)) else x, obj)


def save(obj: Any, path: str, overwrite: bool = True) -> None:
    """Serialize a pytree/Table/object (reference ``File.save``).

    The payload is fully serialized *before* the destination opens: remote
    handlers commit on close, so streaming the pickle directly could replace
    a good checkpoint with a truncated one if serialization failed midway.
    """
    if not overwrite and exists(path):
        raise FileExistsError(path)
    payload = _MAGIC + pickle.dumps(_to_host(obj),
                                    protocol=pickle.HIGHEST_PROTOCOL)
    with _open(path, "wb") as f:
        f.write(payload)


def load(path: str) -> Any:
    """Deserialize (reference ``File.load``)."""
    with _open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"{path} is not a bigdl_tpu checkpoint")
        return pickle.load(f)


# ----------------------------------------------------------- mem:// handler

_MEM_STORE: Dict[str, bytes] = {}
_MEM_CLOCK = itertools.count(1)
_MEM_MTIME: Dict[str, float] = {}


class _WriteBack(io.BytesIO):
    def __init__(self, key: str):
        super().__init__()
        self._key = key

    def close(self):
        _MEM_STORE[self._key] = self.getvalue()
        _MEM_MTIME[self._key] = float(next(_MEM_CLOCK))
        super().close()


def _mem_opener(path: str, mode: str):
    if "w" in mode:
        return _WriteBack(path)
    if path not in _MEM_STORE:
        raise FileNotFoundError(f"mem://{path}")
    return io.BytesIO(_MEM_STORE[path])


def _mem_lister(path: str) -> List[str]:
    prefix = path.rstrip("/") + "/" if path.strip("/") else ""
    return sorted({k[len(prefix):].split("/", 1)[0]
                   for k in _MEM_STORE if k.startswith(prefix)})


register_scheme("mem", _mem_opener, lister=_mem_lister,
                mtime=lambda p: _MEM_MTIME.get(p, 0.0),
                exists=lambda p: p in _MEM_STORE)


def clear_mem_store() -> None:
    """Drop everything saved under ``mem://`` (test isolation)."""
    _MEM_STORE.clear()
    _MEM_MTIME.clear()


# ------------------------------------------------------------ gs:// handler

@functools.lru_cache(maxsize=1)
def _gcs_client():
    try:
        from google.cloud import storage  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            "gs:// checkpoint IO needs the google-cloud-storage client, "
            "which is not installed in this environment; install it, or "
            "file_io.register_scheme('gs', ...) an opener backed by your "
            "own client") from e
    try:
        return storage.Client()
    except Exception as e:  # DefaultCredentialsError and friends
        raise RuntimeError(
            "gs:// checkpoint IO could not authenticate to Google Cloud "
            "Storage (set GOOGLE_APPLICATION_CREDENTIALS or run on a "
            f"machine with application-default credentials): {e}") from e


def _gcs_blob(path: str):
    bucket_name, _, blob_path = path.partition("/")
    return _gcs_client().bucket(bucket_name).blob(blob_path)


class _GcsUpload(io.BytesIO):
    def __init__(self, blob):
        super().__init__()
        self._blob = blob

    def close(self):
        self._blob.upload_from_string(self.getvalue())
        super().close()


def _gcs_opener(path: str, mode: str):
    blob = _gcs_blob(path)
    if "w" in mode:
        return _GcsUpload(blob)
    return io.BytesIO(blob.download_as_bytes())


def _gcs_lister(path: str) -> List[str]:
    bucket_name, _, prefix = path.partition("/")
    prefix = prefix.rstrip("/") + "/" if prefix.strip("/") else ""
    blobs = _gcs_client().list_blobs(bucket_name, prefix=prefix,
                                     delimiter="/")
    return sorted(b.name[len(prefix):] for b in blobs)


def _gcs_mtime(path: str) -> float:
    blob = _gcs_blob(path)
    blob.reload()
    return blob.updated.timestamp() if blob.updated else 0.0


register_scheme("gs", _gcs_opener, lister=_gcs_lister, mtime=_gcs_mtime,
                exists=lambda p: _gcs_blob(p).exists())


# ---------------------------------------------------------- hdfs:// handler
# The reference's actual remote scheme (``File.scala:27`` ``hdfsPrefix``):
# a migrating user's ``hdfs://namenode:port/...`` checkpoint path must not
# die with "unknown scheme". Backed by fsspec -> pyarrow HadoopFileSystem;
# needs libhdfs + a Hadoop client config on the host. On a TPU pod the
# native substrate is ``gs://`` — the error message says so.

def _hdfs_fs_path(path: str):
    try:
        import fsspec
        return fsspec.core.url_to_fs("hdfs://" + path)
    except Exception as e:
        raise RuntimeError(
            "hdfs:// checkpoint IO needs a working Hadoop client "
            "(fsspec -> pyarrow HadoopFileSystem, which loads libhdfs and "
            "reads HADOOP_HOME/CLASSPATH); on TPU the native remote store "
            "is gs:// — or file_io.register_scheme('hdfs', ...) your own "
            f"opener: {e}") from e


def _hdfs_opener(path: str, mode: str):
    fs, p = _hdfs_fs_path(path)
    return fs.open(p, mode)


def _hdfs_lister(path: str) -> List[str]:
    fs, p = _hdfs_fs_path(path)
    return sorted(name.rstrip("/").rsplit("/", 1)[-1]
                  for name in fs.ls(p, detail=False))


def _hdfs_mtime(path: str) -> float:
    fs, p = _hdfs_fs_path(path)
    mt = fs.info(p).get("mtime") or 0.0
    return mt.timestamp() if hasattr(mt, "timestamp") else float(mt)


def _hdfs_exists(path: str) -> bool:
    fs, p = _hdfs_fs_path(path)
    return fs.exists(p)


register_scheme("hdfs", _hdfs_opener, lister=_hdfs_lister,
                mtime=_hdfs_mtime, exists=_hdfs_exists)
