"""RandomGenerator: seedable RNG facade over ``jax.random``.

Reference parity: ``utils/RandomGenerator.scala`` (a hand-written
Mersenne-Twister with per-thread instances and uniform/normal/exponential/
cauchy/logNormal/geometric/bernoulli draws). The TPU-native design replaces
the stateful twister with JAX's splittable counter-based keys — the only RNG
design that stays deterministic under SPMD compilation — while keeping the
reference's *interface*: a process-global, seedable generator object.

Inside ``jit``-traced module code, randomness must come from the RngStream
bound by the functional-apply context (see ``nn/module.py``); this module is
for host-side uses (shuffles, init, data augmentation).
"""

from __future__ import annotations

import threading

import jax
import numpy as np


class RandomGenerator:
    """Per-thread seedable generator (reference ``RandomGenerator.RNG``)."""

    _thread_local = threading.local()

    def __init__(self, seed: int = 1):
        self.set_seed(seed)

    @classmethod
    def RNG(cls) -> "RandomGenerator":
        inst = getattr(cls._thread_local, "inst", None)
        if inst is None:
            inst = cls(seed=1)
            cls._thread_local.inst = inst
        return inst

    def set_seed(self, seed: int) -> "RandomGenerator":
        self._seed = int(seed)
        self._np = np.random.default_rng(self._seed)
        self._key = jax.random.key(self._seed)
        return self

    def get_seed(self) -> int:
        return self._seed

    # -- key plumbing ---------------------------------------------------------
    def next_key(self):
        """Split off a fresh JAX PRNG key."""
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_key_state(self):
        """The current JAX key's raw counter words as a plain int list —
        JSON-able, for RESUME markers (``bigdl_tpu/resilience``): restoring
        it replays the exact key stream position, so a resumed run draws
        the same per-step dropout keys an uninterrupted run would."""
        return [int(w) for w in
                np.asarray(jax.random.key_data(self._key)).ravel()]

    def set_key_state(self, words) -> "RandomGenerator":
        """Restore a key captured by ``get_key_state`` (same impl only)."""
        data = np.asarray(words, np.uint32)
        shape = np.shape(np.asarray(jax.random.key_data(self._key)))
        self._key = jax.random.wrap_key_data(data.reshape(shape))
        return self

    # -- host-side draws (numpy-backed; used by data pipeline / init) --------
    def uniform(self, low: float = 0.0, high: float = 1.0, size=None):
        return self._np.uniform(low, high, size)

    def normal(self, mean: float = 0.0, stdv: float = 1.0, size=None):
        return self._np.normal(mean, stdv, size)

    def exponential(self, lam: float = 1.0, size=None):
        return self._np.exponential(1.0 / lam, size)

    def cauchy(self, median: float = 0.0, sigma: float = 1.0, size=None):
        return median + sigma * self._np.standard_cauchy(size)

    def log_normal(self, mean: float = 1.0, stdv: float = 2.0, size=None):
        # Torch semantics: mean/stdv are of the underlying normal's exp.
        var = stdv * stdv
        mu = np.log(mean * mean / np.sqrt(var + mean * mean))
        sigma = np.sqrt(np.log(var / (mean * mean) + 1.0))
        return self._np.lognormal(mu, sigma, size)

    def geometric(self, p: float = 0.5, size=None):
        return self._np.geometric(p, size)

    def bernoulli(self, p: float = 0.5, size=None):
        return (self._np.random(size) < p).astype(np.float32)

    def randperm(self, n: int) -> np.ndarray:
        """1-based random permutation (Torch ``randperm`` semantics)."""
        return self._np.permutation(n) + 1

    def shuffle(self, arr) -> None:
        self._np.shuffle(arr)


def manual_seed(seed: int) -> None:
    RandomGenerator.RNG().set_seed(seed)
