"""Platform-forcing helper. Kept in its own module so importing it pulls in
nothing beyond the package itself (the ``bigdl_tpu`` package __init__ already
imports jax; this module adds no further weight)."""

from __future__ import annotations

import logging
import os


def ensure_platform() -> None:
    """Make a user-set ``JAX_PLATFORMS`` env var actually stick.

    Some site hooks (e.g. a TPU plugin's sitecustomize) override the jax
    platform config at import time, after which the env var alone is
    ignored; re-asserting it via ``jax.config`` post-import is what makes
    ``JAX_PLATFORMS=cpu python -m bigdl_tpu.apps.lenet ...`` behave as
    documented. No-op when the env var is unset; never imports jax in that
    case."""
    forced = os.environ.get("JAX_PLATFORMS")
    if not forced:
        return
    try:
        import jax
        jax.config.update("jax_platforms", forced)
    except Exception:
        logging.getLogger("bigdl_tpu").debug(
            "could not re-assert JAX_PLATFORMS=%s", forced, exc_info=True)
