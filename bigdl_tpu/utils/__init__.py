"""bigdl_tpu.utils — engine, tables, RNG, file IO (reference ``$B/utils/``)."""

from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.rng import RandomGenerator, manual_seed
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.file_io import save, load
from bigdl_tpu.utils.util import kth_largest
from bigdl_tpu.utils.digraph import DirectedGraph, Node as DiGraphNode
from bigdl_tpu.utils.logger_filter import redirect_logs
