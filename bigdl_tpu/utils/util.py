"""Small utilities (reference ``utils/Util.scala:20``)."""

from __future__ import annotations

import ctypes

import numpy as np


def pow2_bucket(n: int, lo: int, hi: int) -> int:
    """Smallest power of two >= ``n``, clamped into ``[lo, hi]``.

    The shared shape-bucketing primitive: padding a traffic-dependent
    dimension (batch size, prompt length) to a power of two bounds the
    distinct compiled-program set at O(log range) instead of one program
    per observed value (graftlint JG013). ``lo`` floors tiny values into
    one shared bucket; ``hi`` caps the top bucket at the physical limit
    (cache length, max batch) and need not itself be a power of two —
    the top bucket simply saturates at ``hi``. Used by the bucketed
    ``LMServer`` batch padding and ``ContinuousLMServer``'s
    ``prefill_mode="bucketed"`` length fallback."""
    if n < 1:
        raise ValueError(f"pow2_bucket needs n >= 1, got {n}")
    if not 1 <= lo <= hi:
        raise ValueError(f"pow2_bucket needs 1 <= lo <= hi, got "
                         f"lo={lo}, hi={hi}")
    if n > hi:
        raise ValueError(f"pow2_bucket: n={n} exceeds the bucket cap "
                         f"hi={hi}")
    b = 1 << (n - 1).bit_length()       # next power of two >= n
    return min(max(b, lo), hi)


def kth_largest(values, k: int) -> float:
    """k-th largest element, k is 1-based (reference ``Util.kthLargest`` —
    quickselect; used for the straggler-drop threshold). Native-backed."""
    arr = np.ascontiguousarray(values, dtype=np.float64).ravel()
    if not 1 <= k <= arr.size:
        raise ValueError(f"k={k} out of range for {arr.size} values")
    from bigdl_tpu import native
    lib = native.load()
    if lib is not None:
        ptr = arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        return float(lib.bt_kth_largest(ptr, arr.size, k))
    return float(np.partition(arr, arr.size - k)[arr.size - k])
