"""Sharded checkpointing: per-process shard files + resharding restore.

The reference checkpoints by reassembling the full model on the driver and
java-serializing it (``optim/DistriOptimizer.scala:378-400`` via
``getModel``, ``:629-659``); round 4's TPU port kept that shape (gather
sharded leaves to process 0 — fine at 1B on one chip, wrong for multi-host
models). This module removes the gather:

- ``save_sharded(path, tree)``: EVERY process writes exactly the shard
  data it owns (one ``shard-{pidx}.npz`` per process; a leaf slab is
  written by the single shard with ``replica_id == 0``, so replicated
  leaves are stored exactly once, sharded leaves exactly cover the global
  array across files). Process 0 writes ``manifest.json`` (leaf paths,
  global shapes, dtypes) — no process ever materializes a full sharded
  leaf.
- ``load_sharded(path, shardings)``: rebuilds global arrays with
  ``jax.make_array_from_callback`` against a pytree of *target*
  shardings. Each host reads only the slabs overlapping ITS addressable
  shards, assembling them by offset — the target mesh/specs may differ
  arbitrarily from the save-time ones (resharding restore: save on 2x4,
  restore on 4x2).

Format: numpy ``.npz`` members keyed ``<leafpath>||<offsets>||<shape>``,
where offsets/shape locate the slab in the global array. Plain-host leaves
(numpy, scalars) are written by process 0 with offset 0.

``manifest.json`` (format 2) names the participating shard files::

    {"format": 2, "shards": ["shard-00000.npz", ...], "leaves": {...}}

so restore reads EXACTLY the files this save wrote — a snapshot directory
reused by a run with fewer processes no longer resurrects stale
``shard-*.npz`` slabs from the earlier, wider run (process 0 also deletes
non-participating shard files up front). Shard files and the manifest are
written via tmp-file + ``os.replace``, so a file visible under its final
name is complete: a writer killed mid-save leaves either a missing shard
or a missing manifest, both of which the resilience coordinator
(``bigdl_tpu/resilience/coordinator.py``) rejects as a partial snapshot.
Format-1 manifests (a bare leaves dict) remain loadable.

Wired into ``DistriOptimizer`` via ``set_checkpoint(..., sharded=True)``
and auto-detected on ``resume()`` (a checkpoint directory containing
``manifest.json``).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np

_SEP = "||"


def _leaf_key(keypath) -> str:
    return jax.tree_util.keystr(keypath)


def _slab_name(key: str, offsets, shape) -> str:
    return _SEP.join([key, ",".join(map(str, offsets)),
                      ",".join(map(str, shape))])


def _parse_slab(name: str):
    key, offs, shape = name.rsplit(_SEP, 2)
    to_tuple = lambda s: tuple(int(v) for v in s.split(",")) if s else ()
    return key, to_tuple(offs), to_tuple(shape)


def shard_filename(pidx: int) -> str:
    return f"shard-{pidx:05d}.npz"


def _atomic_write_npz(path: str, blobs) -> None:
    # tmp + os.replace: a crash mid-write leaves no file under the final
    # name, so presence == completeness. savez gets a FILE OBJECT — passing
    # a name would make numpy append ".npz" to the tmp suffix.
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **blobs)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_sharded(path: str, tree: Any) -> None:
    """Write this process's shards of ``tree`` under ``path`` (a directory).
    Call from EVERY process; collective-free (each process writes only
    local data)."""
    os.makedirs(path, exist_ok=True)
    pidx = jax.process_index()
    nproc = jax.process_count()
    shard_names = [shard_filename(i) for i in range(nproc)]
    if pidx == 0:
        # clear stale shards from an earlier, WIDER save into this dir:
        # no current process writes those names, so the delete cannot race
        # a live writer (ADVICE: the stale-shard overwrite hazard)
        for fname in os.listdir(path):
            if (fname.startswith("shard-") and fname.endswith(".npz")
                    and fname not in shard_names):
                os.unlink(os.path.join(path, fname))
    blobs = {}
    leaves = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _leaf_key(keypath)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            leaves[key] = {"shape": list(leaf.shape),
                           "dtype": str(leaf.dtype)}
            for sh in leaf.addressable_shards:
                if sh.replica_id != 0:
                    continue  # exactly-once: the 0th replica owns the slab
                offs = tuple((idx.start or 0) for idx in sh.index)
                data = np.asarray(sh.data)
                blobs[_slab_name(key, offs, data.shape)] = data
        else:
            arr = np.asarray(leaf)
            leaves[key] = {"shape": list(arr.shape),
                           "dtype": str(arr.dtype)}
            if pidx == 0:  # host value: identical everywhere, store once
                blobs[_slab_name(key, (0,) * arr.ndim, arr.shape)] = arr
    _atomic_write_npz(os.path.join(path, shard_filename(pidx)), blobs)
    if pidx == 0:
        manifest = {"format": 2, "shards": shard_names, "leaves": leaves}
        tmp = os.path.join(path, f".manifest.tmp-{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(path, "manifest.json"))


def is_sharded_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, "manifest.json"))


def read_manifest(path: str):
    """(leaves, shard_names) from ``manifest.json``. Format 2 names its
    participating shard files; format 1 (a bare leaves dict) returns
    ``shard_names=None`` — restore then globs, the pre-fix behaviour."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    if isinstance(manifest, dict) and "leaves" in manifest:
        return manifest["leaves"], manifest.get("shards")
    return manifest, None


def _slab_index(path: str, shard_names=None):
    """key -> [(npz_file, member_name, offsets, shape)] across the shard
    files (reads only the zip directories, not the data). With
    ``shard_names`` (manifest format 2), ONLY those files are read —
    stale shards from an earlier save into the same dir are invisible;
    a missing participant is an incomplete snapshot."""
    if shard_names is None:
        shard_names = sorted(
            f for f in os.listdir(path)
            if f.startswith("shard-") and f.endswith(".npz"))
    index = {}
    for fname in shard_names:
        full = os.path.join(path, fname)
        if not os.path.exists(full):
            raise ValueError(
                f"snapshot {path} is incomplete: manifest names {fname} "
                "but the file is missing (writer killed mid-save, or not "
                "all processes' shard files were copied)")
        with np.load(full) as z:
            names = list(z.files)
        for name in names:
            key, offs, shape = _parse_slab(name)
            index.setdefault(key, []).append((full, name, offs, shape))
    return index


def load_sharded(path: str, shardings: Any) -> Any:
    """Rebuild the checkpoint onto ``shardings`` (a pytree of
    ``jax.sharding.Sharding`` — or ``None`` leaves for host numpy arrays —
    with the SAME tree structure as the saved tree). Each process reads
    only the slabs overlapping its addressable shards."""
    manifest, shard_names = read_manifest(path)
    index = _slab_index(path, shard_names)
    open_files: dict = {}

    def read_block(key, dtype, starts, stops):
        """Assemble global[starts:stops] from stored slabs."""
        out = np.empty([b - a for a, b in zip(starts, stops)], dtype)
        filled = 0
        for fname, member, offs, shape in index.get(key, ()):
            inter_lo = [max(a, o) for a, o in zip(starts, offs)]
            inter_hi = [min(b, o + s) for b, o, s in zip(stops, offs, shape)]
            if any(lo >= hi for lo, hi in zip(inter_lo, inter_hi)):
                continue
            z = open_files.setdefault(fname, np.load(fname))
            slab = z[member]
            src = tuple(slice(lo - o, hi - o)
                        for lo, hi, o in zip(inter_lo, inter_hi, offs))
            dst = tuple(slice(lo - a, hi - a)
                        for lo, hi, a in zip(inter_lo, inter_hi, starts))
            out[dst] = slab[src]
            filled += int(np.prod([s.stop - s.start for s in dst]))
        if filled < out.size:
            raise ValueError(
                f"checkpoint slabs do not cover {key}[{starts}:{stops}] "
                f"({filled}/{out.size} elements) — incomplete checkpoint "
                "(were all processes' shard files copied?)")
        return out

    def restore(keypath, sharding):
        key = _leaf_key(keypath)
        if key not in manifest:
            raise KeyError(f"{key} not in checkpoint manifest at {path}")
        meta = manifest[key]
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        if sharding is None:
            return read_block(key, dtype, (0,) * len(shape), shape)

        def cb(idx):
            starts = tuple((s.start or 0) for s in idx)
            stops = tuple(s.stop if s.stop is not None else dim
                          for s, dim in zip(idx, shape))
            return read_block(key, dtype, starts, stops)

        return jax.make_array_from_callback(shape, sharding, cb)

    # None marks a host-numpy leaf; flatten must treat it AS a leaf (bare
    # tree_flatten would collapse None into an empty subtree and desync
    # the structure from the saved tree)
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        shardings,
        is_leaf=lambda x: x is None or isinstance(x, jax.sharding.Sharding))
    try:
        leaves = [restore(kp, sh) for kp, sh in flat]
    finally:
        for z in open_files.values():
            z.close()
    return jax.tree_util.tree_unflatten(treedef, leaves)
