"""VGG for CIFAR-10 (reference ``models/vgg/VggForCifar10.scala``) and
configurable VGG-16/19 for ImageNet (the reference's perf-harness models,
``models/utils/LocalOptimizerPerf.scala``). Channels-last input.
"""

from __future__ import annotations

from bigdl_tpu import nn

_IMAGENET_CFG = {
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def _conv_bn_relu(model, n_in, n_out):
    from bigdl_tpu.nn.fused import FusedConv3x3BN, use_fused_3x3
    if use_fused_3x3():
        # every VGG conv is a stride-1 3x3+BN pair: the whole conv stack
        # rides the one-pass Pallas conv+stats kernel under the flag
        (model.add(FusedConv3x3BN(n_in, n_out, init_method="kaiming",
                                  with_bias=True))
              .add(nn.ReLU(True)))
        return n_out
    (model.add(nn.SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1,
                                     init_method="kaiming"))
          .add(nn.SpatialBatchNormalization(n_out))
          .add(nn.ReLU(True)))
    return n_out


def build(class_num: int = 10) -> nn.Sequential:
    """VggForCifar10: input (N, 32, 32, 3)."""
    model = nn.Sequential()
    n_in = 3
    for block in ([64, 64], [128, 128], [256, 256, 256],
                  [512, 512, 512], [512, 512, 512]):
        for w in block:
            n_in = _conv_bn_relu(model, n_in, w)
        model.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
    (model.add(nn.Reshape((512,), batch_mode=True))
          .add(nn.Linear(512, 512))
          .add(nn.BatchNormalization(512))
          .add(nn.ReLU(True))
          .add(nn.Dropout(0.5))
          .add(nn.Linear(512, class_num))
          .add(nn.LogSoftMax()))
    return model


def build_imagenet(class_num: int = 1000, depth: int = 16) -> nn.Sequential:
    """VGG-16/19: input (N, 224, 224, 3)."""
    model = nn.Sequential()
    n_in = 3
    for v in _IMAGENET_CFG[depth]:
        if v == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            n_in = _conv_bn_relu(model, n_in, v)
    (model.add(nn.Reshape((512 * 7 * 7,), batch_mode=True))
          .add(nn.Linear(512 * 7 * 7, 4096))
          .add(nn.ReLU(True))
          .add(nn.Dropout(0.5))
          .add(nn.Linear(4096, 4096))
          .add(nn.ReLU(True))
          .add(nn.Dropout(0.5))
          .add(nn.Linear(4096, class_num))
          .add(nn.LogSoftMax()))
    return model
