"""bigdl_tpu.models — reference workloads (reference ``$B/models/``)."""

from bigdl_tpu.models import lenet
