"""bigdl_tpu.models — reference workloads (reference ``$B/models/``)."""

from bigdl_tpu.models import lenet
from bigdl_tpu.models import vgg
from bigdl_tpu.models import resnet
from bigdl_tpu.models import inception
from bigdl_tpu.models import autoencoder
from bigdl_tpu.models import rnn
from bigdl_tpu.models import transformer
from bigdl_tpu.models import vit
from bigdl_tpu.models.generation import generate, generate_speculative
from bigdl_tpu.models.lm_server import LMServer, make_http_server
from bigdl_tpu.models.serving import ContinuousLMServer
