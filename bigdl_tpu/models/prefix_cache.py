"""Cross-request KV prefix cache for the continuous serving engine
(round 9, ROADMAP #2's reuse half).

At scale, serving traffic is dominated by SHARED PREFIXES — system
prompts, few-shot templates — yet every admission to
``ContinuousLMServer`` re-prefilled from token 0. This module caches the
per-request prefill state partition (``generation.partition_prefill_state``
output: b=1 KV caches + write position) at CHUNK boundaries of the
chunked prefill, so a later admission sharing a chunk-aligned token
prefix copies the cached partition and chunk-prefills only the uncached
tail.

Why chunk alignment, twice over:

- **The snapshot is free.** Between two ``chunk_fn`` dispatches the
  engine holds exactly the state partition the next chunk consumes —
  the snapshot is that value, taken in flight (one device copy, and only
  for prefixes the trie has not seen; known prefixes skip even that).
  No re-slicing, no recompute, no extra program.
- **Hits stay bit-identical.** Resuming a prefill from a chunk boundary
  reproduces the cold run's exact chunk partition of the remaining
  tokens — same fixed-width (1, C) dispatches, same floating-point
  reduction groupings — so a hit admission's greedy output is
  bit-identical to a cold prefill (asserted in tier-1). A mid-chunk
  resume would regroup the tail's attention reductions and lose that
  guarantee, which is why only FULL-chunk boundaries are cached.

Structure: a radix trie over chunk-granular token paths, addressed by a
ROLLING HASH — each stored node is one chunk-aligned prefix, keyed by
the polynomial hash of its tokens, with the exact token tuple kept for
collision rejection. Lookups never enumerate children (they descend by
extending the hash one chunk at a time and probing deepest-first), so
the trie stores its paths flat in one LRU-ordered map.

Bounded by construction (graftlint JG014's discipline applied to KV
instead of programs): ``max_bytes`` caps the held snapshot bytes, and
overflow evicts LEAST-RECENTLY-USED entries one at a time — never
clear-at-cap — with every eviction counted
(``bigdl_prefix_cache_evictions``). All mutation holds the cache's own
lock; the serving worker and a concurrent ``close()``/test probe can
race admissions against evictions safely (JG015-017 stay green).

The trie attaches to the MODEL (``model.__dict__["_prefix_trie"]``,
keyed by (chunk, cache_len) config) so a re-created server over the
same weights keeps its warm prefixes — and ``nn.Module.__getstate__``
pops it, so deepcopy/pickle of a served model never drags cached KV
(or this cache's thread lock, which does not pickle) along.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

__all__ = ["PrefixCache", "prefix_cache_for", "rolling_hash",
           "DEFAULT_PREFIX_CACHE_MB"]

#: Default held-snapshot budget (MiB) for one server's prefix trie.
DEFAULT_PREFIX_CACHE_MB = 64.0

# Polynomial rolling hash over 1-based token ids: extending a prefix by
# one chunk extends its hash without rehashing the whole prefix. The
# Mersenne modulus keeps Python ints small; collisions are survivable
# (the stored token tuple is always verified) so 61 bits is plenty.
_HASH_BASE = 1_000_003
_HASH_MOD = (1 << 61) - 1


def rolling_hash(tokens: Sequence[int], seed: int = 0) -> int:
    """Extend ``seed`` (the hash of everything before ``tokens``) by the
    given tokens — ``rolling_hash(b, rolling_hash(a)) ==
    rolling_hash(a + b)``, the trie-descent identity."""
    h = seed
    for t in tokens:
        h = (h * _HASH_BASE + int(t) + 1) % _HASH_MOD
    return h


class _Node:
    """One stored chunk-aligned prefix: its exact tokens (collision
    check), the owned state-partition snapshot, and its byte cost."""

    __slots__ = ("tokens", "state", "nbytes")

    def __init__(self, tokens: Tuple[int, ...], state: list, nbytes: int):
        self.tokens = tokens
        self.state = state
        self.nbytes = nbytes


class PrefixCache:
    """Chunk-aligned prefix trie of prefill-state snapshots (module doc).

    ``match``/``put`` return plain facts (hit depth, evictions
    performed) and the cache keeps cumulative ``hits``/``misses``/
    ``evictions`` counters; the serving engine mirrors those into its
    metrics registry (this class stays registry-free so one trie can
    serve successive servers with different registries).
    """

    def __init__(self, chunk: int, max_bytes: int):
        if chunk < 1:
            raise ValueError("chunk must be >= 1")
        self.chunk = int(chunk)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # rolling hash of the prefix -> _Node, in LRU order (oldest first)
        self._entries: "OrderedDict[int, _Node]" = OrderedDict()
        self.nbytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def boundaries(self) -> List[int]:
        """Stored prefix depths (token counts), for tests/introspection."""
        with self._lock:
            return sorted(len(n.tokens) for n in self._entries.values())

    # ---------------------------------------------------------------- lookup
    def match(self, tokens: Sequence[int]):
        """Deepest cached chunk-aligned prefix of ``tokens``.

        Returns ``(depth, state_copy)`` — ``depth`` tokens are covered
        by the returned OWNED copy (safe to donate straight into the
        chunk program), or ``(0, None)`` on a miss. Probes boundaries
        deepest-first so one hash walk prices the whole descent."""
        c = self.chunk
        tokens = [int(t) for t in tokens]
        n_aligned = (len(tokens) // c) * c
        probes: List[Tuple[int, int]] = []          # (depth, hash)
        h = 0
        for b in range(c, n_aligned + 1, c):
            h = rolling_hash(tokens[b - c:b], h)
            probes.append((b, h))
        with self._lock:
            for depth, h in reversed(probes):
                node = self._entries.get(h)
                if node is not None and node.tokens == tuple(tokens[:depth]):
                    self._entries.move_to_end(h)
                    self.hits += 1
                    # copy INSIDE the lock (a concurrent eviction must not
                    # drop the node mid-read); jnp.copy only dispatches —
                    # no device sync is held here (JG017)
                    import jax.numpy as jnp
                    return depth, [jnp.copy(x) for x in node.state]
            self.misses += 1
        return 0, None

    # ---------------------------------------------------------------- insert
    def put(self, tokens: Sequence[int], state: list) -> int:
        """Store a snapshot for the chunk-aligned prefix ``tokens``.

        ``state`` is the LIVE partition between chunk dispatches; the
        cache takes its own copy (the caller donates the live value to
        the next program). Known prefixes are refreshed (LRU) without
        copying. Returns the number of LRU evictions the insert forced
        (0 usually); a snapshot larger than the whole budget is refused
        rather than admitted-and-immediately-evicted."""
        if len(tokens) % self.chunk != 0 or not tokens:
            raise ValueError(
                f"prefix length {len(tokens)} is not a whole number of "
                f"chunks (chunk={self.chunk})")
        key = tuple(int(t) for t in tokens)
        h = rolling_hash(key)
        with self._lock:
            node = self._entries.get(h)
            if node is not None and node.tokens == key:
                self._entries.move_to_end(h)        # refresh, copy-free
                return 0
            import jax.numpy as jnp
            nbytes = sum(int(getattr(x, "nbytes", 0)) for x in state)
            if nbytes > self.max_bytes:
                return 0
            if node is not None:                    # hash collision: replace
                self.nbytes -= node.nbytes
            self._entries[h] = _Node(key, [jnp.copy(x) for x in state],
                                     nbytes)
            self.nbytes += nbytes
            evicted = 0
            while self.nbytes > self.max_bytes and len(self._entries) > 1:
                # LRU single-entry eviction, counted — never clear-at-cap
                # (the eviction-storm lesson from the compiled-program
                # caches, JG014, applied to KV bytes)
                _, old = self._entries.popitem(last=False)
                self.nbytes -= old.nbytes
                evicted += 1
            self.evictions += evicted
        return evicted

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.nbytes = 0

    def __repr__(self) -> str:
        return (f"PrefixCache(chunk={self.chunk}, entries={len(self)}, "
                f"bytes={self.nbytes}/{self.max_bytes}, hits={self.hits}, "
                f"misses={self.misses}, evictions={self.evictions})")


def prefix_cache_for(model, *, chunk: int, cache_len: int,
                     max_bytes: int) -> PrefixCache:
    """Get-or-create the model's prefix trie for one prefill config.

    Keyed by (chunk, cache_len) because a snapshot's leaves are shaped
    by the prefill template — a server with a different chunk width or
    cache length cannot consume another config's states. Attached to
    ``model.__dict__`` so re-serving the same weights starts warm;
    popped by ``Module.__getstate__`` so serialization never carries
    cached KV. The per-model config dict is itself bounded (a config is
    operator-chosen, not traffic-chosen, but nothing should grow
    without a cap)."""
    tries = model.__dict__.setdefault("_prefix_trie", OrderedDict())
    key = (int(chunk), int(cache_len))
    pc = tries.get(key)
    if pc is None:
        pc = PrefixCache(chunk, max_bytes)
        tries[key] = pc
        while len(tries) > 4:
            tries.popitem(last=False)
    else:
        tries.move_to_end(key)
        pc.max_bytes = int(max_bytes)   # latest server's budget wins
    return pc
