"""Batched LM serving over the KV-cached decode — the text counterpart of
the reference's serving quadrant (``example/udfpredictor/`` watch-mode
structured-streaming inference, ``ml/DLClassifier.scala:35`` batched
DataFrame transform: the reference serves images by collecting rows into
batches and running one forward per batch; this serves prompts by
collecting requests into micro-batches and running ONE jitted
prefill+decode program per batch).

Design (TPU-first):
- ``models.generate`` compiles one program per (batch, prompt_len,
  max_new, sampling) signature. The batcher therefore quantises the
  signature space: requests are grouped by EXACT prompt length (the causal
  prefill has no padding mask, so mixed lengths cannot share a program),
  the batch dim is padded up to a power-of-two bucket (dummy rows — their
  generations are dropped), and every batch decodes the server's
  ``max_new_tokens`` (eos-frozen rows finish early; per-request limits
  trim the result). Steady state is one compile per (prompt-length,
  batch-bucket) pair, reused forever after.
- batching is dynamic: the worker takes the oldest request, waits up to
  ``batch_timeout_ms`` for same-length company, and dispatches whatever
  gathered — single-request latency is bounded by the timeout, batch
  throughput by ``max_batch``.
- ``python -m bigdl_tpu.apps.transformer serve`` wires this behind a
  stdlib HTTP endpoint (no server-framework dependency, mirroring the
  repo's hand-rolled-wire tradition); ``LMServer`` itself is transport-
  free and unit-testable in-process.
"""

from __future__ import annotations

import itertools
import json
import queue
import threading
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from bigdl_tpu.telemetry import get_registry, instruments, span, tracing
from bigdl_tpu.utils.util import pow2_bucket

# Chrome-trace lifecycle ids for lmserver.request async events (matched
# on (cat, id, name), so they may overlap the continuous server's ids)
_REQUEST_IDS = itertools.count(1)


def fail_requests(reqs, message: str, *, category: str) -> None:
    """Fail stranded requests: set the error, release every blocked
    ``submit()``, close the trace lifecycle. Shared by both serving
    planes (this batcher and ``models/serving.py``) — the close/stop/
    dead-server drains previously hand-rolled this loop five times."""
    for req in reqs:
        req.error = message
        req.done.set()
        tracing.async_end(category, req.rid, error=req.error)


def drain_queue(q: "queue.Queue"):
    """Empty a request queue without blocking; returns the drained items."""
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            break
    return out


@dataclass
class _Request:
    ids: List[int]                      # 1-based prompt token ids
    max_new: int
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[List[int]] = None  # continuation ids (1-based)
    error: Optional[str] = None
    t_submit: float = 0.0               # perf_counter at submit (batch wait)
    rid: int = 0                        # trace-lifecycle id


class LMServer:
    """Micro-batching front end over ``models.generate``.

    ``submit()`` blocks until the request's batch has decoded and returns
    the continuation ids (prompt excluded, eos kept, pad stripped).
    Thread-safe; one worker thread owns the model (generate() itself is
    apply-locked, but serialising dispatch here keeps batches dense
    instead of racing for the chip).
    """

    def __init__(self, model, *, max_batch: int = 8,
                 batch_timeout_ms: float = 20.0,
                 max_new_tokens: int = 64,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 0.0, greedy: bool = False,
                 eos_id: Optional[int] = None, seed: int = 0,
                 registry=None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        # telemetry (docs/OBSERVABILITY.md): batch size / batch wait /
        # batches served + queue depth, scraped via GET /metrics
        self.registry = registry if registry is not None else get_registry()
        self._tm = instruments(self.registry)
        self.model = model
        self.max_batch = max_batch
        self.batch_timeout = batch_timeout_ms / 1000.0
        self.max_new_tokens = max_new_tokens
        self.sampling = dict(temperature=temperature, top_k=top_k,
                             top_p=top_p, greedy=greedy, eos_id=eos_id)
        self._seed = seed
        self._base_key = None  # built lazily (jax imports on first decode)
        self._n_batches = 0
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        # requests displaced from a batch because their length differed:
        # consumed BEFORE the queue and in arrival order, so the next
        # batch anchors on the OLDEST held request — a sustained stream of
        # one length can no longer starve another (ADVICE round 4).
        # _held is rewritten by the worker's gather AND by close() on the
        # client thread; every mutation holds _held_lock (graftlint
        # JG015: a close() racing a timed-out join could strand a held
        # request forever — its done-event would never be set)
        self._held_lock = threading.Lock()
        self._held: List[_Request] = []
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="lm-server-batcher")
        self._worker.start()

    # ------------------------------------------------------------- client API
    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               timeout: Optional[float] = None) -> List[int]:
        """Serve one prompt; returns continuation ids (1-based)."""
        ids = [int(t) for t in prompt_ids]
        if not ids:
            raise ValueError("empty prompt")
        max_new = int(self.max_new_tokens if max_new_tokens is None
                      else max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if max_new > self.max_new_tokens:
            raise ValueError(f"max_new_tokens {max_new} exceeds the "
                             f"server's decode budget {self.max_new_tokens}")
        req = _Request(ids, max_new)
        req.rid = next(_REQUEST_IDS)
        req.t_submit = _now()
        tracing.async_begin("lmserver.request", req.rid,
                            prompt_len=len(ids), max_new=max_new)
        self._queue.put(req)
        self._tm.lmserver_queue_depth.set(self.queue_depth)
        if not req.done.wait(timeout):
            raise TimeoutError("decode did not complete in time")
        if req.error is not None:
            raise RuntimeError(req.error)
        return req.result

    @property
    def queue_depth(self) -> int:
        """Requests queued + held awaiting same-length company."""
        return self._queue.qsize() + len(self._held)

    def close(self):
        self._stop.set()
        self._worker.join(timeout=5)
        # fail anything still queued — a submit() blocked without timeout
        # must not hang forever on a server that will never decode again
        with self._held_lock:
            stranded, self._held = self._held, []
        fail_requests(stranded + drain_queue(self._queue),
                      "server closed before the request was dispatched",
                      category="lmserver.request")

    @property
    def batches_served(self) -> int:
        return self._n_batches

    # ---------------------------------------------------------------- batcher
    def _gather(self) -> Optional[List[_Request]]:
        """Oldest request + up-to-timeout same-length company.

        The anchor is the oldest HELD request when one exists (held =
        displaced from an earlier gather by length mismatch), so every
        request's wait is bounded by the batches ahead of it at arrival —
        strict arrival-order anchoring, no starvation."""
        with self._held_lock:
            first = self._held.pop(0) if self._held else None
        if first is None:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                return None
        batch = [first]
        s = len(first.ids)
        # same-length held company joins immediately (no timeout burn)
        with self._held_lock:
            still_held = []
            for req in self._held:
                if len(req.ids) == s and len(batch) < self.max_batch:
                    batch.append(req)
                else:
                    still_held.append(req)
            self._held = still_held
        deadline = _now() + self.batch_timeout
        while len(batch) < self.max_batch:
            remaining = deadline - _now()
            if remaining <= 0:
                break
            try:
                req = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if len(req.ids) == s:
                batch.append(req)
            else:
                with self._held_lock:
                    self._held.append(req)
        return batch

    def _run(self):
        while not self._stop.is_set():
            with span("lmserver.gather"):
                batch = self._gather()
            self._tm.lmserver_queue_depth.set(self.queue_depth)
            if not batch:
                continue
            try:
                self._decode_batch(batch)
            except Exception as e:  # surface to every waiter, keep serving
                fail_requests(batch, f"{type(e).__name__}: {e}",
                              category="lmserver.request")
        # stop-path drain ON THE WORKER: close() sweeps _held and the
        # queue once after a BOUNDED join — when that join times out
        # (slow decode), this loop may hold or dequeue a request AFTER
        # the sweep; failing the leftovers here guarantees no submit()
        # is ever stranded, whichever side runs last
        with self._held_lock:
            stranded, self._held = self._held, []
        fail_requests(stranded + drain_queue(self._queue),
                      "server closed before the request was dispatched",
                      category="lmserver.request")

    def _decode_batch(self, batch: List[_Request]):
        import jax

        from bigdl_tpu.models.generation import generate
        # anchor's wait from submit to dispatch == the batching latency a
        # single-request client actually pays (bounded by batch_timeout)
        self._tm.lmserver_batch_wait_seconds.observe(
            _now() - batch[0].t_submit)
        self._tm.lmserver_batch_size.observe(len(batch))
        if tracing.is_enabled():
            # dispatch marks on every member's lifecycle lane, with each
            # request's own queue+gather wait (batch-wait attribution)
            t_disp = _now()
            for req in batch:
                tracing.async_instant("lmserver.request", req.rid,
                                      phase="dispatch", batch=len(batch),
                                      wait_s=round(t_disp - req.t_submit, 6))
        s = len(batch[0].ids)
        # batch-bucket: pad with copies of row 0 to the next power of two
        # (saturating at max_batch — the shared pow2_bucket helper, also
        # the serving prefill's length-bucketing fallback) — dummy rows
        # cost compute but keep the compile cache at O(log max_batch)
        # entries per prompt length
        b = pow2_bucket(len(batch), 1, self.max_batch)
        rows = [req.ids for req in batch]
        rows += [rows[0]] * (b - len(rows))
        prompt = np.asarray(rows, np.float32)
        # fold_in, not PRNGKey(seed + n): seed-arithmetic streams from two
        # servers (seeds s, s+1) would share every key one batch apart
        if self._base_key is None:
            self._base_key = jax.random.PRNGKey(self._seed)
        key = jax.random.fold_in(self._base_key, self._n_batches)
        with span("lmserver.decode_batch", batch=len(batch), prompt_len=s):
            out = np.asarray(generate(self.model, prompt,
                                      self.max_new_tokens,
                                      key=key, **self.sampling)).astype(int)
        self._n_batches += 1
        self._tm.lmserver_batches_total.inc()
        self._tm.lmserver_requests_total.inc(len(batch))
        eos = self.sampling["eos_id"]
        for i, req in enumerate(batch):
            cont = out[i, s:s + req.max_new].tolist()
            if eos is not None and eos in cont:
                cont = cont[:cont.index(eos) + 1]  # keep eos, strip pad tail
            req.result = cont
            req.done.set()
            tracing.async_end("lmserver.request", req.rid,
                              tokens=len(cont))


def _now() -> float:
    import time
    return time.monotonic()


# ------------------------------------------------------------------ HTTP rim

def make_http_server(server: LMServer, host: str, port: int, tokenizer=None):
    """Stdlib ``ThreadingHTTPServer`` speaking JSON:

    POST /generate  {"prompt": [ids...]} | {"text": "..."} (needs tokenizer)
                    optional "max_new_tokens"
        -> {"ids": [...], "text": "..."?}
    GET  /health    -> {"ok": true, "batches_served": N, "queue_depth": N}
                       (503 + {"ok": false, "dead": reason} once a
                       continuous server's worker loop has died; 503 +
                       {"ok": false, "draining": reason} while it drains
                       — distinct states, so a balancer can tell "retry
                       elsewhere, shutting down cleanly" from "gone").
                       A fleet router adds per-replica detail via its
                       ``health_extra`` property.
    GET  /metrics   -> Prometheus text exposition (the server's registry;
                       docs/OBSERVABILITY.md has a scrape_config example)

    ``server`` is anything speaking the submit()/queue_depth/
    batches_served surface — a batcher, a continuous server, or a fleet
    ``LMRouter`` (models/router.py) fronting N of them.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from bigdl_tpu.telemetry import (PROMETHEUS_CONTENT_TYPE,
                                     render_prometheus)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet; the app logs itself
            pass

        def _send(self, code: int, body: bytes, content_type: str):
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply(self, code: int, payload: dict):
            self._send(code, json.dumps(payload).encode(),
                       "application/json")

        def do_GET(self):
            if self.path == "/metrics":
                reg = getattr(server, "registry", None)
                return self._send(200, render_prometheus(reg).encode(),
                                  PROMETHEUS_CONTENT_TYPE)
            if self.path != "/health":
                return self._reply(404,
                                   {"error": "GET /health or /metrics"})
            # a dead continuous server (worker-loop/decode failure) must
            # flunk the probe so the orchestrator replaces the replica;
            # a DRAINING one flunks it too (stop sending traffic) but
            # reports the distinct state — it is leaving on purpose and
            # its in-flight work is being handed off, not lost
            dead = getattr(server, "dead_reason", None)
            draining = getattr(server, "drain_reason", None)
            extra = getattr(server, "health_extra", None) or {}
            self._reply(503 if (dead or draining) else 200,
                        {"ok": dead is None and draining is None,
                         "batches_served": server.batches_served,
                         "queue_depth": server.queue_depth,
                         **({"dead": dead} if dead else {}),
                         **({"draining": draining}
                            if (draining and not dead) else {}),
                         **extra})

        def do_POST(self):
            if self.path != "/generate":
                return self._reply(404, {"error": "POST /generate only"})
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if "prompt" in body:
                    ids = [int(t) for t in body["prompt"]]
                elif "text" in body:
                    if tokenizer is None:
                        return self._reply(400, {
                            "error": "text prompts need --tokenizer"})
                    ids = list(tokenizer.encode(str(body["text"])))
                else:
                    return self._reply(400, {
                        "error": "missing 'prompt' (ids) or 'text'"})
                cont = server.submit(ids, body.get("max_new_tokens"))
            except (ValueError, KeyError, TypeError) as e:
                return self._reply(400, {"error": str(e)})
            except Exception as e:
                return self._reply(500, {"error": str(e)})
            payload = {"ids": cont}
            if tokenizer is not None:
                payload["text"] = tokenizer.decode(cont)
            self._reply(200, payload)

    return ThreadingHTTPServer((host, port), Handler)
