"""GloVe+CNN text classifier (reference
``example/utils/TextClassifier.scala:171-196`` ``buildModel``).

The reference reshapes pre-embedded sentences to ``(embeddingDim, 1, seqLen)``
(NCHW) and convolves over the sequence with three conv/pool stages. Here the
TPU-native layout is channels-last: input ``(N, T, E)`` is viewed as NHWC
``(N, T, 1, E)`` with time as the spatial H axis, so every conv lands on the
MXU with the embedding dim as the contracted channel axis.
"""

from __future__ import annotations

from bigdl_tpu import nn


def conv_output_length(sequence_length: int) -> int:
    """Time extent left after the reference's conv5/pool5 x2 + conv5 stages."""
    h = sequence_length - 4      # conv k=5
    h = h // 5                   # pool k=5 s=5
    h = h - 4                    # conv k=5
    h = h // 5                   # pool k=5 s=5
    h = h - 4                    # conv k=5
    return h


def build_cnn(class_num: int, sequence_length: int = 1000,
              embedding_dim: int = 100) -> nn.Sequential:
    """Reference geometry (seq 1000 -> final 35-wide pool -> 1): input
    ``(N, sequence_length, embedding_dim)`` pre-embedded tokens, output
    ``(N, class_num)`` log-probs. The final pool is sized to whatever time
    extent remains so shorter sequence lengths (tests) also collapse to 1."""
    last = conv_output_length(sequence_length)
    if last < 1:
        raise ValueError(
            f"sequence_length {sequence_length} too short for the "
            f"conv5/pool5 x3 stack (needs >= 149)")
    return (nn.Sequential()
            .add(nn.Reshape((sequence_length, 1, embedding_dim),
                            batch_mode=True))
            .add(nn.SpatialConvolution(embedding_dim, 128, 1, 5))
            .add(nn.ReLU())
            .add(nn.SpatialMaxPooling(1, 5))
            .add(nn.SpatialConvolution(128, 128, 1, 5))
            .add(nn.ReLU())
            .add(nn.SpatialMaxPooling(1, 5))
            .add(nn.SpatialConvolution(128, 128, 1, 5))
            .add(nn.ReLU())
            .add(nn.SpatialMaxPooling(1, last))
            .add(nn.Reshape((128,), batch_mode=True))
            .add(nn.Linear(128, 100))
            .add(nn.Linear(100, class_num))
            .add(nn.LogSoftMax()))
