"""Fleet router: health-aware dispatch over N serving replicas.

The zero-loss tier of ROADMAP #1. A ``ContinuousLMServer`` process can
die (decode failure → ``dead``) or be preempted (SIGTERM → ``draining``);
either way its accepted requests leave as ``HandoffCursor``s — host-side
prompt + emitted tokens that survive any device-state loss. This router
turns those cursors into zero request loss:

- **dispatch**: least-loaded over the healthy replicas (``queue_depth``
  plus the router's own in-flight count per replica — a replica whose
  queue is empty but whose slots are saturated with this router's
  requests is not "idle"), round-robin tie-break;
- **retry**: a rejected dispatch (the replica died/drained before
  accepting) retries against another replica, bounded by ``max_retries``
  with exponential backoff;
- **requeue**: a request interrupted AFTER acceptance comes back as
  ``ServerDraining``/``ServerDead`` carrying its cursor; the router
  re-dispatches ``prompt + emitted`` to a peer, whose deterministic
  chunked re-prefill makes the greedy continuation bit-identical to the
  unkilled run (the kill-one-replica drill in
  ``tests/test_serving_fleet.py`` pins this);
- **disaggregation**: with ``prefill_replicas`` configured, admission
  prefill runs on a DEDICATED prefill replica (``prefill_handoff`` →
  serialized b=1 state partition, ``bigdl_handoff_seconds``) and only
  the partition ships to the decode replica — long prompts never steal
  decode-step latency from in-flight streams. If every prefill replica
  is unhealthy (or a chaos injector drops the handoff in transit) the
  router falls back to local prefill on the decode replica: the fleet
  degrades to the aggregated topology instead of failing requests.

Transport: the router is in-process-first (replicas are server OBJECTS —
the same process, tests, and the single-host multi-replica ``serve
--replicas N``) and fronts HTTP via ``make_http_server`` unchanged: it
duck-types the server surface (``submit``/``queue_depth``/
``batches_served``/``dead_reason``) and adds ``health_extra`` so
``GET /health`` reports per-replica states. No worker threads of its
own: ``submit()`` runs on the calling client thread, so the only shared
state is the replica table + tie-break counter (lock-guarded; graftlint
JG015-017 clean).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

from bigdl_tpu.models.serving import ReplicaUnavailable, ServerDead
from bigdl_tpu.telemetry import get_registry, instruments

__all__ = ["Replica", "LMRouter"]


class Replica:
    """One routed replica: a server object plus its fleet metadata."""

    def __init__(self, server, name: Optional[str] = None,
                 role: str = "decode"):
        if role not in ("decode", "prefill"):
            raise ValueError(f"role must be 'decode' or 'prefill', "
                            f"got {role!r}")
        self.server = server
        self.name = name or f"{role}-{id(server):x}"
        self.role = role
        # router-side in-flight count: submits this router has parked on
        # the replica (its queue_depth drops to 0 the moment a request
        # is ADMITTED into a slot, which is exactly when the slot stops
        # being free — without this, a saturated replica looks idle).
        # Written by many client threads; the OWNING router's lock
        # serializes every mutation.
        self.inflight = 0

    @property
    def state(self) -> str:
        if self.server.dead_reason is not None:
            return "dead"
        if getattr(self.server, "drain_reason", None) is not None:
            return "draining"
        return "ok"

    @property
    def healthy(self) -> bool:
        return self.state == "ok"

    @property
    def load(self) -> int:
        return int(self.server.queue_depth) + self.inflight

    def describe(self) -> dict:
        d = {"name": self.name, "role": self.role, "state": self.state,
             "queue_depth": int(self.server.queue_depth),
             "inflight": self.inflight}
        if self.state == "dead":
            d["dead"] = self.server.dead_reason
        elif self.state == "draining":
            d["draining"] = self.server.drain_reason
        return d


def _as_replica(obj, role: str, idx: int) -> Replica:
    if isinstance(obj, Replica):
        return obj
    return Replica(obj, name=f"{role}-{idx}", role=role)


class LMRouter:
    """Health-aware least-loaded router over N replicas (see module
    docstring). Exposes the ``submit()/queue_depth/batches_served/
    dead_reason`` surface of a single server, so ``make_http_server``
    and the scoreboard drive a fleet exactly like one replica."""

    def __init__(self, replicas: Sequence, *,
                 prefill_replicas: Sequence = (),
                 max_retries: int = 4, backoff_s: float = 0.02,
                 registry=None, chaos=None):
        if not replicas:
            raise ValueError("router needs at least one decode replica")
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.registry = registry if registry is not None else get_registry()
        self._tm = instruments(self.registry)
        self.replicas = [_as_replica(r, "decode", i)
                         for i, r in enumerate(replicas)]
        self.prefill_replicas = [_as_replica(r, "prefill", i)
                                 for i, r in enumerate(prefill_replicas)]
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        # serving-plane chaos injectors with an on_handoff hook (the
        # drop-one-handoff drill) fire in _ship_prefill
        self._chaos = [inj for inj in (chaos or [])
                       if hasattr(inj, "on_handoff")]
        # guards the tie-break counter and every Replica.inflight
        # mutation (submit() runs on many client threads at once)
        self._lock = threading.Lock()
        self._rr = 0

    # ------------------------------------------------------------ dispatch
    def _pick(self, pool: List[Replica]) -> Optional[Replica]:
        """Least-loaded healthy replica; round-robin among ties so equal
        replicas share traffic instead of replica 0 taking everything."""
        live = [r for r in pool if r.healthy]
        if not live:
            return None
        with self._lock:
            self._rr += 1
            best = min(range(len(live)),
                       key=lambda i: (live[i].load,
                                      (i - self._rr) % len(live)))
            rep = live[best]
            rep.inflight += 1
        return rep

    def _release(self, rep: Replica) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)

    def _chaos_drop(self) -> bool:
        for inj in self._chaos:
            if inj.on_handoff(self):
                return True
        return False

    def _ship_prefill(self, ids: List[int],
                      emitted: List[int]) -> Optional[bytes]:
        """Disaggregation's ship: run the prefill on a dedicated prefill
        replica and return the serialized partition — or None to fall
        back to local prefill on the decode replica (no healthy prefill
        replica, or the bounded ship retries ran dry)."""
        for attempt in range(self.max_retries + 1):
            rep = self._pick(self.prefill_replicas)
            if rep is None:
                return None
            try:
                t0 = time.perf_counter()
                blob = rep.server.prefill_handoff(
                    ids, emitted if emitted else None)
                self._tm.handoff_seconds.observe(
                    time.perf_counter() - t0)
            except ReplicaUnavailable:
                self._tm.router_retries_total.inc()
                continue
            finally:
                self._release(rep)
            if self._chaos_drop():
                # the partition evaporated in transit (chaos
                # drop-handoff): re-ship — prefill is deterministic, a
                # second partition is the same partition
                self._tm.router_retries_total.inc()
                continue
            return blob
        return None

    # ---------------------------------------------------------- client API
    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               timeout: Optional[float] = None) -> List[int]:
        """Serve one prompt through the fleet. Zero-loss contract: a
        replica failing or draining mid-request only moves the request —
        its cursor re-dispatches to a peer and the greedy continuation
        stays bit-identical to an unkilled run."""
        self._tm.router_requests_total.inc()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        emitted: List[int] = []
        attempt = 0
        last_err: Optional[str] = None
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        "request did not complete within the timeout"
                        + (f" (last replica error: {last_err})"
                           if last_err else ""))
            state = (self._ship_prefill(list(prompt_ids), emitted)
                     if self.prefill_replicas else None)
            rep = self._pick(self.replicas)
            if rep is None:
                raise ServerDead(
                    "no healthy replicas"
                    + (f" (last replica error: {last_err})"
                       if last_err else ""))
            try:
                return rep.server.submit(prompt_ids, max_new_tokens,
                                         remaining,
                                         emitted=emitted or None,
                                         state=state)
            except ReplicaUnavailable as e:
                last_err = f"{rep.name}: {e}"
                if e.cursor is not None:
                    # the request had been ACCEPTED there — take the
                    # cursor's progress (a superset of ours: it includes
                    # any prefix we resumed it with) and requeue
                    emitted = list(e.cursor.emitted)
                    self._tm.router_requeues_total.inc()
                attempt += 1
                if attempt > self.max_retries:
                    raise
                self._tm.router_retries_total.inc()
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            finally:
                self._release(rep)

    # ------------------------------------------- single-server duck typing
    @property
    def queue_depth(self) -> int:
        return sum(r.server.queue_depth for r in self.replicas
                   if r.healthy)

    @property
    def batches_served(self) -> int:
        return sum(r.server.batches_served for r in self.replicas)

    @property
    def dead_reason(self) -> Optional[str]:
        """The fleet is only 'dead' when NO decode replica can serve —
        one dead replica is routine (that is the point of a router)."""
        if any(r.healthy for r in self.replicas):
            return None
        return "no healthy replicas: " + "; ".join(
            f"{r.name}={r.state}" for r in self.replicas)

    @property
    def health_extra(self) -> dict:
        """Per-replica detail merged into ``GET /health`` by
        ``make_http_server``."""
        return {"replicas": [r.describe() for r in
                             self.replicas + self.prefill_replicas]}

    def drain(self, reason: str = "router drain") -> None:
        """Drain every replica exactly once (the whole-fleet SIGTERM
        path; a server may back both a decode and a prefill replica)."""
        seen = set()
        for r in self.replicas + self.prefill_replicas:
            drain = getattr(r.server, "drain", None)
            if drain is None or id(r.server) in seen:
                continue
            seen.add(id(r.server))
            drain(reason)

    def close(self) -> None:
        """Close every replica exactly once (replicas may share a
        server object across roles)."""
        seen = set()
        for r in self.replicas + self.prefill_replicas:
            if id(r.server) in seen:
                continue
            seen.add(id(r.server))
            r.server.close()
