"""Autoregressive generation for causal LMs — KV-cached, fully jitted.

New TPU-native capability (the reference stops at training + batch predict:
``optim/Predictor.scala``; it has no sequence decoding of any kind). The
flagship transformer LM (``models/transformer.build_lm``) needs a sampling
path for a user to actually *use* the model, so this module provides one,
designed XLA-first:

- the KV cache is module BUFFER state, so the existing ``functional_apply``
  machinery threads it functionally — the decode loop is a single jitted
  program: one prefill forward over the prompt, then ``lax.scan`` over the
  new-token steps (one token per step, cache carried through the scan);
- shapes are static: the cache is allocated at ``prompt_len + max_new``
  up front, finished sequences are masked, never resized (XLA requirement);
- sampling (greedy / temperature / top-k / nucleus top-p) runs on-device
  inside the same program via ``jax.random.categorical``.

Token ids follow the framework's 1-based Torch convention (LookupTable,
ClassNLLCriterion): valid ids are ``1..vocab_size``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.attention import _AddedPositionBase, MultiHeadAttention
from bigdl_tpu.nn.linear import LMHead, Linear, TiedLMHead
from bigdl_tpu.nn.module import Module, _apply_lock, functional_apply
from bigdl_tpu.nn.recurrent import TimeDistributed

# Retained compiled decode programs per model (one per generate()
# signature: batch/length/sampling tuple). Serving traffic varies the
# signature, and each program closes over the model — unbounded growth
# pins every program resident forever (graftlint JG014). Past the cap
# the OLDEST signature's program is evicted (single entry, counted in
# bigdl_compile_cache_evictions_total{site="generation.decode"} —
# clear-at-cap forced every live signature to recompile at once); a
# re-seen evicted signature pays one recompile.
_GENERATE_FNS_CAP = 32


def _evict_oldest(cache: dict, site: str) -> None:
    """Drop the least-recently-inserted program from a signature-keyed
    compile cache and count it (oldest-first single-entry eviction — the
    anti-storm replacement for clear-at-cap)."""
    from bigdl_tpu.telemetry import get_registry, instruments
    cache.pop(next(iter(cache)))
    instruments(get_registry()).compile_cache_evictions_total.labels(
        site=site).inc()


def filter_top_k(logprobs: jax.Array, k: int) -> jax.Array:
    """Keep the k highest-probability tokens; the rest get -inf."""
    if k <= 0 or k >= logprobs.shape[-1]:
        return logprobs
    kth = jax.lax.top_k(logprobs, k)[0][..., -1:]
    return jnp.where(logprobs < kth, -jnp.inf, logprobs)

def filter_top_p(logprobs: jax.Array, p: float) -> jax.Array:
    """Nucleus filtering: keep the smallest set of tokens whose cumulative
    probability reaches ``p`` (always at least the argmax). Input must be
    normalised log-probabilities."""
    if p <= 0.0 or p >= 1.0:
        return logprobs
    sorted_lp = jnp.flip(jnp.sort(logprobs, axis=-1), axis=-1)
    cum = jnp.cumsum(jnp.exp(sorted_lp), axis=-1)
    # token kept iff the mass BEFORE it is still < p (top-1 always kept)
    keep = (cum - jnp.exp(sorted_lp)) < p
    thresh = jnp.min(jnp.where(keep, sorted_lp, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logprobs < thresh, -jnp.inf, logprobs)

def sample_token(logprobs: jax.Array, key: Optional[jax.Array], *,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 0.0, greedy: bool = False) -> jax.Array:
    """One sampling step over (B, V) log-probs -> (B,) 1-based token ids.

    With ``top_k > 0`` the whole tail runs FUSED on the (B, k) candidate
    sliver: one ``top_k`` over V, then temperature/top-p/Gumbel-argmax on
    k values — mathematically identical to filter+renormalise+categorical
    (Gumbel-max trick), but it drops every other V-wide kernel from the
    decode step. Measured on chip: top-k sampling cost fell from
    +182 us/step to near-greedy (PERF.md round 4) — at B=1 the decode is
    per-kernel-overhead-bound, so kernel COUNT is the lever."""
    if greedy:
        return jnp.argmax(logprobs, axis=-1).astype(jnp.int32) + 1
    lp = logprobs.astype(jnp.float32)
    if top_k > 0 and top_k < lp.shape[-1]:
        vals, idx = jax.lax.top_k(lp, top_k)          # (B, k) sorted desc
        if temperature != 1.0:
            vals = vals / max(float(temperature), 1e-6)
        vals = jax.nn.log_softmax(vals, axis=-1)      # renormalised over k
        if 0.0 < top_p < 1.0:
            # nucleus within the (already sorted) candidates: keep entries
            # while the mass BEFORE them is < p (top-1 always kept)
            cum = jnp.cumsum(jnp.exp(vals), axis=-1)
            keep = (cum - jnp.exp(vals)) < top_p
            vals = jnp.where(keep, vals, -jnp.inf)
        g = jax.random.gumbel(key, vals.shape)
        choice = jnp.argmax(vals + g, axis=-1)        # Gumbel-max == sample
        tok = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
        return tok.astype(jnp.int32) + 1
    if temperature != 1.0:
        lp = lp / max(float(temperature), 1e-6)
    # re-normalise so top_p trims the nucleus of the REMAINING distribution
    # (standard composed semantics; filter_top_p requires normalised
    # log-probs)
    lp = filter_top_p(jax.nn.log_softmax(lp, axis=-1), top_p)
    return jax.random.categorical(key, lp, axis=-1).astype(jnp.int32) + 1


def _decode_modules(model: Module):
    mhas = [m for m in model.modules() if isinstance(m, MultiHeadAttention)]
    pes = [m for m in model.modules() if isinstance(m, _AddedPositionBase)]
    # LM-head tails compute only the LAST position while decoding — the
    # prefill otherwise materialises (B, S0, V) log-probs just to sample
    # one token
    heads = [m for m in model.modules()
             if isinstance(m, (LMHead, TiedLMHead))]
    # A TimeDistributed is last-position-sliced ONLY when it is plausibly
    # the vocab head (inner Linear, exactly one instance) — slicing a
    # mid-network TimeDistributed would silently corrupt generations.
    tds = [m for m in model.modules() if isinstance(m, TimeDistributed)]
    if tds:
        if len(tds) > 1:
            raise ValueError(
                f"model has {len(tds)} TimeDistributed modules; generate() "
                "can only last-position-slice a single LM-head tail "
                "(TimeDistributed(Linear) as the vocab projection)")
        if isinstance(getattr(tds[0], "inner", None), Linear):
            heads.append(tds[0])
        # non-Linear inner: leave it alone — it computes every position
    if not mhas:
        raise ValueError("generate() needs a model with MultiHeadAttention "
                         "layers (see models/transformer.build_lm)")
    return mhas, pes, heads


def _pos_table_len(pe) -> int:
    """Capacity (max positions) of any additive positional encoding."""
    return pe.pos_table().shape[0]


def _build_decode_fn(model: Module, max_new_tokens: int, temperature: float,
                     top_k: int, top_p: float, greedy: bool,
                     eos_id: Optional[int], pad_id: int,
                     repetition_penalty: float = 1.0,
                     min_new_tokens: int = 0):
    """Pure (params, buffers, prompt, key) -> (B, S0+max_new) id matrix."""
    rep = float(repetition_penalty)

    def sample(logp, key, seen, t):
        if rep != 1.0:
            # CTRL-style: log-probs are negative, so multiplying a seen
            # token's log-prob by the penalty (> 1) pushes it down
            logp = jnp.where(seen, logp * rep, logp)
        if eos_id is not None and min_new_tokens > 0:
            # t = index of the token being generated (0-based)
            logp = jnp.where((t < min_new_tokens)
                             & (jnp.arange(logp.shape[-1])[None, :]
                                == eos_id - 1), -jnp.inf, logp)
        return sample_token(logp, key, temperature=temperature, top_k=top_k,
                            top_p=top_p, greedy=greedy)

    def run(params, buffers, prompt, key):
        out, bufs = functional_apply(model, params, buffers, prompt,
                                     training=False)
        v = out.shape[-1]
        if rep != 1.0:
            seen = jnp.zeros((prompt.shape[0], v), bool)
            idx0 = jnp.clip(prompt.astype(jnp.int32) - 1, 0, v - 1)
            seen = seen.at[jnp.arange(prompt.shape[0])[:, None],
                           idx0].set(True)
        else:
            seen = jnp.zeros((prompt.shape[0], 1), bool)  # unused
        key, sub = jax.random.split(key)
        tok = sample(out[:, -1].astype(jnp.float32), sub, seen, 0)
        if rep != 1.0:
            seen = seen.at[jnp.arange(tok.shape[0]), tok - 1].set(True)
        if eos_id is None:
            done = jnp.zeros(tok.shape, bool)
        else:
            done = tok == eos_id

        def body(carry, t):
            bufs, tok, key, done, seen = carry
            step_in = tok[:, None].astype(prompt.dtype)
            out, bufs = functional_apply(model, params, bufs, step_in,
                                         training=False)
            key, sub = jax.random.split(key)
            nxt = sample(out[:, -1].astype(jnp.float32), sub, seen, t)
            nxt = jnp.where(done, jnp.int32(pad_id), nxt)
            if rep != 1.0:
                seen = seen.at[jnp.arange(nxt.shape[0]), nxt - 1].set(True)
            if eos_id is not None:
                done = done | (nxt == eos_id)
            return (bufs, nxt, key, done, seen), nxt

        (_, _, _, _, _), rest = jax.lax.scan(
            body, (bufs, tok, key, done, seen),
            jnp.arange(1, max_new_tokens))
        toks = jnp.concatenate([tok[:, None], rest.T], axis=1)
        return jnp.concatenate([prompt, toks.astype(prompt.dtype)], axis=1)

    from bigdl_tpu.telemetry.profiling import tracked_jit
    return tracked_jit(run, site="generation.decode")


def _map_cache_leaves(buffers, fn, other_fn=None):
    """Apply ``fn`` to every KV-cache leaf (k_cache/v_cache) in a buffer
    tree, and ``other_fn`` (default: identity) to every other leaf."""
    import jax.tree_util as jtu

    def visit(path, leaf):
        key = str(path[-1].key) if path and hasattr(path[-1], "key") else ""
        if key in ("k_cache", "v_cache"):
            return fn(leaf)
        return leaf if other_fn is None else other_fn(leaf)

    return jtu.tree_map_with_path(visit, buffers)


def _build_beam_fn(model: Module, max_new_tokens: int, num_beams: int,
                   length_penalty: float, eos_id: Optional[int], pad_id: int):
    """Pure (params, buffers, prompt) -> (B, S0+max_new) best-beam ids.

    Standard batched beam search over the KV cache: prefill once at batch
    B, tile the caches to B*num_beams, then each scan step scores all
    (beam, token) continuations, keeps the top ``num_beams`` per batch
    item, and REORDERS the caches by each survivor's parent beam (a
    take-along-batch gather applied to every cache leaf). Finished beams
    (emitted ``eos_id``) are frozen: their only continuation is ``pad_id``
    at unchanged score. The returned sequence is the best beam under
    GNMT-style length normalisation ``score / len(tokens)**length_penalty``.
    """
    n = num_beams

    def run(params, buffers, prompt):
        b, s0 = prompt.shape
        out, bufs = functional_apply(model, params, buffers, prompt,
                                     training=False)
        logp0 = out[:, -1].astype(jnp.float32)              # (B, V)
        v = logp0.shape[-1]
        if eos_id is not None and not 1 <= pad_id <= v:
            raise ValueError(
                f"pad_id {pad_id} outside the vocab 1..{v}: frozen beams "
                "continue with pad_id, so it must be a real token id")
        # initial beams: top-n first tokens (filler beams at -inf when the
        # vocab is smaller than the beam width)
        k0 = min(n, v)
        scores0, idx = jax.lax.top_k(logp0, k0)             # (B, k0)
        if k0 < n:
            scores0 = jnp.pad(scores0, ((0, 0), (0, n - k0)),
                              constant_values=-jnp.inf)
            idx = jnp.pad(idx, ((0, 0), (0, n - k0)),
                          constant_values=pad_id - 1)
        scores = scores0
        tok = (idx + 1).astype(jnp.int32)
        done = (tok == eos_id) if eos_id is not None else jnp.zeros(
            tok.shape, bool)
        if k0 < n:  # filler beams are frozen from the start
            done = done | (jnp.arange(n)[None, :] >= k0)
        lengths = jnp.ones(tok.shape, jnp.float32)
        # tile caches to B*n (batch-major: beams of item i are contiguous)
        bufs = _map_cache_leaves(bufs, lambda x: jnp.repeat(x, n, axis=0))
        seqs = jnp.zeros((b, n, max_new_tokens), jnp.int32)
        seqs = seqs.at[:, :, 0].set(tok)

        def body(carry, t):
            bufs, tok, scores, done, lengths, seqs = carry
            step_in = tok.reshape(b * n, 1).astype(prompt.dtype)
            out, bufs = functional_apply(model, params, bufs, step_in,
                                         training=False)
            logp = out[:, -1].astype(jnp.float32).reshape(b, n, v)
            if eos_id is not None:
                # frozen beams may only emit pad at unchanged score
                frozen = jnp.full((v,), -jnp.inf).at[pad_id - 1].set(0.0)
                logp = jnp.where(done[..., None], frozen, logp)
            total = scores[..., None] + logp                # (B, n, V)
            scores, flat_idx = jax.lax.top_k(total.reshape(b, n * v), n)
            parent = flat_idx // v                          # (B, n)
            tok = (flat_idx % v + 1).astype(jnp.int32)
            take = lambda arr: jnp.take_along_axis(arr, parent, axis=1)
            done = take(done)
            lengths = take(lengths) + jnp.where(done, 0.0, 1.0)
            seqs = jnp.take_along_axis(seqs, parent[..., None], axis=1)
            seqs = seqs.at[:, :, t].set(jnp.where(done, pad_id, tok))
            if eos_id is not None:
                done = done | (tok == eos_id)
            flat_parent = (jnp.arange(b)[:, None] * n + parent).reshape(-1)
            bufs = _map_cache_leaves(
                bufs, lambda x: jnp.take(x, flat_parent, axis=0))
            return (bufs, tok, scores, done, lengths, seqs), None

        if max_new_tokens > 1:
            (bufs, tok, scores, done, lengths, seqs), _ = jax.lax.scan(
                body, (bufs, tok, scores, done, lengths, seqs),
                jnp.arange(1, max_new_tokens))
        norm = scores / jnp.power(jnp.maximum(lengths, 1.0), length_penalty)
        best = jnp.argmax(norm, axis=1)                     # (B,)
        best_seq = jnp.take_along_axis(
            seqs, best[:, None, None], axis=1)[:, 0]        # (B, max_new)
        return jnp.concatenate(
            [prompt, best_seq.astype(prompt.dtype)], axis=1)

    from bigdl_tpu.telemetry.profiling import tracked_jit
    return tracked_jit(run, site="generation.beam")


def generate(model: Module, prompt, max_new_tokens: int, *,
             temperature: float = 1.0, top_k: int = 0, top_p: float = 0.0,
             greedy: bool = False, eos_id: Optional[int] = None,
             pad_id: Optional[int] = None,
             repetition_penalty: float = 1.0, min_new_tokens: int = 0,
             num_beams: int = 0, length_penalty: float = 1.0,
             mesh=None, data_axis: str = "data",
             tensor_axis: Optional[str] = None,
             rolling_cache: bool = False,
             key: Optional[jax.Array] = None) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    ``prompt``: (B, S) or (S,) 1-based token ids (any numeric dtype).
    Returns prompt+continuation, shape (B, S + max_new_tokens). Sequences
    that emit ``eos_id`` are frozen: subsequent positions hold ``pad_id``
    (default: ``eos_id``). Sampling is greedy when ``greedy`` or
    ``temperature + filters`` select it deterministically; otherwise draws
    use ``key`` (default PRNGKey(0) — pass your own for varied samples).
    ``num_beams > 1`` switches to deterministic beam search (per-batch-item
    beams over the KV cache, GNMT length penalty) — incompatible with the
    stochastic ``top_k``/``top_p`` filters.

    ``mesh``: a ``jax.sharding.Mesh`` for distributed decoding — the
    prompt and every KV-cache buffer shard over ``data_axis`` (the axis
    size must divide the batch) and GSPMD propagates the layout through
    the whole prefill+scan program. Parameters replicate by default
    (embarrassingly parallel — no collectives); with ``tensor_axis`` set,
    weights additionally shard Megatron-style over that mesh axis
    (``parallel.tensor_parallel.infer_param_specs``) for models too large
    to replicate per device — GSPMD inserts the per-layer collectives.

    The whole decode — prompt prefill, per-token steps, sampling — is one
    jitted program per (shape, sampling-config); compiled programs are
    cached on the model instance.
    """
    if num_beams > 1 and (top_k or top_p):
        raise ValueError("beam search is deterministic; top_k/top_p do not "
                         "compose with num_beams")
    if num_beams > 1 and (repetition_penalty != 1.0 or min_new_tokens):
        raise ValueError("repetition_penalty/min_new_tokens apply to the "
                         "sampling path, not beam search")
    if repetition_penalty <= 0:
        raise ValueError("repetition_penalty must be > 0")
    if num_beams == 1:
        greedy = True  # width-1 beam search IS greedy decoding
    prompt = jnp.asarray(prompt)
    squeeze = prompt.ndim == 1
    if squeeze:
        prompt = prompt[None]
    if max_new_tokens <= 0:
        return prompt[0] if squeeze else prompt
    b, s0 = prompt.shape
    total = s0 + max_new_tokens
    mhas, pes, heads = _decode_modules(model)
    for pe in pes:
        if _pos_table_len(pe) < total:
            raise ValueError(
                f"model max_len {_pos_table_len(pe)} < prompt+max_new_tokens "
                f"{total}; rebuild the model with a larger max_len")
    if pad_id is None:
        pad_id = eos_id if eos_id is not None else 1
    if rolling_cache:
        bad = [m for m in mhas if not getattr(m, "window", None)]
        if bad:
            # validated BEFORE the apply lock is acquired — raising between
            # acquire() and the try/finally would leak the lock forever
            raise ValueError("rolling_cache requires every attention layer "
                             "to have a sliding window (window=N): an "
                             "unbounded-context layer needs every past key")

    # the whole enable_decode -> functional_state -> run -> disable_decode
    # window holds the per-root apply lock (reentrant — functional_state
    # re-acquires it): a concurrent predict/evaluate/generate on the same
    # instance must not observe half-toggled decode state. was_training is
    # read AFTER acquiring — reading it earlier could capture another
    # generate's transient eval mode and restore the wrong mode on exit.
    _lock = _apply_lock(model)
    _lock.acquire()
    was_training = model.training
    try:
        model.evaluate_mode()
        for m in mhas:
            m.enable_decode(b, total, rolling=rolling_cache)
        for m in pes + heads:
            m.enable_decode()
        params, buffers = model.functional_state()
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            if tensor_axis is not None and tensor_axis not in mesh.shape:
                raise ValueError(f"tensor_axis {tensor_axis!r} is not a "
                                 f"mesh axis (mesh has {list(mesh.shape)})")
            if data_axis not in mesh.shape:
                if tensor_axis is None:
                    raise ValueError(
                        f"mesh has no {data_axis!r} axis (axes: "
                        f"{list(mesh.shape)}); pass data_axis=, or "
                        "tensor_axis= for weight-only sharding")
                batch_dim = None  # pure TP: batch replicated
            else:
                batch_dim = data_axis
                axis = mesh.shape[data_axis]
                if b % axis != 0:
                    raise ValueError(
                        f"batch {b} is not a multiple of the mesh "
                        f"'{data_axis}' axis size {axis}")
            repl = NamedSharding(mesh, PartitionSpec())
            row = NamedSharding(mesh, PartitionSpec(batch_dim))
            if tensor_axis is not None:
                from bigdl_tpu.parallel.tensor_parallel import \
                    infer_param_specs
                specs = infer_param_specs(model, axis=tensor_axis,
                                          axis_size=dict(mesh.shape))
                params = jax.tree_util.tree_map(
                    lambda p, sp: jax.device_put(p, NamedSharding(mesh, sp)),
                    params, specs)
            else:
                params = jax.device_put(params, repl)

            def place_cache(x):
                # (B, L, H, Dh): batch over data; heads over tensor when
                # divisible — TP exists for memory headroom, and the KV
                # cache is the long-context memory hog
                head_dim = (tensor_axis if tensor_axis is not None
                            and x.ndim == 4
                            and x.shape[2] % mesh.shape[tensor_axis] == 0
                            else None)
                return jax.device_put(x, NamedSharding(
                    mesh, PartitionSpec(batch_dim, None, head_dim)))

            buffers = _map_cache_leaves(
                buffers, place_cache,
                other_fn=lambda x: jax.device_put(x, repl))
            prompt = jax.device_put(prompt, row)
        cache = model.__dict__.setdefault("_generate_fns", {})
        # NOTE: mesh is intentionally NOT in the key — the built fn is
        # mesh-agnostic, and jax.jit already specialises per input sharding
        sig = (b, s0, max_new_tokens, float(temperature), int(top_k),
               float(top_p), bool(greedy), eos_id, pad_id,
               float(repetition_penalty), int(min_new_tokens),
               int(num_beams), float(length_penalty), bool(rolling_cache))
        fn = cache.get(sig)
        if fn is None:
            while len(cache) >= _GENERATE_FNS_CAP:
                # bound the per-signature family (graftlint JG014): a
                # mixed-traffic server otherwise retains one compiled
                # program per distinct (batch, length, sampling) forever.
                # Oldest-first, ONE entry — clearing everything forced
                # every live signature to recompile right after the wipe
                _evict_oldest(cache, "generation.decode")
            if num_beams > 1:
                fn = _build_beam_fn(model, max_new_tokens, num_beams,
                                    length_penalty, eos_id, pad_id)
            else:
                fn = _build_decode_fn(
                    model, max_new_tokens, temperature, top_k, top_p,
                    greedy, eos_id, pad_id,
                    repetition_penalty=repetition_penalty,
                    min_new_tokens=min_new_tokens)
            # graftlint: ignore[JG013] -- signature-keyed compile family is generate()'s documented contract (one program per static decode signature); bounded by _GENERATE_FNS_CAP above
            cache[sig] = fn
        if num_beams > 1:
            out = fn(params, buffers, prompt)
        else:
            if key is None:
                key = jax.random.PRNGKey(0)
            out = fn(params, buffers, prompt, key)
    finally:
        for m in mhas + pes + heads:
            m.disable_decode()
        model.set_training(was_training)
        _lock.release()
    return out[0] if squeeze else out


# ---------------------------------------------------------------------------
# Speculative decoding (round 4)
# ---------------------------------------------------------------------------

def _set_decode_pos(buffers, value):
    """Set every ``decode_pos`` leaf (MHA caches AND positional encodings)
    to ``value`` — the cache-rewind primitive speculative decoding needs."""
    import jax.tree_util as jtu

    def visit(path, leaf):
        key = str(path[-1].key) if path and hasattr(path[-1], "key") else ""
        if key == "decode_pos":
            return jnp.full_like(leaf, value)
        return leaf

    return jtu.tree_map_with_path(visit, buffers)


def _shift_decode_pos(buffers, delta):
    """Add ``delta`` to every ``decode_pos`` leaf — the PER-ROW rewind
    primitive of continuous-batching speculative decode. ``delta`` is a
    ``(B,)`` array of (non-positive) offsets: each slot rolls its own
    cache back to its own accepted boundary, where ``_set_decode_pos``
    can only force one scalar across the batch."""
    import jax.tree_util as jtu

    def visit(path, leaf):
        key = str(path[-1].key) if path and hasattr(path[-1], "key") else ""
        if key == "decode_pos":
            return leaf + delta.astype(leaf.dtype)
        return leaf

    return jtu.tree_map_with_path(visit, buffers)


#: Buffer-tree leaf names that are PER-REQUEST prefill state (owned,
#: donated, copied per admission) as opposed to shared model buffers
#: (e.g. a quantized model's int8 weights — read-only across requests).
_PREFILL_STATE_KEYS = ("k_cache", "v_cache", "decode_pos")


def partition_prefill_state(bufs):
    """Split a decode-mode buffer tree into ``(state, statics, merge)``.

    ``state`` is the flat list of per-request leaves (KV caches + write
    positions — everything a prefill mutates), ``statics`` the flat list
    of every other buffer leaf, and ``merge(state, statics)`` rebuilds
    the full tree (host-side, no copies). The chunked prefill programs
    donate ONLY the state partition, so the per-admission copy scales
    with the b=1 cache, never with model size (a quantized model's
    weight buffers stay shared across admissions)."""
    import jax.tree_util as jtu
    leaves, treedef = jtu.tree_flatten_with_path(bufs)
    is_state = [bool(p) and hasattr(p[-1], "key")
                and str(p[-1].key) in _PREFILL_STATE_KEYS
                for p, _ in leaves]
    state = [x for (_, x), s in zip(leaves, is_state) if s]
    statics = [x for (_, x), s in zip(leaves, is_state) if not s]

    def merge(state, statics):
        it_s, it_o = iter(state), iter(statics)
        return jtu.tree_unflatten(
            treedef, [next(it_s) if s else next(it_o) for s in is_state])

    return state, statics, merge


def serialize_prefill_state(lp, state) -> bytes:
    """Pack one admission handoff — the (1, V) last-token log-probs plus
    the b=1 state partition from ``partition_prefill_state`` — into a
    single npz blob a peer replica can restore with
    ``deserialize_prefill_state``.

    This is the wire format of prefill/decode disaggregation (the router
    ships it from a prefill replica to a decode replica) and of slot
    migration off a draining server. Arrays are materialised host-side
    in partition order (``s0..sN``), so restore rebuilds the exact list
    the merge/insert machinery expects; bit-exactness holds because the
    values are copied, never re-derived."""
    import io

    import numpy as np
    buf = io.BytesIO()
    arrs = {"lp": np.asarray(lp)}
    for i, x in enumerate(state):
        arrs[f"s{i}"] = np.asarray(x)
    np.savez(buf, **arrs)
    return buf.getvalue()


def deserialize_prefill_state(data: bytes):
    """Restore ``(lp, state)`` from a ``serialize_prefill_state`` blob.
    The state list comes back in partition order, ready for
    ``merge(state, statics)`` against the RECEIVER's shared buffers (the
    statics are model weights — identical across replicas of the same
    build, so only the per-request partition travels)."""
    import io

    import numpy as np
    z = np.load(io.BytesIO(data))
    lp = jnp.asarray(z["lp"])
    n = sum(1 for k in z.files if k.startswith("s"))
    state = [jnp.asarray(z[f"s{i}"]) for i in range(n)]
    return lp, state


def build_chunked_prefill_fns(model: Module, template_bufs, *,
                              site: str = "serving.prefill",
                              registry=None):
    """O(1)-compile chunked prompt prefill: exactly TWO programs
    regardless of prompt length (the fix for the serving compile storm —
    one program per distinct length, graftlint JG013/ROADMAP #1).

    ``template_bufs`` is the b=1 decode-mode buffer tree the server
    prefills from; its partition (``partition_prefill_state``) is baked
    into the programs. Returns ``(chunk_fn, last_fn, state0, statics,
    merge)``:

    - ``chunk_fn(params, state, statics, chunk, new_pos) -> state``:
      one fixed-width ``(1, C)`` chunk through the warm-cache chunked
      attention branch (``nn.attention._attend_decode``'s multi-token
      path — the same machinery speculative verification uses): k/v
      write at the true cache positions ``decode_pos..decode_pos+C-1``
      and the position mask ``k_pos <= q_pos`` keeps right-padding in a
      ragged final chunk from ever being attended. ``new_pos`` (traced)
      then forces ``decode_pos`` to the TRUE token count, so the pad
      writes are re-covered by the next call. The head stays
      last-position-sliced; intermediate chunks never materialise
      logits.
    - ``last_fn(params, state, statics, tok) -> (last log-probs,
      state)``: the prompt's final token as a single warm step — its
      ``(1, V)`` log-probs are the admission sample, read at the
      token's true position with no dynamic indexing into a padded
      chunk.

    Trace-time contract (the serving engine's ``_single_mode`` handles
    this): every attention module must have ``_decode_prefilled = True``
    when either program is traced, so a cold cache takes the masked
    warm-cache branch — correct at ``decode_pos = 0`` because unwritten
    cache slots sit beyond the ``k_pos <= q_pos`` mask.

    Both programs DONATE the ``state`` partition (caches + positions):
    the chunk loop threads one cache through ⌈(L-1)/C⌉ sequential
    calls, and without donation each call would allocate-and-copy the
    full b=1 cache instead of updating it in place. The caller must
    pass an OWNED state (copy ``state0`` once per prefill, never hand
    over the template's own leaves); ``statics`` rides along
    non-donated, shared across every admission.
    """
    from bigdl_tpu.telemetry.profiling import tracked_jit

    state0, statics, merge = partition_prefill_state(template_bufs)

    def extract(bufs):
        # the state partition of an UPDATED full tree (functional_apply
        # preserves structure, so the template's partition applies)
        return partition_prefill_state(bufs)[0]

    def run_chunk(params, state, statics, chunk, new_pos):
        _, bufs = functional_apply(model, params, merge(state, statics),
                                   chunk, training=False)
        # the forward advanced decode_pos by the full chunk width, pad
        # included; rewind to the true count INSIDE the program (one
        # fused write, no extra host dispatch)
        return extract(_set_decode_pos(bufs, new_pos))

    def run_last(params, state, statics, tok):
        lp, bufs = functional_apply(model, params, merge(state, statics),
                                    tok, training=False)
        return lp[:, -1], extract(bufs)

    return (tracked_jit(run_chunk, site=site, registry=registry,
                        donate_argnums=(1,)),
            tracked_jit(run_last, site=site, registry=registry,
                        donate_argnums=(1,)),
            state0, statics, merge)


def build_bucketed_prefill_fn(model: Module, *,
                              site: str = "serving.prefill",
                              registry=None):
    """Power-of-two length-bucketed prompt prefill — the fallback for
    models whose attention path can't take the masked warm-cache chunk
    (``prefill_mode="bucketed"``): ONE ``tracked_jit`` wrapper whose
    input is the prompt right-padded to its ``pow2_bucket`` length, so
    XLA specializes one program per BUCKET (O(log max_len) total), not
    per length. Runs the standard cold-cache causal prefill; the LM
    heads must be in ``_decode_all`` mode at trace time because the true
    last token sits at ``last_idx`` (traced), not at the padded end."""
    from bigdl_tpu.telemetry.profiling import tracked_jit

    def run(params, bufs, prompt, last_idx):
        lp, bufs = functional_apply(model, params, bufs, prompt,
                                    training=False)
        return jnp.take(lp, last_idx, axis=1), bufs

    return tracked_jit(run, site=site, registry=registry)


def generate_speculative(target: Module, draft: Module, prompt,
                         max_new_tokens: int, *, spec_len: int = 4,
                         eos_id: Optional[int] = None,
                         pad_id: Optional[int] = None,
                         key: Optional[jax.Array] = None,
                         temperature: float = 1.0) -> jax.Array:
    """Speculative decoding: the DRAFT proposes ``spec_len`` tokens
    per round, the TARGET verifies them in ONE chunked forward, and the
    accepted prefix is emitted plus one target-sourced token — so each
    round emits 1..spec_len+1 tokens for one target dispatch.

    Two modes:

    - ``key=None`` (default): GREEDY — the longest proposal prefix
      matching the target's argmax is accepted plus the target's own next
      token (the bonus). Output is EXACTLY the target's greedy generation
      (the draft only changes speed, never tokens; differentially tested).
    - ``key=PRNGKey``: SAMPLED — rejection-sampling speculative decoding
      (Leviathan et al. / Chen et al.): proposals are drawn from the
      draft distribution q, proposal i is accepted with probability
      ``min(1, p_i(x)/q_i(x))`` against the target distribution p, the
      first rejection resamples from the residual ``max(p - q, 0)``
      (renormalized), and full acceptance samples the bonus from
      ``p_{k+1}``. The emitted sequence is distributed EXACTLY as
      sampling from the target alone — proven by the standard telescoping
      argument and verified empirically by the distribution-matching test
      (``tests/test_generation.py::TestSpeculativeSampled``).
      ``temperature`` rescales BOTH distributions before proposal and
      acceptance (the exactness theorem is per-distribution-pair).

    TPU-first mechanics: every round has STATIC shapes (the draft runs a
    fixed spec_len+1-step ``lax.scan`` — the +1 step writes the last
    proposal into the draft's own cache so full acceptance stays
    consistent; the target verifies a fixed (1, spec_len+1) chunk via the
    warm-cache chunked attention path), acceptance is a mask reduction,
    and the cache rewind is a ``decode_pos`` reset — stale entries beyond
    it are overwritten by later writes. The whole decode is one jitted
    ``lax.while_loop`` program.

    B=1 only (acceptance length is per-row; a batched version would need
    per-row cache positions). Draft and target must share the vocab.
    """
    prompt = jnp.asarray(prompt)
    if prompt.ndim == 1:
        prompt = prompt[None]
    b, s0 = prompt.shape
    if b != 1:
        raise ValueError("speculative decoding is B=1 (per-row acceptance "
                         "lengths need per-row cache positions)")
    if spec_len < 1:
        raise ValueError("spec_len must be >= 1")
    if temperature <= 0:
        raise ValueError("temperature must be > 0")
    sampled = key is not None
    k = int(spec_len)
    cap = s0 + max_new_tokens + k + 2  # cache slack for over-appended chunks
    if pad_id is None:
        pad_id = eos_id if eos_id is not None else 1

    t_mods = _decode_modules(target)
    d_mods = _decode_modules(draft)
    for pe in t_mods[1] + d_mods[1]:
        if _pos_table_len(pe) < cap:
            raise ValueError(
                f"model max_len {_pos_table_len(pe)} < prompt + max_new + "
                f"spec_len slack {cap}; rebuild with a larger max_len")

    # deterministic acquisition order (by id) — concurrent
    # generate_speculative(A, B) and (B, A) must not AB/BA-deadlock
    _locks = [_apply_lock(m) for m in
              sorted({id(target): target, id(draft): draft}.values(),
                     key=id)]
    for lk in _locks:
        lk.acquire()
    t_training, d_training = target.training, draft.training
    try:
        for model, (mhas, pes, heads) in ((target, t_mods), (draft, d_mods)):
            model.evaluate_mode()
            for m in mhas:
                m.enable_decode(b, cap)
            for m in pes + heads:
                m.enable_decode()
        t_params, t_bufs = target.functional_state()
        d_params, d_bufs = draft.functional_state()
        t_heads, d_heads = t_mods[2], d_mods[2]

        def _retemp(lp):
            # log-probs -> temperature-rescaled log-probs (dividing
            # log-probs by T differs from logits/T by a constant, which
            # the renormalisation removes)
            if temperature == 1.0:
                return lp
            return jax.nn.log_softmax(lp / temperature, axis=-1)

        def run(t_params, t_bufs, d_params, d_bufs, prompt, rng):
            # prefill both models with SLICED heads ((B, 1, V) — the full
            # (B, S0, V) prefill log-probs are what head slicing exists to
            # avoid); the flags flip before the chunk phase is traced
            # below, and cache hits never re-read them
            for m in t_heads + d_heads:
                m._decode_all = False
            t_out, t_bufs = functional_apply(target, t_params, t_bufs,
                                             prompt, training=False)
            if sampled:
                rng, k0 = jax.random.split(rng)
                cur = jax.random.categorical(
                    k0, _retemp(t_out[:, -1])).astype(jnp.int32) + 1
            else:
                cur = jnp.argmax(t_out[:, -1], axis=-1).astype(jnp.int32) + 1
            _, d_bufs = functional_apply(draft, d_params, d_bufs, prompt,
                                         training=False)
            for m in t_heads + d_heads:
                m._decode_all = True  # verification needs ALL chunk logits
            out0 = jnp.full((b, max_new_tokens + k + 1), jnp.int32(pad_id))
            # emit the prefill token as position 0
            out0 = out0.at[:, 0].set(cur)
            done0 = (cur == eos_id) if eos_id is not None else \
                jnp.zeros_like(cur, bool)
            pos0 = jnp.int32(s0)

            def cond(carry):
                _, _, _, count, _, done, _, _, _ = carry
                return (count < max_new_tokens) & ~done[0]

            def body(carry):
                t_bufs, d_bufs, out, count, cur, done, t_pos, d_pos, rng \
                    = carry
                rng, sub = jax.random.split(rng)
                dkeys = jax.random.split(sub, k + 3)

                # draft: k proposals + one extra step that writes the last
                # proposal into the draft cache (full-acceptance support)
                def dstep(c, step_key):
                    bufs, tok = c
                    lp, bufs = functional_apply(
                        draft, d_params, bufs,
                        tok[:, None].astype(prompt.dtype), training=False)
                    q = _retemp(lp[:, -1])
                    if sampled:
                        nxt = jax.random.categorical(
                            step_key, q).astype(jnp.int32) + 1
                    else:
                        nxt = jnp.argmax(q, axis=-1).astype(jnp.int32) + 1
                    return (bufs, nxt), (nxt, q)

                (d_bufs, _), (d_toks, d_qs) = jax.lax.scan(
                    dstep, (d_bufs, cur), dkeys[:k + 1])
                d_toks = d_toks[:k, :, 0] if d_toks.ndim == 3 else d_toks[:k]
                d_props = d_toks.T if d_toks.ndim == 2 else d_toks[None]
                # d_props: (B, k); d_qs: (k+1, B, V) draft log-probs

                # target: one chunked verification forward over
                # [cur, d_1..d_k] — logits for every position
                chunk = jnp.concatenate(
                    [cur[:, None], d_props], axis=1).astype(prompt.dtype)
                t_lp, t_bufs = functional_apply(target, t_params, t_bufs,
                                                chunk, training=False)
                t_lp = _retemp(t_lp)
                g = jnp.argmax(t_lp, axis=-1).astype(jnp.int32) + 1
                # g[:, i] = target's token after consuming chunk[:, :i+1]

                if sampled:
                    # rejection sampling (exact target distribution):
                    # accept proposal i iff u_i < p_i(x_i)/q_i(x_i)
                    props0 = d_props[0] - 1                 # 0-based (k,)
                    p_tok = jnp.take_along_axis(
                        t_lp[0, :k], props0[:, None], 1)[:, 0]
                    q_tok = jnp.take_along_axis(
                        d_qs[:k, 0], props0[:, None], 1)[:, 0]
                    us = jax.random.uniform(dkeys[k + 1], (k,))
                    accept = jnp.log(us) < (p_tok - q_tok)
                    n_acc = jnp.argmin(jnp.concatenate(
                        [accept, jnp.zeros((1,), bool)])).astype(jnp.int32)
                    # next token: residual max(p - q, 0) at the rejection
                    # point; full acceptance (n_acc == k) samples the
                    # bonus straight from p_{k+1} (residual with q = 0)
                    t_row = jnp.exp(t_lp[0, n_acc])
                    q_row = jnp.where(
                        n_acc < k,
                        jnp.exp(d_qs[jnp.minimum(n_acc, k - 1), 0]), 0.0)
                    res = jnp.maximum(t_row - q_row, 0.0)
                    tot = jnp.sum(res)
                    # p == q exactly -> empty residual; the theorem's
                    # conditional is then p itself
                    probs = jnp.where(tot > 0, res / jnp.maximum(tot, 1e-38),
                                      t_row)
                    logits = jnp.where(probs > 0, jnp.log(
                        jnp.maximum(probs, 1e-38)), -jnp.inf)
                    bonus = jax.random.categorical(
                        dkeys[k + 2], logits).astype(jnp.int32) + 1
                else:
                    # longest matching prefix of proposals
                    match = d_props == g[:, :k]            # (B, k)
                    n_acc = jnp.argmin(
                        jnp.concatenate([match, jnp.zeros((b, 1), bool)],
                                        axis=1), axis=1)[0]  # first mismatch
                    bonus = g[0, n_acc]
                # emitted this round: d_1..d_n, bonus  -> (k+1,) vector
                emit = jnp.where(jnp.arange(k + 1) < n_acc,
                                 jnp.concatenate(
                                     [d_props[0],
                                      jnp.zeros((1,), jnp.int32)]),
                                 bonus)
                emit = jnp.where(jnp.arange(k + 1) > n_acc, pad_id, emit)
                n_emit = n_acc + 1
                if eos_id is not None:
                    is_eos = (emit == eos_id) & \
                        (jnp.arange(k + 1) < n_emit)
                    any_eos = jnp.any(is_eos)
                    first_eos = jnp.argmax(is_eos)
                    n_emit = jnp.where(any_eos, first_eos + 1, n_emit)
                    done = done | any_eos
                # stale tail beyond n_emit is pad (overwritten next round
                # anyway, and the final mask re-pads)
                out = jax.lax.dynamic_update_slice(
                    out, emit[None].astype(out.dtype), (0, count))
                count = count + n_emit
                # rewind both caches to the accepted boundary
                t_pos = t_pos + n_acc + 1
                d_pos = d_pos + n_acc + 1
                t_bufs = _set_decode_pos(t_bufs, t_pos)
                d_bufs = _set_decode_pos(d_bufs, d_pos)
                cur = bonus[None]
                return (t_bufs, d_bufs, out, count, cur, done, t_pos, d_pos,
                        rng)

            carry = (t_bufs, d_bufs, out0, jnp.int32(1), cur, done0,
                     pos0, pos0, rng)
            carry = jax.lax.while_loop(cond, body, carry)
            out, count = carry[2], carry[3]
            # final mask: positions >= count -> pad; trim to max_new
            keep = jnp.arange(out.shape[1])[None, :] < count
            out = jnp.where(keep, out, pad_id)[:, :max_new_tokens]
            return jnp.concatenate(
                [prompt, out.astype(prompt.dtype)], axis=1)

        cache = target.__dict__.setdefault("_spec_fns", {})
        sig = (id(draft), b, s0, int(max_new_tokens), k, eos_id, pad_id,
               sampled, float(temperature))
        fn = cache.get(sig)
        if fn is None:
            while len(cache) >= 8:
                # bound the cache: each program closes over a draft Module
                # (params included) — unbounded growth would pin dropped
                # drafts resident forever. Oldest-first single eviction.
                _evict_oldest(cache, "generation.speculative")
            from bigdl_tpu.telemetry.profiling import tracked_jit
            fn = tracked_jit(run, site="generation.speculative")
            # graftlint: ignore[JG013] -- per-(draft, signature) compile family by design; bounded by the oldest-first eviction at 8 above
            cache[sig] = fn
        rng_in = key if sampled else jax.random.PRNGKey(0)
        result = fn(t_params, t_bufs, d_params, d_bufs, prompt, rng_in)
    finally:
        for model, (mhas, pes, heads) in ((target, t_mods), (draft, d_mods)):
            for m in heads:
                m._decode_all = False
            for m in mhas + pes + heads:
                m.disable_decode()
        target.set_training(t_training)
        draft.set_training(d_training)
        for lk in reversed(_locks):
            lk.release()
    return result
