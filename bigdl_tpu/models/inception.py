"""Inception v1 / GoogLeNet (reference ``models/inception/Inception_v1.scala``)
and Inception v2 / BN-Inception (``models/inception/Inception_v2.scala``),
built as Concat-of-Sequential branches like the reference; channels-last.
"""

from __future__ import annotations

from bigdl_tpu import nn


def _conv(n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0, name=""):
    return (nn.Sequential()
            .add(nn.SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                                       init_method="xavier").set_name(name + "conv"))
            .add(nn.ReLU(True)))


def inception_module(n_in, c1x1, c3x3r, c3x3, c5x5r, c5x5, pool_proj,
                     name="inception"):
    """One inception block: 4 parallel branches concatenated on channels
    (reference ``Inception_v1.scala`` inception() builder — Concat on dim 1
    of NCHW, i.e. the channel axis)."""
    concat = nn.Concat(1).set_name(name)
    concat.add(_conv(n_in, c1x1, 1, 1, name=f"{name}/1x1/"))
    concat.add(nn.Sequential()
               .add(_conv(n_in, c3x3r, 1, 1, name=f"{name}/3x3r/"))
               .add(_conv(c3x3r, c3x3, 3, 3, 1, 1, 1, 1, name=f"{name}/3x3/")))
    concat.add(nn.Sequential()
               .add(_conv(n_in, c5x5r, 1, 1, name=f"{name}/5x5r/"))
               .add(_conv(c5x5r, c5x5, 5, 5, 1, 1, 2, 2, name=f"{name}/5x5/")))
    concat.add(nn.Sequential()
               .add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1))
               .add(_conv(n_in, pool_proj, 1, 1, name=f"{name}/pool_proj/")))
    return concat


def build(class_num: int = 1000) -> nn.Sequential:
    """Inception v1 main tower (no aux classifiers, like the reference's
    ``Inception_v1_NoAuxClassifier``); input (N, 224, 224, 3)."""
    model = (nn.Sequential()
             .add(nn.stem_conv7(3, 64, init_method="xavier",
                                name="conv1/7x7_s2"))
             .add(nn.ReLU(True))
             .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
             .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
             .add(_conv(64, 64, 1, 1, name="conv2/3x3_reduce/"))
             .add(_conv(64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3/"))
             .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
             .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
             .add(inception_module(192, 64, 96, 128, 16, 32, 32, "inception_3a"))
             .add(inception_module(256, 128, 128, 192, 32, 96, 64, "inception_3b"))
             .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
             .add(inception_module(480, 192, 96, 208, 16, 48, 64, "inception_4a"))
             .add(inception_module(512, 160, 112, 224, 24, 64, 64, "inception_4b"))
             .add(inception_module(512, 128, 128, 256, 24, 64, 64, "inception_4c"))
             .add(inception_module(512, 112, 144, 288, 32, 64, 64, "inception_4d"))
             .add(inception_module(528, 256, 160, 320, 32, 128, 128, "inception_4e"))
             .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
             .add(inception_module(832, 256, 160, 320, 32, 128, 128, "inception_5a"))
             .add(inception_module(832, 384, 192, 384, 48, 128, 128, "inception_5b"))
             .add(nn.SpatialAveragePooling(7, 7, 1, 1))
             .add(nn.Dropout(0.4))
             .add(nn.Reshape((1024,), batch_mode=True))
             .add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
             .add(nn.LogSoftMax()))
    return model


def _conv_bn(n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0, name=""):
    """conv -> BN(eps=1e-3) -> ReLU triple used throughout Inception v2
    (reference ``Inception_v2.scala`` Inception_Layer_v2). 1x1 pairs
    collapse into the Pallas-fused module under ``BIGDL_TPU_FUSED_1X1=1``
    (same opt-in as the ResNet builder; see PERF.md)."""
    from bigdl_tpu.nn.fused import (FusedConv1x1BN, FusedConv3x3BN,
                                    use_fused_1x1, use_fused_3x3)
    if (kw, kh, pw, ph) == (1, 1, 0, 0) and sw == sh and use_fused_1x1():
        # with_bias: the unfused pair's conv carries a bias (reference
        # default) — keep the parameter schema identical across the flag
        return (nn.Sequential()
                .add(FusedConv1x1BN(n_in, n_out, sw, eps=1e-3,
                                    init_method="xavier",
                                    with_bias=True).set_name(name))
                .add(nn.ReLU(True)))
    if ((kw, kh, pw, ph, sw, sh) == (3, 3, 1, 1, 1, 1)
            and use_fused_3x3()):
        return (nn.Sequential()
                .add(FusedConv3x3BN(n_in, n_out, eps=1e-3,
                                    init_method="xavier",
                                    with_bias=True).set_name(name))
                .add(nn.ReLU(True)))
    if (kw, kh, sw, sh, pw, ph) == (7, 7, 2, 2, 3, 3):
        # ImageNet stem: space-to-depth form (PERF.md round 3)
        return (nn.Sequential()
                .add(nn.stem_conv7(n_in, n_out, init_method="xavier",
                                   name=name))
                .add(nn.SpatialBatchNormalization(n_out, 1e-3)
                     .set_name(name + "/bn"))
                .add(nn.ReLU(True)))
    return (nn.Sequential()
            .add(nn.SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                                       init_method="xavier").set_name(name))
            .add(nn.SpatialBatchNormalization(n_out, 1e-3)
                 .set_name(name + "/bn"))
            .add(nn.ReLU(True)))


def inception_module_v2(n_in, c1x1, c3x3r, c3x3, cd3x3r, cd3x3, pool_mode,
                        pool_proj, name="inception"):
    """One BN-Inception block: 1x1 / 3x3 / double-3x3 / pool branches.

    ``c1x1 == 0`` drops the 1x1 branch and switches the 3x3 / double-3x3
    tails to stride 2 (the grid-reduction blocks 3c/4e); ``pool_mode`` is
    "avg" or "max", with ``pool_proj == 0`` meaning a stride-2 max pool and
    no projection (reference ``Inception_v2.scala`` Inception_Layer_v2)."""
    reduction = c1x1 == 0
    stride = 2 if reduction else 1
    concat = nn.Concat(1).set_name(name)
    if not reduction:
        concat.add(_conv_bn(n_in, c1x1, 1, 1, name=f"{name}/1x1"))
    concat.add(nn.Sequential()
               .add(_conv_bn(n_in, c3x3r, 1, 1, name=f"{name}/3x3_reduce"))
               .add(_conv_bn(c3x3r, c3x3, 3, 3, stride, stride, 1, 1,
                             name=f"{name}/3x3")))
    concat.add(nn.Sequential()
               .add(_conv_bn(n_in, cd3x3r, 1, 1,
                             name=f"{name}/double3x3_reduce"))
               .add(_conv_bn(cd3x3r, cd3x3, 3, 3, 1, 1, 1, 1,
                             name=f"{name}/double3x3a"))
               .add(_conv_bn(cd3x3, cd3x3, 3, 3, stride, stride, 1, 1,
                             name=f"{name}/double3x3b")))
    pool = nn.Sequential()
    if pool_mode == "avg":
        pool.add(nn.SpatialAveragePooling(3, 3, 1, 1, 1, 1).ceil())
    elif pool_proj != 0:
        pool.add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil())
    else:
        pool.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    if pool_proj != 0:
        pool.add(_conv_bn(n_in, pool_proj, 1, 1, name=f"{name}/pool_proj"))
    concat.add(pool)
    return concat


def build_v2(class_num: int = 1000) -> nn.Sequential:
    """Inception v2 / BN-Inception main tower (no aux classifiers, like the
    reference's ``Inception_v2_NoAuxClassifier``); input (N, 224, 224, 3)."""
    model = (nn.Sequential()
             .add(_conv_bn(3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2"))
             .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
             .add(_conv_bn(64, 64, 1, 1, name="conv2/3x3_reduce"))
             .add(_conv_bn(64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3"))
             .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
             .add(inception_module_v2(192, 64, 64, 64, 64, 96, "avg", 32,
                                      "inception_3a"))
             .add(inception_module_v2(256, 64, 64, 96, 64, 96, "avg", 64,
                                      "inception_3b"))
             .add(inception_module_v2(320, 0, 128, 160, 64, 96, "max", 0,
                                      "inception_3c"))
             .add(inception_module_v2(576, 224, 64, 96, 96, 128, "avg", 128,
                                      "inception_4a"))
             .add(inception_module_v2(576, 192, 96, 128, 96, 128, "avg", 128,
                                      "inception_4b"))
             .add(inception_module_v2(576, 160, 128, 160, 128, 160, "avg", 96,
                                      "inception_4c"))
             .add(inception_module_v2(576, 96, 128, 192, 160, 192, "avg", 96,
                                      "inception_4d"))
             .add(inception_module_v2(576, 0, 128, 192, 192, 256, "max", 0,
                                      "inception_4e"))
             .add(inception_module_v2(1024, 352, 192, 320, 160, 224, "avg",
                                      128, "inception_5a"))
             .add(inception_module_v2(1024, 352, 192, 320, 192, 224, "max",
                                      128, "inception_5b"))
             .add(nn.SpatialAveragePooling(7, 7, 1, 1).ceil())
             .add(nn.Reshape((1024,), batch_mode=True))
             .add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
             .add(nn.LogSoftMax()))
    return model
