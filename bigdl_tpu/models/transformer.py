"""Causal transformer language model — the flagship of the NEW long-context
capability (no reference analogue; SURVEY §5.7: the reference's longest
sequence machinery is a scalar RNN time loop, and the task brief requires
ring-attention/Ulysses context parallelism as first-class capability).

Built from the same module zoo as every other model: LookupTable embedding,
sinusoidal positions, ``TransformerEncoder`` (flash-attention capable,
optionally sequence-sharded via ``seq_axis``), tied to a Linear LM head.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu import nn


def build_lm(vocab_size: int, embed_dim: int = 128, num_heads: int = 4,
             ffn_dim: int = 256, num_layers: int = 2,
             max_len: int = 1024, dropout: float = 0.0,
             seq_axis: Optional[str] = None,
             seq_mode: str = "ring",
             seq_layout: str = "contiguous",
             moe_experts: int = 0, moe_k: int = 2,
             fused_head: bool = False,
             tie_embeddings: bool = False,
             rope: bool = False, activation: str = "gelu",
             norm: str = "layer",
             num_kv_heads: Optional[int] = None,
             rope_theta: float = 10000.0,
             pos: str = "sinusoidal",
             bias: bool = True,
             head_bias: Optional[bool] = None,
             norm_eps: Optional[float] = None,
             window: Optional[int] = None,
             rope_scaling: Optional[dict] = None,
             qkv_bias: bool = False) -> nn.Sequential:
    """Causal LM: 1-based token ids (N, T) -> log-probs (N, T, vocab).

    ``seq_axis="seq"`` shards every attention layer over the mesh sequence
    axis (ring attention or Ulysses per ``seq_mode``) — long-context
    training is a constructor argument, not a different model.
    ``seq_layout="zigzag"`` selects the balanced causal ring layout; the
    training loop must then permute the embedded sequence (and targets)
    with ``parallel.context.zigzag_permutation`` before sharding — see
    ``apps/transformer.py --ringLayout zigzag``.

    ``fused_head=True`` swaps the ``TimeDistributed(Linear) -> LogSoftMax``
    tail for ``nn.LMHead``; train with ``nn.FusedLMHeadCriterion`` and the
    (B, S, vocab) logits are never materialised (``ops/lm_head_ce.py``).
    Eval/predict/generate still see log-probs (LMHead computes them in
    eval mode); the head weight keeps Linear's (V, E) layout.

    ``tie_embeddings=True`` (GPT-2-style) shares ONE (V, E) matrix between
    the embedding and the vocab projection (``nn.TiedLMHead`` — saves V*E
    params and its gradient combines both uses); implies the fused-CE
    training path, so train with ``nn.FusedLMHeadCriterion``.

    ``rope=True`` replaces the additive sinusoidal PositionalEncoding with
    rotary embeddings on q/k (relative positions; the modern standard) —
    the PE module is dropped entirely. Composes with ``seq_axis`` context
    parallelism (round 5): each shard rotates at its GLOBAL positions
    (contiguous or zigzag ring layout, Ulysses) — the long-context Llama
    training recipe.

    ``activation="swiglu"`` + ``norm="rms"`` + ``rope=True`` +
    ``tie_embeddings=True`` is the Llama-family block recipe — every
    piece composes with the fused-CE tail, KV-cached generation, and
    int8 quantization.

    Checkpoint-parity knobs (``interop/hf.py`` builds with these):
    ``pos="learned"`` uses a trained GPT-2-style ``wpe`` table instead of
    the sinusoidal encoding (ignored under ``rope``); ``bias=False``
    drops every affine bias (Llama convention); ``rope_theta`` sets the
    rotary frequency base (500000 for Llama-3-era models);
    ``head_bias`` overrides ``bias`` for the untied LM head."""
    embed = nn.LookupTable(vocab_size, embed_dim)
    m = nn.Sequential().add(embed)
    # plain attribute (not a parameter): rope models have no positional
    # table to infer context length from, so exporters read this
    m.lm_max_len = max_len
    if not rope:
        if pos == "learned":
            m.add(nn.LearnedPositionalEncoding(embed_dim, max_len, dropout))
        elif pos == "sinusoidal":
            m.add(nn.PositionalEncoding(embed_dim, max_len, dropout))
        else:
            raise ValueError(f"unknown pos {pos!r}: 'sinusoidal' or 'learned'")
    elif dropout:
        # keep the embedding-stream dropout the PE module would have applied
        m.add(nn.Dropout(dropout))
    m.add(nn.TransformerEncoder(num_layers, embed_dim, num_heads,
                                ffn_dim, dropout=dropout, causal=True,
                                activation=activation, norm=norm,
                                seq_axis=seq_axis, seq_mode=seq_mode,
                                seq_layout=seq_layout,
                                moe_experts=moe_experts,
                                moe_k=moe_k, rope=rope,
                                num_kv_heads=num_kv_heads,
                                rope_theta=rope_theta, bias=bias,
                                norm_eps=norm_eps, window=window,
                                rope_scaling=rope_scaling,
                                qkv_bias=qkv_bias))
    if tie_embeddings:
        return m.add(nn.TiedLMHead(embed))
    hb = bias if head_bias is None else head_bias
    if fused_head:
        return m.add(nn.LMHead(embed_dim, vocab_size, with_bias=hb))
    return (m.add(nn.TimeDistributed(nn.Linear(embed_dim, vocab_size,
                                               with_bias=hb)))
            .add(nn.LogSoftMax()))
