"""Vision Transformer — beyond-reference model family built ENTIRELY from
the existing zoo (patch embedding = strided ``SpatialConvolution``,
``TransformerEncoder`` without the causal mask, mean-pool head).

The reference's newest vision model is Inception-v2 (2016); ViT shows the
attention stack introduced for the LM doubles as a modern vision family
with zero new layer code. NHWC in (B, H, W, C) like every conv model here;
1-based labels out (LogSoftMax + ClassNLL), so the standard Optimizer /
Top1Accuracy tooling applies unchanged.

Shapes follow ViT-S/16-style conventions; ``build(1000)`` is ViT-S/16
(22M params). Mean pooling replaces the CLS token (simpler, equally
standard — no sequence-position bookkeeping), and positions are learned
(``CAdd`` over the token grid), matching the original ViT recipe.
"""

from __future__ import annotations

from bigdl_tpu import nn


def build(class_num: int, image_size: int = 224, patch_size: int = 16,
          embed_dim: int = 384, num_heads: int = 6, ffn_dim: int = 1536,
          num_layers: int = 12, dropout: float = 0.0) -> nn.Sequential:
    """ViT classifier: (B, H, W, C) NHWC images -> (B, class_num) log-probs.

    Defaults are ViT-S/16. The patch embedding is one strided conv (the
    standard trick: conv k=p, s=p == unfold+linear, and it lands on the
    MXU as a single big matmul).
    """
    if image_size % patch_size != 0:
        raise ValueError(f"image_size {image_size} must be a multiple of "
                         f"patch_size {patch_size}")
    n_patches = (image_size // patch_size) ** 2
    return (nn.Sequential()
            .add(nn.SpatialConvolution(3, embed_dim, patch_size, patch_size,
                                       patch_size, patch_size))
            .add(nn.Reshape((n_patches, embed_dim), batch_mode=True))
            # learned positions: one bias per (token, channel)
            .add(nn.CAdd((n_patches, embed_dim)))
            .add(nn.TransformerEncoder(num_layers, embed_dim, num_heads,
                                       ffn_dim, dropout=dropout,
                                       causal=False))
            .add(nn.Mean(dimension=2))          # token mean-pool (1-based dim)
            .add(nn.Linear(embed_dim, class_num))
            .add(nn.LogSoftMax()))
