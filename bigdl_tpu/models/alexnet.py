"""AlexNet (reference ``example/loadmodel/AlexNet.scala`` — the Caffe
BVLC-AlexNet geometry used by ModelValidator's import path: grouped convs,
cross-map LRN, 227x227 BGR input). Layer names follow the Caffe deploy
definition so ``load_caffe`` matches weights by name."""

from __future__ import annotations

from bigdl_tpu import nn


def build(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(3, 96, 11, 11, 4, 4).set_name("conv1"))
    m.add(nn.ReLU().set_name("relu1"))
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm1"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"))
    m.add(nn.SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, n_group=2)
          .set_name("conv2"))
    m.add(nn.ReLU().set_name("relu2"))
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm2"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"))
    m.add(nn.SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1).set_name("conv3"))
    m.add(nn.ReLU().set_name("relu3"))
    m.add(nn.SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, n_group=2)
          .set_name("conv4"))
    m.add(nn.ReLU().set_name("relu4"))
    m.add(nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, n_group=2)
          .set_name("conv5"))
    m.add(nn.ReLU().set_name("relu5"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"))
    # Caffe fc6 weights contract over a C,H,W flatten; our layout is NHWC,
    # so reorder to NCHW before flattening or imported weights are permuted
    m.add(nn.Transpose([(2, 4), (3, 4)]))
    m.add(nn.Reshape((256 * 6 * 6,), batch_mode=True))
    m.add(nn.Linear(256 * 6 * 6, 4096).set_name("fc6"))
    m.add(nn.ReLU().set_name("relu6"))
    if has_dropout:
        m.add(nn.Dropout(0.5).set_name("drop6"))
    m.add(nn.Linear(4096, 4096).set_name("fc7"))
    m.add(nn.ReLU().set_name("relu7"))
    if has_dropout:
        m.add(nn.Dropout(0.5).set_name("drop7"))
    m.add(nn.Linear(4096, class_num).set_name("fc8"))
    m.add(nn.LogSoftMax())
    return m
