"""ResNet (reference ``models/resnet/ResNet.scala:58``): CIFAR-10 basic-block
variants (depth = 6n+2) and ImageNet bottleneck variants (50/101/152).

Built from the container zoo exactly like the reference (Sequential +
ConcatTable(shortcut, main) + CAddTable + ReLU); kaiming/MSR init on convs
(reference ``MSRinit``), BN gamma=1 beta=0, channels-last layout. Shortcut
type B (1x1 conv projection on dimension change) is the default, as in the
reference's ImageNet config.
"""

from __future__ import annotations

from bigdl_tpu import nn

_IMAGENET_CFG = {
    18: ([2, 2, 2, 2], "basic"),
    34: ([3, 4, 6, 3], "basic"),
    50: ([3, 4, 6, 3], "bottleneck"),
    101: ([3, 4, 23, 3], "bottleneck"),
    152: ([3, 8, 36, 3], "bottleneck"),
}


def _conv(n_in, n_out, k, stride=1, pad=0):
    return nn.SpatialConvolution(n_in, n_out, k, k, stride, stride, pad, pad,
                                 with_bias=False, init_method="kaiming")




def _use_fused_1x1() -> bool:
    from bigdl_tpu.nn.fused import use_fused_1x1
    return use_fused_1x1()


def _add_conv_bn(seq, n_in, n_out, k, stride=1, pad=0):
    """conv(+BN) pair; 1x1 pairs collapse into the Pallas-fused module when
    ``BIGDL_TPU_FUSED_1X1=1``, stride-1 3x3 pairs when
    ``BIGDL_TPU_FUSED_3X3=1`` (opt-in pending the on-chip A/B — see PERF.md;
    note the fused modules change parameter-tree naming, so checkpoints are
    not interchangeable across the flags)."""
    if k == 1 and pad == 0 and _use_fused_1x1():
        from bigdl_tpu.nn.fused import FusedConv1x1BN
        return seq.add(FusedConv1x1BN(n_in, n_out, stride))
    if k == 3 and pad == 1 and stride == 1:
        from bigdl_tpu.nn.fused import FusedConv3x3BN, use_fused_3x3
        if use_fused_3x3():
            return seq.add(FusedConv3x3BN(n_in, n_out))
    return (seq.add(_conv(n_in, n_out, k, stride, pad))
            .add(nn.SpatialBatchNormalization(n_out)))


def _shortcut(n_in, n_out, stride, shortcut_type="B"):
    if n_in != n_out or stride != 1:
        if shortcut_type == "A":
            # identity + zero-pad channels (dim 3 = C in HWC), avg-pool spatial
            return (nn.Sequential()
                    .add(nn.SpatialAveragePooling(1, 1, stride, stride))
                    .add(nn.Padding(3, n_out - n_in, 3)))
        return _add_conv_bn(nn.Sequential(), n_in, n_out, 1, stride)
    return nn.Identity()


def _basic_block(n_in, n_out, stride, shortcut_type="B"):
    main = _add_conv_bn(nn.Sequential(), n_in, n_out, 3, stride, 1)
    main.add(nn.ReLU())
    _add_conv_bn(main, n_out, n_out, 3, 1, 1)
    return (nn.Sequential()
            .add(nn.ConcatTable().add(main).add(_shortcut(n_in, n_out, stride,
                                                          shortcut_type)))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


def _bottleneck(n_in, n_mid, stride, shortcut_type="B"):
    n_out = n_mid * 4
    main = _add_conv_bn(nn.Sequential(), n_in, n_mid, 1)
    main.add(nn.ReLU())
    _add_conv_bn(main, n_mid, n_mid, 3, stride, 1)
    main.add(nn.ReLU())
    _add_conv_bn(main, n_mid, n_out, 1)
    return (nn.Sequential()
            .add(nn.ConcatTable().add(main).add(_shortcut(n_in, n_out, stride,
                                                          shortcut_type)))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


def build(class_num: int = 1000, depth: int = 50,
          shortcut_type: str = "B") -> nn.Sequential:
    """ImageNet ResNet; input (N, 224, 224, 3)."""
    assert depth in _IMAGENET_CFG, f"unsupported depth {depth}"
    layers, block_kind = _IMAGENET_CFG[depth]
    model = (nn.Sequential()
             .add(nn.stem_conv7(3, 64, with_bias=False,
                                init_method="kaiming"))
             .add(nn.SpatialBatchNormalization(64))
             .add(nn.ReLU())
             .add(nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)))
    widths = [64, 128, 256, 512]
    n_in = 64
    for stage, (w, reps) in enumerate(zip(widths, layers)):
        for i in range(reps):
            stride = 2 if (stage > 0 and i == 0) else 1
            if block_kind == "bottleneck":
                model.add(_bottleneck(n_in, w, stride, shortcut_type))
                n_in = w * 4
            else:
                model.add(_basic_block(n_in, w, stride, shortcut_type))
                n_in = w
    model.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    model.add(nn.Reshape((n_in,), batch_mode=True))
    model.add(nn.Linear(n_in, class_num))
    model.add(nn.LogSoftMax())
    return model


def build_cifar(class_num: int = 10, depth: int = 20,
                shortcut_type: str = "A") -> nn.Sequential:
    """CIFAR ResNet (depth = 6n+2; reference CIFAR config uses shortcut A).
    Input (N, 32, 32, 3)."""
    assert (depth - 2) % 6 == 0, "CIFAR ResNet depth must be 6n+2"
    n = (depth - 2) // 6
    model = _add_conv_bn(nn.Sequential(), 3, 16, 3, 1, 1)
    model.add(nn.ReLU())
    n_in = 16
    for stage, w in enumerate([16, 32, 64]):
        for i in range(n):
            stride = 2 if (stage > 0 and i == 0) else 1
            model.add(_basic_block(n_in, w, stride, shortcut_type))
            n_in = w
    model.add(nn.SpatialAveragePooling(8, 8, 1, 1))
    model.add(nn.Reshape((64,), batch_mode=True))
    model.add(nn.Linear(64, class_num))
    model.add(nn.LogSoftMax())
    return model
