"""Continuous batching LM serving engine (round 5, VERDICT #6).

The bucketed ``models/lm_server.py`` groups requests by exact prompt
length and decodes whole batches in lockstep: one long generation blocks
its bucket, and mixed-length traffic fragments into tiny batches. This
engine replaces lockstep with SLOTS (the vLLM-style iteration-level
scheduler, built TPU-first on static shapes):

- the model sits permanently in *continuous* decode mode: (slots, L) KV
  caches with a PER-ROW ``decode_pos`` (``nn.attention
  ._attend_decode_continuous``) — every slot lives at its own position in
  its own sequence, and ONE jitted step program advances them all;
- a new request prefills OUT-OF-BAND as a b=1 forward in FIXED-SIZE
  CHUNKS (``prefill_mode="chunked"``, the default): ⌈(L-1)/C⌉ chunks of
  ``prefill_chunk`` tokens through the warm-cache chunked attention
  branch plus one single-token step for the last prompt token — exactly
  TWO compiled programs regardless of prompt length, where the old
  per-length prefill compiled one program per distinct length (the
  compile storm ROADMAP #1 tracked; graftlint JG013's frozen fire
  fixture is that pre-fix code). ``prefill_mode="bucketed"`` is the
  fallback for attention paths that can't take the masked chunk: the
  prompt pads to its power-of-two ``pow2_bucket`` length and one
  wrapper specializes per bucket (O(log max_len) programs). Either way
  a jitted insert then scatters the (1, L) cache into a free slot row
  and sets that row's ``decode_pos`` — admission never recompiles or
  disturbs running slots;
- steps dispatch in blocks of ``decode_block`` tokens (a ``lax.scan`` —
  amortizes the per-dispatch host cost); finished rows (eos/budget) free
  their slot at the next block boundary and the queue admits strictly
  FIFO, so no request can be starved (the ADVICE round-4 finding against
  the bucketed ``_gather``).

Dead slots keep computing garbage (their rows are never read) — the TPU
trade: wasted lanes are cheaper than a recompile or a dynamic shape.

Round 9 layers two first-class serving modes onto this engine:

- CROSS-REQUEST KV PREFIX CACHE (``models/prefix_cache.py``, on by
  default in chunked mode; ``BIGDL_PREFIX_CACHE=0`` disables): the
  chunked prefill snapshots its per-request state partition at every
  FULL chunk boundary into a per-model trie keyed by a rolling hash of
  the chunk-aligned token prefix. An admission sharing a cached prefix
  copies the b=1 partition and chunk-prefills only the uncached tail —
  TTFT collapses on hits (``bigdl_serving_ttft_hit_seconds`` vs
  ``_miss_``) while greedy outputs stay bit-identical to a cold prefill
  (a chunk-boundary resume reproduces the cold run's exact chunk
  partition, hence its exact floating-point reductions). Size-bounded
  with counted LRU eviction.
- SPECULATIVE DECODE (``draft=...``, ``BIGDL_SPEC_LEN``): the draft
  model lives in its own (slots, L) continuous decode state, prefilled
  and slot-inserted alongside the target on every admission. Each round
  the draft proposes ``spec_len`` tokens per row (a ``lax.scan`` of
  single-token steps) and the target verifies carried-token + proposals
  in ONE multi-token continuous forward — the chunked verification path
  (``nn.attention._attend_decode_continuous``'s chunk branch: per-row
  write positions, per-row masks). Per-row first-mismatch acceptance
  emits 1..spec_len+1 tokens per dispatch and rolls BOTH caches back to
  each row's accepted boundary (a per-row ``decode_pos`` shift; the
  stale writes sit behind the position mask until overwritten).
  Greedy-only — acceptance is exact argmax match, which is what keeps
  outputs bit-identical to the non-speculative path. ``decode_block``
  is ignored in this mode: one round is one dispatch.

Restrictions: rope models only (additive positional-encoding modules
track a shared scalar position), no beam search. Sampling is the server's
(greedy/temperature/top_k/top_p via ``generation.sample_token``).

``ContinuousLMServer`` exposes the same ``submit()/close()`` surface as
``LMServer``, so ``make_http_server`` and ``apps.transformer serve
--continuous`` reuse it unchanged.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import functional_apply
from bigdl_tpu.models.generation import (_decode_modules,
                                         _shift_decode_pos,
                                         build_bucketed_prefill_fn,
                                         build_chunked_prefill_fns,
                                         deserialize_prefill_state,
                                         partition_prefill_state,
                                         sample_token,
                                         serialize_prefill_state)
from bigdl_tpu.models.lm_server import drain_queue, fail_requests
from bigdl_tpu.models.prefix_cache import (DEFAULT_PREFIX_CACHE_MB,
                                           prefix_cache_for)
from bigdl_tpu.telemetry import get_registry, instruments, span, tracing
from bigdl_tpu.telemetry.profiling import (sample_device_memory,
                                           tracked_jit)
from bigdl_tpu.utils.util import pow2_bucket

# Smallest prefill length bucket (prefill_mode="bucketed"): prompts
# shorter than this share one program instead of minting one per small
# power of two. The top bucket saturates at max_len.
_PREFILL_BUCKET_LO = 16

# One id per submitted request, process-wide: the Chrome-trace async
# lifecycle key (serving.request) and the rid arg on every phase span.
# itertools.count is GIL-atomic — submit() runs on client threads.
_REQUEST_IDS = itertools.count(1)


@dataclass
class HandoffCursor:
    """The migratable request cursor: everything a PEER replica needs to
    finish an interrupted request with bit-identical greedy output —
    re-prefilling ``ids + emitted`` reproduces the donor's exact chunked
    reductions, so the continuation is the continuation the unkilled run
    would have produced. Sampled (non-greedy) resumes are best-effort:
    the admission key advances per admission, so a migrated draw comes
    from a fresh stream."""
    ids: List[int]                      # the original prompt
    emitted: List[int]                  # tokens produced before the cut
    max_new: int                        # the ORIGINAL token budget


class ReplicaUnavailable(RuntimeError):
    """``submit()`` failed because this replica cannot serve. ``cursor``
    (when set) carries the accepted request's resume state — the caller
    (the router) re-dispatches it to a peer; ``cursor=None`` means the
    request never entered this replica and can simply be retried."""

    def __init__(self, message: str, cursor: Optional[HandoffCursor] = None):
        super().__init__(message)
        self.cursor = cursor


class ServerDraining(ReplicaUnavailable):
    """Planned unavailability (SIGTERM/drain): the replica is finishing
    or handing off its in-flight work — retry elsewhere, this process is
    shutting down cleanly."""


class ServerDead(ReplicaUnavailable):
    """Unplanned unavailability (decode/worker failure): the donated
    cache state is gone and the server will never serve again — retry
    elsewhere against a healthy replica; this one needs a restart."""


@dataclass
class _Request:
    ids: List[int]
    max_new: int
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[List[int]] = None
    error: Optional[str] = None
    t_submit: float = 0.0               # perf_counter at submit (TTFT/SLO)
    rid: int = 0                        # trace-lifecycle id (serving.request)
    emitted0: List[int] = field(default_factory=list)  # resume-cursor prefix
    state_blob: Optional[bytes] = None  # shipped prefill partition (disagg)
    handoff: Optional[HandoffCursor] = None
    fail_kind: Optional[str] = None     # "draining" | "dead" | None


class _Slot:
    __slots__ = ("req", "emitted", "new_count")

    def __init__(self, req):
        self.req = req
        self.emitted: List[int] = []
        self.new_count = 0


def _build_insert_fn(registry):
    """Jitted scatter of a prefilled b=1 cache into slot row ``slot``
    (slot/plen are traced scalars, so ONE compile per buffer-tree
    signature). Model-agnostic tree surgery — the same wrapper serves
    the target insert and, in speculative mode, the draft insert as a
    second signature."""
    def insert_prog(big, small, slot, plen):
        flat_b, treedef = jax.tree_util.tree_flatten_with_path(big)
        flat_s = jax.tree_util.tree_flatten_with_path(small)[0]
        out = []
        for (kp, bg), (_, sm) in zip(flat_b, flat_s):
            name = str(kp[-1])
            if "k_cache" in name or "v_cache" in name:
                # the chunked-prefill template cache is padded to a
                # whole number of chunks; only the first max_len entries
                # are live (anything past the prompt is masked pad
                # garbage) — slice before the scatter (no-op when the
                # template is not longer than the slot row; a spec-mode
                # slot row carries spec_len+1 slack the template lacks,
                # and the tail past the copy stays masked the same way)
                out.append(jax.lax.dynamic_update_slice(
                    bg, sm.astype(bg.dtype)[:, :bg.shape[1]],
                    (slot,) + (0,) * (bg.ndim - 1)))
            elif "decode_pos" in name:
                out.append(jax.lax.dynamic_update_slice(
                    bg, plen[None].astype(bg.dtype), (slot,)))
            else:
                out.append(bg)
        return jax.tree_util.tree_unflatten(treedef, out)

    return tracked_jit(insert_prog, site="serving.insert",
                       registry=registry,
                       donate_argnums=(0,))


class _PrefillPipeline:
    """The out-of-band b=1 admission-prefill machine for ONE model.

    PR 15 built this inline for the target; speculative serving runs
    the SAME admission prefill against the draft (its (slots, L)
    continuous cache needs the prompt too), so the machinery — the
    decode-mode templates, the O(1) program set, the trace-time flag
    context, and now the prefix trie — lives here once and the server
    instantiates it per model."""

    def __init__(self, model, *, mode: str, chunk: int, slots: int,
                 max_len: int, big_len: int, registry, site: str,
                 prefix_bytes: int = 0):
        mhas, pes, heads = _decode_modules(model)
        if pes:
            raise ValueError(
                "continuous batching requires a rope model (additive "
                "positional encodings track one shared position; "
                "build_lm(rope=True))")
        if not mhas:
            raise ValueError("model has no attention layers to cache")
        self.model = model
        self.mhas, self.heads = mhas, heads
        self.mode, self.chunk, self.max_len = mode, chunk, max_len
        # run() flips module-level trace flags and threads the template
        # state — serialize it: the worker's admission prefill and a
        # router thread's prefill_handoff() may hit the same pipeline
        self._run_lock = threading.Lock()
        model.evaluate_mode()
        # single-request decode template (the prefill signature) FIRST,
        # then the persistent continuous state. The chunked template
        # cache is padded up to a whole number of chunks so the final
        # (right-padded) chunk's k/v write never clips against the cache
        # end — the insert slices the copy back down to the slot row.
        if mode == "chunked":
            self.cache_len = -(-max_len // chunk) * chunk
        else:
            self.cache_len = max_len
        for m in mhas:
            m.enable_decode(1, self.cache_len)
        for m in heads:
            m.enable_decode()
        _, small0 = model.functional_state()
        # COPY the template leaves: non-cache buffers (e.g. a quantized
        # model's int8 weights live in the buffer tree) are otherwise the
        # very arrays the donating step/insert programs consume — the
        # first admission would delete the prefill template's references
        self.small_bufs0 = jax.tree_util.tree_map(jnp.copy, small0)
        for m in mhas:
            m.enable_decode(slots, big_len, continuous=True)
        self.params, self.buffers = model.functional_state()
        # the O(1) prefill program set, built BEFORE the worker thread
        # starts (wrappers are cheap; XLA programs compile lazily inside
        # tracked_jit at first dispatch, counted per signature in
        # bigdl_compiles_total{site})
        if mode == "chunked":
            (self.chunk_fn, self.last_fn, self.state0,
             self.statics, self.merge) = build_chunked_prefill_fns(
                model, self.small_bufs0, site=site, registry=registry)
            self.bucket_fn = None
            # the cross-request prefix trie rides on the MODEL (warm
            # prefixes survive a server restart over the same weights;
            # __getstate__ pops it). Chunked mode only — bucketed
            # prefill has no chunk-aligned snapshots to key on.
            self.prefix = (prefix_cache_for(
                model, chunk=chunk, cache_len=self.cache_len,
                max_bytes=prefix_bytes) if prefix_bytes > 0 else None)
        else:
            self.chunk_fn = self.last_fn = None
            self.bucket_fn = build_bucketed_prefill_fn(
                model, site=site, registry=registry)
            self.prefix = None

    @property
    def fns(self):
        """The O(1) prefill program set — chunked mode holds the chunk +
        last-token pair, bucketed mode one wrapper that specializes per
        power-of-two bucket. Collapsed from the pre-PR-15 per-prompt-
        length LRU (one program per distinct length, the compile storm
        graftlint JG013's fire fixture preserves)."""
        fns = {"chunk": self.chunk_fn, "last": self.last_fn,
               "bucket": self.bucket_fn}
        return {k: v for k, v in fns.items() if v is not None}

    def single_mode(self, prefilled: bool, all_logits: bool = False):
        """Context: flip the attention modules to single-request decode
        semantics for tracing/running the b=1 prefill programs.

        ``prefilled`` is the trace-time cache temperature: True traces
        the warm-cache masked branch (chunked prefill — correct on a
        cold cache too, the position mask excludes unwritten slots),
        False the cold causal fast path (bucketed prefill, which always
        starts from scratch). ``all_logits`` flips the LM heads to emit
        every position (the bucketed program reads the true last token
        at a traced index inside the padded bucket)."""
        pipe = self

        class _Ctx:
            def __enter__(self):
                for m in pipe.mhas:
                    m._continuous = False
                    m._decode_prefilled = prefilled
                if all_logits:
                    for h in pipe.heads:
                        h._decode_all = True
                return self

            def __exit__(self, *a):
                for m in pipe.mhas:
                    m._continuous = True
                    m._decode_prefilled = True
                if all_logits:
                    for h in pipe.heads:
                        h._decode_all = False

        return _Ctx()

    def _prefill_chunked(self, ids: List[int]):
        """Chunked b=1 prompt prefill: ⌈(L-1)/C⌉ fixed-width chunks that
        write k/v at the true cache positions (final chunk right-padded,
        pads masked and re-covered via the in-program ``decode_pos``
        rewind), then ONE single-token step for the last prompt token
        whose (1, V) log-probs feed the admission sample. Two compiled
        programs total, any L — and with the prefix trie, only the
        UNCACHED tail's chunks are dispatched on a hit."""
        c = self.chunk
        n = len(ids) - 1        # last token runs as the lp-producing step
        hit = 0
        state = None
        if self.prefix is not None:
            # deepest cached chunk-aligned prefix of the chunked portion
            # (already an owned copy, safe to donate into the chunk loop)
            hit, state = self.prefix.match(ids[:n])
        if state is None:
            # both prefill programs donate the per-request STATE
            # partition (caches + positions — in-place updates across
            # the chunk loop); hand them an OWNED copy so the template
            # survives this admission. Shared buffers (a quantized
            # model's int8 weights) ride along non-donated: the
            # per-admission copy scales with the b=1 cache, never with
            # model size.
            state = [jnp.copy(x) for x in self.state0]
        statics = self.statics
        for start in range(hit, n, c):
            valid = min(c, n - start)
            chunk = np.ones((1, c), np.float32)   # pad id 1: any valid id
            chunk[0, :valid] = ids[start:start + valid]
            state = self.chunk_fn(self.params, state, statics,
                                  jnp.asarray(chunk),
                                  jnp.int32(start + valid))
            if self.prefix is not None and valid == c:
                # FULL-chunk boundary: the live state IS the snapshot —
                # the trie copies it (known prefixes skip even the copy)
                # before the next dispatch donates it away. Ragged final
                # chunks are never cached: a mid-chunk resume would
                # regroup the tail's reductions and break bit-exactness.
                self.prefix.put(ids[:start + valid], state)
        last = np.asarray([[ids[-1]]], np.float32)
        lp, state = self.last_fn(self.params, state, statics,
                                 jnp.asarray(last))
        # the insert consumes the FULL small tree (structure must match
        # the big tree leaf-for-leaf); merge is host-side, copy-free
        return lp, self.merge(state, statics), hit

    def _prefill_bucketed(self, ids: List[int]):
        """Length-bucketed b=1 prompt prefill (fallback mode): the
        prompt right-pads to its power-of-two bucket and runs the
        standard cold causal prefill — one program per BUCKET
        (O(log max_len) total), with the true last token's log-probs
        read at a traced index."""
        plen = len(ids)
        cap = self.cache_len
        bsz = pow2_bucket(plen, min(_PREFILL_BUCKET_LO, cap), cap)
        prompt = np.ones((1, bsz), np.float32)
        prompt[0, :plen] = ids
        lp, bufs = self.bucket_fn(self.params, self.small_bufs0,
                                  jnp.asarray(prompt), jnp.int32(plen - 1))
        return lp, bufs, 0

    def run(self, ids: List[int]):
        """Mode dispatch + compile accounting: returns ``(lp, small
        buffer tree, prefix-hit depth, programs built)`` — any program
        the flight recorder built during this prefill counts as serving
        recompile churn (per NEW SIGNATURE — a bucketed wrapper minting
        its second bucket counts exactly like a fresh program build)."""
        with self._run_lock:
            fns = self.fns
            before = sum(fn.compiles for fn in fns.values())
            if self.mode == "bucketed":
                with self.single_mode(prefilled=False, all_logits=True):
                    lp, small, hit = self._prefill_bucketed(ids)
            else:
                with self.single_mode(prefilled=True):
                    lp, small, hit = self._prefill_chunked(ids)
            built = sum(fn.compiles for fn in fns.values()) - before
            return lp, small, hit, built

    def disable(self):
        for m in self.mhas + self.heads:
            m.disable_decode()


class ContinuousLMServer:
    """Slot-scheduled continuous-batching server over one rope LM."""

    def __init__(self, model, *, slots: int = 8, max_len: int = 256,
                 decode_block: int = 8, max_new_tokens: int = 64,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 0.0, greedy: bool = False,
                 eos_id: Optional[int] = None, seed: int = 0,
                 registry=None, prefill_mode: Optional[str] = None,
                 prefill_chunk: Optional[int] = None,
                 draft=None, spec_len: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_mb: Optional[float] = None,
                 chaos=None):
        if slots < 1:
            raise ValueError("slots must be >= 1")
        # prompt prefill strategy (both O(1)-compile; ROADMAP #1):
        # "chunked" (default) = fixed-size chunks through the warm-cache
        # chunked attention branch, two programs total; "bucketed" =
        # pad the prompt to its power-of-two bucket, one program per
        # bucket — the fallback for attention paths that can't take the
        # masked multi-token chunk. Env levers mirror the args so a
        # deployment can flip modes without code changes.
        mode = (prefill_mode if prefill_mode is not None
                else os.environ.get("BIGDL_PREFILL_MODE", "chunked"))
        if mode not in ("chunked", "bucketed"):
            raise ValueError(f"prefill_mode must be 'chunked' or "
                             f"'bucketed', got {mode!r}")
        chunk = int(prefill_chunk if prefill_chunk is not None
                    else os.environ.get("BIGDL_PREFILL_CHUNK", "128"))
        if chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        # a chunk wider than the cache buys nothing and multiplies the
        # template-cache memory and per-prompt prefill work (prompts
        # never exceed max_len - max_new); clamp rather than reject so
        # the 128 default composes with small test/serving caches
        chunk = min(chunk, max_len)
        self.prefill_mode = mode
        self.prefill_chunk = chunk
        # speculative decode config (mirroring the prefill levers:
        # constructor args first, BIGDL_SPEC_* env as deployment default)
        self.draft = draft
        if draft is not None:
            if draft is model:
                raise ValueError(
                    "draft must be a separate module instance (one module "
                    "cannot hold two decode states at once)")
            if not greedy:
                raise ValueError(
                    "speculative serving is greedy-only: acceptance is "
                    "exact argmax match against the target, which is what "
                    "keeps outputs bit-identical to non-speculative decode")
            k = int(spec_len if spec_len is not None
                    else os.environ.get("BIGDL_SPEC_LEN", "4"))
            if k < 1:
                raise ValueError("spec_len must be >= 1")
            self.spec_len = k
        else:
            self.spec_len = 0
        # prefix-cache config: on by default in chunked mode (the cache
        # keys on chunk-aligned snapshots; bucketed prefill has none)
        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "BIGDL_PREFIX_CACHE", "1").lower() not in (
                    "0", "off", "false", "no")
        mb = float(prefix_cache_mb if prefix_cache_mb is not None
                   else os.environ.get("BIGDL_PREFIX_CACHE_MB",
                                       str(DEFAULT_PREFIX_CACHE_MB)))
        prefix_bytes = (int(mb * (1 << 20))
                        if (prefix_cache and mode == "chunked") else 0)
        self.prefix_cache_enabled = prefix_bytes > 0
        # telemetry (docs/OBSERVABILITY.md): TTFT / per-token latency /
        # queue depth / slot occupancy — the serving SLO surface, exposed
        # by make_http_server as GET /metrics
        self.registry = registry if registry is not None else get_registry()
        self._tm = instruments(self.registry)
        self._tm.serving_slots_total.set(slots)
        self.model = model
        self.slots = slots
        self.max_len = max_len
        self.decode_block = max(1, int(decode_block))
        self.max_new_tokens = max_new_tokens
        self.sampling = dict(temperature=temperature, top_k=top_k,
                             top_p=top_p, greedy=greedy)
        self.eos_id = eos_id
        self._seed = seed
        # Disjoint key streams, collision-free by construction: the old
        # ad-hoc arithmetic (seed + n_admitted*7919 + 1 for admissions,
        # seed + steps*31 + 17 for decode blocks) lands both families on
        # the SAME PRNGKey for some (n, steps) pair — e.g. admission 10
        # and step 2554 — correlating an admitted token draw with a whole
        # decode block (found by graftlint JG003).
        self._admit_key = jax.random.fold_in(jax.random.PRNGKey(seed), 0)
        self._step_key = jax.random.fold_in(jax.random.PRNGKey(seed), 1)
        self._steps = 0
        self._n_served = 0
        self._n_admitted = 0

        # continuous caches carry spec_len+1 rows of length slack in
        # speculative mode: a request finishing at max_len still runs a
        # final verification chunk whose writes land up to spec_len
        # positions past its last committed token (masked, then rolled
        # back — but the cache must physically hold them)
        big_len = max_len + (self.spec_len + 1 if draft is not None else 0)
        self._pipeline = _PrefillPipeline(
            model, mode=mode, chunk=chunk, slots=slots, max_len=max_len,
            big_len=big_len, registry=self.registry,
            site="serving.prefill", prefix_bytes=prefix_bytes)
        self._mhas, self._heads = self._pipeline.mhas, self._pipeline.heads
        self.params = self._pipeline.params
        self.buffers = self._pipeline.buffers
        if draft is not None:
            self._d_pipeline = _PrefillPipeline(
                draft, mode=mode, chunk=chunk, slots=slots,
                max_len=max_len, big_len=big_len, registry=self.registry,
                site="serving.draft_prefill", prefix_bytes=prefix_bytes)
            self.d_params = self._d_pipeline.params
            self.d_buffers = self._d_pipeline.buffers
        else:
            self._d_pipeline = None
            self.d_params = self.d_buffers = None
        self._step_fn = None
        self._insert_fn = None
        self._spec_fn = None
        self._prefix_evictions_seen = 0

        # serving-plane chaos injectors (resilience/chaos.py): anything
        # with an on_decode_block(server) hook is polled at each block
        # boundary INSIDE the decode try — a raising injector (the
        # kill-replica drill) exercises the real die path mid-stream
        self._chaos = [inj for inj in (chaos or [])
                       if hasattr(inj, "on_decode_block")]
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._dead: Optional[str] = None     # set once; never cleared
        self._draining: Optional[str] = None  # set once; distinct from dead
        # drain()/close() lifecycle arbitration: first caller wins the
        # state transition, every later call is a harmless no-op sweep —
        # close() stays idempotent under a concurrent drain
        self._lifecycle_lock = threading.Lock()
        # _prefix_evictions_seen read-modify-write happens on the worker
        # (admission) AND router threads (prefill_handoff) — serialize it
        self._prefix_sync_lock = threading.Lock()
        # slot bookkeeping is touched by the worker thread AND by
        # close()/client threads — every mutation of _free/_active holds
        # this lock (found by graftlint JG015: close() clearing _active
        # concurrently with the worker's admit/finish could double-free
        # a slot when the join below times out)
        self._state_lock = threading.Lock()
        self._free = list(range(slots))
        self._active: dict = {}          # slot -> _Slot
        self._last_tok = np.ones((slots,), np.int32)
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="lm-server-continuous")
        self._worker.start()

    # ------------------------------------------------------------ client API
    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               timeout: Optional[float] = None, *,
               emitted: Optional[List[int]] = None,
               state: Optional[bytes] = None) -> List[int]:
        """Serve one prompt. ``emitted`` resumes a migrated request from
        its ``HandoffCursor``: the server re-prefills ``prompt + emitted``
        (deterministic, so the greedy continuation is bit-identical to
        the donor's unkilled run) and the result INCLUDES the resumed
        prefix. ``state`` admits a shipped prefill partition
        (``serialize_prefill_state`` from a prefill replica) instead of
        prefilling locally — the disaggregated decode path."""
        ids = [int(t) for t in prompt_ids]
        if not ids:
            raise ValueError("empty prompt")
        max_new = int(self.max_new_tokens if max_new_tokens is None
                      else max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(ids) + max_new > self.max_len:
            raise ValueError(f"prompt {len(ids)} + max_new {max_new} "
                             f"exceeds the server max_len {self.max_len}")
        emitted0 = [int(t) for t in (emitted or [])]
        if emitted0:
            # a cursor that already satisfied its budget (or hit eos)
            # needs no decode at all — the donor just never got to
            # deliver the result
            if self.eos_id is not None and self.eos_id in emitted0:
                return emitted0[:emitted0.index(self.eos_id) + 1][:max_new]
            if len(emitted0) >= max_new:
                return emitted0[:max_new]
        if state is not None and self.draft is not None:
            raise ValueError(
                "state handoff is incompatible with speculative serving "
                "(the draft replica's partition does not travel)")
        if self._dead is not None:
            # fail IMMEDIATELY: a dead worker loop will never drain the
            # queue, and waiting out the client timeout helps nobody
            raise ServerDead(f"server is dead: {self._dead}")
        if self._draining is not None:
            # distinct from dead: the replica is going away ON PURPOSE —
            # the caller should retry elsewhere, nothing is lost
            raise ServerDraining(f"server is draining: {self._draining}")
        req = _Request(ids, max_new)
        req.emitted0 = emitted0
        req.state_blob = state
        req.rid = next(_REQUEST_IDS)
        req.t_submit = time.perf_counter()
        # request lifecycle: one async lane per rid in the Chrome trace —
        # submit opens it, admission marks it, completion/failure closes
        # it; the queue_wait/prefill/insert spans carry the same rid
        tracing.async_begin("serving.request", req.rid,
                            prompt_len=len(ids), max_new=max_new)
        self._queue.put(req)
        if not req.done.is_set() and (self._dead is not None
                                      or self._draining is not None):
            # the worker stopped between the check and the enqueue; its
            # final sweep may have missed this request — fail it here
            # (with a cursor, so a router can still re-dispatch it)
            if self._dead is not None:
                self._fail_handoff(req, emitted0,
                                   f"server is dead: {self._dead}", "dead")
            else:
                self._fail_handoff(req, emitted0,
                                   f"server is draining: {self._draining}",
                                   "draining")
        self._tm.serving_queue_depth.set(self._queue.qsize())
        if not req.done.wait(timeout):
            raise TimeoutError("decode did not complete in time")
        if req.error is not None:
            if req.fail_kind == "draining":
                raise ServerDraining(req.error, cursor=req.handoff)
            if req.fail_kind == "dead":
                raise ServerDead(req.error, cursor=req.handoff)
            raise RuntimeError(req.error)
        return req.result

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot (the /health SLO signal)."""
        return self._queue.qsize()

    @property
    def dead_reason(self) -> Optional[str]:
        """Why the worker loop stopped serving (None while healthy). Once
        set, every ``submit()`` raises immediately — restart the server;
        the donated-buffer state after a decode failure is not
        recoverable in place."""
        return self._dead

    @property
    def drain_reason(self) -> Optional[str]:
        """Why the server stopped ADMITTING (None unless draining).
        Distinct from ``dead_reason``: a draining replica failed nothing
        — every interrupted request left with a ``HandoffCursor`` and
        ``/health`` reports ``draining`` so a router stops routing here
        without declaring the replica lost."""
        return self._draining

    def drain(self, reason: str = "drain requested") -> None:
        """Graceful shutdown (the SIGTERM path): stop admitting, stop
        the decode loop at the next block boundary, and hand every
        accepted-but-unfinished request off as a ``HandoffCursor``
        (prompt ids + emitted tokens + budget) raised to its waiting
        ``submit()`` as ``ServerDraining`` — a router re-dispatches the
        cursor to a peer, whose deterministic re-prefill keeps greedy
        outputs bit-identical to an unkilled run. Idempotent, and safe
        to race with ``close()``: the first lifecycle call wins, later
        ones only re-sweep (finding nothing)."""
        with self._lifecycle_lock:
            if self._dead is not None or self._draining is not None:
                return
            self._draining = reason
        self._tm.serving_drains_total.inc()
        self._stop.set()
        self._worker.join(timeout=10)
        self._sweep_stranded()

    def close(self):
        """Stop the worker and fail anything still pending. Idempotent,
        including under a CONCURRENT ``drain()``: both sides snapshot-
        and-clear the slot table under ``_state_lock``, so each stranded
        request is failed exactly once — and when the drain got there
        first, with its handoff cursor intact (``_fail_handoff`` never
        overwrites a request that already completed or failed)."""
        self._stop.set()
        self._worker.join(timeout=10)
        for p in self._pipelines:
            p.disable()
        self._sweep_stranded()

    def prefill_handoff(self, prompt_ids,
                        emitted: Optional[List[int]] = None) -> bytes:
        """Run the admission prefill WITHOUT taking a slot and return
        the serialized handoff partition (last-token log-probs + b=1
        state) for a DECODE replica's ``submit(..., state=blob)`` — the
        prefill half of prefill/decode disaggregation. Raises
        ``ServerDraining``/``ServerDead`` like ``submit`` so the router's
        health logic applies unchanged."""
        ids = ([int(t) for t in prompt_ids]
               + [int(t) for t in (emitted or [])])
        if not ids:
            raise ValueError("empty prompt")
        if self._dead is not None:
            raise ServerDead(f"server is dead: {self._dead}")
        if self._draining is not None:
            raise ServerDraining(f"server is draining: {self._draining}")
        if self._d_pipeline is not None:
            raise ValueError("prefill handoff is incompatible with "
                             "speculative serving (the draft partition "
                             "does not travel)")
        with span("serving.prefill", plen=len(ids), rid=0,
                  mode=self.prefill_mode):
            lp, small, hit, built = self._pipeline.run(ids)
        if built:
            self._tm.serving_recompiles_total.inc(built)
        self._sync_prefix_metrics(hit)
        state = partition_prefill_state(small)[0]
        return serialize_prefill_state(lp, state)

    @property
    def batches_served(self) -> int:
        return self._n_served

    @property
    def requests_admitted(self) -> int:
        """Requests admitted into slots over this server's lifetime —
        the trigger the serving-plane chaos injectors key off."""
        return self._n_admitted

    @property
    def decode_blocks(self) -> int:
        """Decode blocks started (1-based inside the current block) —
        the other chaos trigger."""
        return self._steps

    # ------------------------------------------------------------- programs
    @property
    def _pipelines(self):
        """The live prefill pipelines (target always; draft in
        speculative mode)."""
        return ([self._pipeline] if self._d_pipeline is None
                else [self._pipeline, self._d_pipeline])

    @property
    def _prefill_fns(self):
        """The target's O(1) prefill program set (see
        ``_PrefillPipeline.fns``)."""
        return self._pipeline.fns

    @property
    def _prefill_cache_len(self):
        """Template cache length of the target prefill pipeline."""
        return self._pipeline.cache_len

    def _run_prefill(self, ids: List[int]):
        """Admission prefill across every pipeline: the target produces
        the sampling log-probs; in speculative mode the DRAFT prefills
        the same prompt right after (its continuous cache needs the
        prompt too — each pipeline keeps its own prefix trie over its
        own state shapes, so a hot prefix skips chunks for both).
        Compile accounting: any program the flight recorder built during
        this prefill counts as serving recompile churn (per NEW
        SIGNATURE — a bucketed wrapper minting its second bucket counts
        exactly like a fresh program build)."""
        lp, small, hit, built = self._pipeline.run(ids)
        d_small = None
        if self._d_pipeline is not None:
            _d_lp, d_small, _d_hit, d_built = self._d_pipeline.run(ids)
            built += d_built
        if built:
            self._tm.serving_recompiles_total.inc(built)
        self._sync_prefix_metrics(hit)
        return lp, small, d_small, hit

    def _sync_prefix_metrics(self, hit: int) -> None:
        """Mirror the trie's plain counters into the registry families.
        Hit/miss count ADMISSIONS (the target trie's verdict — one count
        per prefill, so hit rate reads directly as hits/(hits+misses));
        evictions and held bytes aggregate over both pipelines' tries in
        speculative mode."""
        caches = [p.prefix for p in self._pipelines
                  if p.prefix is not None]
        if not caches:
            return
        (self._tm.prefix_cache_hits if hit
         else self._tm.prefix_cache_misses).inc()
        with self._prefix_sync_lock:
            ev = sum(pc.evictions for pc in caches)
            if ev > self._prefix_evictions_seen:
                self._tm.prefix_cache_evictions.inc(
                    ev - self._prefix_evictions_seen)
                self._prefix_evictions_seen = ev
        self._tm.prefix_cache_bytes.set(sum(pc.nbytes for pc in caches))

    def _insert(self):
        """The slot-insert program (built on first use; the draft insert
        in speculative mode is the SAME wrapper specializing on the
        draft's buffer-tree signature)."""
        if self._insert_fn is None:
            self._insert_fn = _build_insert_fn(self.registry)
            self._tm.serving_recompiles_total.inc()
        return self._insert_fn

    def _step(self):
        """Jitted decode_block-token step over ALL slots."""
        if self._step_fn is None:
            model = self.model
            sampling = self.sampling
            block = self.decode_block

            def run(params, bufs, toks, key):
                def one(carry, kk):
                    bufs, tok = carry
                    lp, bufs = functional_apply(
                        model, params, bufs,
                        tok[:, None].astype(jnp.float32), training=False)
                    nxt = sample_token(lp[:, -1], kk, **sampling)
                    return (bufs, nxt), nxt

                keys = jax.random.split(key, block)
                (bufs, _), out = jax.lax.scan(one, (bufs, toks), keys)
                return out.T, bufs      # (slots, block)

            self._step_fn = tracked_jit(run, site="serving.step",
                                        registry=self.registry,
                                        donate_argnums=(1,))
            self._tm.serving_recompiles_total.inc()
        return self._step_fn

    def _spec(self):
        """Jitted speculative round over ALL slots: the draft proposes
        ``spec_len`` tokens per row (a scan of single-token continuous
        steps; one extra step commits the last proposal's k/v), the
        target verifies carried-token + proposals in ONE multi-token
        continuous forward (``_attend_decode_continuous``'s chunk
        branch — the chunked verification path), and per-row
        first-mismatch acceptance emits 1..spec_len+1 tokens. Both
        caches then roll back PER ROW to the accepted boundary
        (``_shift_decode_pos``); the rejected writes sit behind the
        position mask until the next round overwrites them. Greedy ids
        are argmax+1 — exactly ``sample_token(greedy=True)`` — so the
        accepted stream is bit-identical to the non-speculative path."""
        if self._spec_fn is None:
            target = self.model
            draft = self.draft
            k = self.spec_len

            def run(params, bufs, d_params, d_bufs, toks):
                def propose(carry, _):
                    db, tok = carry
                    lp, db = functional_apply(
                        draft, d_params, db,
                        tok[:, None].astype(jnp.float32), training=False)
                    nxt = (jnp.argmax(lp[:, -1], axis=-1)
                           + 1).astype(jnp.int32)
                    return (db, nxt), nxt

                # k+1 draft steps: step i consumes proposal i-1; the
                # final step's OUTPUT is discarded but its input write
                # commits proposal k's k/v (kept on acceptance, rolled
                # back with everything else on rejection)
                (d_bufs, _), props = jax.lax.scan(
                    propose, (d_bufs, toks), None, length=k + 1)
                d_props = props[:k].T                      # (slots, k)
                chunk = jnp.concatenate([toks[:, None], d_props], axis=1)
                lp, bufs = functional_apply(
                    target, params, bufs, chunk.astype(jnp.float32),
                    training=False)
                g = (jnp.argmax(lp, axis=-1) + 1).astype(jnp.int32)
                match = d_props == g[:, :k]
                # first mismatch per row; k when the whole draft matched
                # (the appended False column is argmin's sentinel)
                n_acc = jnp.argmin(jnp.concatenate(
                    [match, jnp.zeros((match.shape[0], 1), bool)],
                    axis=1).astype(jnp.int32), axis=1)
                bonus = jnp.take_along_axis(g, n_acc[:, None],
                                            axis=1)[:, 0]
                ar = jnp.arange(k + 1)[None, :]
                props_pad = jnp.concatenate(
                    [d_props, jnp.zeros((d_props.shape[0], 1),
                                        jnp.int32)], axis=1)
                emit = jnp.where(ar < n_acc[:, None], props_pad,
                                 bonus[:, None])
                n_emit = n_acc + 1
                # both models advanced decode_pos by k+1; roll each row
                # back to its own accepted boundary
                delta = n_emit - (k + 1)
                bufs = _shift_decode_pos(bufs, delta)
                d_bufs = _shift_decode_pos(d_bufs, delta)
                return emit, n_emit, bonus, bufs, d_bufs

            self._spec_fn = tracked_jit(run, site="serving.spec_step",
                                        registry=self.registry,
                                        donate_argnums=(1, 3))
            self._tm.serving_recompiles_total.inc()
        return self._spec_fn

    def _spec_round(self):
        """Dispatch one speculative round with the TARGET heads in
        all-positions mode — a trace-time flag (only the FIRST call per
        signature traces, but flipping around every dispatch is a few
        attribute writes). The draft heads stay last-sliced: its scan
        steps are single-token."""
        for h in self._heads:
            h._decode_all = True
        try:
            emit, n_emit, cur, bufs, d_bufs = self._spec()(
                self.params, self.buffers, self.d_params, self.d_buffers,
                jnp.asarray(self._last_tok))
        finally:
            for h in self._heads:
                h._decode_all = False
        return (np.asarray(emit), np.asarray(n_emit),
                np.asarray(cur).astype(np.int32), bufs, d_bufs)

    def _restore_handoff(self, blob: bytes):
        """Admit a SHIPPED prefill partition (disaggregation's decode
        half): deserialize, validate the leaf shapes against this
        server's own template, and merge with the LOCAL statics — model
        weights are identical across replicas of one build, so only the
        per-request partition ever travels."""
        lp, state = deserialize_prefill_state(blob)
        pipe = self._pipeline
        if len(state) != len(pipe.state0):
            raise ValueError(
                f"handoff partition has {len(state)} leaves; this "
                f"server's prefill template has {len(pipe.state0)}")
        for i, (got, want) in enumerate(zip(state, pipe.state0)):
            if got.shape != want.shape:
                raise ValueError(
                    f"handoff leaf {i} has shape {got.shape}, template "
                    f"expects {want.shape} (mismatched prefill mode/"
                    f"chunk between prefill and decode replicas?)")
        return lp, pipe.merge(state, pipe.statics)

    # --------------------------------------------------------------- worker
    def _admit(self, req: _Request) -> bool:
        # the CONTEXT the caches must hold: the prompt plus any resumed
        # cursor prefix (a migrated request re-prefills both — that
        # deterministic replay is what keeps greedy outputs bit-exact)
        plen = len(req.ids) + len(req.emitted0)
        t_admit = time.perf_counter()
        # queue-wait attribution: the retrodicted submit->admission span
        # plus an instant on the request's async lane, both under its rid
        tracing.complete_event("serving.queue_wait", req.t_submit, t_admit,
                               rid=req.rid)
        try:
            with span("serving.prefill", plen=plen, rid=req.rid,
                      mode=self.prefill_mode):
                if req.state_blob is not None:
                    lp, small = self._restore_handoff(req.state_blob)
                    d_small, hit = None, 0
                else:
                    lp, small, d_small, hit = self._run_prefill(
                        req.ids + req.emitted0)
                # key advances per ADMISSION (not per completion — several
                # admits can happen between completions, and identical
                # prompts sampled under a reused key would correlate
                # perfectly)
                self._n_admitted += 1
                key = jax.random.fold_in(self._admit_key, self._n_admitted)
                tok = int(sample_token(lp, key, **self.sampling)[0])
            # peek, insert, THEN pop: an insert failure must not leak the
            # slot. (The insert donates self.buffers; a RUNTIME failure
            # mid-insert can still invalidate them — compile-time errors,
            # the common case, happen before donation.) The device-side
            # insert runs OUTSIDE the state lock.
            with self._state_lock:
                slot = self._free[-1]
            with span("serving.insert", slot=slot, rid=req.rid):
                self.buffers = self._insert()(
                    self.buffers, small, jnp.int32(slot), jnp.int32(plen))
                if d_small is not None:
                    # the draft cache needs the prompt too (same wrapper,
                    # second signature); its decode_pos lands on the same
                    # plen so both models enter the round at position P
                    self.d_buffers = self._insert()(
                        self.d_buffers, d_small, jnp.int32(slot),
                        jnp.int32(plen))
            with self._state_lock:
                self._free.pop()
            tracing.async_instant("serving.request", req.rid,
                                  phase="admitted", slot=slot)
            # admission grows the live KV footprint — one of the two
            # watermark sampling points (the other is the step boundary)
            sample_device_memory(self.registry)
            # first token sampled == time-to-first-token for this request
            ttft = time.perf_counter() - req.t_submit
            self._tm.serving_ttft_seconds.observe(ttft)
            if self.prefix_cache_enabled:
                # the hit/miss TTFT split is the prefix cache's headline
                # effect — p50(hit) / p50(miss) is the scoreboard column
                (self._tm.serving_ttft_hit_seconds if hit
                 else self._tm.serving_ttft_miss_seconds).observe(ttft)
            self._tm.serving_admissions_total.inc()
            self._tm.serving_tokens_total.inc()
            sl = _Slot(req)
            sl.emitted = list(req.emitted0) + [tok]
            sl.new_count = len(req.emitted0) + 1
            self._last_tok[slot] = tok
            if self._finish_if_done(slot, sl):
                return True
            with self._state_lock:
                self._active[slot] = sl
            self._tm.serving_slots_occupied.set(len(self._active))
            return True
        except Exception as e:  # noqa: BLE001 — fail the one request
            req.error = f"{type(e).__name__}: {e}"
            req.done.set()
            tracing.async_end("serving.request", req.rid, error=req.error)
            self._tm.serving_request_errors_total.inc()
            return False

    def _finish_if_done(self, slot: int, sl: _Slot) -> bool:
        eos = self.eos_id
        hit_eos = eos is not None and sl.emitted and sl.emitted[-1] == eos
        if hit_eos or sl.new_count >= sl.req.max_new:
            sl.req.result = sl.emitted[:sl.req.max_new]
            sl.req.done.set()
            tracing.async_end("serving.request", sl.req.rid,
                              tokens=len(sl.req.result))
            self._n_served += 1
            self._tm.serving_requests_completed_total.inc()
            self._tm.serving_request_latency_seconds.observe(
                time.perf_counter() - sl.req.t_submit)
            with self._state_lock:
                if slot in self._active:
                    del self._active[slot]
                self._free.append(slot)
            self._tm.serving_slots_occupied.set(len(self._active))
            return True
        return False

    def _fail_handoff(self, req: _Request, emitted: List[int],
                      message: str, kind: str) -> None:
        """Fail one request WITH its resume cursor: the host-side prompt
        + emitted tokens survive any device-state loss, so even a dead
        replica's accepted requests leave with everything a peer needs
        to finish them bit-identically (greedy). Skips requests that
        already completed or failed — a second sweeper must not
        overwrite the first one's verdict (or a delivered result)."""
        if req.done.is_set():
            return
        req.handoff = HandoffCursor(ids=list(req.ids),
                                    emitted=list(emitted),
                                    max_new=req.max_new)
        req.fail_kind = kind
        req.error = message
        req.done.set()
        tracing.async_end("serving.request", req.rid, error=message)

    def _sweep_stranded(self) -> None:
        """Snapshot-and-clear every in-flight slot and queued request,
        then fail them — with handoff cursors when the server is
        draining (migration), plain errors on an ordinary close. Shared
        by ``close()``, ``drain()`` and the worker's stop-path (each
        side may run it; the snapshot under ``_state_lock`` guarantees
        every request is failed at most once)."""
        with self._state_lock:
            stranded = list(self._active.items())
            self._active.clear()
            self._free.extend(s for s, _ in stranded)
        queued = drain_queue(self._queue)
        draining = self._draining
        if draining is not None:
            msg = f"server draining: {draining}"
            for _s, sl in stranded:
                self._fail_handoff(sl.req, sl.emitted, msg, "draining")
            for req in queued:
                self._fail_handoff(req, req.emitted0, msg, "draining")
        else:
            fail_requests([sl.req for _s, sl in stranded],
                          "server closed mid-generation",
                          category="serving.request")
            fail_requests(queued,
                          "server closed before the request was dispatched",
                          category="serving.request")
        self._tm.serving_slots_occupied.set(0)
        self._tm.serving_queue_depth.set(0)

    def _die(self, reason: str) -> None:
        """Dead-server state (ADVICE medium, ROADMAP #1): fail every
        in-flight AND queued request NOW, mark the server dead so later
        ``submit()`` calls raise immediately instead of queueing against a
        worker that will never serve them. Never cleared — a decode-step
        failure invalidates the donated cache buffers, so the only safe
        continuation is a new server. Every failed request still leaves
        with its ``HandoffCursor`` (the cursor is host-side state): a
        router re-dispatches it to a healthy peer and the kill loses
        zero accepted requests."""
        self._dead = reason
        self._tm.serving_request_errors_total.inc(len(self._active))
        with self._state_lock:
            stranded = list(self._active.items())
            self._active.clear()
            self._free.extend(slot for slot, _ in stranded)
        for _s, sl in stranded:
            self._fail_handoff(sl.req, sl.emitted,
                               f"server died: {reason}", "dead")
        self._tm.serving_slots_occupied.set(0)
        queued = drain_queue(self._queue)
        for req in queued:
            self._fail_handoff(req, req.emitted0,
                               f"server is dead: {reason}", "dead")
        self._tm.serving_request_errors_total.inc(len(queued))
        self._tm.serving_queue_depth.set(0)

    def _run(self):
        try:
            self._run_loop()
        except Exception as e:  # noqa: BLE001 — the worker-thread boundary
            # an unexpected worker-loop error must not strand clients on
            # their timeouts: declare the server dead and fail everyone
            self._die(f"{type(e).__name__}: {e}")

    def _run_loop(self):
        self._serve_loop()
        # stop-path sweep ON THE WORKER (mirrors close()/drain()): the
        # client-side sweep runs after a BOUNDED join, so on a timed-out
        # join this loop may have admitted or dequeued a request after
        # it — fail the leftovers here so nobody waits out a client
        # timeout, whichever side runs last
        self._sweep_stranded()

    def _serve_loop(self):
        while not self._stop.is_set():
            # strict-FIFO admission into free slots (starvation-free)
            while self._free:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                self._admit(req)
            # refresh AFTER the drain, every pass — a gauge written only
            # on submit would stay stale (showing phantom backlog) once a
            # failed admission or an idle loop empties the queue
            self._tm.serving_queue_depth.set(self._queue.qsize())
            if not self._active:
                try:
                    req = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                self._admit(req)
                continue
            # one decode round for every slot (dead rows compute garbage):
            # a decode_block scan of single-token steps, or in speculative
            # mode one draft+verify round emitting 1..spec_len+1 tokens
            # per row
            self._steps += 1
            counts = None           # spec mode: per-row emit counts
            try:
                for inj in self._chaos:
                    # serving-plane injectors: a raising hook (the
                    # kill-replica drill) lands in the except below and
                    # drives the REAL die path mid-stream; a sleeping
                    # hook (delay-decode) stretches exactly one block
                    inj.on_decode_block(self)
                t_block = time.perf_counter()
                with span("serving.decode_block",
                          live=len(self._active)) as sp:
                    if tracing.is_enabled():
                        # which requests this block advanced (rid linkage;
                        # list built only when the tracer is on)
                        sp.annotate(rids=[sl.req.rid
                                          for sl in self._active.values()])
                    if self.draft is not None:
                        (toks, counts, cur,
                         self.buffers, self.d_buffers) = self._spec_round()
                    else:
                        key = jax.random.fold_in(self._step_key,
                                                 self._steps)
                        toks, self.buffers = self._step()(
                            self.params, self.buffers,
                            jnp.asarray(self._last_tok), key)
                        toks = np.asarray(toks)
            except Exception as e:  # noqa: BLE001 — fail fast AND dead
                # a decode-step failure fails every in-flight request NOW
                # (clients see the error instead of hanging to their
                # timeout) and marks the server DEAD: the step donated
                # self.buffers, so the cache state is gone — "keep
                # admitting" (the PR-5 behaviour) only converted every
                # later request into a slower failure. submit() now raises
                # immediately (ADVICE medium finding, serving.py:302).
                self._die(f"decode step failed: {type(e).__name__}: {e}")
                return
            live = list(self._active.keys())
            # per-token latency: round wall-clock (np.asarray is the host
            # sync) amortized over the tokens the round produced — fixed
            # decode_block, or the measured mean emit count of live rows
            # in speculative mode (the acceptance rate is what makes the
            # round worth its dispatch)
            per_round = (self.decode_block if counts is None
                         else float(np.mean(counts[live])))
            self._tm.serving_token_latency_seconds.observe(
                (time.perf_counter() - t_block) / per_round)
            self._tm.serving_decode_blocks_total.inc()
            if counts is not None:
                # each live row was proposed spec_len draft tokens and
                # accepted counts-1 of them (the +1 is the target's own
                # bonus token, not a draft acceptance)
                self._tm.spec_proposed_tokens_total.inc(
                    self.spec_len * len(live))
                self._tm.spec_accepted_tokens_total.inc(
                    int(counts[live].sum()) - len(live))
            sample_device_memory(self.registry)
            self._last_tok = (cur if counts is not None
                              else toks[:, -1].astype(np.int32))
            eos = self.eos_id
            live_tokens = 0
            for slot, sl in list(self._active.items()):
                row = (toks[slot] if counts is None
                       else toks[slot][:counts[slot]])
                for t in row:
                    t = int(t)
                    sl.emitted.append(t)
                    sl.new_count += 1
                    live_tokens += 1
                    if ((eos is not None and t == eos)
                            or sl.new_count >= sl.req.max_new):
                        break
                self._finish_if_done(slot, sl)
            if live_tokens:
                self._tm.serving_tokens_total.inc(live_tokens)
