"""Continuous batching LM serving engine (round 5, VERDICT #6).

The bucketed ``models/lm_server.py`` groups requests by exact prompt
length and decodes whole batches in lockstep: one long generation blocks
its bucket, and mixed-length traffic fragments into tiny batches. This
engine replaces lockstep with SLOTS (the vLLM-style iteration-level
scheduler, built TPU-first on static shapes):

- the model sits permanently in *continuous* decode mode: (slots, L) KV
  caches with a PER-ROW ``decode_pos`` (``nn.attention
  ._attend_decode_continuous``) — every slot lives at its own position in
  its own sequence, and ONE jitted step program advances them all;
- a new request prefills OUT-OF-BAND as a b=1 forward in FIXED-SIZE
  CHUNKS (``prefill_mode="chunked"``, the default): ⌈(L-1)/C⌉ chunks of
  ``prefill_chunk`` tokens through the warm-cache chunked attention
  branch plus one single-token step for the last prompt token — exactly
  TWO compiled programs regardless of prompt length, where the old
  per-length prefill compiled one program per distinct length (the
  compile storm ROADMAP #1 tracked; graftlint JG013's frozen fire
  fixture is that pre-fix code). ``prefill_mode="bucketed"`` is the
  fallback for attention paths that can't take the masked chunk: the
  prompt pads to its power-of-two ``pow2_bucket`` length and one
  wrapper specializes per bucket (O(log max_len) programs). Either way
  a jitted insert then scatters the (1, L) cache into a free slot row
  and sets that row's ``decode_pos`` — admission never recompiles or
  disturbs running slots;
- steps dispatch in blocks of ``decode_block`` tokens (a ``lax.scan`` —
  amortizes the per-dispatch host cost); finished rows (eos/budget) free
  their slot at the next block boundary and the queue admits strictly
  FIFO, so no request can be starved (the ADVICE round-4 finding against
  the bucketed ``_gather``).

Dead slots keep computing garbage (their rows are never read) — the TPU
trade: wasted lanes are cheaper than a recompile or a dynamic shape.

Restrictions: rope models only (additive positional-encoding modules
track a shared scalar position), no beam search. Sampling is the server's
(greedy/temperature/top_k/top_p via ``generation.sample_token``).

``ContinuousLMServer`` exposes the same ``submit()/close()`` surface as
``LMServer``, so ``make_http_server`` and ``apps.transformer serve
--continuous`` reuse it unchanged.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import functional_apply
from bigdl_tpu.models.generation import (_decode_modules,
                                         build_bucketed_prefill_fn,
                                         build_chunked_prefill_fns,
                                         sample_token)
from bigdl_tpu.telemetry import get_registry, instruments, span, tracing
from bigdl_tpu.telemetry.profiling import (sample_device_memory,
                                           tracked_jit)
from bigdl_tpu.utils.util import pow2_bucket

# Smallest prefill length bucket (prefill_mode="bucketed"): prompts
# shorter than this share one program instead of minting one per small
# power of two. The top bucket saturates at max_len.
_PREFILL_BUCKET_LO = 16

# One id per submitted request, process-wide: the Chrome-trace async
# lifecycle key (serving.request) and the rid arg on every phase span.
# itertools.count is GIL-atomic — submit() runs on client threads.
_REQUEST_IDS = itertools.count(1)


@dataclass
class _Request:
    ids: List[int]
    max_new: int
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[List[int]] = None
    error: Optional[str] = None
    t_submit: float = 0.0               # perf_counter at submit (TTFT/SLO)
    rid: int = 0                        # trace-lifecycle id (serving.request)


class _Slot:
    __slots__ = ("req", "emitted", "new_count")

    def __init__(self, req):
        self.req = req
        self.emitted: List[int] = []
        self.new_count = 0


class ContinuousLMServer:
    """Slot-scheduled continuous-batching server over one rope LM."""

    def __init__(self, model, *, slots: int = 8, max_len: int = 256,
                 decode_block: int = 8, max_new_tokens: int = 64,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 0.0, greedy: bool = False,
                 eos_id: Optional[int] = None, seed: int = 0,
                 registry=None, prefill_mode: Optional[str] = None,
                 prefill_chunk: Optional[int] = None):
        if slots < 1:
            raise ValueError("slots must be >= 1")
        # prompt prefill strategy (both O(1)-compile; ROADMAP #1):
        # "chunked" (default) = fixed-size chunks through the warm-cache
        # chunked attention branch, two programs total; "bucketed" =
        # pad the prompt to its power-of-two bucket, one program per
        # bucket — the fallback for attention paths that can't take the
        # masked multi-token chunk. Env levers mirror the args so a
        # deployment can flip modes without code changes.
        mode = (prefill_mode if prefill_mode is not None
                else os.environ.get("BIGDL_PREFILL_MODE", "chunked"))
        if mode not in ("chunked", "bucketed"):
            raise ValueError(f"prefill_mode must be 'chunked' or "
                             f"'bucketed', got {mode!r}")
        chunk = int(prefill_chunk if prefill_chunk is not None
                    else os.environ.get("BIGDL_PREFILL_CHUNK", "128"))
        if chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        # a chunk wider than the cache buys nothing and multiplies the
        # template-cache memory and per-prompt prefill work (prompts
        # never exceed max_len - max_new); clamp rather than reject so
        # the 128 default composes with small test/serving caches
        chunk = min(chunk, max_len)
        self.prefill_mode = mode
        self.prefill_chunk = chunk
        # telemetry (docs/OBSERVABILITY.md): TTFT / per-token latency /
        # queue depth / slot occupancy — the serving SLO surface, exposed
        # by make_http_server as GET /metrics
        self.registry = registry if registry is not None else get_registry()
        self._tm = instruments(self.registry)
        self._tm.serving_slots_total.set(slots)
        mhas, pes, heads = _decode_modules(model)
        if pes:
            raise ValueError(
                "continuous batching requires a rope model (additive "
                "positional encodings track one shared position; "
                "build_lm(rope=True))")
        if not mhas:
            raise ValueError("model has no attention layers to cache")
        self.model = model
        self._mhas, self._heads = mhas, heads
        self.slots = slots
        self.max_len = max_len
        self.decode_block = max(1, int(decode_block))
        self.max_new_tokens = max_new_tokens
        self.sampling = dict(temperature=temperature, top_k=top_k,
                             top_p=top_p, greedy=greedy)
        self.eos_id = eos_id
        self._seed = seed
        # Disjoint key streams, collision-free by construction: the old
        # ad-hoc arithmetic (seed + n_admitted*7919 + 1 for admissions,
        # seed + steps*31 + 17 for decode blocks) lands both families on
        # the SAME PRNGKey for some (n, steps) pair — e.g. admission 10
        # and step 2554 — correlating an admitted token draw with a whole
        # decode block (found by graftlint JG003).
        self._admit_key = jax.random.fold_in(jax.random.PRNGKey(seed), 0)
        self._step_key = jax.random.fold_in(jax.random.PRNGKey(seed), 1)
        self._steps = 0
        self._n_served = 0
        self._n_admitted = 0

        model.evaluate_mode()
        # single-request decode template (the prefill signature) FIRST,
        # then the persistent continuous state. The chunked template
        # cache is padded up to a whole number of chunks so the final
        # (right-padded) chunk's k/v write never clips against the cache
        # end — the insert slices the copy back down to max_len.
        if mode == "chunked":
            self._prefill_cache_len = -(-max_len // chunk) * chunk
        else:
            self._prefill_cache_len = max_len
        for m in mhas:
            m.enable_decode(1, self._prefill_cache_len)
        for m in heads:
            m.enable_decode()
        _, small0 = model.functional_state()
        # COPY the template leaves: non-cache buffers (e.g. a quantized
        # model's int8 weights live in the buffer tree) are otherwise the
        # very arrays the donating step/insert programs consume — the
        # first admission would delete the prefill template's references
        self._small_bufs0 = jax.tree_util.tree_map(jnp.copy, small0)
        for m in mhas:
            m.enable_decode(slots, max_len, continuous=True)
        self.params, self.buffers = model.functional_state()
        # the O(1) prefill program set, built BEFORE the worker thread
        # starts (wrappers are cheap; XLA programs compile lazily inside
        # tracked_jit at first dispatch, counted per signature in
        # bigdl_compiles_total{site="serving.prefill"})
        if mode == "chunked":
            (self._chunk_fn, self._last_fn, self._prefill_state0,
             self._prefill_statics, self._prefill_merge) = \
                build_chunked_prefill_fns(model, self._small_bufs0,
                                          registry=self.registry)
            self._bucket_fn = None
        else:
            self._chunk_fn = self._last_fn = None
            self._bucket_fn = build_bucketed_prefill_fn(
                model, registry=self.registry)
        self._step_fn = None
        self._insert_fn = None

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._dead: Optional[str] = None     # set once; never cleared
        # slot bookkeeping is touched by the worker thread AND by
        # close()/client threads — every mutation of _free/_active holds
        # this lock (found by graftlint JG015: close() clearing _active
        # concurrently with the worker's admit/finish could double-free
        # a slot when the join below times out)
        self._state_lock = threading.Lock()
        self._free = list(range(slots))
        self._active: dict = {}          # slot -> _Slot
        self._last_tok = np.ones((slots,), np.int32)
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="lm-server-continuous")
        self._worker.start()

    # ------------------------------------------------------------ client API
    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               timeout: Optional[float] = None) -> List[int]:
        ids = [int(t) for t in prompt_ids]
        if not ids:
            raise ValueError("empty prompt")
        max_new = int(self.max_new_tokens if max_new_tokens is None
                      else max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(ids) + max_new > self.max_len:
            raise ValueError(f"prompt {len(ids)} + max_new {max_new} "
                             f"exceeds the server max_len {self.max_len}")
        if self._dead is not None:
            # fail IMMEDIATELY: a dead worker loop will never drain the
            # queue, and waiting out the client timeout helps nobody
            raise RuntimeError(f"server is dead: {self._dead}")
        req = _Request(ids, max_new)
        req.rid = next(_REQUEST_IDS)
        req.t_submit = time.perf_counter()
        # request lifecycle: one async lane per rid in the Chrome trace —
        # submit opens it, admission marks it, completion/failure closes
        # it; the queue_wait/prefill/insert spans carry the same rid
        tracing.async_begin("serving.request", req.rid,
                            prompt_len=len(ids), max_new=max_new)
        self._queue.put(req)
        if self._dead is not None and not req.done.is_set():
            # the worker died between the check and the enqueue; its final
            # drain may have missed this request — fail it here
            req.error = f"server is dead: {self._dead}"
            req.done.set()
            tracing.async_end("serving.request", req.rid, error=req.error)
        self._tm.serving_queue_depth.set(self._queue.qsize())
        if not req.done.wait(timeout):
            raise TimeoutError("decode did not complete in time")
        if req.error is not None:
            raise RuntimeError(req.error)
        return req.result

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot (the /health SLO signal)."""
        return self._queue.qsize()

    @property
    def dead_reason(self) -> Optional[str]:
        """Why the worker loop stopped serving (None while healthy). Once
        set, every ``submit()`` raises immediately — restart the server;
        the donated-buffer state after a decode failure is not
        recoverable in place."""
        return self._dead

    def close(self):
        self._stop.set()
        self._worker.join(timeout=10)
        for m in self._mhas + self._heads:
            m.disable_decode()
        with self._state_lock:
            stranded = list(self._active.values())
            self._active.clear()
        for sl in stranded:
            sl.req.error = "server closed mid-generation"
            sl.req.done.set()
            tracing.async_end("serving.request", sl.req.rid,
                              error=sl.req.error)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.error = "server closed before the request was dispatched"
            req.done.set()
            tracing.async_end("serving.request", req.rid, error=req.error)

    @property
    def batches_served(self) -> int:
        return self._n_served

    # ------------------------------------------------------------- programs
    @property
    def _prefill_fns(self):
        """The O(1) prefill program set — chunked mode holds the chunk +
        last-token pair, bucketed mode one wrapper that specializes per
        power-of-two bucket. Collapsed from the pre-PR-15 per-prompt-
        length LRU (one program per distinct length, the compile storm
        graftlint JG013's fire fixture preserves)."""
        fns = {"chunk": self._chunk_fn, "last": self._last_fn,
               "bucket": self._bucket_fn}
        return {k: v for k, v in fns.items() if v is not None}

    def _single_mode(self, prefilled: bool, all_logits: bool = False):
        """Context: flip the attention modules to single-request decode
        semantics for tracing/running the b=1 prefill programs.

        ``prefilled`` is the trace-time cache temperature: True traces
        the warm-cache masked branch (chunked prefill — correct on a
        cold cache too, the position mask excludes unwritten slots),
        False the cold causal fast path (bucketed prefill, which always
        starts from scratch). ``all_logits`` flips the LM heads to emit
        every position (the bucketed program reads the true last token
        at a traced index inside the padded bucket)."""
        server = self

        class _Ctx:
            def __enter__(self):
                for m in server._mhas:
                    m._continuous = False
                    m._decode_prefilled = prefilled
                if all_logits:
                    for h in server._heads:
                        h._decode_all = True
                return self

            def __exit__(self, *a):
                for m in server._mhas:
                    m._continuous = True
                    m._decode_prefilled = True
                if all_logits:
                    for h in server._heads:
                        h._decode_all = False

        return _Ctx()

    def _prefill_chunked(self, ids: List[int]):
        """Chunked b=1 prompt prefill: ⌈(L-1)/C⌉ fixed-width chunks that
        write k/v at the true cache positions (final chunk right-padded,
        pads masked and re-covered via the in-program ``decode_pos``
        rewind), then ONE single-token step for the last prompt token
        whose (1, V) log-probs feed the admission sample. Two compiled
        programs total, any L."""
        c = self.prefill_chunk
        # both prefill programs donate the per-request STATE partition
        # (caches + positions — in-place updates across the chunk loop);
        # hand them an OWNED copy so the template survives this
        # admission. Shared buffers (a quantized model's int8 weights)
        # ride along non-donated: the per-admission copy scales with the
        # b=1 cache, never with model size.
        state = [jnp.copy(x) for x in self._prefill_state0]
        statics = self._prefill_statics
        n = len(ids) - 1        # last token runs as the lp-producing step
        for start in range(0, n, c):
            valid = min(c, n - start)
            chunk = np.ones((1, c), np.float32)   # pad id 1: any valid id
            chunk[0, :valid] = ids[start:start + valid]
            state = self._chunk_fn(self.params, state, statics,
                                   jnp.asarray(chunk),
                                   jnp.int32(start + valid))
        last = np.asarray([[ids[-1]]], np.float32)
        lp, state = self._last_fn(self.params, state, statics,
                                  jnp.asarray(last))
        # the insert consumes the FULL small tree (structure must match
        # the big tree leaf-for-leaf); merge is host-side, copy-free
        return lp, self._prefill_merge(state, statics)

    def _prefill_bucketed(self, ids: List[int]):
        """Length-bucketed b=1 prompt prefill (fallback mode): the
        prompt right-pads to its power-of-two bucket and runs the
        standard cold causal prefill — one program per BUCKET
        (O(log max_len) total), with the true last token's log-probs
        read at a traced index."""
        plen = len(ids)
        cap = self._prefill_cache_len
        bsz = pow2_bucket(plen, min(_PREFILL_BUCKET_LO, cap), cap)
        prompt = np.ones((1, bsz), np.float32)
        prompt[0, :plen] = ids
        return self._bucket_fn(self.params, self._small_bufs0,
                               jnp.asarray(prompt), jnp.int32(plen - 1))

    def _run_prefill(self, ids: List[int]):
        """Mode dispatch + compile accounting: any program the flight
        recorder built during this prefill counts as serving recompile
        churn (per NEW SIGNATURE — a bucketed wrapper minting its
        second bucket counts exactly like a fresh program build)."""
        fns = self._prefill_fns
        before = sum(fn.compiles for fn in fns.values())
        if self.prefill_mode == "bucketed":
            with self._single_mode(prefilled=False, all_logits=True):
                out = self._prefill_bucketed(ids)
        else:
            with self._single_mode(prefilled=True):
                out = self._prefill_chunked(ids)
        built = sum(fn.compiles for fn in fns.values()) - before
        if built:
            self._tm.serving_recompiles_total.inc(built)
        return out

    def _insert(self):
        """Jitted scatter of a prefilled b=1 cache into slot row ``slot``
        (one compile total: slot/plen are traced scalars)."""
        if self._insert_fn is None:
            def run(big, small, slot, plen):
                flat_b, treedef = jax.tree_util.tree_flatten_with_path(big)
                flat_s = jax.tree_util.tree_flatten_with_path(small)[0]
                out = []
                for (kp, bg), (_, sm) in zip(flat_b, flat_s):
                    name = str(kp[-1])
                    if "k_cache" in name or "v_cache" in name:
                        # the chunked-prefill template cache is padded to
                        # a whole number of chunks; only the first
                        # max_len entries are live (anything past the
                        # prompt is masked pad garbage) — slice before
                        # the scatter (no-op when lengths already match)
                        out.append(jax.lax.dynamic_update_slice(
                            bg, sm.astype(bg.dtype)[:, :bg.shape[1]],
                            (slot,) + (0,) * (bg.ndim - 1)))
                    elif "decode_pos" in name:
                        out.append(jax.lax.dynamic_update_slice(
                            bg, plen[None].astype(bg.dtype), (slot,)))
                    else:
                        out.append(bg)
                return jax.tree_util.tree_unflatten(treedef, out)

            self._insert_fn = tracked_jit(run, site="serving.insert",
                                          registry=self.registry,
                                          donate_argnums=(0,))
            self._tm.serving_recompiles_total.inc()
        return self._insert_fn

    def _step(self):
        """Jitted decode_block-token step over ALL slots."""
        if self._step_fn is None:
            model = self.model
            sampling = self.sampling
            block = self.decode_block

            def run(params, bufs, toks, key):
                def one(carry, kk):
                    bufs, tok = carry
                    lp, bufs = functional_apply(
                        model, params, bufs,
                        tok[:, None].astype(jnp.float32), training=False)
                    nxt = sample_token(lp[:, -1], kk, **sampling)
                    return (bufs, nxt), nxt

                keys = jax.random.split(key, block)
                (bufs, _), out = jax.lax.scan(one, (bufs, toks), keys)
                return out.T, bufs      # (slots, block)

            self._step_fn = tracked_jit(run, site="serving.step",
                                        registry=self.registry,
                                        donate_argnums=(1,))
            self._tm.serving_recompiles_total.inc()
        return self._step_fn

    # --------------------------------------------------------------- worker
    def _admit(self, req: _Request) -> bool:
        plen = len(req.ids)
        t_admit = time.perf_counter()
        # queue-wait attribution: the retrodicted submit->admission span
        # plus an instant on the request's async lane, both under its rid
        tracing.complete_event("serving.queue_wait", req.t_submit, t_admit,
                               rid=req.rid)
        try:
            with span("serving.prefill", plen=plen, rid=req.rid,
                      mode=self.prefill_mode):
                lp, small = self._run_prefill(req.ids)
                # key advances per ADMISSION (not per completion — several
                # admits can happen between completions, and identical
                # prompts sampled under a reused key would correlate
                # perfectly)
                self._n_admitted += 1
                key = jax.random.fold_in(self._admit_key, self._n_admitted)
                tok = int(sample_token(lp, key, **self.sampling)[0])
            # peek, insert, THEN pop: an insert failure must not leak the
            # slot. (The insert donates self.buffers; a RUNTIME failure
            # mid-insert can still invalidate them — compile-time errors,
            # the common case, happen before donation.) The device-side
            # insert runs OUTSIDE the state lock.
            with self._state_lock:
                slot = self._free[-1]
            with span("serving.insert", slot=slot, rid=req.rid):
                self.buffers = self._insert()(
                    self.buffers, small, jnp.int32(slot), jnp.int32(plen))
            with self._state_lock:
                self._free.pop()
            tracing.async_instant("serving.request", req.rid,
                                  phase="admitted", slot=slot)
            # admission grows the live KV footprint — one of the two
            # watermark sampling points (the other is the step boundary)
            sample_device_memory(self.registry)
            # first token sampled == time-to-first-token for this request
            self._tm.serving_ttft_seconds.observe(
                time.perf_counter() - req.t_submit)
            self._tm.serving_admissions_total.inc()
            self._tm.serving_tokens_total.inc()
            sl = _Slot(req)
            sl.emitted = [tok]
            sl.new_count = 1
            self._last_tok[slot] = tok
            if self._finish_if_done(slot, sl):
                return True
            with self._state_lock:
                self._active[slot] = sl
            self._tm.serving_slots_occupied.set(len(self._active))
            return True
        except Exception as e:  # noqa: BLE001 — fail the one request
            req.error = f"{type(e).__name__}: {e}"
            req.done.set()
            tracing.async_end("serving.request", req.rid, error=req.error)
            self._tm.serving_request_errors_total.inc()
            return False

    def _finish_if_done(self, slot: int, sl: _Slot) -> bool:
        eos = self.eos_id
        hit_eos = eos is not None and sl.emitted and sl.emitted[-1] == eos
        if hit_eos or sl.new_count >= sl.req.max_new:
            sl.req.result = sl.emitted[:sl.req.max_new]
            sl.req.done.set()
            tracing.async_end("serving.request", sl.req.rid,
                              tokens=len(sl.req.result))
            self._n_served += 1
            self._tm.serving_requests_completed_total.inc()
            self._tm.serving_request_latency_seconds.observe(
                time.perf_counter() - sl.req.t_submit)
            with self._state_lock:
                if slot in self._active:
                    del self._active[slot]
                self._free.append(slot)
            self._tm.serving_slots_occupied.set(len(self._active))
            return True
        return False

    def _die(self, reason: str) -> None:
        """Dead-server state (ADVICE medium, ROADMAP #1): fail every
        in-flight AND queued request NOW, mark the server dead so later
        ``submit()`` calls raise immediately instead of queueing against a
        worker that will never serve them. Never cleared — a decode-step
        failure invalidates the donated cache buffers, so the only safe
        continuation is a new server."""
        self._dead = reason
        self._tm.serving_request_errors_total.inc(len(self._active))
        with self._state_lock:
            stranded = list(self._active.items())
            self._active.clear()
            self._free.extend(slot for slot, _ in stranded)
        for _slot, sl in stranded:
            sl.req.error = f"server died: {reason}"
            sl.req.done.set()
            tracing.async_end("serving.request", sl.req.rid,
                              error=sl.req.error)
        self._tm.serving_slots_occupied.set(0)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.error = f"server is dead: {reason}"
            req.done.set()
            tracing.async_end("serving.request", req.rid, error=req.error)
            self._tm.serving_request_errors_total.inc()
        self._tm.serving_queue_depth.set(0)

    def _run(self):
        try:
            self._run_loop()
        except Exception as e:  # noqa: BLE001 — the worker-thread boundary
            # an unexpected worker-loop error must not strand clients on
            # their timeouts: declare the server dead and fail everyone
            self._die(f"{type(e).__name__}: {e}")

    def _run_loop(self):
        self._serve_loop()
        # stop-path drain ON THE WORKER (mirrors close()): the client-
        # side sweep runs after a BOUNDED join, so on a timed-out join
        # this loop may have admitted or dequeued a request after it —
        # fail the leftovers here so nobody waits out a client timeout,
        # whichever side runs last
        with self._state_lock:
            stranded = list(self._active.items())
            self._active.clear()
            self._free.extend(s for s, _ in stranded)
        for _slot, sl in stranded:
            sl.req.error = "server closed mid-generation"
            sl.req.done.set()
            tracing.async_end("serving.request", sl.req.rid,
                              error=sl.req.error)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.error = "server closed before the request was dispatched"
            req.done.set()
            tracing.async_end("serving.request", req.rid, error=req.error)

    def _serve_loop(self):
        while not self._stop.is_set():
            # strict-FIFO admission into free slots (starvation-free)
            while self._free:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                self._admit(req)
            # refresh AFTER the drain, every pass — a gauge written only
            # on submit would stay stale (showing phantom backlog) once a
            # failed admission or an idle loop empties the queue
            self._tm.serving_queue_depth.set(self._queue.qsize())
            if not self._active:
                try:
                    req = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                self._admit(req)
                continue
            # one decode block for every slot (dead rows compute garbage)
            self._steps += 1
            key = jax.random.fold_in(self._step_key, self._steps)
            try:
                t_block = time.perf_counter()
                with span("serving.decode_block",
                          live=len(self._active)) as sp:
                    if tracing.is_enabled():
                        # which requests this block advanced (rid linkage;
                        # list built only when the tracer is on)
                        sp.annotate(rids=[sl.req.rid
                                          for sl in self._active.values()])
                    toks, self.buffers = self._step()(
                        self.params, self.buffers,
                        jnp.asarray(self._last_tok), key)
                    toks = np.asarray(toks)
            except Exception as e:  # noqa: BLE001 — fail fast AND dead
                # a decode-step failure fails every in-flight request NOW
                # (clients see the error instead of hanging to their
                # timeout) and marks the server DEAD: the step donated
                # self.buffers, so the cache state is gone — "keep
                # admitting" (the PR-5 behaviour) only converted every
                # later request into a slower failure. submit() now raises
                # immediately (ADVICE medium finding, serving.py:302).
                self._die(f"decode step failed: {type(e).__name__}: {e}")
                return
            # per-token latency: block wall-clock (np.asarray is the host
            # sync) amortized over the block — one observation per block
            # keeps the hot loop at a few locked ops per decode_block
            # tokens, not per token
            self._tm.serving_token_latency_seconds.observe(
                (time.perf_counter() - t_block) / self.decode_block)
            self._tm.serving_decode_blocks_total.inc()
            sample_device_memory(self.registry)
            self._last_tok = toks[:, -1].astype(np.int32)
            eos = self.eos_id
            live_tokens = 0
            for slot, sl in list(self._active.items()):
                for t in toks[slot]:
                    t = int(t)
                    sl.emitted.append(t)
                    sl.new_count += 1
                    live_tokens += 1
                    if ((eos is not None and t == eos)
                            or sl.new_count >= sl.req.max_new):
                        break
                self._finish_if_done(slot, sl)
            if live_tokens:
                self._tm.serving_tokens_total.inc(live_tokens)
